"""Rule → compiled-artifact pipeline.

``compile_ruleset`` turns format-neutral ``Rule`` objects (from seclang.py /
sigpack.py) into a ``CompiledRuleset``: packed bitap tables + per-rule
metadata arrays + confirm descriptors.  The artifact serializes to disk
(npz + json) — this is the framework's checkpoint analog (SURVEY.md §5
"Checkpoint/resume": versioned compiled-NFA tables, atomically hot-swapped
on device like the reference's proton.db sync-node flow).

Scan-variant model: each stream (uri/args/headers/body/resp_*) is scanned
in up to six normalization variants:

    0 raw           — bytes as received
    1 urldec        — urlDecodeUni + removeNulls
    2 urldec_html   — urldec + htmlEntityDecode
    3 squash_raw    — raw with all SQUASH_BYTES deleted (whitespace \\ ' " ^)
    4 squash_dec    — urldec_html with all SQUASH_BYTES deleted
    5 squash_urldec — urldec with all SQUASH_BYTES deleted (no html decode
                      — that decode can DELETE factor bytes of rules whose
                      chain doesn't include it)

A rule is assigned the variant matching its transform chain, so factor
matching stays *sound* (never misses) while the CPU confirm stage applies
the rule's exact transforms.  Soundness of the squash variants: deletion
transforms (compressWhitespace / removeWhitespace / cmdLine) let attackers
interleave deletable bytes inside a payload (``w"get`` → ``wget``); both
the scanned stream AND the rule's factors have the same SQUASH_BYTES
deleted, so the factor fires iff the post-transform text contains it.
Factor positions whose class is a subset of SQUASH_BYTES are dropped
(neighbors become adjacent, exactly as in the stream); positions whose
class only partially overlaps are split points (survival is ambiguous).

``normalizePath`` rules get factors split at path separators: nginx-style
path normalization only deletes chunks that contain a '/', so any
slash-free factor fragment present in the normalized text is literally
present in the raw stream.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ingress_plus_tpu.compiler import factors as F
from ingress_plus_tpu.compiler.bitap import BitapTables, pack_factors
from ingress_plus_tpu.compiler.reduce import (
    ReductionConfig,
    coarsen_byte_classes,
    reduce_rule_groups,
)
from ingress_plus_tpu.compiler.regex_ast import RegexUnsupported, parse_regex
from ingress_plus_tpu.compiler.seclang import (
    CLASSES,
    CLASS_INDEX,
    NON_SCANNED_SCALAR_BASES as F_NON_SCANNED,
    Rule,
    STREAMS,
    STREAM_INDEX,
    _classify_setvar,
    _id_matcher,
    _invalidate_tx_names,
    _static_skip_condition,
)

#: scan-row normalization variants (serve/normalize.py variant_chain).
#: "squash_urldec" (5) exists because htmlEntityDecode can DELETE factor
#: bytes ("&#x61;" → "a" removes '#'): a ws-collapse+urlDecode rule whose
#: own chain has NO html transform must be scanned on squash(urldec), not
#: squash(html(urldec)) — the round-3 prefilter gate caught rule 942170
#: losing its '#' factor to the html decode of the scanned row.
VARIANTS = ("raw", "urldec", "urldec_html", "squash_raw", "squash_dec",
            "squash_urldec")
N_SV = len(STREAMS) * len(VARIANTS)  # stream-variant row space

#: the word-tier split (docs/SCAN_KERNEL.md): streams every request row
#: can carry vs the body/response streams only some requests produce.
#: Factors owned exclusively by tail-stream rules pack after
#: BitapTables.n_head_words so bodyless dispatches scan a word prefix.
HEAD_STREAMS = ("uri", "args", "headers")
N_HEAD_SV = len(HEAD_STREAMS) * len(VARIANTS)

#: default approximate-reduction config (compiler/reduce.py): modest
#: candidate-inflation budget, 16-byte factor windows, exact prefix
#: merging and word tiering on.  Pass ``reduction=ReductionConfig.off()``
#: for bit-identical legacy tables (the frozen bench fixture does).
DEFAULT_REDUCTION = ReductionConfig()

_DECODE_TRANSFORMS = {
    "urlDecode", "urlDecodeUni", "jsDecode", "cssDecode", "hexDecode",
    "base64Decode",
}
_HTML_TRANSFORMS = {"htmlEntityDecode"}
_WS_COLLAPSE = {"compressWhitespace", "removeWhitespace", "cmdLine"}
_PATH_TRANSFORMS = {"normalizePath", "normalisePath", "normalizePathWin"}
#: comment transforms rewrite text in ways no scan variant models
#: ("un/**/ion" → "un ion" resp. "union"): any factor extracted from the
#: post-transform pattern could miss the pre-transform bytes, so rules
#: carrying them compile always-confirm (sound; exact CPU evaluation)
_COMMENT_TRANSFORMS = {"replaceComments", "removeCommentsChar"}
#: decode transforms with NO scan-variant twin (the lanes only model
#: urlDecode(Uni) + htmlEntityDecode): the pattern matches DECODED text
#: but the scanned rows hold the encoded form — base64("expression(")
#: contains no "expression" — so factors from these rules can miss
#: every true match.  Always-confirm instead (rulecheck PR: the
#: lane.unmodeled-decode analyzer class pins this invariant).
_UNMODELED_DECODE_TRANSFORMS = {"base64Decode", "hexDecode", "jsDecode",
                                "cssDecode"}
_WS_BYTES = frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B])
# Bytes deleted by the squash variants (stream side AND factor side).
# Superset of what cmdLine deletes; whitespace covers compress/remove.
SQUASH_BYTES = _WS_BYTES | frozenset([0x5C, 0x27, 0x22, 0x5E])  # \ ' " ^
_PATH_SEP_BYTES = frozenset([0x2F, 0x5C])  # / and \\

SEVERITY_SCORE = {
    "CRITICAL": 5, "ERROR": 4, "WARNING": 3, "NOTICE": 2, "INFO": 1, "DEBUG": 1,
}

# ------------------------------------------------------- CRS anomaly mode
# Real CRS v3 blocks via tx.anomaly_score accumulation: crs-setup.conf's
# SecAction initializes the weights (tx.critical_anomaly_score=5, ...),
# each rule does setvar:'tx.anomaly_score_pl1=+%{tx.critical_anomaly_
# score}', and a 949-family rule blocks when TX:ANOMALY_SCORE >= the
# threshold.  We resolve that WHOLE protocol AT COMPILE TIME: setvar
# increments become the rule_score vector (anomaly accumulation is the
# engine's score matmul — nothing per-request), and the 949 rule becomes
# the pipeline's anomaly_threshold.  SURVEY.md §2.2 libmodsecurity row.

#: CRS-standard weights, used when no SecAction overrides them — a bare
#: CRS rules file without crs-setup.conf still scores canonically
_TX_DEFAULTS = {
    "critical_anomaly_score": "5",
    "error_anomaly_score": "4",
    "warning_anomaly_score": "3",
    "notice_anomaly_score": "2",
}

_MACRO_RE = re.compile(r"%\{([^}]+)\}")


def resolve_macros(text: str, env: Dict[str, str],
                   max_depth: int = 5) -> Optional[str]:
    """Expand %{tx.NAME} macros from the static TX env.  Returns None if
    any macro stays unresolved (caller abstains / keeps the raw text)."""
    for _ in range(max_depth):
        if "%{" not in text:
            return text

        unresolved = False

        def sub(m: "re.Match[str]") -> str:
            nonlocal unresolved
            name = m.group(1).strip().lower()
            if name.startswith("tx."):
                val = env.get(name[3:])
                if val is not None:
                    return val
            unresolved = True
            return m.group(0)

        new = _MACRO_RE.sub(sub, text)
        if unresolved:
            return None
        text = new
    return None  # cyclic definitions


def _apply_setvars(env: Dict[str, str], setvars: List[str]) -> None:
    """Fold setvar actions into the static env.  Form normalization is
    shared with the parse-time env (seclang._classify_setvar) so the
    two layers can never diverge; the compile env differs only in
    resolving full multi-hop %{tx.*} macros.  Deletes, increments and
    unresolvable macros INVALIDATE the entry — a stale literal would
    expand into confirm arguments ModSecurity evaluates differently."""
    for sv in setvars:
        key, kind, val = _classify_setvar(sv)
        if kind is None:
            continue
        if kind in ("delete", "increment"):
            env.pop(key, None)
            continue
        resolved = resolve_macros(val, env)
        if resolved is not None:
            env[key] = resolved
        else:
            env.pop(key, None)


def _anomaly_increment(rule: Rule, env: Dict[str, str]) -> Optional[int]:
    """The rule's anomaly-score contribution from its setvar actions
    ('tx.<x>anomaly_score<y>=+%{...}'), resolved statically; None when
    the rule doesn't participate in anomaly scoring."""
    for sv in rule.setvars:
        name, sep, val = sv.partition("=")
        if not sep or "anomaly_score" not in name.lower():
            continue
        val = val.strip()
        if not val.startswith("+"):
            continue
        resolved = resolve_macros(val[1:].strip(), env)
        if resolved is None:
            continue
        m = re.match(r"\s*(\d+)", resolved)
        if m:
            return int(m.group(1))
    return None


#: TX selectors that ARE the inbound request-blocking score.  Outbound
#: (959-style response evaluation) and per-PL sub-score rules
#: (TX:ANOMALY_SCORE_PL1) must NOT set the pipeline's request threshold —
#: on real CRS the outbound threshold (4) sorts after 949110's inbound
#: (5) and a last-wins match would silently lower the blocking bar
#: (round-3 review finding).
_INBOUND_SCORE_SELECTORS = {
    "ANOMALY_SCORE", "INBOUND_ANOMALY_SCORE",
    "BLOCKING_INBOUND_ANOMALY_SCORE",
}


def _threshold_from_rule(rule: Rule, env: Dict[str, str]) -> Optional[int]:
    """Detect the 949-style blocking rule: TX:ANOMALY_SCORE '@ge N'
    (N possibly a %{tx.*} macro).  Returns the resolved threshold."""
    if rule.operator not in ("ge", "gt"):
        return None
    def _is_inbound(t: str) -> bool:
        base, _, sel = t.partition(":")
        return (base.strip().upper() == "TX"
                and sel.strip().upper() in _INBOUND_SCORE_SELECTORS)
    if not any(_is_inbound(t) for t in rule.raw_targets):
        return None
    resolved = resolve_macros(rule.argument.strip(), env)
    if resolved is None:
        return None
    m = re.match(r"\s*(\d+)", resolved)
    if not m:
        return None
    n = int(m.group(1))
    return n + 1 if rule.operator == "gt" else n

# NOTE on operator coverage: the per-operator branches in
# _factor_group_for decide which operators contribute prefilter factors
# (rx/pm/contains/... families).  Rules with any OTHER operator (@eq,
# @validateByteRange, ... — the CRS 920 protocol family) and negated
# operators are NOT dropped: they compile with an empty factor group, so
# the rule_nfactors==0 always-confirm path evaluates them exactly on CPU
# (models/confirm.py) for every applicable request.

# Heuristic trigger factors for the strict-grammar detectors (libdetection
# analog).  These gate the CPU confirm stage; soundness vs our own
# models/libdetect implementation is asserted by tests/test_libdetect.py.
_SQLI_TRIGGERS = [
    "'", '"', "`", "--", "/*", "#", ";", "=", "union", "select", "sleep(",
    "benchmark(", "0x", "||", "char(",
]
_XSS_TRIGGERS = ["<", ">", "javascript:", "on", "&#", "src=", "%3c", "%3e"]


def _lit_seq(text: str, fold: bool) -> F.ClassSeq:
    seq = []
    for ch in text.encode("utf-8", "surrogateescape"):
        s = frozenset([ch])
        if fold:
            if 0x41 <= ch <= 0x5A:
                s = frozenset([ch, ch + 0x20])
            elif 0x61 <= ch <= 0x7A:
                s = frozenset([ch, ch - 0x20])
        seq.append(s)
    return tuple(seq)


def _squash_group(group: F.Group) -> F.Group:
    """Rewrite factors for the squash variants: positions whose class is
    entirely deletable vanish (neighbors join, as in the squashed stream);
    ambiguous positions (class partially deletable) split the factor; per
    alternative the best fragment is kept (still mandatory)."""
    out: F.Group = []
    for seq in group:
        frags: List[List[frozenset]] = [[]]
        for cls in seq:
            if cls <= SQUASH_BYTES:
                continue  # deleted on both sides — neighbors become adjacent
            if cls & SQUASH_BYTES:
                frags.append([])  # ambiguous survival → split
            else:
                frags[-1].append(cls)
        best = max(frags, key=lambda f: F.seq_bits(tuple(f)))
        if not best:
            return []  # an alternative squashes away entirely → unusable
        out.append(tuple(best))
    return out


def _split_at(group: F.Group, split_bytes: frozenset) -> F.Group:
    """Split factors at positions that may contain ``split_bytes`` and keep
    the best fragment per alternative (used for normalizePath rules, whose
    deletions always contain a path separator)."""
    out: F.Group = []
    for seq in group:
        frags: List[List[frozenset]] = [[]]
        for cls in seq:
            if cls & split_bytes:
                frags.append([])
            else:
                frags[-1].append(cls)
        best = max(frags, key=lambda f: F.seq_bits(tuple(f)))
        if not best:
            return []
        out.append(tuple(best))
    return out


@dataclass
class RuleMeta:
    """Per-rule compile result (everything the runtime needs off-device)."""

    rule: Rule
    index: int
    variant: int
    has_prefilter: bool
    confirm: Dict  # JSON-serializable confirm descriptor


@dataclass
class CompiledRuleset:
    """Device tables + metadata; the deployable/hot-swappable artifact."""

    tables: BitapTables
    rules: List[RuleMeta]
    # (n_rules, N_SV) bool — which stream-variant rows count for each rule
    rule_sv_mask: np.ndarray
    rule_class: np.ndarray      # (n_rules,) int32 → CLASSES
    rule_score: np.ndarray      # (n_rules,) int32 anomaly score
    rule_action: np.ndarray     # (n_rules,) int32 0=pass 1=block 2=deny
    rule_paranoia: np.ndarray   # (n_rules,) int32
    rule_ids: np.ndarray        # (n_rules,) int64 CRS ids
    version: str = ""
    #: CRS anomaly-mode config resolved at compile time from SecAction
    #: setvars + the 949-style threshold rule (None = pack doesn't use
    #: anomaly mode; the pipeline then keeps its default threshold)
    anomaly_threshold: Optional[int] = None
    paranoia_hint: Optional[int] = None
    #: runtime ctl exclusions (the CRS exclusion-package shape), resolved
    #: to concrete rule ids at compile time: carrying rule INDEX →
    #: {"remove_ids": [id, ...],              # ctl:ruleRemoveById/ByTag
    #:  "target_excl": {str(id): [tok, ...]}, # ctl:ruleRemoveTargetById
    #:  "engine_off": bool}                   # ctl:ruleEngine=Off
    #: Applied per request by the confirm stage when the carrying rule
    #: matches (models/pipeline.py finalize).
    ctl_specs: Dict[int, Dict] = field(default_factory=dict)
    #: approximate-reduction provenance (compiler/reduce.py
    #: ReductionReport.to_dict(), None = exact compile) — serialized
    #: with the artifact and surfaced by rulecheck's JSON report
    reduction: Optional[Dict] = None

    @property
    def n_rules(self) -> int:
        return len(self.rules)

    @property
    def classes(self) -> Tuple[str, ...]:
        return tuple(CLASSES)

    def fingerprint(self) -> str:
        h = hashlib.sha256()
        for a in (self.tables.byte_table, self.tables.init_mask,
                  self.tables.final_mask, self.rule_sv_mask):
            h.update(np.ascontiguousarray(a).tobytes())
        # confirm descriptors and ctl exclusions change detection
        # behavior WITHOUT touching any scan table (SecRuleUpdateTargetById
        # edits, ctl:ruleRemoveById swaps...) — a fingerprint blind to
        # them made the RulesetWatcher skip hot-swapping exclusion-only
        # changes (round-3 review finding)
        h.update(json.dumps(
            [m.confirm for m in self.rules], sort_keys=True).encode())
        h.update(json.dumps(
            {str(k): v for k, v in self.ctl_specs.items()},
            sort_keys=True).encode())
        return h.hexdigest()[:16]

    # ---------------------------------------------------------- serialize

    def save(self, path: str | Path) -> None:
        """Write the checkpoint artifact: <path>.npz + <path>.json."""
        path = Path(path)
        t = self.tables
        np.savez_compressed(
            path.with_suffix(".npz"),
            byte_table=t.byte_table, init_mask=t.init_mask,
            final_mask=t.final_mask, factor_word=t.factor_word,
            factor_bit=t.factor_bit, factor_rule_indptr=t.factor_rule_indptr,
            factor_rule_ids=t.factor_rule_ids, rule_nfactors=t.rule_nfactors,
            factor_len=t.factor_len, rule_sv_mask=self.rule_sv_mask,
            rule_class=self.rule_class, rule_score=self.rule_score,
            rule_action=self.rule_action, rule_paranoia=self.rule_paranoia,
            rule_ids=self.rule_ids,
            n_head_words=np.asarray(t.n_head_words, np.int32),
            n_prefix_shared=np.asarray(t.n_prefix_shared, np.int32),
        )
        meta = {
            "version": self.version or self.fingerprint(),
            "n_rules": self.n_rules,
            "classes": CLASSES,
            "streams": STREAMS,
            "variants": VARIANTS,
            "confirm": [m.confirm for m in self.rules],
            # tags drive tenant (EP) rule-subset masks — must survive the
            # checkpoint roundtrip (control/tenants.py)
            "tags": [list(m.rule.tags) for m in self.rules],
            "anomaly_threshold": self.anomaly_threshold,
            "paranoia_hint": self.paranoia_hint,
            "ctl_specs": {str(k): v for k, v in self.ctl_specs.items()},
            "reduction": self.reduction,
        }
        path.with_suffix(".json").write_text(json.dumps(meta))

    @classmethod
    def load(cls, path: str | Path) -> "CompiledRuleset":
        path = Path(path)
        z = np.load(path.with_suffix(".npz"))
        meta = json.loads(path.with_suffix(".json").read_text())
        tables = BitapTables(
            byte_table=z["byte_table"], init_mask=z["init_mask"],
            final_mask=z["final_mask"], factor_word=z["factor_word"],
            factor_bit=z["factor_bit"],
            factor_rule_indptr=z["factor_rule_indptr"],
            factor_rule_ids=z["factor_rule_ids"],
            rule_nfactors=z["rule_nfactors"], factor_len=z["factor_len"],
            # pre-interning checkpoints carry no tier boundary: the full
            # width (post_init default) keeps them loadable unchanged
            n_head_words=(int(z["n_head_words"])
                          if "n_head_words" in z.files else -1),
            n_prefix_shared=(int(z["n_prefix_shared"])
                             if "n_prefix_shared" in z.files else 0),
        )
        rules = []
        action_names = {0: "pass", 1: "block", 2: "deny"}
        all_tags = meta.get("tags", [[]] * len(meta["confirm"]))
        for i, confirm in enumerate(meta["confirm"]):
            rule = Rule(
                rule_id=int(z["rule_ids"][i]),
                operator=confirm["op"],
                argument=confirm.get("arg", ""),
                targets=list(confirm.get("targets", ["args"])),
                raw_targets=list(confirm.get("raw_targets", [])),
                transforms=confirm.get("transforms", []),
                action=action_names[int(z["rule_action"][i])],
                tags=list(all_tags[i]),
            )
            rules.append(RuleMeta(rule=rule, index=i,
                                  variant=confirm.get("variant", 0),
                                  has_prefilter=bool(tables.rule_nfactors[i]),
                                  confirm=confirm))
        return cls(
            tables=tables, rules=rules, rule_sv_mask=z["rule_sv_mask"],
            rule_class=z["rule_class"], rule_score=z["rule_score"],
            rule_action=z["rule_action"], rule_paranoia=z["rule_paranoia"],
            rule_ids=z["rule_ids"], version=meta["version"],
            anomaly_threshold=meta.get("anomaly_threshold"),
            paranoia_hint=meta.get("paranoia_hint"),
            ctl_specs={int(k): v
                       for k, v in meta.get("ctl_specs", {}).items()},
            reduction=meta.get("reduction"),
        )


def _rule_variant(rule: Rule) -> int:
    t = set(rule.transforms)
    if t & _WS_COLLAPSE:
        if t & _HTML_TRANSFORMS:
            return 4          # squash(html(urldec))
        if t & _DECODE_TRANSFORMS:
            return 5          # squash(urldec) — html decode would be
                              # UNSOUND here (can delete factor bytes
                              # the rule's own chain keeps)
        return 3              # squash(raw)
    if t & _HTML_TRANSFORMS:
        return 2
    if t & _DECODE_TRANSFORMS:
        return 1
    return 0


def _factor_group_for(rule: Rule) -> Tuple[F.Group, Dict]:
    """Extract the rule's factor group + confirm descriptor."""
    fold = "lowercase" in rule.transforms or rule.operator in ("pm", "pmFromFile", "pmf")
    op = rule.operator
    confirm: Dict = {
        "op": op, "arg": rule.argument, "transforms": rule.transforms,
        "fold": fold, "variant": _rule_variant(rule),
    }
    if op == "rx":
        try:
            ast = parse_regex(rule.argument, ignorecase=fold)
            group = F.best_factor_group(ast) or []
        except RegexUnsupported as e:
            confirm["regex_unsupported"] = str(e)
            group = []
    elif op in ("pm", "pmf", "pmFromFile"):
        # phrases (one per line, from @pmFromFile) or whitespace words
        words = (rule.argument.split("\n") if "\n" in rule.argument
                 else rule.argument.split())
        words = [w for w in (w.strip() for w in words) if w]
        confirm["words"] = words
        group = [F.best_window(_lit_seq(w, fold=True)) for w in words]
    elif op in ("contains", "containsWord", "streq", "beginsWith",
                "endsWith"):
        group = [F.best_window(_lit_seq(rule.argument, fold))]
    # @within is NOT in the literal family: it inverts containment (the
    # VARIABLE must occur inside the argument), so a short variable
    # value matches without the stream ever containing the full
    # argument — a factor over the argument text would silently kill
    # the rule (rulecheck PR: found statically by the prefilter audit's
    # certification pass).  Confirm-only.
    elif op == "detectSQLi":
        group = [F.best_window(_lit_seq(w, True)) for w in _SQLI_TRIGGERS]
    elif op == "detectXSS":
        group = [F.best_window(_lit_seq(w, True)) for w in _XSS_TRIGGERS]
    else:
        group = []

    if rule.negate:
        # inverted match: absence of a pattern has no scannable factors —
        # always-confirm, evaluated exactly (and inverted) on CPU.  The
        # op-specific confirm fields (words etc.) above are still needed:
        # the confirm stage evaluates the op, THEN inverts.
        confirm["negate"] = True
        return [], confirm

    # Targets whose text never appears in a scanned stream (the HTTP
    # status/protocol/method scalars): a prefilter factor could never
    # fire there, silently killing the rule — always-confirm instead
    # (round-3 review: RESPONSE_STATUS "@rx ^5\d\d$" compiled a dead
    # prefilter against resp_headers bytes).
    if rule.raw_targets:
        bases = {t.strip().lstrip("&!").split(":", 1)[0].upper()
                 for t in rule.raw_targets if t.strip()}
        # ANY non-scanned target makes the rule always-confirm, not just
        # all-non-scanned (round-4 review): a mixed REQUEST_URI|
        # REMOTE_ADDR rule with a scanned-side prefilter would silently
        # drop the REMOTE_ADDR leg whenever the uri bytes miss — a
        # prefilter may only gate targets whose text it can actually see
        if bases and bases & F_NON_SCANNED:
            return [], confirm

    # Soundness fix-ups for destructive transforms (see module docstring).
    t = set(rule.transforms)
    if t & _COMMENT_TRANSFORMS:
        return [], confirm
    if t & _UNMODELED_DECODE_TRANSFORMS:
        return [], confirm
    if t & _PATH_TRANSFORMS and group:
        group = _split_at(group, _PATH_SEP_BYTES)
    if t & _WS_COLLAPSE and group:
        group = _squash_group(group)

    # Discard degenerate groups: an empty alternative fires everywhere, and
    # a group whose weakest alternative carries <2 bits of information
    # (e.g. a single near-full byte class) fires on ~all traffic — worse
    # than honestly marking the rule always-confirm.
    group = [s for s in group if len(s) > 0]
    if group and min(F.seq_bits(s) for s in group) < 2.0:
        group = []
    return group, confirm


def compile_ruleset(
    rules: Sequence[Rule],
    base_path: Optional[str | Path] = None,
    include_chains: bool = True,
    reduction: Optional[ReductionConfig] = None,
) -> CompiledRuleset:
    """Compile rules → CompiledRuleset.

    ``reduction`` configures the pack-size-invariance passes
    (compiler/reduce.py + bitap prefix merging; docs/SCAN_KERNEL.md):
    None = DEFAULT_REDUCTION (budgeted approximate reduction ON — the
    prefilter may over-trigger within the candidate-inflation budget,
    verdicts are unchanged because the exact confirm lane decides);
    ``ReductionConfig.off()`` = bit-identical legacy tables.

    Chained rules contribute the FIRST scannable link's factors (a chain hit
    requires every link; prefiltering on one link is sound); the confirm
    descriptor carries all links for exact AND evaluation.

    ``base_path`` is accepted for compatibility but unused: @pmFromFile is
    resolved at SecLang parse time (seclang.parse_seclang).

    EVERY rule compiles — non-scan operators (@eq, @validateByteRange,
    ...) and negated operators get an empty factor group and ride the
    always-confirm path; nothing is silently dropped (a dropped CRS 920
    rule would be a silent protocol-check hole).

    CRS anomaly mode resolves statically (see the "CRS anomaly mode"
    block above): SecAction config rules fold into a TX env and are
    dropped from the pack; per-rule setvar increments become
    rule_score; the 949-style rule becomes ``anomaly_threshold``;
    resolvable %{tx.*} macros in operator arguments are expanded so the
    confirm stage sees literal values.
    """
    # ---- pass 0: static TX environment + config-rule partition.
    # Mirrors the parser's conditional-setvar semantics (seclang.py):
    # a SecRule whose condition resolves statically TRUE folds like a
    # SecAction, FALSE never fires, and a request-dependent condition
    # INVALIDATES its written names — review finding: folding only
    # SecActions left this env disagreeing with the parse-time env on
    # the same tree (unresolved thresholds, stale macro expansions).
    env: Dict[str, str] = dict(_TX_DEFAULTS)
    scannable = []
    anomaly_threshold: Optional[int] = None
    for rule in rules:
        if (rule.operator == "unconditionalMatch" and not rule.raw_targets
                and rule.setvars):
            _apply_setvars(env, rule.setvars)   # SecAction config rule
            continue
        scannable.append(rule)
        sv_chain = list(rule.setvars)
        if rule.chain is not None:
            verdict = None          # conjunction: never static here
            link: Optional[Rule] = rule.chain
            while link is not None:
                sv_chain.extend(link.setvars)
                link = link.chain
        elif sv_chain:
            verdict = _static_skip_condition(
                "|".join(rule.raw_targets), rule.negate, rule.operator,
                rule.argument, env)
        if sv_chain:
            if verdict is True:
                _apply_setvars(env, sv_chain)
            elif verdict is None:
                _invalidate_tx_names(env, sv_chain)
            # statically FALSE: the rule never fires — env untouched
    if "detection_paranoia_level" in env or "paranoia_level" in env:
        try:
            paranoia_hint: Optional[int] = int(
                env.get("detection_paranoia_level",
                        env.get("paranoia_level", "1")))
        except ValueError:
            paranoia_hint = None
    else:
        paranoia_hint = None
    thr = env.get("inbound_anomaly_score_threshold")
    if thr is not None and re.match(r"\s*\d+", thr):
        anomaly_threshold = int(re.match(r"\s*(\d+)", thr).group(1))
    for rule in scannable:
        t = _threshold_from_rule(rule, env)
        if t is not None:
            anomaly_threshold = t
        links = rule.chain
        while links is not None:
            t = _threshold_from_rule(links, env)
            if t is not None:
                anomaly_threshold = t
            links = links.chain
        # expand resolvable %{tx.*} macros in operator arguments so the
        # confirm stage evaluates literals instead of abstaining
        link: Optional[Rule] = rule
        while link is not None:
            if "%{" in link.argument:
                resolved = resolve_macros(link.argument, env)
                if resolved is not None:
                    link.argument = resolved
            link = link.chain

    metas: List[RuleMeta] = []
    groups: List[F.Group] = []
    sv_mask = np.zeros((len(scannable), N_SV), dtype=bool)
    rule_class = np.zeros((len(scannable),), dtype=np.int32)
    rule_score = np.zeros((len(scannable),), dtype=np.int32)
    rule_action = np.zeros((len(scannable),), dtype=np.int32)
    rule_paranoia = np.ones((len(scannable),), dtype=np.int32)
    rule_ids = np.zeros((len(scannable),), dtype=np.int64)

    for i, rule in enumerate(scannable):
        group, confirm = _factor_group_for(rule)
        if include_chains and rule.chain is not None:
            links = []
            link: Optional[Rule] = rule.chain
            while link is not None:
                _, link_confirm = _factor_group_for(link)
                link_confirm["targets"] = link.targets
                link_confirm["raw_targets"] = link.raw_targets
                links.append(link_confirm)
                link = link.chain
            confirm["chain"] = links
        confirm["targets"] = rule.targets
        confirm["raw_targets"] = rule.raw_targets
        variant = confirm["variant"]

        groups.append(group)
        metas.append(RuleMeta(rule=rule, index=i, variant=variant,
                              has_prefilter=bool(group), confirm=confirm))
        for stream in rule.targets:
            sv = STREAM_INDEX[stream] * len(VARIANTS) + variant
            sv_mask[i, sv] = True
        rule_class[i] = CLASS_INDEX[rule.attack_class]
        inc = _anomaly_increment(rule, env)
        if inc is None and rule.chain is not None:
            # CRS puts the setvar on the LAST chain link sometimes
            link = rule.chain
            while link is not None and inc is None:
                inc = _anomaly_increment(link, env)
                link = link.chain
        rule_score[i] = (inc if inc is not None
                         else SEVERITY_SCORE.get(rule.severity.upper(), 3))
        rule_action[i] = {"pass": 0, "block": 1, "deny": 2}[rule.action]
        rule_paranoia[i] = rule.paranoia
        rule_ids[i] = rule.rule_id

    cfg = DEFAULT_REDUCTION if reduction is None else reduction
    report = None
    # profile-priced compile (ISSUE 15, docs/RETUNE.md): measured traffic
    # re-weights the budget math and pins hot rules' factors; everything
    # stays strictly over-approximating — the profile is pricing only
    prof = cfg.profile if cfg.approximate else None
    rule_w = hot_mask = None
    if prof is not None:
        rule_w = prof.rule_weights(rule_ids)
        hot_ids = prof.hot_rule_ids(cfg.hot_frac)
        hot_mask = np.asarray([int(r) in hot_ids for r in rule_ids],
                              dtype=bool)
    if cfg.approximate:
        groups, rep = reduce_rule_groups(groups, cfg, rule_weights=rule_w,
                                         hot_rules=hot_mask)
        report = rep
        if prof is not None:
            report.profile_hash = prof.content_hash()
    if prof is not None and cfg.qr_relax_top > 0:
        # rules the profile ranks most expensive to confirm get relaxed
        # quick-reject literal derivation (models/confirm.py qr_relax:
        # shorter mandatory literals are still sound — absence of a
        # mandatory literal disproves a match at any length).  The flag
        # rides the confirm descriptor, so it is fingerprint-covered.
        relax_ids = set(prof.top_expensive_confirms(cfg.qr_relax_top))
        n_relaxed = 0
        for i, m in enumerate(metas):
            if int(rule_ids[i]) in relax_ids:
                m.confirm["qr_relax"] = 1
                n_relaxed += 1
        if report is not None:
            report.qr_relaxed = n_relaxed
    rule_tier = None
    if cfg.word_tiering:
        # tail tier: rules whose every scanned stream is body/response —
        # their factors pack after n_head_words so a dispatch carrying
        # only uri/args/headers rows can slice the word axis
        tail_streams = set(STREAMS) - set(HEAD_STREAMS)
        rule_tier = np.asarray(
            [1 if (r.targets and set(r.targets) <= tail_streams) else 0
             for r in scannable], dtype=np.int32)
    tables = pack_factors(groups, n_rules=len(scannable),
                          prefix_merge=cfg.prefix_merge,
                          rule_tier=rule_tier)
    if cfg.approximate and cfg.class_merge and report is not None:
        owners = np.diff(tables.factor_rule_indptr).astype(np.int64)
        bt, n_merges, k_in, k_out, cspent = coarsen_byte_classes(
            tables.byte_table, tables.factor_word, tables.factor_bit,
            tables.factor_len, owners,
            budget_frac=max(0.0, cfg.budget - report.spent),
            merge_cap=cfg.class_merge_cap,
            mu=prof.byte_mu() if prof is not None else None)
        tables.byte_table = bt
        report.class_merges = n_merges
        report.classes_in = k_in
        report.classes_out = k_out
        report.spent += cspent
    if report is not None:
        report.prefix_shared = tables.n_prefix_shared
        report.spent = round(report.spent, 5)
    ctl_specs = _resolve_ctls(scannable, rule_ids)
    cr = CompiledRuleset(
        tables=tables, rules=metas, rule_sv_mask=sv_mask,
        rule_class=rule_class, rule_score=rule_score,
        rule_action=rule_action, rule_paranoia=rule_paranoia,
        rule_ids=rule_ids, anomaly_threshold=anomaly_threshold,
        paranoia_hint=paranoia_hint, ctl_specs=ctl_specs,
        reduction=report.to_dict() if report is not None else None,
    )
    cr.version = cr.fingerprint()
    return cr


def _resolve_ctls(scannable: List[Rule],
                  rule_ids: np.ndarray) -> Dict[int, Dict]:
    """Resolve each rule's ctl actions against the finished pack.

    Id specs (single ids, "lo-hi" ranges) become the concrete rule ids
    present in THIS pack, so the runtime applies plain masks with zero
    parsing; tag/msg-based variants resolve their regex the same way.
    Handled: ruleRemoveById/ByTag/ByMsg, ruleRemoveTargetById/ByTag/
    ByMsg, ruleEngine=Off|DetectionOnly.  Other ctl keys (auditEngine,
    requestBodyProcessor, ...) control ModSecurity plumbing we don't
    model and are ignored — but EVERY ctl-carrying rule still gets a
    spec entry (possibly empty), so the pipeline always knows it is
    config machinery and never reports it as a detection hit."""
    specs: Dict[int, Dict] = {}
    all_ids = [int(r) for r in rule_ids]

    def _ids_for_pattern(val: str, field: str):
        try:
            pat = re.compile(val)
        except re.error:
            return []
        out = []
        for j, r in enumerate(scannable):
            hay = r.tags if field == "tags" else [r.msg]
            if any(pat.search(t) for t in hay):
                out.append(all_ids[j])
        return out

    for i, rule in enumerate(scannable):
        remove: set = set()
        target_excl: Dict[str, List[str]] = {}
        engine = None            # None | "off" | "detection_only"
        ctls = list(rule.ctls)
        link = rule.chain
        while link is not None:           # ctl may sit on a chain link
            ctls.extend(link.ctls)
            link = link.chain

        def _add_target_excl(rids, target: str) -> None:
            for rid in rids:
                target_excl.setdefault(str(rid), [])
                if target not in target_excl[str(rid)]:
                    target_excl[str(rid)].append(target)

        for c in ctls:
            key, _, val = c.partition("=")
            key, val = key.strip(), val.strip()
            if key == "ruleEngine":
                if val.lower() == "off":
                    engine = "off"
                elif val.lower() == "detectiononly" and engine != "off":
                    # monitoring for this request: detect + log, never
                    # block (ModSecurity's DetectionOnly transaction
                    # semantics — round-3 review: silently ignoring it
                    # over-blocked where ModSecurity would pass)
                    engine = "detection_only"
            elif key == "ruleRemoveById":
                match = _id_matcher([val])
                remove.update(rid for rid in all_ids if match(rid))
            elif key == "ruleRemoveByTag":
                remove.update(_ids_for_pattern(val, "tags"))
            elif key == "ruleRemoveByMsg":
                remove.update(_ids_for_pattern(val, "msg"))
            elif key in ("ruleRemoveTargetById", "ruleRemoveTargetByTag",
                         "ruleRemoveTargetByMsg"):
                spec_txt, _, target = val.partition(";")
                target = target.strip()
                if not target:
                    continue
                if key == "ruleRemoveTargetById":
                    match = _id_matcher([spec_txt])
                    rids = [rid for rid in all_ids if match(rid)]
                else:
                    rids = _ids_for_pattern(
                        spec_txt.strip(),
                        "tags" if key.endswith("ByTag") else "msg")
                _add_target_excl(rids, target)
        if ctls:
            specs[i] = {"remove_ids": sorted(remove),
                        "target_excl": target_excl,
                        "engine": engine,
                        # legacy key, kept for checkpoints written by
                        # earlier builds that read/wrote a bool
                        "engine_off": engine == "off"}
    return specs
