"""PCRE-subset regex parser → AST over byte classes.

The reference's detection engines consume PCRE (libmodsecurity/CRS `@rx`) and
proprietary signature syntax (libproton; closed source — SURVEY.md §2.2).  We
parse the PCRE subset the CRS-shaped corpus uses into an AST of byte-level
character classes, from which factors.py extracts mandatory factors for the
TPU bitap prefilter.  Constructs an NFA cannot express (backreferences,
lookaround) raise ``RegexUnsupported`` — those rules still run, prefiltered
by whatever factors are extractable and confirmed exactly on CPU.

Supported: literals, escapes (incl. \\xHH, \\d\\D\\w\\W\\s\\S), classes with
ranges/negation/POSIX names, ``.``, alternation, groups ``(?:...)``/named/
capturing, inline flags ``(?i)``/``(?s)``/``(?m)`` (set-only), quantifiers
``* + ? {m} {m,} {m,n}`` with lazy/possessive suffixes, anchors ``^ $ \\b
\\B \\A \\z \\Z``, ``\\Q...\\E`` quoting.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

ALL_BYTES = frozenset(range(256))
DOT_NO_NL = frozenset(b for b in range(256) if b != 0x0A)

_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B)) + list(range(0x61, 0x7B)) + [0x5F]
)
_SPACE = frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B])

_POSIX = {
    "alpha": frozenset(list(range(0x41, 0x5B)) + list(range(0x61, 0x7B))),
    "digit": _DIGIT,
    "alnum": frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x5B)) + list(range(0x61, 0x7B))),
    "upper": frozenset(range(0x41, 0x5B)),
    "lower": frozenset(range(0x61, 0x7B)),
    "space": _SPACE,
    "blank": frozenset([0x20, 0x09]),
    "punct": frozenset(b for b in range(0x21, 0x7F) if not (chr(b).isalnum())),
    "xdigit": frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x47)) + list(range(0x61, 0x67))),
    "cntrl": frozenset(list(range(0x00, 0x20)) + [0x7F]),
    "print": frozenset(range(0x20, 0x7F)),
    "graph": frozenset(range(0x21, 0x7F)),
    "word": _WORD,
}


class RegexUnsupported(Exception):
    """Raised for constructs outside the NFA-expressible subset."""


# ---------------------------------------------------------------- AST nodes


@dataclass(frozen=True)
class Lit:
    """One position matching any byte in ``chars``."""

    chars: frozenset

    def __repr__(self) -> str:  # compact for debugging
        if len(self.chars) == 256:
            return "Lit(ANY)"
        if len(self.chars) <= 4:
            return "Lit(%s)" % "".join(chr(c) if 0x20 <= c < 0x7F else "\\x%02x" % c for c in sorted(self.chars))
        return "Lit(<%d bytes>)" % len(self.chars)


@dataclass(frozen=True)
class Concat:
    parts: Tuple


@dataclass(frozen=True)
class Alt:
    options: Tuple


@dataclass(frozen=True)
class Repeat:
    node: object
    min: int
    max: Optional[int]  # None = unbounded


@dataclass(frozen=True)
class Anchor:
    kind: str  # '^' '$' 'b' 'B'


@dataclass
class _Flags:
    ignorecase: bool = False
    dotall: bool = False
    multiline: bool = False

    def copy(self) -> "_Flags":
        return _Flags(self.ignorecase, self.dotall, self.multiline)


def _fold_case(chars: frozenset) -> frozenset:
    out = set(chars)
    for b in chars:
        if 0x41 <= b <= 0x5A:
            out.add(b + 0x20)
        elif 0x61 <= b <= 0x7A:
            out.add(b - 0x20)
    return frozenset(out)


# ---------------------------------------------------------------- parser


class _Parser:
    def __init__(self, pattern: str):
        self.pat = pattern
        self.i = 0
        self.n = len(pattern)
        self._pending_sets: set = set()

    def error(self, msg: str) -> RegexUnsupported:
        return RegexUnsupported("%s at %d in %r" % (msg, self.i, self.pat))

    def peek(self) -> str:
        return self.pat[self.i] if self.i < self.n else ""

    def next(self) -> str:
        if self.i >= self.n:
            raise self.error("unexpected end of pattern")
        c = self.pat[self.i]
        self.i += 1
        return c

    def eat(self, c: str) -> None:
        if self.peek() != c:
            raise self.error("expected %r" % c)
        self.i += 1

    # alternation level
    def parse_alt(self, flags: _Flags):
        options = [self.parse_concat(flags)]
        while self.peek() == "|":
            self.next()
            options.append(self.parse_concat(flags))
        if len(options) == 1:
            return options[0]
        return Alt(tuple(options))

    def parse_concat(self, flags: _Flags):
        parts = []
        while self.i < self.n and self.peek() not in "|)":
            item = self.parse_quantified(flags)
            if item is not None:
                parts.append(item)
        if not parts:
            return Concat(())
        if len(parts) == 1:
            return parts[0]
        return Concat(tuple(parts))

    def parse_quantified(self, flags: _Flags):
        atom = self.parse_atom(flags)
        if atom is None:
            return None
        while True:
            c = self.peek()
            if c == "*":
                self.next()
                atom = Repeat(atom, 0, None)
            elif c == "+":
                self.next()
                atom = Repeat(atom, 1, None)
            elif c == "?":
                self.next()
                atom = Repeat(atom, 0, 1)
            elif c == "{":
                save = self.i
                rep = self._try_brace()
                if rep is None:
                    self.i = save
                    return atom
                lo, hi = rep
                atom = Repeat(atom, lo, hi)
            else:
                return atom
            # lazy / possessive suffix — same matched language
            if self.peek() and self.peek() in "?+":
                self.next()

    def _try_brace(self) -> Optional[Tuple[int, Optional[int]]]:
        # at '{'; returns (min, max|None) or None if not a quantifier
        assert self.next() == "{"
        start = self.i
        while self.i < self.n and self.pat[self.i].isdigit():
            self.i += 1
        if self.i == start and self.peek() != ",":
            return None
        lo = int(self.pat[start : self.i]) if self.i > start else 0
        if self.peek() == "}":
            self.next()
            return (lo, lo)
        if self.peek() != ",":
            return None
        self.next()
        start = self.i
        while self.i < self.n and self.pat[self.i].isdigit():
            self.i += 1
        hi = int(self.pat[start : self.i]) if self.i > start else None
        if self.peek() != "}":
            return None
        self.next()
        return (lo, hi)

    def parse_atom(self, flags: _Flags):
        c = self.peek()
        if c == "(":
            return self.parse_group(flags)
        if c == "[":
            return Lit(self.parse_class(flags))
        if c == ".":
            self.next()
            return Lit(ALL_BYTES if flags.dotall else DOT_NO_NL)
        if c == "^":
            self.next()
            return Anchor("^")
        if c == "$":
            self.next()
            return Anchor("$")
        if c == "\\":
            return self.parse_escape(flags)
        if c in "*+?{":
            if c == "{":  # literal brace when not a quantifier
                self.next()
                return Lit(self._single(ord("{"), flags))
            raise self.error("dangling quantifier")
        self.next()
        return Lit(self._single(ord(c), flags))

    def _single(self, b: int, flags: _Flags) -> frozenset:
        s = frozenset([b])
        return _fold_case(s) if flags.ignorecase else s

    def parse_group(self, flags: _Flags):
        self.eat("(")
        inner_flags = flags.copy()
        if self.peek() == "?":
            self.next()
            c = self.peek()
            if c == ":":
                self.next()
            elif c in "=!":
                raise self.error("lookahead unsupported")
            elif c == "<":
                self.next()
                if self.peek() in "=!":
                    raise self.error("lookbehind unsupported")
                # named group (?<name>...)
                while self.peek() not in (">", ""):
                    self.next()
                self.eat(">")
            elif c == "P":
                self.next()
                if self.peek() == "<":
                    self.next()
                    while self.peek() not in (">", ""):
                        self.next()
                    self.eat(">")
                else:
                    raise self.error("(?P subgroup reference unsupported")
            elif c == ">":  # atomic group — same language
                self.next()
            elif c in "imsx-":
                on = True
                while self.peek() and self.peek() in "imsx-":
                    f = self.next()
                    if f == "-":
                        on = False
                    elif f == "i":
                        inner_flags.ignorecase = on
                    elif f == "s":
                        inner_flags.dotall = on
                    elif f == "m":
                        inner_flags.multiline = on
                    # 'x' extended mode unsupported inside; tolerate set
                if self.peek() == ")":
                    self.next()
                    # flags-to-end-of-enclosing-group: mutate caller's flags
                    flags.ignorecase = inner_flags.ignorecase
                    flags.dotall = inner_flags.dotall
                    flags.multiline = inner_flags.multiline
                    return None
                self.eat(":")
            else:
                raise self.error("unsupported group (?%s" % c)
        node = self.parse_alt(inner_flags)
        self.eat(")")
        return node

    def parse_escape(self, flags: _Flags):
        self.eat("\\")
        if self.i >= self.n:
            raise self.error("trailing backslash")
        c = self.next()
        if c.isdigit() and c != "0":
            raise self.error("backreference \\%s unsupported" % c)
        simple = {
            "n": 0x0A, "r": 0x0D, "t": 0x09, "f": 0x0C, "v": 0x0B,
            "a": 0x07, "e": 0x1B, "0": 0x00,
        }
        if c in simple:
            return Lit(frozenset([simple[c]]))
        if c == "x":
            h = self.pat[self.i : self.i + 2]
            if len(h) == 2 and all(x in "0123456789abcdefABCDEF" for x in h):
                self.i += 2
                return Lit(self._single(int(h, 16), flags))
            raise self.error("bad \\x escape")
        if c == "d":
            return Lit(_DIGIT)
        if c == "D":
            return Lit(ALL_BYTES - _DIGIT)
        if c == "w":
            return Lit(_WORD)
        if c == "W":
            return Lit(ALL_BYTES - _WORD)
        if c == "s":
            return Lit(_SPACE)
        if c == "S":
            return Lit(ALL_BYTES - _SPACE)
        if c == "b":
            return Anchor("b")
        if c == "B":
            return Anchor("B")
        if c == "A":
            return Anchor("^")
        if c in ("z", "Z"):
            return Anchor("$")
        if c == "Q":  # \Q ... \E literal span
            parts = []
            while self.i < self.n:
                if self.pat[self.i] == "\\" and self.pat[self.i + 1 : self.i + 2] == "E":
                    self.i += 2
                    break
                parts.append(Lit(self._single(ord(self.next()), flags)))
            return Concat(tuple(parts)) if len(parts) != 1 else parts[0]
        if c in ("K", "G", "p", "P", "R", "X", "C", "k", "g"):
            raise self.error("\\%s unsupported" % c)
        # any other escaped char is a literal (\. \/ \\ \[ etc.)
        return Lit(self._single(ord(c), flags))

    def parse_class(self, flags: _Flags) -> frozenset:
        self.eat("[")
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        chars: set = set()
        first = True
        while True:
            if self.i >= self.n:
                raise self.error("unterminated class")
            c = self.peek()
            if c == "]" and not first:
                self.next()
                break
            first = False
            if c == "[" and self.pat[self.i : self.i + 2] == "[:":
                end = self.pat.find(":]", self.i)
                if end < 0:
                    raise self.error("bad POSIX class")
                name = self.pat[self.i + 2 : end]
                if name not in _POSIX:
                    raise self.error("POSIX class %r unsupported" % name)
                chars |= _POSIX[name]
                self.i = end + 2
                continue
            lo = self._class_char()
            if lo is None:  # class-shorthand escape like \d consumed whole set
                continue
            if self.peek() == "-" and self.pat[self.i + 1 : self.i + 2] not in ("]", ""):
                self.next()
                hi = self._class_char()
                if hi is None:
                    raise self.error("bad range")
                if hi < lo:
                    raise self.error("reversed range")
                chars |= set(range(lo, hi + 1))
            else:
                chars.add(lo)
        # stash shorthand sets accumulated by _class_char
        chars |= self._pending_sets
        self._pending_sets = set()
        out = frozenset(chars)
        if flags.ignorecase:
            out = _fold_case(out)
        if negate:
            out = ALL_BYTES - out
        if not out:
            raise self.error("empty class")
        return out

    def _class_char(self) -> Optional[int]:
        c = self.next()
        if c != "\\":
            return ord(c)
        e = self.next()
        simple = {
            "n": 0x0A, "r": 0x0D, "t": 0x09, "f": 0x0C, "v": 0x0B,
            "a": 0x07, "e": 0x1B, "0": 0x00, "b": 0x08,
        }
        if e in simple:
            return simple[e]
        if e == "x":
            h = self.pat[self.i : self.i + 2]
            if len(h) == 2 and all(x in "0123456789abcdefABCDEF" for x in h):
                self.i += 2
                return int(h, 16)
            raise self.error("bad \\x in class")
        sets = {"d": _DIGIT, "D": ALL_BYTES - _DIGIT, "w": _WORD,
                "W": ALL_BYTES - _WORD, "s": _SPACE, "S": ALL_BYTES - _SPACE}
        if e in sets:
            self._pending_sets |= set(sets[e])
            return None
        return ord(e)


def parse_regex(pattern: str, ignorecase: bool = False):
    """Parse ``pattern`` into an AST.  Raises RegexUnsupported."""
    p = _Parser(pattern)
    flags = _Flags(ignorecase=ignorecase)
    node = p.parse_alt(flags)
    if p.i != p.n:
        raise p.error("unbalanced )")
    return node
