"""Ruleset compiler: SecLang/regex → bit-parallel NFA tables for TPU.

Pipeline (SURVEY.md §7 "Ruleset compiler"):

    SecLang rules (CRS v3 shaped) ──seclang.py──▶ Rule objects
    Rule regex ──regex_ast.py──▶ AST
    AST ──factors.py──▶ mandatory factor groups (class sequences)
    factors ──bitap.py──▶ packed shift-and tables (uint32 words)
    everything ──ruleset.py──▶ CompiledRuleset artifact (save/load = the
                               framework's "checkpoint": versioned, hot-swappable)

The TPU kernel (ops/) evaluates the bitap prefilter exactly; full-PCRE
semantics (backrefs, lookaround, anchors) are recovered by the CPU confirm
stage (models/confirm.py) that runs only on prefilter hits — the hybrid
design named in SURVEY.md §7 "hard parts #1".
"""

from ingress_plus_tpu.compiler.regex_ast import (  # noqa: F401
    RegexUnsupported,
    parse_regex,
)
from ingress_plus_tpu.compiler.ruleset import (  # noqa: F401
    CompiledRuleset,
    compile_ruleset,
)
