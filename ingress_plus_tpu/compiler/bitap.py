"""Pack factor groups into bit-parallel shift-and (bitap) tables.

The scan recurrence, evaluated per input byte on TPU (ops/scan.py):

    S' = ((S << 1) | INIT) & B[byte]          # uint32 words, lane-parallel
    M |= S' & FINAL                           # sticky match accumulator

Key packing property: every factor occupies a *contiguous bit range inside a
single 32-bit word*, so the left shift never needs to carry across words —
the kernel is purely element-wise over (batch, words), which vectorizes
perfectly on the TPU VPU and shards trivially along the word axis (tensor
parallelism, SURVEY.md §2.4).

Cross-factor shift spill is harmless by construction: the bit shifted out of
factor A's last position lands on factor B's start bit, which is OR'd with
INIT (always active, unanchored search) before the AND — so the spilled bit
changes nothing.  This mirrors the classic multi-pattern Baeza-Yates–Gonnet
construction (see PAPERS.md: Hyperscan-style shift-and literature).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ingress_plus_tpu.compiler.factors import ClassSeq

WORD_BITS = 32


@dataclass
class BitapTables:
    """Packed scan tables + factor metadata.

    Arrays (all numpy, ready for device upload):
      byte_table   (256, n_words) uint32 — B[byte]: positional class masks
      init_mask    (n_words,)     uint32 — factor start bits
      final_mask   (n_words,)     uint32 — factor end bits
      factor_word  (n_factors,)   int32  — word index of each factor's final bit
      factor_bit   (n_factors,)   int32  — bit index of each factor's final bit
      factor_rule_indptr / factor_rule_ids — CSR map factor → rule indices
                   (many rules can share one deduped factor)
      rule_nfactors (n_rules,)    int32  — 0 ⇒ rule has no prefilter (always
                   confirm); >0 ⇒ rule fires iff ≥1 of its factors fires
    """

    byte_table: np.ndarray
    init_mask: np.ndarray
    final_mask: np.ndarray
    factor_word: np.ndarray
    factor_bit: np.ndarray
    factor_rule_indptr: np.ndarray
    factor_rule_ids: np.ndarray
    rule_nfactors: np.ndarray
    factor_len: np.ndarray  # (n_factors,) int32 — for streaming halo width
    #: word-tier boundary (docs/SCAN_KERNEL.md "per-bucket slicing"):
    #: words [0, n_head_words) hold every factor that can fire on a
    #: short-stream row (uri/args/headers); words beyond it hold factors
    #: owned exclusively by body/response-only rules, so a dispatch
    #: whose rows carry no body/response stream-variant may scan the
    #: word prefix only.  Defaults to the full width (no tiering).
    n_head_words: int = -1
    #: factors that share a longer host factor's bit chain (exact
    #: shared-prefix merging) — provenance only, no runtime meaning
    n_prefix_shared: int = 0

    def __post_init__(self):
        if self.n_head_words < 0:
            self.n_head_words = self.byte_table.shape[1]

    @property
    def n_words(self) -> int:
        return self.byte_table.shape[1]

    @property
    def n_factors(self) -> int:
        return self.factor_word.shape[0]

    @property
    def max_factor_len(self) -> int:
        return int(self.factor_len.max()) if self.n_factors else 0


def pack_factors(
    rule_factors: Sequence[List[ClassSeq]],
    n_rules: int | None = None,
    prefix_merge: bool = False,
    rule_tier: Optional[np.ndarray] = None,
) -> BitapTables:
    """Pack per-rule factor groups into shared tables.

    rule_factors[r] is rule r's alternative list (possibly empty = no
    prefilter).  Identical ClassSeqs across rules are deduplicated
    (factor interning — rules reference deduped factors through the
    factor→rule CSR map).

    ``prefix_merge=True`` additionally merges shared prefixes EXACTLY:
    a factor whose class sequence equals the first |A| positions of an
    already-placed longer factor occupies ZERO new bits — chain bit
    |A|-1 of the host is active iff the last |A| bytes matched exactly
    A, so marking that interior bit in ``final_mask`` and pointing the
    short factor's (word, bit) at it reproduces its semantics
    bit-for-bit.  (General trie merging at branch points is NOT
    possible in plain shift-and: the left shift cannot fan one parent
    bit out to two child chains without a per-step scatter.)

    ``rule_tier`` (n_rules,) int8/int32, 0 = head, 1 = tail: factors
    owned by at least one tier-0 rule pack into the leading words;
    factors owned ONLY by tier-1 rules pack after ``n_head_words``, so
    a dispatch that provably cannot fire them (no body/response rows)
    may scan the word prefix alone.  Prefix merging never crosses the
    boundary in the unsound direction: tail hosts are placed after
    every head factor, so a head factor can never land in tail words.
    """
    if n_rules is None:
        n_rules = len(rule_factors)

    # Dedup factors; remember which rules own each.
    uniq: Dict[ClassSeq, List[int]] = {}
    for r, group in enumerate(rule_factors):
        for seq in group:
            if not (1 <= len(seq) <= WORD_BITS):
                raise ValueError("factor length %d out of range" % len(seq))
            uniq.setdefault(seq, []).append(r)

    def _tier(seq: ClassSeq) -> int:
        if rule_tier is None:
            return 0
        return int(min(int(rule_tier[r]) for r in uniq[seq]))

    # first-fit decreasing inside each tier; stable, so insertion
    # (= rule) order breaks length ties deterministically
    seqs = sorted(uniq.keys(), key=lambda s: (_tier(s), -len(s)))

    # Bin-pack into words: each factor gets len(seq) contiguous bits.
    # Tail-tier factors open a fresh word region (n_head_words is the
    # boundary); prefix-merged factors ride a host's bits instead.
    word_used: List[int] = []
    placements: List[Tuple[int, int]] = []  # (word, offset) per seq
    merged: List[bool] = []
    prefix_host: Dict[ClassSeq, Tuple[int, int]] = {}
    n_head_words: Optional[int] = None
    head_words_frozen = False
    n_shared = 0
    for seq in seqs:
        L = len(seq)
        if rule_tier is not None and not head_words_frozen \
                and _tier(seq) == 1:
            n_head_words = len(word_used)
            head_words_frozen = True
        if prefix_merge and seq in prefix_host:
            placements.append(prefix_host[seq])
            merged.append(True)
            n_shared += 1
            continue
        lo = (n_head_words or 0) if head_words_frozen else 0
        for w in range(lo, len(word_used)):
            if word_used[w] + L <= WORD_BITS:
                placements.append((w, word_used[w]))
                word_used[w] += L
                break
        else:
            placements.append((len(word_used), 0))
            word_used.append(L)
        merged.append(False)
        if prefix_merge:
            w, off = placements[-1]
            for pl in range(1, L):
                prefix_host.setdefault(seq[:pl], (w, off))
    n_words = max(1, len(word_used))
    if n_head_words is None:
        n_head_words = n_words

    byte_table = np.zeros((256, n_words), dtype=np.uint32)
    init_mask = np.zeros((n_words,), dtype=np.uint32)
    final_mask = np.zeros((n_words,), dtype=np.uint32)
    factor_word = np.zeros((len(seqs),), dtype=np.int32)
    factor_bit = np.zeros((len(seqs),), dtype=np.int32)
    factor_len = np.zeros((len(seqs),), dtype=np.int32)

    indptr = [0]
    rule_ids: List[int] = []
    rule_nfactors = np.zeros((n_rules,), dtype=np.int32)

    for f, (seq, (w, off), shared) in enumerate(
            zip(seqs, placements, merged)):
        L = len(seq)
        init_mask[w] |= np.uint32(1 << off)
        final_mask[w] |= np.uint32(1 << (off + L - 1))
        factor_word[f] = w
        factor_bit[f] = off + L - 1
        factor_len[f] = L
        if not shared:   # a shared prefix's bits are the host's bits
            for j, cls in enumerate(seq):
                bit = np.uint32(1 << (off + j))
                for b in cls:
                    byte_table[b, w] |= bit
        owners = sorted(set(uniq[seq]))
        rule_ids.extend(owners)
        indptr.append(len(rule_ids))
        for r in owners:
            rule_nfactors[r] += 1

    return BitapTables(
        byte_table=byte_table,
        init_mask=init_mask,
        final_mask=final_mask,
        factor_word=factor_word,
        factor_bit=factor_bit,
        factor_rule_indptr=np.asarray(indptr, dtype=np.int32),
        factor_rule_ids=np.asarray(rule_ids, dtype=np.int32),
        rule_nfactors=rule_nfactors,
        factor_len=factor_len,
        n_head_words=n_head_words,
        n_prefix_shared=n_shared,
    )


def reference_scan(tables: BitapTables, data: bytes) -> np.ndarray:
    """Pure-numpy oracle for the scan recurrence.  Returns the sticky match
    mask M (n_words,) uint32 after scanning ``data``.  Used by tests to
    validate both the packing and the TPU kernels."""
    S = np.zeros((tables.n_words,), dtype=np.uint32)
    M = np.zeros((tables.n_words,), dtype=np.uint32)
    B = tables.byte_table
    init = tables.init_mask
    final = tables.final_mask
    for byte in data:
        S = ((S << np.uint32(1)) | init) & B[byte]
        M |= S & final
    return M


def matches_to_factors(tables: BitapTables, M: np.ndarray) -> np.ndarray:
    """Match mask → boolean (n_factors,) factor-hit vector."""
    return ((M[tables.factor_word] >> tables.factor_bit.astype(np.uint32)) & 1).astype(bool)


def factors_to_rules(tables: BitapTables, factor_hits: np.ndarray) -> np.ndarray:
    """Factor hits → boolean (n_rules,) rule prefilter-hit vector."""
    n_rules = tables.rule_nfactors.shape[0]
    out = np.zeros((n_rules,), dtype=bool)
    hit_idx = np.nonzero(factor_hits)[0]
    for f in hit_idx:
        lo, hi = tables.factor_rule_indptr[f], tables.factor_rule_indptr[f + 1]
        out[tables.factor_rule_ids[lo:hi]] = True
    return out
