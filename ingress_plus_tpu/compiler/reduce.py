"""Budgeted approximate sigpack reduction — scan cost from *structure*,
not rule count.

BENCH_r05's own attribution proved throughput degrades ~linearly with
ruleset size (1405 rules / 343 words → 5013 req/s vs 2009 rules / 535
words → 2250 on the same host): every packed word widens the per-byte
scan recurrence, so the automaton itself — not the ops shell — is where
pack growth is paid.  This module shrinks the factor universe the way
the approximate-NFA literature does for NIDS prefilters (PAPERS.md:
"Approximate Reduction of Finite Automata for High-Speed NIDS",
arXiv:1710.08647): every operation may only make the prefilter fire
MORE often (a strict over-approximation — extra candidates are absorbed
by the exact CPU confirm lane, which decides every verdict), and the
aggregate over-firing is bounded by a configurable *candidate-inflation
budget* priced against a fixed byte-frequency model of web traffic and
measurable against a real corpus (``measure_inflation``).

Reduction pipeline (all deterministic — pack fingerprints must be
reproducible; no RNG, no wall clock):

  1. window truncation   — factors longer than ``max_factor_len`` keep
                           their highest-information window.  A window
                           of a mandatory factor is itself mandatory.
  2. case-fold widening  — widen alpha positions to the case-insensitive
                           closure when ≥2 distinct factors collapse to
                           the same canonical (superset classes ⇒ fires
                           on a superset; widening that dedupes pays for
                           its bits twice over).
  3. near-identical pair merge — same-length factors whose positionwise
                           class union stays tight are replaced by the
                           union factor (fires whenever either would).
  4. byte-class coarsening (``coarsen_byte_classes``, post-pack) — merge
                           near-duplicate byte equivalence classes of
                           the packed byte_table by OR-ing their rows.
                           The recurrence is monotone in table bits
                           (S' = ((S<<1)|I) & B[byte]), so added bits
                           only ever ADD matches; fewer distinct rows =
                           smaller class-pair gather tables on device.

Ops 1-3 rewrite the factor universe before packing; the exact interning
and shared-prefix bit merging live in compiler/bitap.pack_factors.
``budget <= 0`` disables every approximate op (exact mode; the
budget-boundary contract pinned by tests/test_pack_reduction.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ingress_plus_tpu.compiler import factors as F
from ingress_plus_tpu.compiler.factors import ClassSeq

if TYPE_CHECKING:   # import cycle: profile.py prices with byte_model
    from ingress_plus_tpu.compiler.profile import MeasuredProfile

__all__ = [
    "ReductionConfig",
    "ReductionReport",
    "reduce_rule_groups",
    "coarsen_byte_classes",
    "byte_model",
    "batch_reference_scan",
    "candidate_matrix",
    "measure_inflation",
]


@dataclass(frozen=True)
class ReductionConfig:
    """Knobs for the approximate reduction.

    ``budget`` is the allowed *relative candidate-mass inflation* under
    the byte-frequency model: 0.25 means the estimated expected number
    of (request, rule) prefilter candidates may grow by at most 25%.
    It is a modeling bound enforced greedily per merge; the measured
    end-to-end inflation on a real corpus (``measure_inflation``, the
    bench PACKSCALE leg) is typically far below it because merges are
    taken cheapest-first.  ``budget <= 0`` = exact mode (no approximate
    op fires; tables are bit-identical to the unreduced compile when
    ``prefix_merge`` is also off)."""

    budget: float = 0.25
    #: window-truncation target: factors longer than this keep their
    #: best (highest-information) window.  12 selective bytes carry
    #: ~70+ bits — overwhelming for a prefilter — at a third of the
    #: device word cost of the 32-byte maximum.
    max_factor_len: int = 12
    fold_merge: bool = True
    pair_merge: bool = True
    #: positionwise union merge acceptance: |union class| may not exceed
    #: this multiple of the larger input class (keeps merged factors
    #: tight so their fire rate stays near the inputs')
    pair_widen_cap: float = 2.0
    class_merge: bool = True
    #: ceiling on byte-class merges per compile (coarsening is the one
    #: op whose cost model is per-pack global; the cap bounds it even
    #: if the budget math would allow more)
    class_merge_cap: int = 64
    #: EXACT shared-prefix bit merging in pack_factors (not budget
    #: accounted — it never changes scan semantics)
    prefix_merge: bool = True
    #: EXACT word tiering: pack factors owned only by body/response
    #: rules into the trailing words (enables per-bucket word slicing)
    word_tiering: bool = True
    #: measured-traffic pricing (ISSUE 15, docs/RETUNE.md): when set,
    #: the profile's observed byte distribution replaces the static
    #: ``byte_model`` in every merge/coarsen price, per-rule candidate
    #: rates re-weight the owner mass (hot rules' factors become
    #: expensive to widen), and the hottest rules' factors are pinned
    #: to their exact windows.  A pricing input ONLY — soundness never
    #: depends on it (``compare=False``: two configs differing only in
    #: profile still compare equal as knob sets; the pack fingerprint
    #: covers the resulting tables regardless).
    profile: Optional["MeasuredProfile"] = field(default=None,
                                                compare=False)
    #: fraction of observed-active rules pinned hot (exact windows)
    hot_frac: float = 0.1
    #: how many top-expensive-confirm rules get relaxed quick-reject
    #: literal derivation (models/confirm.py qr_relax)
    qr_relax_top: int = 16

    @classmethod
    def off(cls) -> "ReductionConfig":
        """Legacy-exact mode: bit-identical tables to the pre-reduction
        compiler (used by the frozen bench fixture so cross-round
        throughput numbers stay comparable)."""
        return cls(budget=0.0, max_factor_len=F.MAX_FACTOR_LEN,
                   fold_merge=False, pair_merge=False, class_merge=False,
                   prefix_merge=False, word_tiering=False)

    @property
    def approximate(self) -> bool:
        return self.budget > 0.0


@dataclass
class ReductionReport:
    """Provenance of one reduction run — serialized into the compiled
    artifact's json meta and surfaced by rulecheck's JSON report, so an
    operator can always answer "what did the compiler merge, and what
    did it cost" for the pack actually serving."""

    budget: float = 0.0
    spent: float = 0.0            # estimated inflation actually spent
    factors_in: int = 0           # unique factors before reduction
    factors_out: int = 0
    truncated: int = 0
    fold_merged: int = 0          # factors absorbed by fold canonicals
    pair_merged: int = 0          # factors absorbed by union merges
    prefix_shared: int = 0        # factors riding a host's bits (exact)
    class_merges: int = 0         # byte-class coarsening merges
    classes_in: int = 0
    classes_out: int = 0
    #: measured end-to-end candidate inflation on a corpus sample
    #: (filled by bench / tests via measure_inflation; None = unmeasured)
    measured_inflation: Optional[float] = None
    #: content hash of the MeasuredProfile that priced this reduction
    #: (None = static byte model) — the provenance chain retune audits
    profile_hash: Optional[str] = None
    #: factors pinned to exact windows by the profile's hot-rule tier
    hot_factors: int = 0
    #: rules whose quick-reject derivation was relaxed (qr_relax)
    qr_relaxed: int = 0
    notes: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict:
        d = dict(self.__dict__)
        # plain-python scalars only: this dict goes through json.dumps
        # in CompiledRuleset.save and the rulecheck report
        for k, v in d.items():
            if isinstance(v, (np.floating, np.integer)):
                d[k] = v.item()
        d["notes"] = list(self.notes)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "ReductionReport":
        out = cls()
        for k, v in (d or {}).items():
            if hasattr(out, k):
                setattr(out, k, v)
        return out


# --------------------------------------------------------------- byte model

_MODEL: Optional[np.ndarray] = None


def byte_model() -> np.ndarray:
    """Fixed (256,) byte-frequency model of normalized web-request text,
    used to price factor fire rates.  Deliberately a constant (not
    corpus-derived): compile output must be deterministic across hosts
    and corpora.  Shape: alphanumerics dominate, URL/form punctuation is
    common, the rest of ASCII is rare, non-ASCII is negligible-but-
    nonzero (decoded bodies do carry it)."""
    global _MODEL
    if _MODEL is not None:
        return _MODEL
    w = np.full(256, 0.02, dtype=np.float64)      # high/control floor
    for b in range(0x20, 0x7F):
        w[b] = 0.4                                # printable baseline
    for b in range(ord("a"), ord("z") + 1):
        w[b] = 4.0
    for b in range(ord("A"), ord("Z") + 1):
        w[b] = 1.0
    for b in range(ord("0"), ord("9") + 1):
        w[b] = 2.0
    for ch in "/=&?.-_%+:;, ":
        w[ord(ch)] = 2.0
    _MODEL = w / w.sum()
    return _MODEL


def _seq_prob(seq: ClassSeq, mu: np.ndarray) -> float:
    """P(a random position starts a match of ``seq``) under the model —
    the per-position fire rate the budget math prices merges with."""
    p = 1.0
    for cls in seq:
        m = 0.0
        for b in cls:
            m += mu[b]
        p *= m
        if p == 0.0:
            return 0.0
    return p


# ------------------------------------------------------ factor-level passes


def _fold_close(cls: frozenset) -> frozenset:
    """Case-insensitive closure of a byte class."""
    out = set(cls)
    for b in cls:
        if 0x41 <= b <= 0x5A:
            out.add(b + 0x20)
        elif 0x61 <= b <= 0x7A:
            out.add(b - 0x20)
    return frozenset(out)


def _fold_seq(seq: ClassSeq) -> ClassSeq:
    return tuple(_fold_close(c) for c in seq)


def _sig(seq: ClassSeq) -> bytes:
    """Cheap locality signature for the neighbor pair-merge scan: the
    folded minimum byte per position.  Near-identical factors (case
    variants, small class widenings of the same literal) sort adjacent."""
    out = bytearray()
    for cls in seq:
        b = min(cls)
        if 0x41 <= b <= 0x5A:
            b += 0x20
        out.append(b)
    return bytes(out)


# Ubiquitous wire tokens: strings present in essentially every normalized
# request row (header names, protocol/UA boilerplate).  The independence
# assumption in _seq_prob cannot see that a merged union's positions
# correlate into one of these, so a union that happens to cover e.g.
# "user-agent" is priced as astronomically rare while actually firing on
# every row — the one failure mode where the greedy merge can silently
# destroy a prefilter group's selectivity.  Merges whose OUTPUT matches a
# wire token (when no input did) are vetoed outright instead of priced.
_WIRE_LITERALS: Tuple[bytes, ...] = (
    b"user-agent", b"accept-encoding", b"accept-language", b"accept",
    b"content-type", b"content-length", b"connection", b"keep-alive",
    b"cookie", b"referer", b"host", b"mozilla/", b"http/1.",
    b"gzip, deflate", b"text/html", b"charset", b"multipart/form-data",
    b"x-www-form-urlencoded", b"applewebkit", b"gecko",
)


def _matches_wire_literal(seq: ClassSeq) -> bool:
    """True if ``seq`` (case-folded) can match inside any ubiquitous wire
    token — i.e. the factor would fire on essentially every request."""
    folded = [_fold_close(c) for c in seq]
    n = len(folded)
    for lit in _WIRE_LITERALS:
        if len(lit) < n:
            continue
        for off in range(len(lit) - n + 1):
            if all(lit[off + j] in folded[j] for j in range(n)):
                return True
    return False


def _apply_mapping(mapping: Dict[ClassSeq, ClassSeq],
                   seq: ClassSeq) -> ClassSeq:
    """Chase merge chains (A→B, B→C ⇒ A→C), path-compressing."""
    seen = []
    while seq in mapping and mapping[seq] != seq:
        seen.append(seq)
        seq = mapping[seq]
    for s in seen:
        mapping[s] = seq
    return seq


def reduce_rule_groups(
    rule_factors: Sequence[List[ClassSeq]],
    cfg: ReductionConfig,
    rule_weights: Optional[np.ndarray] = None,
    hot_rules: Optional[np.ndarray] = None,
) -> Tuple[List[List[ClassSeq]], ReductionReport]:
    """Apply the factor-level approximate passes (truncate / fold-widen /
    pair-merge) to per-rule factor groups under ``cfg.budget``.

    Soundness: every rewrite replaces an alternative with one that
    matches a SUPERSET of strings (wider classes and/or a sub-window),
    so "every rule match contains a group match" is preserved and the
    prefilter can only gain candidates, never lose one.  The budget is
    spent greedily cheapest-first on the estimated candidate-mass
    increase Σ_f p(f)·|owner rules of f|.

    Profile pricing (ISSUE 15): ``rule_weights`` (R,) floats scale each
    owner rule's mass contribution by its observed candidate rate, and
    ``hot_rules`` (R,) bool pins the hottest rules' factors out of every
    approximate pass — their prefilter precision is what keeps the
    confirm lane cheap, so their windows stay exact while cold rules
    absorb the budget.  Both are pricing/tiering inputs only: the
    superset argument above never depends on them."""
    report = ReductionReport(budget=cfg.budget)
    groups = [list(g) for g in rule_factors]
    # factor universe: seq → owner-rule mass (shared factors price once
    # per owning rule — each owner books its own candidates; with a
    # profile, each owner books at its measured candidate weight)
    owners: Dict[ClassSeq, float] = {}
    for i, g in enumerate(groups):
        w = 1.0 if rule_weights is None else float(rule_weights[i])
        for s in dict.fromkeys(g):
            owners[s] = owners.get(s, 0.0) + w
    report.factors_in = len(owners)
    if not cfg.approximate or not owners:
        report.factors_out = len(owners)
        return groups, report

    hot: set = set()
    if hot_rules is not None:
        for i, g in enumerate(groups):
            if hot_rules[i]:
                hot.update(g)
    report.hot_factors = len(hot)

    mu = None
    if cfg.profile is not None:
        mu = cfg.profile.byte_mu()
    if mu is None:
        mu = byte_model()
    base_mass = sum(_seq_prob(s, mu) * n for s, n in owners.items())
    base_mass = max(base_mass, 1e-300)
    budget_mass = cfg.budget * base_mass
    spent = 0.0
    mapping: Dict[ClassSeq, ClassSeq] = {}

    def owners_of(seq: ClassSeq) -> float:
        return owners.get(seq, 0.0)

    # ---- pass 1: window truncation (cheapest possible inflation: a
    # high-information window of len>=max_factor_len is still absurdly
    # selective, so ΔM ≈ 0 — but it is charged like everything else)
    cands = []
    for seq in owners:
        if seq in hot:
            continue   # hot tier: exact windows, no approximate rewrite
        if len(seq) > cfg.max_factor_len:
            short = F.best_window(seq, cfg.max_factor_len)
            d = (_seq_prob(short, mu) - _seq_prob(seq, mu)) * owners_of(seq)
            cands.append((max(d, 0.0), seq, short))
    for d, seq, short in sorted(cands, key=lambda t: (t[0], _sig(t[1]))):
        if spent + d > budget_mass:
            break
        mapping[seq] = short
        spent += d
        report.truncated += 1

    def _universe() -> Dict[ClassSeq, float]:
        u: Dict[ClassSeq, float] = {}
        for s, n in owners.items():
            t = _apply_mapping(mapping, s)
            u[t] = u.get(t, 0.0) + n
        return u

    # ---- pass 2: case-fold widening where it dedupes
    if cfg.fold_merge:
        uni = _universe()
        by_fold: Dict[ClassSeq, List[ClassSeq]] = {}
        for s in uni:
            if s in hot:
                continue
            by_fold.setdefault(_fold_seq(s), []).append(s)
        cands2 = []
        for canon, members in by_fold.items():
            distinct = [m for m in members if m != canon]
            if len(members) < 2 or not distinct:
                continue
            if _matches_wire_literal(canon) and not any(
                    _matches_wire_literal(m) for m in members):
                continue   # widening would cover request boilerplate
            total = sum(uni[m] for m in members)
            d = _seq_prob(canon, mu) * total - sum(
                _seq_prob(m, mu) * uni[m] for m in members)
            cands2.append((max(d, 0.0), canon, members))
        for d, canon, members in sorted(
                cands2, key=lambda t: (t[0], _sig(t[1]))):
            if spent + d > budget_mass:
                continue
            spent += d
            for m in members:
                if m != canon:
                    mapping[_apply_mapping(mapping, m)] = canon
                    report.fold_merged += 1

    # ---- pass 3: near-identical same-length union merges (signature-
    # sorted neighbor scan keeps this O(n log n) and deterministic)
    if cfg.pair_merge:
        uni = _universe()
        by_len: Dict[int, List[ClassSeq]] = {}
        for s in uni:
            if s in hot:
                continue
            by_len.setdefault(len(s), []).append(s)
        merges = []
        for L, seqs in sorted(by_len.items()):
            seqs.sort(key=_sig)
            for i, a in enumerate(seqs):
                for b in seqs[i + 1:i + 9]:   # neighbor window
                    u = []
                    ok = True
                    for ca, cb in zip(a, b):
                        cu = ca | cb
                        if len(cu) > max(4, cfg.pair_widen_cap
                                         * max(len(ca), len(cb))):
                            ok = False
                            break
                        u.append(cu)
                    if not ok:
                        continue
                    useq = tuple(u)
                    if _matches_wire_literal(useq) and not (
                            _matches_wire_literal(a)
                            or _matches_wire_literal(b)):
                        continue   # union would cover request boilerplate
                    d = (_seq_prob(useq, mu) * (uni[a] + uni[b])
                         - _seq_prob(a, mu) * uni[a]
                         - _seq_prob(b, mu) * uni[b])
                    merges.append((max(d, 0.0), a, b, useq))
        merged_away: set = set()
        for d, a, b, useq in sorted(
                merges, key=lambda t: (t[0], _sig(t[1]), _sig(t[2]))):
            if a in merged_away or b in merged_away:
                continue
            if spent + d > budget_mass:
                continue
            spent += d
            mapping[_apply_mapping(mapping, a)] = useq
            mapping[_apply_mapping(mapping, b)] = useq
            merged_away.add(a)
            merged_away.add(b)
            report.pair_merged += 2 if useq not in (a, b) else 1

    # ---- rewrite the rule groups through the final mapping
    out_groups: List[List[ClassSeq]] = []
    final: Dict[ClassSeq, int] = {}
    for g in groups:
        ng = list(dict.fromkeys(_apply_mapping(mapping, s) for s in g))
        out_groups.append(ng)
        for s in dict.fromkeys(ng):
            final[s] = final.get(s, 0) + 1
    report.factors_out = len(final)
    report.spent = spent / base_mass
    return out_groups, report


# ------------------------------------------------- byte-class coarsening


def coarsen_byte_classes(
    byte_table: np.ndarray,       # (256, W) uint32 — mutated copy returned
    factor_word: np.ndarray,
    factor_bit: np.ndarray,
    factor_len: np.ndarray,
    factor_owners: np.ndarray,    # (F,) int — owner-rule count per factor
    budget_frac: float,
    merge_cap: int = 64,
    mu: Optional[np.ndarray] = None,   # pricing model override (profile)
) -> Tuple[np.ndarray, int, int, int, float]:
    """Merge near-duplicate byte equivalence classes of the packed table
    by OR-ing their rows (monotone in the recurrence ⇒ matches only
    grow).  Returns (new_byte_table, n_merges, classes_in, classes_out,
    spent_frac).

    The estimated inflation of merging classes (U, V) is computed
    per factor from the positionwise class-mass ratios after the merge,
    weighted by the factor's fire rate and owner count — the same
    candidate-mass currency the factor-level passes spend."""
    bt = byte_table.astype(np.uint32).copy()
    if mu is None:
        mu = byte_model()
    uniq, inv = np.unique(bt, axis=0, return_inverse=True)
    inv = np.asarray(inv).ravel()
    k = uniq.shape[0]
    if budget_frac <= 0.0 or k <= 2 or merge_cap <= 0:
        return bt, 0, k, k, 0.0

    W = bt.shape[1]
    bits = ((uniq[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
            ).astype(np.float64)                       # (k, W, 32)
    class_mass = np.array([mu[inv == c].sum() for c in range(k)])
    # per-(w,bit) class mass currently reaching that state bit
    pos_mass = np.einsum("c,cwb->wb", class_mass, bits)  # (W, 32)
    pos_mass = np.maximum(pos_mass, 1e-12)

    # factor position bookkeeping: flat (w*32+bit) ids per factor
    fpos: List[np.ndarray] = []
    for f in range(factor_word.shape[0]):
        w = int(factor_word[f])
        fin = int(factor_bit[f])
        L = int(factor_len[f])
        fpos.append(w * 32 + np.arange(fin - L + 1, fin + 1))
    flat = np.concatenate(fpos) if fpos else np.zeros(0, np.int64)
    lens = np.array([len(p) for p in fpos], dtype=np.int64)
    starts = np.zeros_like(lens)
    if len(lens):
        starts[1:] = np.cumsum(lens)[:-1]
    log_pm = np.log(pos_mass).ravel()
    # factor fire rate p(f) under the model (product of position masses)
    fprob = (np.exp(np.add.reduceat(log_pm[flat], starts))
             if len(flat) else np.zeros(0))
    if len(lens):
        fprob[lens == 0] = 0.0
    base_mass = float((fprob * factor_owners).sum())
    if base_mass <= 0.0:
        return bt, 0, k, k, 0.0
    budget_mass = budget_frac * base_mass

    # candidate pairs: nearest rows by bit distance, via sorted popcount
    # neighborhood (deterministic, O(k^2) worst case but k is ~10^2)
    order = np.lexsort(uniq.T[::-1])
    cands = []
    for oi in range(k):
        for oj in range(oi + 1, min(oi + 13, k)):
            i, j = int(order[oi]), int(order[oj])
            # Δ per (w,bit): bits one side reaches and the other doesn't
            di = bits[i] - bits[j]
            add = (np.maximum(di, 0) * class_mass[j]
                   + np.maximum(-di, 0) * class_mass[i])   # (W, 32)
            if not add.any():
                continue
            ratio = np.log1p(add / pos_mass).ravel()
            if len(flat):
                fd = np.exp(np.add.reduceat(ratio[flat], starts))
                fd[lens == 0] = 1.0
                dmass = float(((fd - 1.0) * fprob * factor_owners).sum())
            else:
                dmass = 0.0
            cands.append((dmass, i, j))
    cands.sort(key=lambda t: (t[0], t[1], t[2]))
    taken: set = set()
    spent = 0.0
    n_merges = 0
    for dmass, i, j in cands:
        if n_merges >= merge_cap or spent + dmass > budget_mass:
            break
        if i in taken or j in taken:
            continue
        merged = uniq[i] | uniq[j]
        bt[inv == i] = merged
        bt[inv == j] = merged
        taken.add(i)
        taken.add(j)
        spent += dmass
        n_merges += 1
    k_out = int(np.unique(bt, axis=0).shape[0])
    return bt, n_merges, k, k_out, spent / base_mass


# --------------------------------------------------- measured verification


def batch_reference_scan(tables, rows: Sequence[bytes]) -> np.ndarray:
    """Vectorized numpy twin of compiler.bitap.reference_scan over a row
    batch: returns (B, W) uint32 sticky match masks.  This is the CPU
    oracle the measured-inflation gate and the equivalence tests scan
    with (no jax involvement — usable inside the compiler)."""
    B = len(rows)
    W = tables.n_words
    S = np.zeros((B, W), dtype=np.uint32)
    M = np.zeros((B, W), dtype=np.uint32)
    if B == 0:
        return M
    maxlen = max((len(r) for r in rows), default=0)
    toks = np.zeros((B, maxlen), dtype=np.int64)
    lens = np.zeros(B, dtype=np.int64)
    for i, r in enumerate(rows):
        toks[i, :len(r)] = np.frombuffer(r, dtype=np.uint8)
        lens[i] = len(r)
    bt = tables.byte_table
    init = tables.init_mask[None, :]
    for t in range(maxlen):
        live = lens > t
        if not live.any():
            break
        S_new = ((S << np.uint32(1)) | init) & bt[toks[:, t]]
        S = np.where(live[:, None], S_new, S)
        M = np.where(live[:, None], M | (S_new & tables.final_mask[None, :]),
                     M)
    return M


def candidate_matrix(tables, rows: Sequence[bytes]) -> np.ndarray:
    """(B, R) bool prefilter candidate matrix for raw byte rows (no
    stream-variant masking — this is the raw factor→rule gate the
    budget bounds)."""
    from ingress_plus_tpu.compiler.bitap import (
        factors_to_rules,
        matches_to_factors,
    )

    M = batch_reference_scan(tables, rows)
    R = tables.rule_nfactors.shape[0]
    out = np.zeros((len(rows), R), dtype=bool)
    for i in range(len(rows)):
        out[i] = factors_to_rules(tables, matches_to_factors(tables, M[i]))
    return out


def measure_inflation(exact_tables, reduced_tables,
                      rows: Sequence[bytes]) -> Dict:
    """Measured candidate inflation of ``reduced_tables`` over
    ``exact_tables`` on a row sample, plus the superset check (a single
    lost candidate = an unsound reduction = a bug).  Returns a dict
    ready for reports/PACKSCALE.json / the rulecheck provenance block."""
    ce = candidate_matrix(exact_tables, rows)
    cr = candidate_matrix(reduced_tables, rows)
    lost = int(np.logical_and(ce, ~cr).sum())
    n_exact = int(ce.sum())
    n_red = int(cr.sum())
    return {
        "rows": len(rows),
        "candidates_exact": n_exact,
        "candidates_reduced": n_red,
        "lost_candidates": lost,          # MUST be 0 (soundness)
        "inflation": (round((n_red - n_exact) / n_exact, 4)
                      if n_exact else 0.0),
    }
