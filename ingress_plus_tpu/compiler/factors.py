"""Mandatory-factor extraction: regex AST → byte-class sequences.

A *factor* is a fixed-length sequence of byte classes such that every match
of the rule's regex contains (at some offset) a string matching one of the
rule's factor alternatives.  The TPU bitap kernel scans for factors; the CPU
confirm stage re-checks full regex semantics on hits.  This is the
Hyperscan-style literal-factor decomposition chosen in SURVEY.md §7 for the
libproton/CRS hot loop, built to be *sound*: a factor set never misses a
true match (it may over-trigger; the confirm stage removes false positives).

Terminology:
  ClassSeq  — tuple of frozensets (byte classes), one per position.
  Group     — list of ClassSeq alternatives; "every match contains one of
              these".  A rule's prefilter uses its best-scoring group.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

from ingress_plus_tpu.compiler.regex_ast import (
    Alt,
    Anchor,
    Concat,
    Lit,
    Repeat,
)

ClassSeq = Tuple[frozenset, ...]
Group = List[ClassSeq]

MAX_FACTOR_LEN = 32      # one factor must fit in a 32-bit bitap word
MAX_ALTERNATIVES = 64    # cap on enumeration blowup per group
MIN_GROUP_BITS = 6.0     # below this a group is too weak to prefilter


def seq_bits(seq: ClassSeq) -> float:
    """Information content of a class sequence (selectivity score)."""
    return sum(math.log2(256.0 / max(1, len(c))) for c in seq)


def best_window(seq: ClassSeq, width: int = MAX_FACTOR_LEN) -> ClassSeq:
    """Highest-information contiguous window of at most ``width`` positions."""
    if len(seq) <= width:
        return seq
    scores = [math.log2(256.0 / max(1, len(c))) for c in seq]
    best_i, best_s = 0, sum(scores[:width])
    cur = best_s
    for i in range(1, len(seq) - width + 1):
        cur += scores[i + width - 1] - scores[i - 1]
        if cur > best_s:
            best_i, best_s = i, cur
    return seq[best_i : best_i + width]


def _trim(seq: ClassSeq) -> ClassSeq:
    """Drop uninformative (all-byte) edges, clamp to MAX_FACTOR_LEN."""
    lo, hi = 0, len(seq)
    while lo < hi and len(seq[lo]) == 256:
        lo += 1
    while hi > lo and len(seq[hi - 1]) == 256:
        hi -= 1
    return best_window(seq[lo:hi])


def enumerate_seqs(node, cap: int = MAX_ALTERNATIVES) -> Optional[List[ClassSeq]]:
    """Exactly enumerate the class sequences ``node`` can match, or None if
    unbounded / too many.  Zero-width nodes yield [()]."""
    if isinstance(node, Lit):
        return [(node.chars,)]
    if isinstance(node, Anchor):
        return [()]
    if isinstance(node, Concat):
        acc: List[ClassSeq] = [()]
        for part in node.parts:
            sub = enumerate_seqs(part, cap)
            if sub is None:
                return None
            acc = [a + s for a in acc for s in sub]
            if len(acc) > cap:
                return None
        return acc
    if isinstance(node, Alt):
        out: List[ClassSeq] = []
        for opt in node.options:
            sub = enumerate_seqs(opt, cap)
            if sub is None:
                return None
            out.extend(sub)
            if len(out) > cap:
                return None
        # dedup
        return list(dict.fromkeys(out))
    if isinstance(node, Repeat):
        if node.max is None or node.max > 8:
            return None
        base = enumerate_seqs(node.node, cap)
        if base is None:
            return None
        out = []
        for k in range(node.min, node.max + 1):
            acc: List[ClassSeq] = [()]
            for _ in range(k):
                acc = [a + s for a in acc for s in base]
                if len(acc) > cap:
                    return None
            out.extend(acc)
            if len(out) > cap:
                return None
        return list(dict.fromkeys(out))
    raise TypeError("unknown node %r" % (node,))


def _score_group(group: Group) -> float:
    """A group is as strong as its weakest alternative."""
    if not group:
        return -1.0
    return min(seq_bits(s) for s in group)


def _finish_group(seqs: List[ClassSeq]) -> Optional[Group]:
    """Trim/clamp alternatives; a group with any empty alternative is useless
    (it would match everywhere)."""
    out = []
    for s in dict.fromkeys(seqs):
        t = _trim(s)
        if len(t) == 0:
            return None
        out.append(t)
    if not out or len(out) > MAX_ALTERNATIVES:
        return None
    return out


def mandatory_groups(node) -> List[Group]:
    """All mandatory groups of ``node``: for every returned group, any string
    matching ``node`` contains a substring matching one of the group's
    alternatives."""
    # Whole-node enumeration is the strongest possible group.
    whole = enumerate_seqs(node)
    if whole is not None:
        g = _finish_group(whole)
        return [g] if g else []

    if isinstance(node, Repeat):
        if node.min >= 1:
            return mandatory_groups(node.node)
        return []

    if isinstance(node, Alt):
        combined: Group = []
        for opt in node.options:
            subgroups = mandatory_groups(opt)
            if not subgroups:
                return []  # one branch has no factor → alt has none
            best = max(subgroups, key=_score_group)
            combined.extend(best)
            if len(combined) > MAX_ALTERNATIVES:
                return []
        g = _finish_group(combined)
        return [g] if g else []

    if isinstance(node, Concat):
        groups: List[Group] = []
        run: List[ClassSeq] = [()]  # cross product of enumerable children

        def close_run():
            nonlocal run
            if run and run != [()]:
                g = _finish_group(run)
                if g:
                    groups.append(g)
            run = [()]

        for part in node.parts:
            sub = enumerate_seqs(part)
            if sub is not None and len(sub) * len(run) <= MAX_ALTERNATIVES:
                run = [a + s for a in run for s in sub]
                # keep run length bounded; overly long seqs get trimmed later
                if max((len(s) for s in run), default=0) > 4 * MAX_FACTOR_LEN:
                    close_run()
            else:
                close_run()
                groups.extend(mandatory_groups(part))
        close_run()
        return groups

    return []


def best_factor_group(node) -> Optional[Group]:
    """The highest-scoring mandatory group, or None if nothing usable."""
    groups = [g for g in mandatory_groups(node) if _score_group(g) >= MIN_GROUP_BITS]
    if not groups:
        return None
    return max(groups, key=_score_group)
