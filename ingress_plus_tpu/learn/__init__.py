"""Learned scoring lane (ISSUE 8, docs/LEARNED_SCORING.md).

The ModSec-Learn result (arXiv:2406.13547, PAPERS.md): CRS's fixed
per-rule anomaly weights and global threshold are a hand-tuned linear
model over the rule-activation vector — training that linear model on
labeled traffic cuts false positives at equal recall.  PR 3's
``RuleStats`` already observes exactly that representation per request;
this package closes the loop:

- ``features``  — per-request rule-activation bitmaps keyed by CRS rule
  id (so features survive pack swaps via rule-id remapping), plus the
  labeled ``FeatureDataset`` container the trainer/CI gate share.
- ``head``      — the versioned ``ScoringHead`` artifact (weights +
  rule-id map + calibrated threshold + provenance hash) and the
  ``LearnedScorer`` that binds it to one compiled pack's rule axis for
  serving (one tiny matmul over the confirmed-hit bitmap inside
  finalize; the fixed-weight score is still computed and exported so
  live divergence is observable).
- ``train``     — deterministic seeded logistic trainer + the
  zero-new-FN threshold calibration against the fixed-weight baseline,
  and ``compare_scorers`` (the MODELGATE / bench quality block).

Rollout safety: scoring-head swaps ride the PR 5 ``RolloutController``
stages (``admit_scoring``) — admission (schema + coverage + golden
replay vs the incumbent scorer), shadow, canary with the verdict-diff
trigger, auto-rollback, and scorer LKG persistence — so a bad model can
never block traffic the fixed weights wouldn't.
"""

from ingress_plus_tpu.learn.features import FeatureDataset, remap_columns
from ingress_plus_tpu.learn.head import (
    LearnedScorer,
    ScoringHead,
    load_lkg_scorer,
    persist_lkg_scorer,
)

# learn.train is NOT imported eagerly: it doubles as the trainer CLI
# (`python -m ingress_plus_tpu.learn.train`), and a package __init__
# that pre-imports it trips runpy's re-execution warning for every CLI
# user.  Import trainer symbols from ingress_plus_tpu.learn.train.

__all__ = [
    "FeatureDataset",
    "LearnedScorer",
    "ScoringHead",
    "load_lkg_scorer",
    "persist_lkg_scorer",
    "remap_columns",
]
