"""Feature extraction for the learned scoring lane (docs/LEARNED_SCORING.md).

The feature representation is the per-request **rule-activation bitmap**
over one compiled pack's rule axis — exactly what ``RuleStats`` folds
per finalize batch (PR 3) and what ModSec-Learn trains on.  Two lanes:

- ``confirmed`` — rules whose confirm regex matched (the exact lane the
  verdict is scored from; the serving feature).
- ``candidates`` — prefilter candidate rules (sound over-approximation;
  kept as an ablation axis — a head trained on candidates could score
  during brownout rung 1, where the confirm lane is skipped).

Features are KEYED BY CRS RULE ID, not by sigpack row: a pack swap
reorders/adds/removes rows, so every artifact carries its rule-id map
and ``remap_columns`` aligns a matrix (or a weight vector) onto another
pack's axis by id.  Rules absent from the target axis drop (their
weight contributes nothing — the coverage fraction is the admission
gate's signal); rules new to the target axis get a zero column.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: bump when the on-disk dataset layout changes incompatibly
DATASET_SCHEMA = 1


def remap_columns(x: np.ndarray, from_ids: Sequence[int],
                  to_ids: Sequence[int]) -> Tuple[np.ndarray, float]:
    """Align columns of ``x`` (..., len(from_ids)) keyed by ``from_ids``
    onto the ``to_ids`` axis.  Returns ``(aligned, coverage)`` where
    ``coverage`` is the fraction of distinct source ids present in the
    target axis — the admission gate's rule-id-map coverage check.

    Duplicate ids (a multi-row compile of one CRS rule — each row is a
    distinct feature column) pair up POSITIONALLY: the k-th target
    occurrence of an id takes the k-th source occurrence's column, so a
    head trained on a pack binds back onto that same pack (or any pack
    preserving the duplicate structure) bit-exactly.  Target
    occurrences beyond the source's count fall back to the first
    source occurrence."""
    from_arr = np.asarray(from_ids, dtype=np.int64)
    to_arr = np.asarray(to_ids, dtype=np.int64)
    src_occ: Dict[int, List[int]] = {}
    for i, rid in enumerate(from_arr):
        src_occ.setdefault(int(rid), []).append(i)
    out = np.zeros(x.shape[:-1] + (len(to_arr),), dtype=x.dtype)
    found = 0
    hit_src: set = set()
    taken: Dict[int, int] = {}
    for j, rid in enumerate(to_arr):
        rid = int(rid)
        occ = src_occ.get(rid)
        if occ is None:
            continue
        k = taken.get(rid, 0)
        taken[rid] = k + 1
        out[..., j] = x[..., occ[k] if k < len(occ) else occ[0]]
        if rid not in hit_src:
            hit_src.add(rid)
            found += 1
    coverage = found / max(len(src_occ), 1)
    return out, coverage


@dataclass
class FeatureDataset:
    """Labeled per-request activation dataset — the shared input of the
    trainer, the CI ``modelgate``, and the tests (one export, three
    consumers; utils/export_corpus.py builds it)."""

    #: (N, R) confirmed-hit bitmaps (uint8 0/1) — the serving features
    x: np.ndarray
    #: (N,) labels: 1 = attack, 0 = benign
    y: np.ndarray
    #: (R,) CRS rule id per feature column — the portability key
    rule_ids: np.ndarray
    #: (R,) fixed CRS anomaly weight per column (the baseline scorer)
    rule_score: np.ndarray
    #: fixed-weight operating threshold the pack was compiled with
    anomaly_threshold: int
    #: (N, R) prefilter-candidate bitmaps (ablation lane), optional
    x_candidates: Optional[np.ndarray] = None
    #: per-request ids (corpus provenance; len N)
    request_ids: List[str] = field(default_factory=list)
    meta: Dict = field(default_factory=dict)

    @property
    def n(self) -> int:
        return int(self.x.shape[0])

    @property
    def n_features(self) -> int:
        return int(self.x.shape[1])

    def fingerprint(self) -> str:
        """Content hash — provenance for artifacts trained on this
        dataset (ties a head to its exact training data)."""
        h = hashlib.sha256()
        for a in (self.x, self.y, self.rule_ids, self.rule_score):
            h.update(np.ascontiguousarray(a).tobytes())
        h.update(str(self.anomaly_threshold).encode())
        return "ds-" + h.hexdigest()[:16]

    def remap(self, to_rule_ids: Sequence[int],
              to_rule_score: Optional[np.ndarray] = None,
              anomaly_threshold: Optional[int] = None
              ) -> "FeatureDataset":
        """The dataset re-keyed onto another pack's rule axis (pack-swap
        survival for recorded features)."""
        x2, cov = remap_columns(self.x, self.rule_ids, to_rule_ids)
        xc2 = None
        if self.x_candidates is not None:
            xc2, _ = remap_columns(self.x_candidates, self.rule_ids,
                                   to_rule_ids)
        rs = (np.asarray(to_rule_score, dtype=np.int64)
              if to_rule_score is not None
              else np.zeros((len(to_rule_ids),), dtype=np.int64))
        return FeatureDataset(
            x=x2, y=self.y.copy(),
            rule_ids=np.asarray(to_rule_ids, dtype=np.int64),
            rule_score=rs,
            anomaly_threshold=(self.anomaly_threshold
                               if anomaly_threshold is None
                               else anomaly_threshold),
            x_candidates=xc2, request_ids=list(self.request_ids),
            meta={**self.meta, "remapped_coverage": round(cov, 4)})

    # ------------------------------------------------------ persistence

    def save(self, path: str | Path) -> Path:
        """``<path>.npz`` (arrays) + ``<path>.json`` (schema + meta) —
        the CompiledRuleset.save convention."""
        p = Path(path)
        arrays = {
            "x": self.x.astype(np.uint8),
            "y": self.y.astype(np.uint8),
            "rule_ids": self.rule_ids.astype(np.int64),
            "rule_score": self.rule_score.astype(np.int64),
        }
        if self.x_candidates is not None:
            arrays["x_candidates"] = self.x_candidates.astype(np.uint8)
        np.savez_compressed(p.with_suffix(".npz"), **arrays)
        p.with_suffix(".json").write_text(json.dumps({
            "schema": DATASET_SCHEMA,
            "n": self.n,
            "n_features": self.n_features,
            "anomaly_threshold": int(self.anomaly_threshold),
            "fingerprint": self.fingerprint(),
            "request_ids": self.request_ids,
            "meta": self.meta,
        }, indent=1))
        return p.with_suffix(".npz")

    @classmethod
    def load(cls, path: str | Path) -> "FeatureDataset":
        p = Path(path)
        meta = json.loads(p.with_suffix(".json").read_text())
        if meta.get("schema") != DATASET_SCHEMA:
            raise ValueError("unsupported dataset schema %r"
                             % meta.get("schema"))
        with np.load(p.with_suffix(".npz")) as z:
            ds = cls(
                x=z["x"], y=z["y"], rule_ids=z["rule_ids"],
                rule_score=z["rule_score"],
                anomaly_threshold=int(meta["anomaly_threshold"]),
                x_candidates=(z["x_candidates"]
                              if "x_candidates" in z.files else None),
                request_ids=list(meta.get("request_ids", [])),
                meta=dict(meta.get("meta", {})))
        if meta.get("fingerprint") and \
                meta["fingerprint"] != ds.fingerprint():
            raise ValueError("dataset content hash mismatch (corrupt or "
                             "tampered): %s != %s"
                             % (ds.fingerprint(), meta["fingerprint"]))
        return ds
