"""ScoringHead artifact + LearnedScorer serving binding.

The artifact is the unit that rolls out (docs/LEARNED_SCORING.md):
weights + rule-id map + calibrated threshold + provenance, persisted as
``<path>.npz`` + ``<path>.json`` with a content hash the loader
verifies — a truncated or hand-edited artifact is rejected at load, the
first admission stage.

Serving: ``LearnedScorer`` binds a head onto one compiled pack's rule
axis by CRS rule id (pack swaps re-bind — the rule-id remap is what
lets a trained head survive a ruleset rollout).  The score is one tiny
matmul over the request's confirmed-hit bitmap; ``score_confirmed`` is
the sparse row-dot the CPU finalize loop runs per request and
``score_batch`` is the dense batched form — parity-pinned in
tests/test_learned_scoring.py, so the two are interchangeable and the
batched form is what a device-resident finalize dispatches.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from ingress_plus_tpu.learn.features import remap_columns

#: bump when the on-disk artifact layout changes incompatibly
HEAD_SCHEMA = 1

#: scorer last-known-good pointer file name (lives next to the pack LKG
#: pointer in --lkg-dir; separate pointer — pack and scorer roll out
#: and roll back independently)
SCORER_LKG_POINTER = "LKG_SCORER"


@dataclass
class ScoringHead:
    """Versioned learned-scorer artifact (weights + rule-id map +
    threshold + provenance)."""

    #: (F,) CRS rule id per weight — the portability key
    rule_ids: np.ndarray
    #: (F,) float32 per-rule weight
    weights: np.ndarray
    bias: float
    #: calibrated operating threshold (zero-new-FN calibration,
    #: learn/train.py) — a request flags when its confirmed-hit margin
    #: reaches this
    threshold: float
    version: str = ""
    #: training provenance: dataset fingerprint, seed, config, baseline
    #: comparison at calibration time
    provenance: Dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.rule_ids = np.asarray(self.rule_ids, dtype=np.int64)
        self.weights = np.asarray(self.weights, dtype=np.float32)
        if not self.version:
            self.version = self.fingerprint()

    def fingerprint(self) -> str:
        """Content hash over everything that affects a verdict (weights,
        rule-id map, bias, threshold) — the artifact-hash-stability
        anchor the CI modelgate pins (same data + same seed must
        reproduce this exactly)."""
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(self.rule_ids).tobytes())
        h.update(np.ascontiguousarray(self.weights).tobytes())
        h.update(np.float64(self.bias).tobytes())
        h.update(np.float64(self.threshold).tobytes())
        return "lh-" + h.hexdigest()[:16]

    def validate(self) -> None:
        """Schema gate (first admission stage): shapes line up, values
        finite, threshold present.  Raises ValueError."""
        if self.rule_ids.ndim != 1 or self.weights.ndim != 1:
            raise ValueError("rule_ids and weights must be 1-d")
        if len(self.rule_ids) != len(self.weights):
            raise ValueError(
                "rule-id map (%d) and weights (%d) length mismatch"
                % (len(self.rule_ids), len(self.weights)))
        if len(self.rule_ids) == 0:
            raise ValueError("empty scoring head")
        if not np.isfinite(self.weights).all():
            raise ValueError("non-finite weight(s)")
        for name, v in (("bias", self.bias), ("threshold", self.threshold)):
            if not np.isfinite(float(v)):
                raise ValueError("non-finite %s" % name)

    # ------------------------------------------------------ persistence

    def save(self, path: str | Path) -> Path:
        p = Path(path)
        self.validate()
        np.savez_compressed(p.with_suffix(".npz"),
                            rule_ids=self.rule_ids,
                            weights=self.weights)
        p.with_suffix(".json").write_text(json.dumps({
            "schema": HEAD_SCHEMA,
            "kind": "scoring_head",
            "version": self.version,
            "bias": float(self.bias),
            "threshold": float(self.threshold),
            "n_rules": int(len(self.rule_ids)),
            "content_sha": self.fingerprint(),
            "provenance": self.provenance,
        }, indent=1))
        return p.with_suffix(".npz")

    @classmethod
    def load(cls, path: str | Path) -> "ScoringHead":
        """Load + verify: schema version, shape validation, and the
        content hash recorded at save time — a corrupt/tampered
        artifact raises here, before any gate sees it."""
        p = Path(path)
        meta = json.loads(p.with_suffix(".json").read_text())
        if meta.get("kind") != "scoring_head":
            raise ValueError("not a scoring-head artifact: kind=%r"
                             % meta.get("kind"))
        if meta.get("schema") != HEAD_SCHEMA:
            raise ValueError("unsupported scoring-head schema %r"
                             % meta.get("schema"))
        with np.load(p.with_suffix(".npz")) as z:
            head = cls(rule_ids=z["rule_ids"], weights=z["weights"],
                       bias=float(meta["bias"]),
                       threshold=float(meta["threshold"]),
                       version=str(meta.get("version", "")),
                       provenance=dict(meta.get("provenance", {})))
        head.validate()
        if meta.get("content_sha") and \
                meta["content_sha"] != head.fingerprint():
            raise ValueError(
                "scoring-head content hash mismatch (corrupt or "
                "tampered): %s != %s"
                % (head.fingerprint(), meta["content_sha"]))
        return head


class LearnedScorer:
    """A ScoringHead bound to one compiled pack's rule axis.

    Binding resolves the head's rule-id-keyed weights onto the pack's
    sigpack-row order once per install (``DetectionPipeline._install``)
    — the per-request hot path is then a plain dot with no id lookups.
    ``coverage`` is the fraction of head rules found in the pack (the
    admission gate's rule-id-map coverage check); weight mass carried by
    missing rules simply contributes nothing, which only LOWERS learned
    scores — fail-toward-the-fixed-baseline, never toward over-blocking
    relative to the head's calibration.
    """

    def __init__(self, head: ScoringHead, ruleset) -> None:
        head.validate()
        self.head = head
        self.ruleset_version: str = ruleset.version
        pack_ids = np.asarray(ruleset.rule_ids, dtype=np.int64)
        if len(head.rule_ids) == len(pack_ids) and \
                (head.rule_ids == pack_ids).all():
            # identical axis (the head was trained on THIS pack):
            # positional bind, bit-exact with calibration even when a
            # multi-row rule repeats one CRS id with distinct per-row
            # weights (remap pairs duplicates positionally too, but the
            # short circuit makes the common case trivially exact)
            w, cov = head.weights.reshape(1, -1), 1.0
        else:
            w, cov = remap_columns(
                head.weights.reshape(1, -1), head.rule_ids, pack_ids)
        #: (R,) float32 weights on the pack's rule axis
        self.w: np.ndarray = np.ascontiguousarray(
            w[0], dtype=np.float32)
        self.bias: float = float(head.bias)
        self.threshold: float = float(head.threshold)
        #: fraction of head rule ids present in this pack
        self.coverage: float = float(cov)

    @property
    def version(self) -> str:
        return self.head.version

    def score_confirmed(self, confirmed: Sequence[int]) -> float:
        """Sparse dot over a request's confirmed rule indices — the
        finalize-loop form (identical result to ``score_batch`` on the
        equivalent bitmap row; parity-pinned)."""
        if not len(confirmed):
            return self.bias
        return float(
            self.w[np.asarray(confirmed, dtype=np.int64)].sum()
            + self.bias)

    def score_batch(self, bitmap: np.ndarray) -> np.ndarray:
        """(Q, R) activation bitmap → (Q,) learned margins: the one tiny
        matmul.  Device-friendly: dense, no gather, shape-stable in R."""
        return bitmap.astype(np.float32) @ self.w + np.float32(self.bias)

    def snapshot(self) -> dict:
        """/scoring endpoint body fragment."""
        order = np.argsort(-np.abs(self.head.weights), kind="stable")[:16]
        return {
            "version": self.head.version,
            "threshold": round(self.threshold, 6),
            "bias": round(self.bias, 6),
            "rules_in_head": int(len(self.head.rule_ids)),
            "coverage": round(self.coverage, 4),
            "bound_ruleset": self.ruleset_version,
            "provenance": self.head.provenance,
            "top_weights": [
                {"rule_id": int(self.head.rule_ids[i]),
                 "weight": round(float(self.head.weights[i]), 4)}
                for i in order],
        }


# ----------------------------------------------------------- LKG store
# Same write-then-rename discipline as the pack LKG (control/rollout.py
# persist_lkg), separate pointer: the scorer is an independent rollout
# axis — rolling a pack back must not silently drop a good model, and
# vice versa.


def persist_lkg_scorer(head: ScoringHead, lkg_dir: str | Path,
                       keep: int = 2) -> Path:
    """Atomically persist ``head`` as the last-known-good scorer."""
    d = Path(lkg_dir)
    d.mkdir(parents=True, exist_ok=True)
    base = d / ("scorer-%s" % head.version)
    tmp = d / (".tmp-scorer-%s" % head.version)
    head.save(tmp)
    os.replace(tmp.with_suffix(".npz"), base.with_suffix(".npz"))
    os.replace(tmp.with_suffix(".json"), base.with_suffix(".json"))
    ptr_tmp = d / (SCORER_LKG_POINTER + ".tmp")
    ptr_tmp.write_text(json.dumps({"artifact": base.name,
                                   "version": head.version}))
    os.replace(ptr_tmp, d / SCORER_LKG_POINTER)
    olds: List[Path] = sorted(
        (p for p in d.glob("scorer-*.json") if p.stem != base.stem),
        key=lambda p: p.stat().st_mtime)
    for p in olds[:max(0, len(olds) - (keep - 1))]:
        p.unlink(missing_ok=True)
        p.with_suffix(".npz").unlink(missing_ok=True)
    return base


def load_lkg_scorer(lkg_dir: str | Path) -> Optional[ScoringHead]:
    """Load the last-known-good scoring head, or None when absent or
    unreadable — startup must serve (fixed weights) either way."""
    d = Path(lkg_dir)
    ptr = d / SCORER_LKG_POINTER
    if not ptr.is_file():
        return None
    try:
        meta = json.loads(ptr.read_text())
        return ScoringHead.load(d / meta["artifact"])
    except Exception:
        return None
