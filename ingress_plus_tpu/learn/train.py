"""Offline trainer for the learned scoring head (docs/LEARNED_SCORING.md).

Deterministic by construction: full-batch gradient descent on a
logistic loss in float64, zeros init, fixed iteration count — the same
dataset and config reproduce bit-identical weights (and therefore the
same artifact content hash) on every retrain, which is what the CI
``modelgate`` pins.  No new dependencies: plain numpy (the matmul is
small — the golden corpus is thousands of rows by ~2k rules).

Decision semantics mirror serving (models/pipeline.py finalize): a
request can only flag when at least one rule CONFIRMED, so rows with an
empty activation bitmap carry no decision signal and are excluded from
the gradient (recorded in provenance).  The calibration step then picks
the operating threshold under a **zero-new-FN constraint** against the
fixed-weight baseline: the largest threshold that keeps every
baseline-detected attack detected — maximizing benign-block reduction
without giving back any recall the fixed weights already had.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

import numpy as np

from ingress_plus_tpu.learn.features import FeatureDataset
from ingress_plus_tpu.learn.head import ScoringHead


@dataclass
class TrainConfig:
    """Trainer knobs.  ``seed`` is recorded in provenance; the
    full-batch closed-form iteration is deterministic regardless, the
    seed exists so a future stochastic trainer stays reproducible."""

    seed: int = 20260804
    iters: int = 300
    lr: float = 0.5
    #: L2 on the weights (not the bias) — keeps rules the corpus never
    #: activates at exactly zero and bounds weight growth on tiny data
    l2: float = 1e-3
    #: threshold safety margin subtracted after calibration (float
    #: slack so a serving-side float32 round never flips a calibrated
    #: attack to a miss)
    margin: float = 1e-4


def train_head(x: np.ndarray, y: np.ndarray,
               config: Optional[TrainConfig] = None
               ) -> tuple[np.ndarray, float]:
    """Logistic regression on activation bitmaps → ``(weights, bias)``.

    Full-batch GD, float64, zeros init: deterministic.  Rows with no
    active feature are dropped (they cannot flag at serve time)."""
    cfg = config or TrainConfig()
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    live = x.any(axis=1)
    x, y = x[live], y[live]
    n, f = x.shape
    if n == 0:
        raise ValueError("no rows with active features to train on")
    w = np.zeros((f,), dtype=np.float64)
    b = 0.0
    for _ in range(cfg.iters):
        z = x @ w + b
        p = 1.0 / (1.0 + np.exp(-np.clip(z, -30.0, 30.0)))
        g = p - y
        gw = x.T @ g / n + cfg.l2 * w
        gb = float(g.mean())
        w -= cfg.lr * gw
        b -= cfg.lr * gb
    return w.astype(np.float32), float(b)


def fixed_flags(ds: FeatureDataset) -> np.ndarray:
    """The fixed-weight baseline decision per row: CRS anomaly sum over
    confirmed rules >= the pack's threshold (and at least one hit) —
    exactly finalize's ``attack`` with no learned head installed."""
    score = ds.x.astype(np.int64) @ ds.rule_score.astype(np.int64)
    return (score >= int(ds.anomaly_threshold)) & ds.x.any(axis=1)


def calibrate_threshold(margins: np.ndarray, y: np.ndarray,
                        baseline: np.ndarray, anyhit: np.ndarray,
                        safety_margin: float = 1e-4) -> float:
    """Zero-new-FN threshold: the largest t such that every attack the
    fixed baseline detects has learned margin >= t.  With no
    baseline-detected attacks at all (degenerate corpus) the threshold
    falls back to the benign maximum + margin (flag nothing benign)."""
    protected = (y.astype(bool)) & baseline & anyhit
    if protected.any():
        return float(margins[protected].min()) - safety_margin
    benign_live = (~y.astype(bool)) & anyhit
    if benign_live.any():
        return float(margins[benign_live].max()) + safety_margin
    return 0.0


def compare_scorers(ds: FeatureDataset, head: ScoringHead,
                    curve_points: int = 9) -> Dict:
    """Fixed weights vs learned head on one dataset — the MODELGATE /
    bench-quality comparison block: flags, FPs at equal (or better)
    recall, new-FN count (must be zero), and a calibration curve of
    (threshold, fp, fn) around the operating point."""
    aligned, coverage = _aligned_weights(ds, head)
    anyhit = ds.x.any(axis=1)
    margins = ds.x.astype(np.float64) @ aligned + head.bias
    learned = (margins >= head.threshold) & anyhit
    fixed = fixed_flags(ds)
    y = ds.y.astype(bool)
    new_fn = int((fixed & ~learned & y).sum())
    curve: List[Dict] = []
    lo = float(margins[anyhit].min()) if anyhit.any() else 0.0
    hi = float(margins[anyhit].max()) if anyhit.any() else 1.0
    for t in np.linspace(lo, hi, curve_points):
        flag = (margins >= t) & anyhit
        curve.append({"threshold": round(float(t), 4),
                      "fp": int((flag & ~y).sum()),
                      "fn": int((~flag & y).sum())})
    return {
        "requests": ds.n,
        "attacks": int(y.sum()),
        "benign": int((~y).sum()),
        "coverage": round(coverage, 4),
        "threshold": round(float(head.threshold), 6),
        "fixed": {"flagged": int(fixed.sum()),
                  "fp": int((fixed & ~y).sum()),
                  "fn": int((~fixed & y).sum())},
        "learned": {"flagged": int(learned.sum()),
                    "fp": int((learned & ~y).sum()),
                    "fn": int((~learned & y).sum())},
        "new_fn_vs_fixed": new_fn,
        "fp_reduction": int((fixed & ~y).sum()) - int((learned & ~y).sum()),
        "calibration_curve": curve,
    }


def _aligned_weights(ds: FeatureDataset,
                     head: ScoringHead) -> tuple[np.ndarray, float]:
    from ingress_plus_tpu.learn.features import remap_columns

    if len(head.rule_ids) == len(ds.rule_ids) and \
            (head.rule_ids == ds.rule_ids).all():
        return head.weights.astype(np.float64), 1.0
    w, cov = remap_columns(head.weights.reshape(1, -1), head.rule_ids,
                           ds.rule_ids)
    return w[0].astype(np.float64), cov


def train_from_dataset(ds: FeatureDataset,
                       config: Optional[TrainConfig] = None
                       ) -> ScoringHead:
    """Dataset → trained + calibrated + provenance-stamped head (the
    one-call path the CLI, the CI modelgate, and tests share)."""
    cfg = config or TrainConfig()
    w, b = train_head(ds.x, ds.y, cfg)
    anyhit = ds.x.any(axis=1)
    margins = ds.x.astype(np.float64) @ w.astype(np.float64) + b
    thr = calibrate_threshold(margins, ds.y, fixed_flags(ds), anyhit,
                              safety_margin=cfg.margin)
    head = ScoringHead(
        rule_ids=ds.rule_ids.copy(), weights=w, bias=b, threshold=thr,
        provenance={
            "dataset": ds.fingerprint(),
            "dataset_meta": dict(ds.meta),
            "train_config": asdict(cfg),
            "trained_rows": int(anyhit.sum()),
            "calibration": "zero-new-fn vs fixed weights "
                           "(threshold=%d)" % ds.anomaly_threshold,
        })
    head.provenance["baseline"] = compare_scorers(ds, head,
                                                  curve_points=5)
    # provenance mutation above does not move the content hash (hash
    # covers weights/map/bias/threshold only) — version stays stable
    return head


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ingress_plus_tpu.learn.train",
        description="Train + calibrate a scoring head from a feature "
                    "dataset (utils/export_corpus.py --features) or "
                    "straight from the golden corpus.")
    ap.add_argument("--dataset", default=None,
                    help="feature dataset prefix (the .npz/.json pair "
                         "export_corpus --features wrote); omitted = "
                         "build from the golden corpus in-process")
    ap.add_argument("--out", required=True,
                    help="artifact path prefix (writes .npz + .json)")
    ap.add_argument("--n", type=int, default=2048,
                    help="golden-corpus size when --dataset is omitted")
    ap.add_argument("--corpus-seed", type=int, default=20260729)
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--l2", type=float, default=1e-3)
    args = ap.parse_args(argv)

    if args.dataset:
        ds = FeatureDataset.load(args.dataset)
    else:
        from ingress_plus_tpu.utils.export_corpus import (
            build_feature_dataset)
        ds = build_feature_dataset(n=args.n, seed=args.corpus_seed)
    cfg = TrainConfig(seed=args.seed, iters=args.iters, lr=args.lr,
                      l2=args.l2)
    head = train_from_dataset(ds, cfg)
    out = head.save(args.out)
    base = head.provenance.get("baseline", {})
    print(json.dumps({
        "artifact": str(out),
        "version": head.version,
        "threshold": head.threshold,
        "rules": int(len(head.rule_ids)),
        "fixed_fp": base.get("fixed", {}).get("fp"),
        "learned_fp": base.get("learned", {}).get("fp"),
        "new_fn_vs_fixed": base.get("new_fn_vs_fixed"),
    }, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
