"""Minimal object model for the control plane.

The reference consumes `networking.k8s.io/v1 Ingress` objects through
client-go informers and carries them as `pkg/apis/ingress/types.go†`
structs.  Here the same shapes as plain dataclasses, constructible from
k8s-style dicts (`Ingress.from_dict(yaml.safe_load(...))`) so tests and the
admission path can feed real manifests without a cluster.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Backend:
    """spec.rules[].http.paths[].backend.service analog."""

    service: str = ""
    port: int = 80

    @classmethod
    def from_dict(cls, d: dict) -> "Backend":
        svc = d.get("service", {})
        port = svc.get("port", {})
        return cls(service=svc.get("name", ""),
                   port=int(port.get("number", port.get("name", 0) or 0)))


@dataclass
class PathRule:
    path: str = "/"
    path_type: str = "Prefix"
    backend: Backend = field(default_factory=Backend)

    @classmethod
    def from_dict(cls, d: dict) -> "PathRule":
        return cls(path=d.get("path", "/"),
                   path_type=d.get("pathType", "Prefix"),
                   backend=Backend.from_dict(d.get("backend", {})))


@dataclass
class IngressRule:
    host: str = "_"
    paths: List[PathRule] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "IngressRule":
        http = d.get("http", {}) or {}
        return cls(host=d.get("host", "_") or "_",
                   paths=[PathRule.from_dict(p)
                          for p in http.get("paths", [])])


@dataclass
class Ingress:
    name: str = ""
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)
    rules: List[IngressRule] = field(default_factory=list)
    ingress_class: Optional[str] = None

    @property
    def key(self) -> str:
        return "%s/%s" % (self.namespace, self.name)

    @classmethod
    def from_dict(cls, d: dict) -> "Ingress":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            annotations=dict(meta.get("annotations", {}) or {}),
            rules=[IngressRule.from_dict(r) for r in spec.get("rules", [])],
            ingress_class=spec.get("ingressClassName"),
        )


@dataclass
class ConfigMap:
    """The controller's global ConfigMap (data: str→str)."""

    data: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "ConfigMap":
        return cls(data={k: str(v) for k, v in
                         (d.get("data", {}) or {}).items()})
