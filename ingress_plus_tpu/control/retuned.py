"""The continuous retune daemon: close the telemetry→compiler loop.

``tools/retune.py`` is the PR 15 hand-run tool: measured fleet profile
in, four-gate-vetted repacked ruleset out.  This module promotes it to
a long-running control loop (ROADMAP item 4, docs/RETUNE.md):

    watch /fleet/drift  →  pull the merged /fleet/profile  →
    four-gate retune    →  fleet-staged rollout (control/fleetctl.py)

Hands-free, and deliberately slow-twitch:

- **Rate limited** — at most one retune per ``min_interval_s``, and a
  ``cooldown_s`` freeze after ANY fleet rollback (a pack that just got
  rolled back fleet-wide must not be re-derived ten seconds later from
  the same telemetry that produced it).
- **Structured skips, not crashes** — no drift, an unavailable merged
  profile (e.g. a node serving a newer PROFILE_VERSION degrades the
  merge), a gate failure, or an admission rejection each journal a
  typed skip and leave the incumbent serving everywhere.
- **Journaled** — every cycle appends one JSON line to a bounded
  on-disk ledger (``retuned.jsonl``), so "why didn't the daemon act"
  is answerable after the fact (``dbg fleetctl`` renders the tail).

The ``retune_gate_fail`` fault site (utils/faults.py) forces the gate
verdict to failure — the acceptance drill for "a failed gate leaves
the incumbent serving" rides it.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from pathlib import Path
from typing import Dict, List, Optional

from ingress_plus_tpu.control.fleetctl import (
    FLEET_LIVE,
    FLEET_ROLLED_BACK,
    FleetController,
)
from ingress_plus_tpu.utils import faults

JOURNAL_NAME = "retuned.jsonl"

#: a drift is "actionable" when the fleet went-quiet union is non-empty
#: or any node reports a per-rule hit-rate delta at least this large
DRIFT_DELTA = 0.02

#: typed cycle results (the journal's ``result`` field)
SKIP_MIN_INTERVAL = "skip:min_interval"
SKIP_COOLDOWN = "skip:cooldown"
SKIP_NO_DRIFT = "skip:no_drift"
SKIP_NO_PROFILE = "skip:profile_unavailable"
SKIP_GATES = "skip:gates_failed"
SKIP_ADMISSION = "skip:admission_rejected"
ROLLOUT_LIVE = "rollout:fleet_live"
ROLLOUT_ROLLED_BACK = "rollout:rolled_back"
ROLLOUT_STALLED = "rollout:stalled"
CYCLE_ERROR = "error"


class RetuneDaemon:
    """One watcher, one fleet.  ``cycle()`` is the unit of work (the
    drill and the fault matrix call it directly); ``run_forever()``
    is the deployed daemon loop."""

    def __init__(self, observer, fleet: FleetController,
                 journal_dir,
                 rules: Optional[List[str]] = None,
                 min_interval_s: float = 600.0,
                 cooldown_s: float = 1800.0,
                 drift_delta: float = DRIFT_DELTA,
                 rollout_deadline_s: float = 300.0,
                 retune_kw: Optional[dict] = None,
                 max_journal_entries: int = 512,
                 clock=time.monotonic):
        self.observer = observer        # FleetObserver (or API twin)
        self.fleet = fleet
        self.journal_path = Path(journal_dir) / JOURNAL_NAME
        self.journal_path.parent.mkdir(parents=True, exist_ok=True)
        self.rules = rules              # parsed rules | None = bundled pack
        self.min_interval_s = min_interval_s
        self.cooldown_s = cooldown_s
        self.drift_delta = drift_delta
        self.rollout_deadline_s = rollout_deadline_s
        self.retune_kw = dict(retune_kw or {})
        self.max_journal_entries = max_journal_entries
        self.clock = clock
        self.cycles = 0
        self.retunes = 0
        self.last_cycle: Optional[dict] = None
        self._last_retune_at: Optional[float] = None
        self._cooldown_until: Optional[float] = None

    # ------------------------------------------------------- journal

    #: no journal record serializes under this many bytes — lets a
    #: stat() rule out truncation without reading the ledger
    _MIN_ENTRY_BYTES = 32

    def _journal(self, rec: dict) -> None:
        """Append one cycle record; rewrite keeping the newest half
        when the ledger exceeds its bound (bounded disk, ISSUE 19)."""
        rec = {"at": time.time(), **rec}
        try:
            with self.journal_path.open("a") as f:
                f.write(json.dumps(rec) + "\n")
            self._maybe_truncate()
        except OSError:
            pass  # the ledger is observability, not a serving dependency

    def _maybe_truncate(self) -> None:
        """Halve the ledger once it exceeds its entry bound.  The
        read-truncate-replace pass runs only when a cheap size check
        says it is due, and under an O_EXCL lock — a second writer
        (``--once`` beside the daemon, same shared lkg_dir) appending
        mid-rewrite must not have its record silently replaced away."""
        if (self.journal_path.stat().st_size
                <= self.max_journal_entries * self._MIN_ENTRY_BYTES):
            return
        lock = self.journal_path.with_suffix(".lock")
        try:
            os.close(os.open(str(lock),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        except OSError:
            try:  # a writer that crashed mid-pass must not leak the
                if time.time() - lock.stat().st_mtime > 60.0:  # lock
                    lock.unlink()
            except OSError:
                pass
            return  # another writer is truncating; ours lands next pass
        try:
            lines = self.journal_path.read_text().splitlines()
            if len(lines) > self.max_journal_entries:
                keep = lines[-self.max_journal_entries // 2:]
                tmp = self.journal_path.with_suffix(".tmp")
                tmp.write_text("\n".join(keep) + "\n")
                tmp.replace(self.journal_path)
        finally:
            try:
                lock.unlink()
            except OSError:
                pass

    def journal_tail(self, n: int = 16) -> List[dict]:
        try:
            lines = self.journal_path.read_text().splitlines()
        except OSError:
            return []
        out = []
        for line in lines[-n:]:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out

    # ------------------------------------------------------- signals

    def _drift_reason(self) -> Optional[str]:
        """An actionable-drift probe over /fleet/drift, or None."""
        try:
            d = self.observer.fleet_drift()
        except Exception:  # noqa: BLE001 — unreachable fleet = no signal
            return None
        quiet = d.get("fleet_went_quiet") or []
        if quiet:
            return "fleet_went_quiet:%d rules" % len(quiet)
        worst = 0.0
        for name, rep in (d.get("nodes") or {}).items():
            for row in (rep.get("rules") or []):
                worst = max(worst, abs(float(row.get("delta", 0.0))))
        if worst >= self.drift_delta:
            return "hit_rate_delta:%.4f" % worst
        return None

    def _profile(self):
        """(profile, error) — the merged fleet profile or the typed
        reason it is unavailable (a node publishing a newer
        PROFILE_VERSION already degraded to merge-over-the-rest or an
        explicit error inside the observer; both surface here as a
        structured skip, never a crashed cycle)."""
        try:
            prof = self.observer.merged_profile()
        except Exception as e:  # noqa: BLE001 — daemon must not crash
            return None, "observer error: %s" % e
        if prof is None:
            err = ""
            try:
                err = self.observer.healthz().get(
                    "merged_profile", {}).get("error", "")
            except Exception:
                pass
            return None, err or "no merged profile"
        return prof, ""

    # ------------------------------------------------------- the cycle

    def cycle(self, force: bool = False) -> dict:
        """One daemon cycle.  Returns (and journals) the typed record;
        never raises.  ``force`` skips the rate limiter and the drift
        check (operator break-glass / drill hook), NOT the gates."""
        self.cycles += 1
        now = self.clock()
        rec: Dict = {"cycle": self.cycles, "result": "", "detail": ""}
        try:
            rec.update(self._cycle_inner(now, force))
        except Exception as e:  # noqa: BLE001 — the loop must survive
            rec["result"] = CYCLE_ERROR
            rec["detail"] = "%s: %s" % (type(e).__name__, e)
        self.last_cycle = rec
        self._journal(rec)
        return rec

    def _cycle_inner(self, now: float, force: bool) -> dict:
        if (self._cooldown_until is not None
                and now < self._cooldown_until):
            return {"result": SKIP_COOLDOWN,
                    "detail": "%.0fs left after a fleet rollback"
                              % (self._cooldown_until - now)}
        if not force:
            if (self._last_retune_at is not None
                    and now - self._last_retune_at < self.min_interval_s):
                return {"result": SKIP_MIN_INTERVAL,
                        "detail": "%.0fs since last retune"
                                  % (now - self._last_retune_at)}
            drift = self._drift_reason()
            if drift is None:
                return {"result": SKIP_NO_DRIFT, "detail": ""}
        else:
            drift = "forced"
        prof, perr = self._profile()
        if prof is None:
            return {"result": SKIP_NO_PROFILE, "detail": perr,
                    "drift": drift}
        self._last_retune_at = now
        self.retunes += 1

        # tools/ is scripts, not a package — same import dance as
        # tools/lint.py's retunegate.
        import sys

        tools_dir = str(Path(__file__).resolve().parents[2] / "tools")
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from retune import retune

        report = retune(rules=self.rules, profile=prof,
                        **self.retune_kw)
        cr = report.pop("_retuned_cr", None)
        gates: Dict = {"ok": bool(report.get("ok"))}
        infl = report.get("inflation") or {}
        if isinstance(infl.get("retuned"), dict):
            gates["lost_candidates"] = infl["retuned"].get("lost_candidates")
        replay = report.get("replay") or {}
        gates["replay_new_fns"] = replay.get("new_fns")
        if isinstance(report.get("rollout"), dict):
            gates["staged_state"] = report["rollout"].get("state")
        if faults.fire("retune_gate_fail"):
            report["ok"] = False
            gates["ok"] = False
            gates["injected"] = True
        if not report.get("ok") or cr is None:
            return {"result": SKIP_GATES, "drift": drift,
                    "gates": gates,
                    "detail": "retune gates failed; incumbent stays"}
        incumbent = self.fleet.nodes[0].serving_version
        if cr.version == incumbent:
            return {"result": SKIP_NO_DRIFT, "drift": drift,
                    "detail": "retuned pack == incumbent %s" % incumbent}
        admission = self.fleet.begin(ruleset=cr)
        if not admission.get("ok"):
            return {"result": SKIP_ADMISSION, "drift": drift,
                    "gates": gates,
                    "detail": admission.get("reason", "rejected")}
        state = self.fleet.drive(deadline_s=self.rollout_deadline_s)
        out = {"drift": drift, "gates": gates,
               "candidate": cr.version, "fleet_state": state}
        if state == FLEET_LIVE:
            out["result"] = ROLLOUT_LIVE
        elif state == FLEET_ROLLED_BACK:
            out["result"] = ROLLOUT_ROLLED_BACK
            out["detail"] = self.fleet.rollback_reason
            self._cooldown_until = self.clock() + self.cooldown_s
        else:
            out["result"] = ROLLOUT_STALLED
            out["detail"] = "state %s at deadline" % state
        return out

    # ------------------------------------------------------- lifecycle

    def status(self) -> dict:
        now = self.clock()
        return {
            "cycles": self.cycles,
            "retunes": self.retunes,
            "min_interval_s": self.min_interval_s,
            "cooldown_s": self.cooldown_s,
            "cooldown_left_s": (
                max(0.0, self._cooldown_until - now)
                if self._cooldown_until is not None else 0.0),
            "last_cycle": self.last_cycle,
            "journal": str(self.journal_path),
        }

    def run_forever(self, poll_s: float = 30.0,
                    stop_event=None) -> None:
        import threading

        stop = stop_event or threading.Event()
        while not stop.is_set():
            self.cycle()
            stop.wait(poll_s)


def main(argv=None) -> None:
    """Deployed daemon: HTTP nodes + the fleet aggregator's /fleet
    surfaces.  (In-process fleets wire RetuneDaemon directly.)"""
    from ingress_plus_tpu.control.fleetctl import HttpFleetNode

    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.control.retuned")
    ap.add_argument("--fleet-url", default="127.0.0.1:9911",
                    help="fleet aggregator host:port (/fleet/* surfaces)")
    ap.add_argument("--node", action="append", default=[],
                    metavar="NAME=HOST:PORT", required=False,
                    help="one serve node's HTTP plane; repeatable")
    ap.add_argument("--lkg-dir", required=True,
                    help="shared fleet LKG dir (journal + pointer + packs)")
    ap.add_argument("--poll-s", type=float, default=30.0)
    ap.add_argument("--min-interval-s", type=float, default=600.0)
    ap.add_argument("--cooldown-s", type=float, default=1800.0)
    ap.add_argument("--once", action="store_true",
                    help="run one cycle and print its record")
    ap.add_argument("--force", action="store_true",
                    help="skip the rate limiter and drift check once")
    args = ap.parse_args(argv)

    class _HttpFleetSurfaces:
        """Minimal observer twin over the aggregator's HTTP plane."""

        def __init__(self, target: str):
            self.target = target

        def _get(self, path: str) -> dict:
            import urllib.request

            with urllib.request.urlopen(
                    "http://%s%s" % (self.target, path), timeout=10) as r:
                return json.loads(r.read())

        def fleet_drift(self) -> dict:
            return self._get("/fleet/drift")

        def healthz(self) -> dict:
            return self._get("/fleet/healthz")

        def merged_profile(self):
            from ingress_plus_tpu.compiler.profile import MeasuredProfile

            try:
                return MeasuredProfile.from_dict(
                    self._get("/fleet/profile"))
            except Exception:
                return None

    nodes = []
    for spec in args.node:
        name, sep, target = spec.partition("=")
        if not sep:
            ap.error("--node wants NAME=HOST:PORT, got %r" % spec)
        nodes.append(HttpFleetNode(name, target))
    if not nodes:
        ap.error("the daemon needs at least one --node to roll packs to")
    fleet = FleetController(nodes, args.lkg_dir)
    fleet.recover()
    daemon = RetuneDaemon(_HttpFleetSurfaces(args.fleet_url), fleet,
                          args.lkg_dir,
                          min_interval_s=args.min_interval_s,
                          cooldown_s=args.cooldown_s)
    if args.once:
        print(json.dumps(daemon.cycle(force=args.force), indent=2))
        return
    daemon.run_forever(poll_s=args.poll_s)


if __name__ == "__main__":
    main()
