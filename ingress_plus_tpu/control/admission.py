"""Admission validation: reject broken Ingress objects before they sync.

Reference: `internal/admission/controller/`† — the validating webhook
extracts annotations **strict**, merges the candidate Ingress into the
current model, renders, and runs `nginx -t` on the result; any failure
rejects the object so a typo can't take down the data plane.

The `nginx -t` analog here is a structural lint of the rendered text
(balanced braces, every directive line terminated, no unrendered
placeholders) plus the strict annotation pass — the same code path the
runtime uses lenient.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ingress_plus_tpu.control.annotations import AnnotationError, Extractor
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.model import build_configuration
from ingress_plus_tpu.control.objects import ConfigMap, Ingress
from ingress_plus_tpu.control.template import render


@dataclass
class Review:
    allowed: bool
    messages: List[str] = field(default_factory=list)


def lint_rendered(text: str) -> List[str]:
    """The `nginx -t` stand-in: structural checks on rendered config."""
    problems = []
    depth = 0
    for n, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        depth += s.count("{") - s.count("}")
        if depth < 0:
            problems.append("line %d: unbalanced '}'" % n)
            depth = 0
        if (s and not s.startswith("#") and not s.endswith(("{", "}"))
                and not s.endswith(";")):
            problems.append("line %d: unterminated directive: %r" % (n, s))
    if depth != 0:
        problems.append("unbalanced '{' (%d unclosed)" % depth)
    return problems


def validate(candidate: Ingress,
             existing: Optional[List[Ingress]] = None,
             configmap: Optional[ConfigMap] = None) -> Review:
    g = (GlobalConfig.from_configmap(configmap) if configmap
         else GlobalConfig())
    # 1. strict annotation extraction — first bad value rejects
    try:
        Extractor(strict=True).extract(candidate)
    except AnnotationError as e:
        return Review(allowed=False, messages=[str(e)])

    # 2. dry-run render of the would-be full model
    merged = [i for i in (existing or []) if i.key != candidate.key]
    merged.append(candidate)
    cfg = build_configuration(merged, g)
    text = render(cfg, g)
    problems = lint_rendered(text)
    # Only errors attributable to the CANDIDATE reject it: a pre-existing
    # Ingress with a bad annotation (created before the webhook, or while
    # it was down) must not deadlock admission of every other object.
    # Extractor errors are prefixed with the owning ingress key.
    problems.extend(e for e in cfg.errors
                    if e.startswith(candidate.key + ":"))
    if problems:
        return Review(allowed=False, messages=problems)
    return Review(allowed=True)
