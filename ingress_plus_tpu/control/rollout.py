"""Guarded ruleset rollout — admission-gated staged swaps (docs/ROBUSTNESS.md).

PR 4 made the data plane fail-safe; this module makes the CONTROL plane
fail-safe.  The one-shot hot-swap (`/configuration/ruleset`) put any
pack that merely loads in front of 100% of live traffic instantly — a
pack with dead regexes, an over-blocking rewrite or a latency-regressing
compile shipped with no gate, no ramp and no way back.  The sync-node†
contract (continuous ruleset delivery into a node serving live traffic)
only holds if a rollout can never take detection quality or availability
down with it.

``RolloutController`` owns a staged state machine:

    IDLE ──admit()──▶ ADMITTED ─▶ SHADOW ─▶ CANARY ─▶ LIVE
                 │                   │          │
                 ▼                   ▼          ▼
              REJECTED           REJECTED   ROLLED_BACK

* **Admission gate** — before a candidate touches any traffic it must
  pass (1) the static analyzers that work on a compiled pack (the
  rulecheck subset: prefilter-soundness audit, regex hazards incl.
  confirm-unparsable dead rules, transform-lane consistency — severity
  gated by ``fail_on``, baseline-suppressed like the CI gate), (2) a
  compile smoke on the live serving-engine geometry (same engine kind,
  live pipeline's warm shapes), and (3) a golden-corpus replay (attack
  corpus + hand-authored benign fixtures) whose verdict diff vs the
  incumbent is thresholded: new false-negatives and new benign blocks
  each gate independently.  A rejected pack changes NOTHING — the
  incumbent keeps serving and the caller gets a structured rejection
  report (stage, reason, artifact); ``ipt_swap_rejected_total{reason=}``.

* **Shadow phase** — the candidate runs on a sampled mirror of real
  admitted traffic in a CPU-only side lane (``detect_cpu_only``: never
  the device lane, never the verdict path).  The lane is budget-capped
  (bounded queue + CPU-time token budget) so shadow work can never
  starve the breaker's CPU fallback.  The live verdict diff accumulates
  as ``ipt_rollout_diff_total{kind=new_block|lost_hit|score_delta}``.

* **Canary ramp** — a per-request generation split (deterministic
  request-id hash, so a request's generation never flaps) ramps through
  ``steps`` (1% → 10% → 50% → 100% by default).  Rollback triggers are
  evaluated per step: candidate confirm-error spike, runtime-dead jump
  (the PR 3 drift signal), candidate dispatch failures/hangs, candidate
  fail-open events, or verdict diff beyond threshold → automatic
  rollback to the incumbent; the failed pack is quarantined and the
  reason exported.  The incumbent never stopped serving its share, so
  rollback is simply "stop routing to the candidate".

* **Last-known-good** — every pack that reaches LIVE is persisted
  atomically (version-named artifact, write-then-rename, then an
  atomically replaced ``LKG`` pointer file) into ``lkg_dir``.  On
  startup the server prefers the LKG artifact over a possibly
  mid-rollout pack, so a crash during rollout restarts serving the last
  pack that actually survived traffic (``load_lkg``; the
  ``lkg_corrupt`` fault site exercises the corrupt-pointer fallback).

Break-glass: ``/configuration/ruleset?mode=force`` keeps the old
one-shot semantics (an active rollout is aborted first).  ``dbg
rollout`` renders the state; ``run_swap_drill()`` is the CI harness
behind the ``swapdrill`` gate (tools/lint.py --ci).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from queue import Empty, Full, Queue
from typing import Dict, List, Optional, TYPE_CHECKING, Tuple

if TYPE_CHECKING:
    from ingress_plus_tpu.serve.batcher import Batcher

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.trace import (
    EV_SHADOW,
    flight,
    named_rlock,
)

#: rollout phases (ipt_rollout_state exports the index)
STATES = ("idle", "admitted", "shadow", "canary", "live", "rejected",
          "rolled_back")

IDLE, ADMITTED, SHADOW, CANARY, LIVE, REJECTED, ROLLED_BACK = STATES


class RolloutRejected(Exception):
    """A candidate pack failed a rollout gate; nothing changed.

    Carries the structured rejection report the serve endpoint returns
    verbatim (stage, reason, artifact, detail)."""

    def __init__(self, stage: str, reason: str, artifact: str = "",
                 detail=None):
        super().__init__("%s: %s" % (stage, reason))
        self.report = {"stage": stage, "reason": reason,
                       "artifact": artifact, "detail": detail}


@dataclass
class RolloutConfig:
    """Knobs for the guarded rollout (serve CLI: --rollout-*, --lkg-dir).

    The admission thresholds default to zero tolerance: a candidate that
    loses ANY golden-corpus attack or blocks ANY benign fixture the
    incumbent passes is rejected — relaxing that is an explicit operator
    decision, not a default."""

    #: static-gate severity (the rulecheck --fail-on analog)
    fail_on: str = "error"
    #: canary traffic fractions, ramped in order; last step should be 1.0
    steps: Tuple[float, ...] = (0.01, 0.10, 0.50, 1.0)
    #: candidate-served requests required per step before advancing
    step_min_requests: int = 200
    #: mirrored requests required before shadow promotes to canary
    shadow_min_requests: int = 64
    #: fraction of admitted traffic mirrored into the shadow lane
    shadow_sample: float = 0.25
    #: bounded shadow queue — overflow drops (counted), never blocks
    shadow_queue_cap: int = 256
    #: CPU-time budget for the shadow worker as a fraction of wall time;
    #: over budget the mirror drops instead of scanning (the breaker's
    #: CPU fallback shares these cores and must win)
    shadow_cpu_budget: float = 0.25
    #: golden-corpus replay size (attacks; benign fixtures ride along)
    corpus_n: int = 192
    #: admission replay thresholds (counts, not fractions: zero default)
    max_new_fn: int = 0
    max_new_benign_blocks: int = 0
    #: live rollback triggers (shadow + canary)
    max_confirm_errors: int = 0
    max_runtime_dead_jump: int = 0
    max_candidate_failures: int = 0
    max_candidate_fail_open: int = 0
    #: live verdict-diff rollback: (new_block + lost_hit) / compared
    max_diff_frac: float = 0.02
    #: mirrored verdicts required before the diff fraction can trigger
    diff_min_compared: int = 50
    #: scoring-head admission: minimum fraction of the head's rule-id
    #: map found in the live pack (a head trained against a different
    #: pack generation scores with silently-missing features below this)
    scorer_min_coverage: float = 0.90
    #: last-known-good artifact directory (None disables persistence)
    lkg_dir: Optional[str] = None


def validate_overrides(raw: dict) -> dict:
    """Validate per-rollout config overrides (the admit payload's knob
    surface).  Everything is checked BEFORE any state mutates — a bad
    value raises ValueError and the rollout config is untouched (an
    unvalidated steps list reaching ``split()`` would kill the dispatch
    thread)."""
    out: dict = {}
    for k, v in raw.items():
        if k == "steps":
            try:
                steps = tuple(float(s) for s in v)
            except (TypeError, ValueError):
                raise ValueError("steps must be a list of numbers")
            if not steps or any(not 0.0 < s <= 1.0 for s in steps) \
                    or list(steps) != sorted(steps) or steps[-1] != 1.0:
                raise ValueError(
                    "steps must ascend within (0, 1] and end at 1.0")
            out[k] = steps
        elif k in ("step_min_requests", "shadow_min_requests"):
            iv = int(v)
            if iv < 1:
                raise ValueError("%s must be >= 1" % k)
            out[k] = iv
        elif k == "shadow_sample":
            fv = float(v)
            if not 0.0 <= fv <= 1.0:
                raise ValueError("shadow_sample must be in [0, 1]")
            out[k] = fv
        else:
            raise ValueError("unknown rollout override %r" % k)
    return out


def _hash_frac(request_id: str) -> float:
    """Deterministic [0, 1) per request id.  Monotone ramp: the set of
    ids below fraction f1 is a subset of those below f2 > f1, so growing
    the step only MOVES traffic incumbent→candidate, never back."""
    return (zlib.crc32(request_id.encode("utf-8", "surrogateescape"))
            & 0xFFFFFFFF) / 4294967296.0


def _runtime_dead(pipeline) -> int:
    rs = pipeline.rule_stats
    return int(((rs.candidates > 0) & rs.broken).sum())


# ----------------------------------------------------------- LKG store
# Version-named artifacts + an atomically replaced pointer file: a crash
# at ANY instant leaves the pointer naming a complete artifact pair (the
# new pair lands under a new name before the pointer moves).

LKG_POINTER = "LKG"


def persist_lkg(cr: CompiledRuleset, lkg_dir: str | Path,
                keep: int = 2) -> Path:
    """Atomically persist ``cr`` as the last-known-good pack."""
    d = Path(lkg_dir)
    d.mkdir(parents=True, exist_ok=True)
    version = cr.version or cr.fingerprint()
    base = d / ("pack-%s" % version)
    tmp = d / (".tmp-%s" % version)
    cr.save(tmp)   # writes .npz + .json
    os.replace(tmp.with_suffix(".npz"), base.with_suffix(".npz"))
    os.replace(tmp.with_suffix(".json"), base.with_suffix(".json"))
    ptr_tmp = d / (LKG_POINTER + ".tmp")
    ptr_tmp.write_text(json.dumps({"artifact": base.name,
                                   "version": version}))
    os.replace(ptr_tmp, d / LKG_POINTER)
    # retire old generations (never the one just written)
    packs = sorted((p for p in d.glob("pack-*.json") if p.stem != base.stem),
                   key=lambda p: p.stat().st_mtime)
    for p in packs[:max(0, len(packs) - (keep - 1))]:
        p.unlink(missing_ok=True)
        p.with_suffix(".npz").unlink(missing_ok=True)
    return base


def load_lkg(lkg_dir: str | Path) -> Optional[CompiledRuleset]:
    """Load the last-known-good pack, or None when there is none or it
    is unreadable (corrupt pointer/artifact — the caller falls back to
    its configured rules source; serving must start either way)."""
    d = Path(lkg_dir)
    ptr = d / LKG_POINTER
    if not ptr.is_file():
        return None
    try:
        faults.raise_if("lkg_corrupt")
        meta = json.loads(ptr.read_text())
        return CompiledRuleset.load(d / meta["artifact"])
    except Exception:
        return None


# ------------------------------------------------------- the controller


class RolloutController:
    """Owns the staged rollout state machine; attached to a Batcher as
    ``batcher.rollout``.  The batcher's dispatch thread consults only
    two torn-free bool flags on its clean path (``shadow_active`` /
    ``canary_active``) — an idle controller costs two attribute reads
    per cycle.  State transitions serialize on ``_lock``; the candidate
    pipeline is installed/cleared only under the batcher's swap lock so
    the dispatch thread never sees a half-built generation."""

    def __init__(self, batcher: "Batcher",
                 config: Optional[RolloutConfig] = None):
        self.batcher = batcher
        # _base_config is the attached default; each admit() derives its
        # EFFECTIVE config from it (base + that push's overrides), so an
        # override never leaks into the next rollout
        self._base_config = config or RolloutConfig()
        self.config = self._base_config
        self.state = IDLE
        self.candidate = None            # DetectionPipeline | None
        self.candidate_version = ""
        #: what kind of artifact is rolling out: "ruleset" | "scorer"
        self.candidate_kind = ""
        #: the candidate pipeline's generation tag (ruleset version, or
        #: ruleset+head for a scoring rollout) — what its verdicts are
        #: stamped with; the mirror's self-diff skip keys on THIS, not
        #: on candidate_version (a scoring candidate's version is the
        #: head's, but its verdicts carry the combined tag)
        self.candidate_generation = ""
        self.candidate_artifact = ""     # source path ("" = in-memory)
        self._candidate_cr = None        # CompiledRuleset for LKG persist
        self._candidate_head = None      # ScoringHead for scorer LKG
        self.step_idx = 0
        self.step_served = 0
        self.started_at = 0.0
        self.rollback_reason = ""
        # flags the dispatch thread reads without the lock
        self.shadow_active = False
        self.canary_active = False
        # counters (exported at /metrics and /rollout)
        self.swap_rejected: Dict[str, int] = {}
        self.diff: Dict[str, int] = {"new_block": 0, "lost_hit": 0,
                                     "score_delta": 0}
        self.shadow_mirrored = 0
        self.shadow_compared = 0
        self.shadow_dropped = 0
        self.candidate_requests = 0      # canary-served total
        self.candidate_failures = 0      # dispatch errors/hangs
        self.candidate_fail_open = 0
        self.rollbacks = 0
        self.promotions = 0
        self.last_admission: Optional[dict] = None
        self.history: List[dict] = []    # bounded event log
        # REENTRANT: the accounting helpers below (_event,
        # count_rejected, the shadow/canary counters) serialize on this
        # lock and are called both bare and from under it — concheck
        # found the bare counter bumps racing the shadow thread
        # (conc.unguarded-mutation, ISSUE 11)
        self._lock = named_rlock("RolloutController._lock")
        # shadow lane: bounded queue + one CPU worker + token budget
        self._shadow_q: "Queue" = Queue(maxsize=self.config.shadow_queue_cap)
        self._shadow_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._budget_s = 0.0             # earned CPU seconds (token bucket)
        self._budget_at = time.monotonic()
        self._dead_baseline = 0          # incumbent runtime-dead at admit
        self._admitting = False          # one admission at a time
        # promotion is DEFERRED to tick(): _evaluate can run on the
        # dispatch thread while it holds the batcher's swap lock, and
        # promote() needs that same (non-reentrant) lock — the batcher
        # calls tick() once per cycle after releasing it
        self._promote_pending = False

    # ------------------------------------------------------- accounting

    def _event(self, kind: str, **kw) -> None:
        with self._lock:
            self.history.append({"ts": time.time(), "event": kind, **kw})
            del self.history[:-64]

    def count_rejected(self, reason: str) -> None:
        """Also used by the serve endpoint for force-mode load failures
        (the ``ipt_swap_rejected_total{reason="load"}`` satellite)."""
        with self._lock:
            self.swap_rejected[reason] = \
                self.swap_rejected.get(reason, 0) + 1

    def _reject(self, stage: str, reason: str, detail=None) -> None:
        self.count_rejected(reason)
        with self._lock:
            self.state = REJECTED
            self._clear_candidate()
        self._event("rejected", stage=stage, reason=reason)
        raise RolloutRejected(stage, reason, self.candidate_artifact, detail)

    def _clear_candidate(self) -> None:
        """Under _lock: drop the candidate generation.  Flags first —
        the dispatch thread must stop routing before the pipeline ref
        goes (it re-reads ``self.candidate`` per cycle either way)."""
        self.shadow_active = False
        self.canary_active = False
        # a rolled-back candidate's verdict-cache entries (shadow /
        # canary traffic) must not outlive it — quarantine hygiene
        if self.candidate is not None and \
                getattr(self.candidate, "confirm_cache", None) is not None:
            self.candidate.confirm_cache.invalidate("rollback")
        self.candidate = None
        self._candidate_cr = None
        self._candidate_head = None

    # -------------------------------------------------------- admission

    def admit(self, artifact_path: Optional[str] = None,
              ruleset: Optional[CompiledRuleset] = None,
              paranoia_level: Optional[int] = None,
              overrides: Optional[dict] = None) -> dict:
        """Run the full admission gate and start the shadow phase.

        Raises ``RolloutRejected`` (nothing changed) on any gate
        failure; returns the admission report on success.  ``overrides``
        (validated per-rollout config knobs: steps, step_min_requests,
        shadow_min_requests, shadow_sample) are applied only once the
        in-progress check has passed — a rejected concurrent admit must
        never mutate the ACTIVE rollout's config."""
        if ruleset is None and artifact_path is None:
            raise ValueError("admit() needs an artifact path or a ruleset")
        overrides = validate_overrides(overrides or {})
        with self._lock:
            if self.state in (SHADOW, CANARY) or self._admitting:
                raise RolloutRejected(
                    "admission", "rollout_in_progress", self.candidate_artifact,
                    {"active_candidate": self.candidate_version})
            self._admitting = True
            # effective config for THIS rollout only: base + overrides
            # (a fresh copy even with no overrides, so a previous
            # push's knobs never survive into this one)
            from dataclasses import replace as _dc_replace
            self.config = _dc_replace(self._base_config, **overrides)
        try:
            return self._admit_inner(artifact_path, ruleset, paranoia_level)
        finally:
            with self._lock:
                self._admitting = False

    def _admit_inner(self, artifact_path, ruleset, paranoia_level) -> dict:
        with self._lock:
            self.candidate_artifact = str(artifact_path or "")
        # stage 1: load ----------------------------------------------------
        if ruleset is None:
            try:
                ruleset = CompiledRuleset.load(artifact_path)
            except Exception as e:
                self._reject("load", "load",
                             {"error": "%s: %s" % (type(e).__name__, e)})
        live = self.batcher.pipeline
        if ruleset.version and ruleset.version == live.ruleset.version:
            self._reject("load", "already_live",
                         {"version": ruleset.version})
        # stage 2: static gate (the compiled-pack rulecheck subset) --------
        findings = self._static_gate(ruleset)
        if findings:
            self._reject("static", "rulecheck", {
                "findings": [{"check": f.check, "severity": f.severity,
                              "rule_id": f.rule_id, "message": f.message}
                             for f in findings[:16]],
                "count": len(findings)})
        # stage 3: compile smoke on the live engine geometry ---------------
        try:
            candidate = self._build_candidate(ruleset, paranoia_level)
        except Exception as e:
            self._reject("compile", "compile_smoke",
                         {"error": "%s: %s" % (type(e).__name__, e)})
        # stage 4: golden-corpus replay diff -------------------------------
        replay = self._replay_diff(live, candidate)
        if replay["new_fns"] > self.config.max_new_fn:
            self._reject("replay", "new_fns", replay)
        if replay["benign_new_blocks"] > self.config.max_new_benign_blocks:
            self._reject("replay", "benign_blocks", replay)
        # admitted: adopt the node-wide pressure/counter planes (the
        # cumulative Prometheus counters span generations by design; the
        # brownout ladder is a node signal, not a generation's), zero the
        # replay out of the per-rule telemetry, then open the shadow lane
        candidate.reset_detection_observations()
        candidate.stats = live.stats
        candidate.load_controller = live.load_controller
        report = {
            "state": SHADOW,
            "candidate": ruleset.version,
            "incumbent": live.ruleset.version,
            "artifact": self.candidate_artifact,
            "replay": replay,
        }
        self._enter_admitted(candidate, ruleset.version, "ruleset",
                             report, cr=ruleset)
        self._event("admitted", candidate=ruleset.version)
        return report

    def _enter_admitted(self, candidate, version: str, kind: str,
                        report: dict, cr=None, head=None) -> None:
        """Shared ADMITTED-state install for both artifact kinds
        (ruleset packs and scoring heads): every per-rollout counter
        and baseline resets in ONE place under the lock, then the
        shadow lane opens — a counter added for one kind can never
        leak stale values into the other's next rollout."""
        live = self.batcher.pipeline
        with self._lock:
            self.state = ADMITTED
            self.candidate = candidate
            self._candidate_cr = cr
            self._candidate_head = head
            self.candidate_version = version
            self.candidate_kind = kind
            self.candidate_generation = candidate.generation_tag
            self.step_idx = 0
            self.step_served = 0
            self.candidate_requests = 0
            self.candidate_failures = 0
            self.candidate_fail_open = 0
            self.shadow_mirrored = self.shadow_compared = 0
            self.shadow_dropped = 0
            self.diff = {"new_block": 0, "lost_hit": 0, "score_delta": 0}
            self.rollback_reason = ""
            self._promote_pending = False
            self.started_at = time.time()
            self._dead_baseline = _runtime_dead(live)
            self.last_admission = report
            self._start_shadow_locked()

    def _static_gate(self, ruleset: CompiledRuleset) -> list:
        """The rulecheck checks that run on a COMPILED pack (no SecLang
        source needed): prefilter soundness, regex hazards (incl. the
        confirm-unparsable silently-dead class), transform lanes.
        Baseline suppression mirrors the CI gate: the artifact's own
        baseline when shipped next to it, else the bundled CRS one."""
        from ingress_plus_tpu.analysis import BUNDLED_RULES
        from ingress_plus_tpu.analysis.findings import Baseline, _SEV_RANK
        from ingress_plus_tpu.analysis.lanecheck import check_lanes
        from ingress_plus_tpu.analysis.prefilter_audit import audit_prefilter
        from ingress_plus_tpu.analysis.redos import check_regex_hazards

        findings = []
        findings += audit_prefilter(ruleset.rules, ruleset.tables)
        findings += check_regex_hazards(ruleset.rules)
        findings += check_lanes(ruleset.rules)
        baseline = None
        if self.candidate_artifact:
            cand = Path(self.candidate_artifact).parent \
                / "rulecheck-baseline.json"
            if cand.is_file():
                baseline = cand
        if baseline is None:
            bundled = BUNDLED_RULES / "rulecheck-baseline.json"
            baseline = bundled if bundled.is_file() else None
        if baseline is not None:
            Baseline.load(baseline).apply(findings)
        rank = _SEV_RANK.get(self.config.fail_on, 0)
        return [f for f in findings
                if not f.suppressed and _SEV_RANK[f.severity] <= rank]

    def _build_candidate(self, ruleset: CompiledRuleset,
                         paranoia_level: Optional[int]):
        """Compile smoke: the candidate pipeline on the SAME engine kind
        as the live one (a mesh engine stays mesh), warmed on the live
        pipeline's served shapes, then one real detect — the multi-
        second XLA compiles happen HERE, on the admission thread, never
        in front of canary traffic."""
        from ingress_plus_tpu.models.pipeline import DetectionPipeline
        from ingress_plus_tpu.utils.corpus import generate_corpus

        live = self.batcher.pipeline
        candidate = DetectionPipeline(
            ruleset, mode=live.mode,
            anomaly_threshold=None,   # pack config > incumbent's value
            fail_open=live.fail_open, paranoia_level=paranoia_level,
            # enforcement state rides along: the ACL store is SHARED
            # (live /configuration/acl pushes apply to both generations
            # mid-rollout), bindings are copied at admission — a canary
            # must never un-deny a blocked source
            acl_store=live.acl_store,
            tenant_acl=dict(live.tenant_acl),
            default_acl=live.default_acl,
            # an installed learned head rides a ruleset rollout (rule-id
            # remap re-binds it to the candidate pack's axis) — a pack
            # promote must not silently drop the scoring model
            scoring_head=live.scoring_head,
            engine=live.engine.rebuilt(ruleset))
        # tenant (EP) rule subsets re-derived against the CANDIDATE's
        # rule axis (the same derivation a promote/swap runs)
        tags = getattr(self.batcher, "tenant_tags", None)
        if tags:
            from ingress_plus_tpu.control.sync import tenant_masks
            candidate.tenant_rule_mask = tenant_masks(ruleset, tags)
        for shape in sorted(getattr(live, "seen_shapes", ())):
            candidate.warm_shape(*shape)
        smoke = [lr.request for lr in generate_corpus(n=4, seed=7)]
        verdicts = candidate.detect_strict(smoke)
        if len(verdicts) != len(smoke):
            raise RuntimeError("smoke detect returned %d verdicts for %d "
                               "requests" % (len(verdicts), len(smoke)))
        return candidate

    def _replay_diff(self, live, candidate) -> dict:
        """Golden-corpus replay: attack corpus + benign fixtures through
        both generations, CPU confirm lane only (``detect_cpu_only`` is
        parity-tested exact and touches no device).  The incumbent runs
        as a detached twin sharing the live ENGINE (unused on this path)
        but never the live stats — admission must not pollute the
        serving telemetry."""
        from ingress_plus_tpu.models.pipeline import DetectionPipeline
        from ingress_plus_tpu.utils.benign_fixtures import fixture_requests
        from ingress_plus_tpu.utils.corpus import generate_corpus

        twin = DetectionPipeline(
            live.ruleset, mode="block",
            anomaly_threshold=live.anomaly_threshold,
            # the twin IS the incumbent scorer: a scoring-head rollout
            # diffs learned-vs-learned (or learned-vs-fixed) exactly as
            # live traffic would see it
            scoring_head=live.scoring_head,
            engine=live.engine)
        labeled = generate_corpus(n=self.config.corpus_n,
                                  attack_fraction=0.5, seed=20260804)
        benign = fixture_requests()
        new_fns: List[str] = []
        new_blocks: List[str] = []
        benign_new_blocks: List[str] = []
        lost, gained, score_delta = 0, 0, 0
        B = 64
        reqs = [lr.request for lr in labeled]
        for i in range(0, len(reqs), B):
            chunk = reqs[i:i + B]
            vi = twin.detect_cpu_only(chunk)
            vc = candidate.detect_cpu_only(chunk)
            for lr, a, b in zip(labeled[i:i + B], vi, vc):
                if a.attack and not b.attack:
                    lost += 1
                    if lr.is_attack:
                        new_fns.append(a.request_id)
                if b.attack and not a.attack:
                    gained += 1
                if b.blocked and not a.blocked:
                    new_blocks.append(a.request_id)
                if a.score != b.score:
                    score_delta += 1
        for i in range(0, len(benign), B):
            chunk = benign[i:i + B]
            vi = twin.detect_cpu_only(chunk)
            vc = candidate.detect_cpu_only(chunk)
            for a, b in zip(vi, vc):
                if b.blocked and not a.blocked:
                    benign_new_blocks.append(a.request_id)
        return {
            "corpus_requests": len(reqs),
            "benign_fixtures": len(benign),
            "new_fns": len(new_fns),
            "new_fn_ids": new_fns[:8],
            "new_blocks": len(new_blocks),
            "lost_attack_verdicts": lost,
            "gained_attack_verdicts": gained,
            "score_deltas": score_delta,
            "benign_new_blocks": len(benign_new_blocks),
            "benign_new_block_ids": benign_new_blocks[:8],
        }

    # ------------------------------------------- scoring-head admission

    def admit_scoring(self, artifact_path: Optional[str] = None,
                      head=None, overrides: Optional[dict] = None) -> dict:
        """Admission gate for a LEARNED SCORING HEAD artifact
        (docs/LEARNED_SCORING.md): same staged machinery as a ruleset
        rollout — the candidate generation is the live pack with the
        new head bound, so shadow diffing, the canary ramp, every
        rollback trigger, and LKG recovery apply unchanged.  Stages:

        1. load    — artifact parse + content-hash verification
                     (ScoringHead.load rejects corrupt/tampered files)
        2. schema  — shape/finiteness validation + already-live check
        3. coverage— rule-id-map coverage against the LIVE pack
                     (``scorer_min_coverage``)
        4. compile — candidate pipeline build (shares the live engine:
                     same pack, same warm executables) + smoke detect
        5. replay  — golden-corpus diff vs the INCUMBENT scorer
                     (zero-new-FN / zero-new-benign-block defaults)
        """
        if head is None and artifact_path is None:
            raise ValueError("admit_scoring() needs an artifact path "
                             "or a ScoringHead")
        overrides = validate_overrides(overrides or {})
        with self._lock:
            if self.state in (SHADOW, CANARY) or self._admitting:
                raise RolloutRejected(
                    "admission", "rollout_in_progress",
                    str(artifact_path or ""),
                    {"active_candidate": self.candidate_version})
            self._admitting = True
            from dataclasses import replace as _dc_replace
            self.config = _dc_replace(self._base_config, **overrides)
        try:
            return self._admit_scoring_inner(artifact_path, head)
        finally:
            with self._lock:
                self._admitting = False

    def _admit_scoring_inner(self, artifact_path, head) -> dict:
        from ingress_plus_tpu.learn.head import LearnedScorer, ScoringHead

        with self._lock:
            self.candidate_artifact = str(artifact_path or "")
        # stage 1: load (content hash verified inside load) -----------------
        if head is None:
            try:
                head = ScoringHead.load(artifact_path)
            except Exception as e:
                self._reject("load", "scorer_load",
                             {"error": "%s: %s" % (type(e).__name__, e)})
        # stage 2: schema + already-live -------------------------------------
        try:
            head.validate()
        except ValueError as e:
            self._reject("schema", "scorer_schema", {"error": str(e)})
        live = self.batcher.pipeline
        if live.scoring_head is not None \
                and head.version == live.scoring_head.version:
            self._reject("load", "already_live",
                         {"version": head.version})
        # stage 3: rule-id-map coverage against the live pack ----------------
        scorer = LearnedScorer(head, live.ruleset)
        if scorer.coverage < self.config.scorer_min_coverage:
            self._reject("coverage", "scorer_coverage", {
                "coverage": round(scorer.coverage, 4),
                "required": self.config.scorer_min_coverage,
                "ruleset": live.ruleset.version})
        # stage 4: candidate build + smoke -----------------------------------
        try:
            candidate = self._build_scoring_candidate(head)
        except Exception as e:
            self._reject("compile", "compile_smoke",
                         {"error": "%s: %s" % (type(e).__name__, e)})
        # stage 5: golden-corpus replay vs the incumbent scorer --------------
        replay = self._replay_diff(live, candidate)
        if replay["new_fns"] > self.config.max_new_fn:
            self._reject("replay", "new_fns", replay)
        if replay["benign_new_blocks"] > self.config.max_new_benign_blocks:
            self._reject("replay", "benign_blocks", replay)
        candidate.reset_detection_observations()
        candidate.stats = live.stats
        candidate.load_controller = live.load_controller
        report = {
            "state": SHADOW,
            "kind": "scorer",
            "candidate": head.version,
            "incumbent": live.generation_tag,
            "artifact": self.candidate_artifact,
            "coverage": round(scorer.coverage, 4),
            "threshold": round(float(head.threshold), 6),
            "replay": replay,
        }
        self._enter_admitted(candidate, head.version, "scorer",
                             report, head=head)
        self._event("admitted", candidate=head.version,
                    rollout_kind="scorer")
        return report

    def _build_scoring_candidate(self, head):
        """Candidate pipeline for a scoring rollout: the LIVE pack with
        the new head bound.  The engine is SHARED (same ruleset, same
        device tables, already-warm executables — a scorer changes only
        the CPU finalize step), so the seen-shape sets are adopted from
        the incumbent: candidate dispatches must not book phantom
        recompiles in the efficiency gauges."""
        from ingress_plus_tpu.models.pipeline import DetectionPipeline
        from ingress_plus_tpu.utils.corpus import generate_corpus

        live = self.batcher.pipeline
        candidate = DetectionPipeline(
            live.ruleset, mode=live.mode,
            anomaly_threshold=live.anomaly_threshold,
            fail_open=live.fail_open,
            acl_store=live.acl_store,
            tenant_acl=dict(live.tenant_acl),
            default_acl=live.default_acl,
            engine=live.engine,
            scoring_head=head)
        candidate.tenant_rule_mask = live.tenant_rule_mask
        candidate.seen_shapes = set(live.seen_shapes)
        candidate.seen_lane_shapes = set(live.seen_lane_shapes)
        candidate._seen_exec = set(live._seen_exec)
        smoke = [lr.request for lr in generate_corpus(n=4, seed=7)]
        verdicts = candidate.detect_strict(smoke)
        if len(verdicts) != len(smoke):
            raise RuntimeError("smoke detect returned %d verdicts for %d "
                               "requests" % (len(verdicts), len(smoke)))
        return candidate

    # ----------------------------------------------------- shadow phase

    def _start_shadow_locked(self) -> None:
        self.state = SHADOW
        self._budget_s = 0.0
        self._budget_at = time.monotonic()
        if self._shadow_thread is None:
            self._shadow_thread = threading.Thread(
                target=self._shadow_run, daemon=True, name="ipt-shadow")
            self._shadow_thread.start()
        self.shadow_active = True

    def mirror(self, request, live_verdict) -> None:
        """Offer one live (request, verdict) pair to the shadow lane.
        Called by the batcher AFTER the real verdict resolved — never on
        the verdict path.  Sampled by the same deterministic hash as the
        canary split; overflow drops and counts, never blocks."""
        if not self.shadow_active:
            return
        if _hash_frac(request.request_id) >= self.config.shadow_sample:
            return
        gen = getattr(live_verdict, "generation", "")
        # only FULL incumbent verdicts are diffable: a fail-open or
        # degraded verdict (empty generation / brownout prefilter-only)
        # was never fully scanned by any generation — diffing it against
        # a candidate full scan would book the candidate's CORRECT
        # blocks as divergence and roll back a good pack because the
        # INCUMBENT lane faulted
        if live_verdict.fail_open or live_verdict.degraded or not gen:
            return
        # canary-served candidate verdicts must not diff against the
        # candidate itself (generation stamp from models/pipeline.py;
        # candidate_generation is the candidate PIPELINE's tag — for a
        # scoring rollout that is ruleset+head, not the bare head version)
        if gen == self.candidate_generation:
            return
        try:
            self._shadow_q.put_nowait((request, live_verdict))
            with self._lock:
                self.shadow_mirrored += 1
        except Full:
            with self._lock:
                self.shadow_dropped += 1

    def _shadow_run(self) -> None:
        cfg = self.config
        flight.register_thread("shadow")
        while not self._stop.is_set():
            try:
                request, live_v = self._shadow_q.get(timeout=0.1)
            except Empty:
                continue
            cand = self.candidate
            if cand is None or not self.shadow_active:
                continue
            # CPU token budget: earn budget_frac of elapsed wall time,
            # spend measured scan seconds; broke → drop (counted)
            now = time.monotonic()
            with self._lock:
                self._budget_s = min(
                    self._budget_s + (now - self._budget_at) *
                    cfg.shadow_cpu_budget, 1.0)
                self._budget_at = now
                broke = self._budget_s <= 0.0
                if broke:
                    self.shadow_dropped += 1
            if broke:
                continue
            t0 = time.monotonic()
            flight.begin(EV_SHADOW, cycle=0)
            try:
                if faults.fire("shadow_diverge"):
                    # injected divergence: the candidate "blocks" a
                    # request the incumbent passed (CI rollback drill)
                    with self._lock:
                        self.diff["new_block"] += 1
                        self.shadow_compared += 1
                else:
                    cv = cand.detect_cpu_only([request])[0]
                    self._diff_verdicts(live_v, cv)
            except Exception:
                with self._lock:
                    self.candidate_failures += 1
            finally:
                flight.end(EV_SHADOW, cycle=0)
            with self._lock:
                self._budget_s -= time.monotonic() - t0
            self._evaluate()
            self.tick()

    def _diff_verdicts(self, live_v, cand_v) -> None:
        with self._lock:
            self.shadow_compared += 1
            if cand_v.blocked and not live_v.blocked:
                self.diff["new_block"] += 1
            if live_v.attack and not cand_v.attack:
                self.diff["lost_hit"] += 1
            if cand_v.score != live_v.score:
                self.diff["score_delta"] += 1

    # ----------------------------------------------------- canary phase

    def split(self, items: list) -> tuple:
        """Partition a cycle's (ts, request, fut) items into (incumbent,
        candidate) by the deterministic hash at the current step
        fraction.  Dispatch-thread only."""
        if not self.canary_active:
            return items, []
        steps = self.config.steps
        # clamped read: steps and step_idx are written by other threads;
        # a torn pair must degrade to a wrong fraction, never an
        # IndexError that kills the dispatch thread
        frac = steps[min(self.step_idx, len(steps) - 1)]
        inc, cand = [], []
        for item in items:
            (cand if _hash_frac(item[1].request_id) < frac
             else inc).append(item)
        return inc, cand

    def observe_canary(self, n_served: int, verdicts) -> None:
        """Per-cycle canary accounting + trigger evaluation (dispatch
        thread, after the candidate sub-batch resolved)."""
        with self._lock:
            self.candidate_requests += n_served
            self.step_served += n_served
            for v in verdicts:
                if v.fail_open:
                    self.candidate_fail_open += 1
        self._evaluate()

    def record_candidate_failure(self, reason: str) -> None:
        """A candidate dispatch raised or hung (batcher's guarded call).
        Candidate failures never feed the SHARED breaker — the incumbent
        path must keep its own failure signal clean; they trigger
        rollback instead."""
        with self._lock:
            self.candidate_failures += 1
        self._event("candidate_failure", reason=reason)
        self._evaluate()

    def _triggers(self) -> Optional[str]:
        cfg = self.config
        cand = self.candidate
        if cand is None:
            return None
        if self.candidate_failures > cfg.max_candidate_failures:
            return "candidate_dispatch_failures"
        if self.candidate_fail_open > cfg.max_candidate_fail_open:
            return "candidate_fail_open"
        if int(cand.rule_stats.confirm_errors.sum()) \
                > cfg.max_confirm_errors:
            return "confirm_error_spike"
        if _runtime_dead(cand) - self._dead_baseline \
                > cfg.max_runtime_dead_jump:
            return "runtime_dead_jump"
        if self.shadow_compared >= cfg.diff_min_compared:
            bad = self.diff["new_block"] + self.diff["lost_hit"]
            if bad / self.shadow_compared > cfg.max_diff_frac:
                return "verdict_diff"
        return None

    def _evaluate(self) -> None:
        """Evaluate triggers + phase advancement.  Cheap when nothing is
        pending; serialized transitions under _lock.  May run on the
        dispatch thread WHILE it holds the batcher's swap lock, so the
        one transition that needs that lock (promotion) is only FLAGGED
        here and performed by ``tick()`` off-lock."""
        if not (self.shadow_active or self.canary_active):
            return
        reason = self._triggers()
        if reason is not None:
            self.rollback(reason)
            return
        with self._lock:
            if self.state == SHADOW \
                    and self.shadow_compared >= self.config.shadow_min_requests:
                self.state = CANARY
                self.step_idx = 0
                self.step_served = 0
                self.canary_active = True
                self._event("canary_started",
                            fraction=self.config.steps[0])
                return
            if self.state == CANARY \
                    and self.step_served >= self.config.step_min_requests:
                if self.step_idx + 1 < len(self.config.steps):
                    self.step_idx += 1
                    self.step_served = 0
                    self._event("canary_step",
                                fraction=self.config.steps[self.step_idx])
                else:
                    self._promote_pending = True

    def tick(self) -> None:
        """Deferred-transition pump: the batcher calls this once per
        dispatch cycle AFTER releasing the swap lock; the shadow worker
        calls it between diffs.  No-op unless a promotion is pending."""
        if self._promote_pending:
            with self._lock:
                pending, self._promote_pending = self._promote_pending, False
            if pending:
                self.promote()

    # ------------------------------------------------ promote / rollback

    def promote(self) -> None:
        """Install the candidate as the live generation (the staged
        twin of ``Batcher.swap_ruleset``: the candidate pipeline is
        already built, warm, and carrying its canary-phase RuleStats).
        The ``swap_fail`` fault site guards the boundary — a failure
        here must leave the incumbent serving (fault-matrix invariant),
        recorded as a rollback."""
        cand = self.candidate
        if cand is None:
            return
        b = self.batcher
        try:
            faults.raise_if("swap_fail")
            with b._swap_lock:
                prev = b.pipeline
                prev_stream = b.stream_engine.pipeline
                try:
                    cand.frozen_rule_stats = prev.rule_stats.freeze()
                    # cross-cycle verdict cache: carried like the pool
                    # (generation-keyed — old entries are unreachable
                    # by construction; the drop is hygiene)
                    if getattr(prev, "confirm_cache", None) is not None:
                        prev.confirm_cache.invalidate("promote")
                        cand.confirm_cache = prev.confirm_cache
                    b.pipeline = cand
                    b.stream_engine.pipeline = cand
                    b._reapply_tenants()
                except Exception:
                    # half-installed candidate: restore the incumbent
                    # BEFORE reporting rollback — state must never say
                    # ROLLED_BACK while the candidate is serving
                    b.pipeline = prev
                    b.stream_engine.pipeline = prev_stream
                    try:
                        b._reapply_tenants()
                    except Exception:
                        pass
                    raise
                with self._lock:
                    self.state = LIVE
                    self.canary_active = False
                    self.shadow_active = False
        except Exception as e:
            self.rollback("promote_failed:%s" % type(e).__name__)
            return
        with self._lock:
            self.promotions += 1
            cr, self._candidate_cr = self._candidate_cr, None
            head, self._candidate_head = self._candidate_head, None
            self.candidate = None
        self._event("live", candidate=self.candidate_version,
                    rollout_kind=self.candidate_kind)
        if self.config.lkg_dir and cr is not None:
            try:
                persist_lkg(cr, self.config.lkg_dir)
                self._event("lkg_persisted", version=cr.version)
            except OSError as e:
                # LKG is recovery insurance, not a serving dependency
                self._event("lkg_persist_failed", error=str(e))
        if self.config.lkg_dir and head is not None:
            from ingress_plus_tpu.learn.head import persist_lkg_scorer

            try:
                persist_lkg_scorer(head, self.config.lkg_dir)
                self._event("scorer_lkg_persisted", version=head.version)
            except OSError as e:
                self._event("lkg_persist_failed", error=str(e))

    def rollback(self, reason: str) -> None:
        """Back to the incumbent: stop routing to the candidate (it
        never owned more than its ramp share), quarantine the pack,
        export the reason.  The incumbent's counters and drift-freeze
        state were never touched — there is nothing to restore."""
        with self._lock:
            if self.state not in (SHADOW, CANARY, ADMITTED):
                return
            self.state = ROLLED_BACK
            self.rollback_reason = reason
            self._clear_candidate()
            self.rollbacks += 1
        self.count_rejected("rollback_" + reason.partition(":")[0])
        self._quarantine(reason)
        self._event("rolled_back", reason=reason,
                    candidate=self.candidate_version)

    def abort(self, reason: str = "manual") -> bool:
        """Operator/break-glass abort of an in-flight rollout."""
        with self._lock:
            active = self.state in (ADMITTED, SHADOW, CANARY)
        if active:
            self.rollback(reason)
        return active

    def _quarantine(self, reason: str) -> None:
        if not self.config.lkg_dir:
            return
        try:
            qdir = Path(self.config.lkg_dir) / "quarantine"
            qdir.mkdir(parents=True, exist_ok=True)
            (qdir / ("%s.json" % (self.candidate_version or "unknown"))
             ).write_text(json.dumps({
                 "version": self.candidate_version,
                 "artifact": self.candidate_artifact,
                 "reason": reason,
                 "ts": time.time(),
                 "diff": dict(self.diff),
             }, indent=2))
        except OSError:
            pass   # quarantine is advisory; rollback already happened

    # ---------------------------------------------------------- teardown

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            self._clear_candidate()
        if self._shadow_thread is not None:
            self._shadow_thread.join(timeout=2)
            with self._lock:
                self._shadow_thread = None

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        with self._lock:
            frac = (self.config.steps[self.step_idx]
                    if self.canary_active else
                    (1.0 if self.state == LIVE else 0.0))
            return {
                "state": self.state,
                "candidate": self.candidate_version or None,
                "kind": self.candidate_kind or None,
                "artifact": self.candidate_artifact or None,
                "incumbent": self.batcher.pipeline.ruleset.version,
                "step": self.step_idx,
                "steps": list(self.config.steps),
                "fraction": frac,
                "step_served": self.step_served,
                "step_min_requests": self.config.step_min_requests,
                "shadow": {
                    "active": self.shadow_active,
                    "mirrored": self.shadow_mirrored,
                    "compared": self.shadow_compared,
                    "dropped": self.shadow_dropped,
                    "sample": self.config.shadow_sample,
                },
                "diff": dict(self.diff),
                "candidate_requests": self.candidate_requests,
                "candidate_failures": self.candidate_failures,
                "candidate_fail_open": self.candidate_fail_open,
                "rollbacks": self.rollbacks,
                "promotions": self.promotions,
                "rollback_reason": self.rollback_reason or None,
                "swap_rejected": dict(self.swap_rejected),
                "lkg_dir": self.config.lkg_dir,
                "last_admission": self.last_admission,
                "history": self.history[-16:],
            }


# ===================================================== swap drill (CI)
# The swapdrill gate (tools/lint.py --ci): prove the state machine on a
# real CPU batcher — a good pack reaches LIVE through every phase, a
# rulecheck-dirty pack is REJECTED with zero traffic impact, and a
# forced mid-canary failure auto-rolls back to the incumbent — all while
# every admitted request resolves to exactly one verdict.

_DRILL_INCUMBENT = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
"""

#: the candidate adds one rule — a strict superset whose pattern hits
#: nothing in the golden corpus or the benign fixtures, so the replay
#: diff is clean (a "drop table" rule here was correctly REJECTED by the
#: benign gate: the fixtures carry legitimate SQL-in-prose)
_DRILL_CANDIDATE = _DRILL_INCUMBENT + """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)xp_drillshell\\(" \
    "id:955100,phase:2,block,severity:CRITICAL,tag:'attack-rce'"
"""

#: dead-regex fixture (the PR 2 941290/941300 shape): the pattern is
#: confirm-unparsable -> rulecheck flags the rule silently DEAD at
#: error severity -> the admission static gate must reject the pack
_DRILL_BROKEN = _DRILL_INCUMBENT + """
SecRule ARGS "@rx (?:\\\\u00[0-7]){4,}" \
    "id:999999,phase:2,block,severity:CRITICAL,tag:'attack-generic'"
"""


def _drill_config(lkg_dir: Optional[str] = None) -> RolloutConfig:
    return RolloutConfig(
        steps=(0.25, 1.0), step_min_requests=8, shadow_min_requests=4,
        shadow_sample=1.0, corpus_n=32, diff_min_compared=4,
        lkg_dir=lkg_dir)


def _drill_traffic(batcher, n: int, tag: str, timeout_s: float = 60.0):
    """Push n requests (every 4th an attack) and resolve every future —
    the exactly-one-verdict invariant check rides on the resolve."""
    from ingress_plus_tpu.utils.faults import _collect, _requests

    reqs = _requests(n, attack_every=4, tag=tag)
    futs = [batcher.submit(r) for r in reqs]
    return _collect(futs, timeout_s)


def run_swap_drill(lkg_dir: Optional[str] = None) -> dict:
    """Drive the three canonical rollouts end to end on a CPU batcher;
    returns a report whose ``passed`` the CI gate asserts."""
    import tempfile

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.utils.faults import _mk_batcher

    tmp = None
    if lkg_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="ipt-lkg-")
        lkg_dir = tmp.name
    report: Dict[str, dict] = {}
    cr_inc = compile_ruleset(parse_seclang(_DRILL_INCUMBENT))
    cr_good = compile_ruleset(parse_seclang(_DRILL_CANDIDATE))
    cr_bad = compile_ruleset(parse_seclang(_DRILL_BROKEN))

    def _drill(name: str, fn) -> None:
        t0 = time.monotonic()
        b = _mk_batcher(cr=cr_inc)
        ro = RolloutController(b, _drill_config(lkg_dir))
        b.rollout = ro
        violations: List[str] = []
        try:
            fn(b, ro, violations)
        except Exception as e:  # noqa: BLE001 — a drill crash IS a finding
            violations.append("drill raised %s: %s" % (type(e).__name__, e))
        finally:
            ro.close()
            b.close()
        report[name] = {"ok": not violations, "violations": violations,
                        "state": ro.state,
                        "seconds": round(time.monotonic() - t0, 2)}

    def _good(b, ro, violations):
        ro.admit(ruleset=cr_good)
        deadline = time.monotonic() + 60
        wave = 0
        while ro.state not in (LIVE, REJECTED, ROLLED_BACK) \
                and time.monotonic() < deadline:
            _, viol = _drill_traffic(b, 24, "g%d" % wave)
            violations.extend(viol)
            wave += 1
        if ro.state != LIVE:
            violations.append("good pack never reached LIVE (state=%s, "
                              "reason=%s)" % (ro.state, ro.rollback_reason))
            return
        if b.pipeline.ruleset.version != cr_good.version:
            violations.append("LIVE state but incumbent still serving")
        verdicts, viol = _drill_traffic(b, 16, "post")
        violations.extend(viol)
        if not any(v.attack for v in verdicts):
            violations.append("promoted pack lost detection")
        lkg = load_lkg(lkg_dir)
        if lkg is None or lkg.version != cr_good.version:
            violations.append("LKG not persisted after promote")
        report["good_pack_events"] = {"history": ro.history[-8:]}

    def _broken(b, ro, violations):
        v0 = b.pipeline.ruleset.version
        try:
            ro.admit(ruleset=cr_bad)
            violations.append("rulecheck-dirty pack was admitted")
        except RolloutRejected as e:
            if e.report["stage"] != "static":
                violations.append("broken pack rejected at %r, expected "
                                  "the static gate" % e.report["stage"])
        if b.pipeline.ruleset.version != v0:
            violations.append("rejection mutated the serving generation")
        verdicts, viol = _drill_traffic(b, 16, "rej")
        violations.extend(viol)
        if not any(v.attack and not v.fail_open for v in verdicts):
            violations.append("incumbent lost detection after rejection")
        if ro.swap_rejected.get("rulecheck", 0) < 1:
            violations.append("rejection not counted in swap_rejected")

    def _midcanary(b, ro, violations):
        v0 = b.pipeline.ruleset.version
        ro.admit(ruleset=cr_good)
        deadline = time.monotonic() + 60
        wave = 0
        while ro.state != CANARY and ro.state in (ADMITTED, SHADOW) \
                and time.monotonic() < deadline:
            _, viol = _drill_traffic(b, 24, "m%d" % wave)
            violations.extend(viol)
            wave += 1
        if ro.state != CANARY:
            violations.append("rollout never reached CANARY (state=%s)"
                              % ro.state)
            return
        # forced mid-canary failure: candidate dispatches start raising
        ro.record_candidate_failure("forced_drill_failure")
        _, viol = _drill_traffic(b, 24, "mc")
        violations.extend(viol)
        if ro.state != ROLLED_BACK:
            violations.append("forced canary failure did not roll back "
                              "(state=%s)" % ro.state)
        if b.pipeline.ruleset.version != v0:
            violations.append("rollback did not restore the incumbent")
        verdicts, viol = _drill_traffic(b, 16, "mr")
        violations.extend(viol)
        if not any(v.attack and not v.fail_open for v in verdicts):
            violations.append("incumbent lost detection after rollback")
        qdir = Path(lkg_dir) / "quarantine"
        if not any(qdir.glob("*.json")):
            violations.append("rolled-back pack was not quarantined")

    try:
        _drill("good_pack_to_live", _good)
        _drill("broken_pack_rejected", _broken)
        _drill("mid_canary_rollback", _midcanary)
    finally:
        if tmp is not None:
            tmp.cleanup()
    drills = {k: v for k, v in report.items() if "ok" in v}
    return {"passed": all(r["ok"] for r in drills.values()),
            "drills": report}
