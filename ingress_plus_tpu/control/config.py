"""Global configuration — the ConfigMap tier.

Reference: `internal/ingress/controller/config/config.go`† (~200 typed
keys parsed from the controller ConfigMap by `ReadConfig`, defaults from
`NewDefault`).  This file carries the keys the detection framework owns:
the wallarm-style globals plus the TPU-backend globals the north star
adds (sidecar address, batch window, fail-open policy — SURVEY.md §5
config tiers).  Three-tier precedence, as in the reference:

    CLI flags  >  ConfigMap (this file)  >  per-Ingress annotations
    (annotations override the *defaults*, the ConfigMap sets them)
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Dict, List

from ingress_plus_tpu.control.objects import ConfigMap


def _parse_bool(v: str) -> bool:
    """Strict: unrecognized spellings raise so from_configmap keeps the
    default and REPORTS (a typo in `fail-open` must not silently flip
    fail-open→fail-closed)."""
    s = v.strip().lower()
    if s in ("true", "on", "1", "yes"):
        return True
    if s in ("false", "off", "0", "no"):
        return False
    raise ValueError("not a boolean: %r" % v)


@dataclass
class GlobalConfig:
    # ---- wallarm-style global enablement (`enable-wallarm`† analog)
    enable_detection: bool = False
    default_mode: str = "monitoring"     # cluster-wide default wallarm-mode
    mode_allow_override: str = "on"      # can Ingresses strengthen mode?

    # ---- TPU backend globals (north-star additions)
    detection_backend: str = "cpu"       # cluster default: cpu | tpu
    sidecar_socket: str = "/run/ipt/detect.sock"
    sidecar_http: str = "127.0.0.1:9901"
    batch_window_us: int = 500           # deadline batcher window
    max_batch: int = 256
    fail_open: bool = True               # wallarm-fallback default
    detect_timeout_ms: int = 30          # nginx-side verdict budget
    anomaly_threshold: int = 5
    paranoia_level: int = 2
    ruleset_path: str = ""               # compiled-ruleset artifact dir
    ruleset_sync_interval_s: int = 120   # sync-node† pull cadence
    #: wallarm-acl CONTENT (the reference syncs lists from its cloud; the
    #: open analog is the ConfigMap): JSON string
    #: {"name": {"allow": [cidr], "deny": [...], "greylist": [...]}}
    acls: str = ""

    # ---- representative core keys the template consumes
    server_tokens: bool = False
    client_body_buffer_size: str = "16k"
    proxy_body_size: str = "1m"
    log_format_upstream: str = (
        '$remote_addr - $request "$status" $detect_verdict')

    errors: List[str] = field(default_factory=list)

    @classmethod
    def from_configmap(cls, cm: ConfigMap) -> "GlobalConfig":
        """ReadConfig† analog: kebab-case keys, bad values keep defaults
        and are reported (never crash the sync loop)."""
        cfg = cls()
        typed = {f.name.replace("_", "-"): f for f in fields(cls)
                 if f.name != "errors"}
        for key, raw in sorted(cm.data.items()):
            f = typed.get(key)
            if f is None:
                continue  # core controller owns hundreds more keys
            try:
                if f.type in ("bool", bool):
                    value = _parse_bool(raw)
                elif f.type in ("int", int):
                    value = int(raw)
                else:
                    value = raw.strip()
                setattr(cfg, f.name, value)
            except (ValueError, TypeError) as e:
                cfg.errors.append("%s: %s" % (key, e))
        if cfg.default_mode not in ("off", "monitoring", "safe_blocking",
                                    "block"):
            cfg.errors.append("default-mode: %r invalid" % cfg.default_mode)
            cfg.default_mode = "monitoring"
        if cfg.detection_backend not in ("cpu", "tpu"):
            cfg.errors.append("detection-backend: %r invalid"
                              % cfg.detection_backend)
            cfg.detection_backend = "cpu"
        return cfg
