"""Annotation parsing framework + the WAF annotation set.

Reference: `internal/ingress/annotations/`† — ~60 per-annotation parser
packages behind an `Extractor`, each reading `nginx.ingress.kubernetes.io/
<name>` with typed parsing + validation, and
`internal/ingress/annotations/wallarm/`† for the wallarm set.  The north
star adds `detection-backend: tpu` at exactly this boundary
(BASELINE.json).

Validation mirrors the reference's `annotation-value-word-blocklist`
defense: annotation values land in rendered nginx config, so values that
could break out of the rendered context are rejected at extraction time
(the admission webhook calls the same code strict).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, fields
from typing import Callable, Dict, List, Optional

from ingress_plus_tpu.control.objects import Ingress

PREFIX = "nginx.ingress.kubernetes.io/"

# characters that could escape an nginx directive / template context
_BLOCKLIST_RE = re.compile(r'[{}$;\n\r"\'\\]|\.\./')

MODES = ("off", "monitoring", "safe_blocking", "block")
BACKENDS = ("cpu", "tpu")


class AnnotationError(ValueError):
    """Raised in strict mode (admission); lenient extraction logs-and-
    defaults instead, matching the controller's runtime behavior."""


def _check_value(name: str, raw: str) -> str:
    if _BLOCKLIST_RE.search(raw):
        raise AnnotationError(
            "annotation %s value %r contains blocklisted characters"
            % (name, raw))
    return raw


@dataclass
class Spec:
    """One annotation: its name, parse/validate function, and default."""

    name: str
    parse: Callable[[str], object]
    default: object
    target: str  # field on DetectionConfig


def _enum(options) -> Callable[[str], str]:
    def p(raw: str) -> str:
        v = raw.strip().lower()
        if v not in options:
            raise AnnotationError("expected one of %s, got %r"
                                  % (",".join(options), raw))
        return v
    return p


def _bool(raw: str) -> bool:
    v = raw.strip().lower()
    if v in ("true", "on", "1", "yes"):
        return True
    if v in ("false", "off", "0", "no"):
        return False
    raise AnnotationError("expected boolean, got %r" % raw)


def _int(lo: int, hi: int) -> Callable[[str], int]:
    def p(raw: str) -> int:
        try:
            v = int(raw.strip())
        except ValueError:
            raise AnnotationError("expected integer, got %r" % raw)
        if not lo <= v <= hi:
            raise AnnotationError("expected %d..%d, got %d" % (lo, hi, v))
        return v
    return p


def _str(raw: str) -> str:
    return raw.strip()


def _csv(raw: str) -> List[str]:
    return [x.strip() for x in raw.split(",") if x.strip()]


@dataclass
class DetectionConfig:
    """Per-Ingress WAF config — the wallarm `Config`† struct analog, plus
    the TPU-backend extension.  One of these hangs off every Location in
    the model (model.py)."""

    # wallarm annotation set (reference parity)
    mode: str = "off"                   # wallarm-mode
    mode_allow_override: str = "on"     # wallarm-mode-allow-override:
                                        #   on | off | strict
    fallback: bool = True               # wallarm-fallback (fail-open)
    instance: str = ""                  # wallarm-instance / application
    block_page: str = ""                # wallarm-block-page
    acl: str = ""                       # wallarm-acl
    enable_libdetection: bool = True    # wallarm-enable-libdetection
    parse_response: bool = False        # wallarm-parse-response
    parse_websocket: bool = False       # wallarm-parse-websocket
    unpack_response: bool = False       # wallarm-unpack-response
    parser_disable: List[str] = field(default_factory=list)

    # the north-star extension (BASELINE.json)
    detection_backend: str = "cpu"      # detection-backend: cpu | tpu
    anomaly_threshold: int = 0          # 0 = inherit global
    paranoia_level: int = 0             # 0 = inherit global
    rule_subset: List[str] = field(default_factory=list)
                                        # detection-rule-tags: EP tenant
                                        # rule-subset selection

    # filled by the model builder (EP routing), not by annotations
    tenant: int = 0
    # which fields were explicitly set by annotations (vs defaults) — the
    # global-merge tier needs the difference: an explicit
    # `wallarm-mode: off` is an opt-out and must never be promoted to the
    # cluster default, while an absent annotation must be
    explicit: frozenset = frozenset()

    @property
    def enabled(self) -> bool:
        return self.mode != "off"


SPECS: List[Spec] = [
    Spec("wallarm-mode", _enum(MODES), "off", "mode"),
    Spec("wallarm-mode-allow-override", _enum(("on", "off", "strict")),
         "on", "mode_allow_override"),
    Spec("wallarm-fallback", _bool, True, "fallback"),
    Spec("wallarm-instance", _str, "", "instance"),
    Spec("wallarm-application", _str, "", "instance"),  # newer alias wins
    Spec("wallarm-block-page", _str, "", "block_page"),
    Spec("wallarm-acl", _str, "", "acl"),
    Spec("wallarm-enable-libdetection", _bool, True, "enable_libdetection"),
    Spec("wallarm-parse-response", _bool, False, "parse_response"),
    Spec("wallarm-parse-websocket", _bool, False, "parse_websocket"),
    Spec("wallarm-unpack-response", _bool, False, "unpack_response"),
    Spec("wallarm-parser-disable", _csv, [], "parser_disable"),
    Spec("detection-backend", _enum(BACKENDS), "cpu", "detection_backend"),
    Spec("detection-anomaly-threshold", _int(0, 1000), 0,
         "anomaly_threshold"),
    Spec("detection-paranoia-level", _int(0, 4), 0, "paranoia_level"),
    Spec("detection-rule-tags", _csv, [], "rule_subset"),
]

_BY_NAME: Dict[str, Spec] = {s.name: s for s in SPECS}


class Extractor:
    """`annotations.Extractor.Extract`† analog.

    lenient (controller runtime): bad values fall back to the default so
    one broken Ingress can't take down the sync loop; errors are
    collected for metrics/events.
    strict (admission webhook): first bad value raises AnnotationError.
    """

    def __init__(self, strict: bool = False):
        self.strict = strict
        self.errors: List[str] = []

    def extract(self, ing: Ingress) -> DetectionConfig:
        cfg = DetectionConfig()
        explicit = set()
        # iterate in SPECS order (not annotation-name order) so declared
        # precedence holds: e.g. wallarm-application overrides its legacy
        # alias wallarm-instance when both are present
        for spec in SPECS:
            raw = ing.annotations.get(PREFIX + spec.name)
            if raw is None:
                continue
            try:
                value = spec.parse(_check_value(spec.name, raw))
            except AnnotationError as e:
                if self.strict:
                    raise AnnotationError("%s: %s" % (ing.key, e)) from e
                self.errors.append("%s: %s" % (ing.key, e))
                continue
            setattr(cfg, spec.target, value)
            explicit.add(spec.target)
        cfg.explicit = frozenset(explicit)
        return cfg


def known_annotations() -> List[str]:
    return [PREFIX + s.name for s in SPECS]
