"""dbg — inspection CLI for the serve loop's dynamic-config plane.

Reference: `cmd/dbg/main.go`† queries the controller's Lua unix-socket
endpoints (`/configuration/backends`, ...) to show the live dynamic
state.  Same idea against our HTTP plane:

    python -m ingress_plus_tpu.control.dbg conf     [--server host:port]
    python -m ingress_plus_tpu.control.dbg health
    python -m ingress_plus_tpu.control.dbg metrics
    python -m ingress_plus_tpu.control.dbg latency  [--sidecar host:port]
    python -m ingress_plus_tpu.control.dbg tenants --set '{"1": ["attack-sqli"]}'
    python -m ingress_plus_tpu.control.dbg ruleset --swap /path/artifact \
        [--paranoia 2]
    python -m ingress_plus_tpu.control.dbg rulecheck [--rules path] \
        [--fail-on error]
    python -m ingress_plus_tpu.control.dbg evadecheck [--rules path] \
        [--fail-on error]
    python -m ingress_plus_tpu.control.dbg rules    [--server host:port]
    python -m ingress_plus_tpu.control.dbg drift    [--server host:port]
    python -m ingress_plus_tpu.control.dbg scoring  [--swap head.npz] [--force]
    python -m ingress_plus_tpu.control.dbg breaker  [--server host:port]
    python -m ingress_plus_tpu.control.dbg faults   [--set 'site:times=1']
    python -m ingress_plus_tpu.control.dbg fleet    [--server host:port]

``fleet`` renders the fleet telemetry plane (docs/OBSERVABILITY.md
"Fleet telemetry") from the aggregator's ``/fleet/healthz`` +
``/fleet/slo``: the node table (up/stale, pack generation, requests,
p99, confirm share), skew findings, the merged-profile hash, and the
SLO burn-rate table.  ``--server`` points at the aggregator
(``control/fleetobs.py``, default port 9911), not a serve node.

``rules`` renders the detection-plane telemetry (ISSUE 3): top rules by
prefilter candidates with confirm outcomes and false-candidate rates
(from ``/rules/stats``), the runtime dead-rule list (``/rules/health``
— the runtime twin of ``rulecheck``), and the device-efficiency
gauges; ``drift`` renders per-rule hit-rate deltas across the most
recent hot reload (``/rules/drift``), went-quiet rules flagged.

``latency`` renders the serve plane's stage-level latency attribution
(ISSUE 1): per-stage p50/p90/p99 from the /metrics histograms plus the
/debug/slow exemplar ring as terminal tables; ``--sidecar`` adds the
native sidecar's per-upstream EWMA hop timing from its --status-port.

``tenants`` renders the tenant-isolation plane (docs/ROBUSTNESS.md
"Tenant isolation") from ``/tenants``: fair-queue depths, per-tenant
admitted/shed/degraded counters, quarantine state and the top-offender
sketch; ``--set`` still pushes a tenant→tags table to
``/configuration/tenants``.

``breaker`` renders the fail-safe serve plane (docs/ROBUSTNESS.md):
circuit-breaker state/trips, the brownout ladder rung + queue-delay
EWMA, admission queue depth and shed counters (from ``/healthz``);
``faults`` inspects — or with ``--set`` installs, ``--set ''``
clears — the deterministic fault-injection plan (``/faults``).

``rulecheck`` runs the static ruleset analyzer (ISSUE 2, analysis/ —
see docs/ANALYSIS.md) locally over a rules tree (default: the bundled
CRS tree) and renders the findings table; exit code mirrors the CI
gate (nonzero on unsuppressed findings at/above ``--fail-on``).
``evadecheck`` does the same for the evasion-closure analyzer
(docs/ANALYSIS.md "Evasion analysis"); ``concheck`` for the
serve-plane concurrency analyzer.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _call(server: str, path: str, payload=None, timeout: float = 10) -> str:
    url = "http://%s%s" % (server, path)
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method="POST" if data else "GET",
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode()
    except urllib.error.HTTPError as e:
        # 4xx bodies are STRUCTURED reports (e.g. the rollout admission
        # gate's {stage, reason, findings}) — the operator needs them,
        # not just "HTTP Error 422"
        try:
            body = e.read().decode()
        except Exception:
            body = ""
        raise OSError("%s%s" % (e, ("\n" + body) if body.strip() else ""))


def render_latency(metrics_text: str, slow: dict,
                   sidecar: dict | None = None) -> str:
    """Terminal tables for `dbg latency` (separated from main so tests
    can drive it on real endpoint output without a TTY)."""
    from ingress_plus_tpu.utils.trace import (
        STAGES, stage_breakdown_from_metrics)

    lines = []
    sb = stage_breakdown_from_metrics(metrics_text)
    if sb is None:
        lines.append("stage histograms: MISSING or malformed in /metrics"
                     " (server predates the latency-attribution layer?)")
    else:
        lines.append("%-8s %10s %12s %12s %12s"
                     % ("stage", "count", "p50_us", "p90_us", "p99_us"))
        order = [s for s in STAGES if s in sb] \
            + sorted(set(sb) - set(STAGES))
        for stage in order:
            e = sb[stage]
            lines.append("%-8s %10d %12.1f %12.1f %12.1f"
                         % (stage, e["count"], e["p50_us"], e["p90_us"],
                            e["p99_us"]))
    ex = slow.get("slowest", [])
    lines.append("")
    lines.append("slowest requests (%d retained):" % len(ex))
    # attribution dims (ISSUE 12 satellite): lane=device, wrk=confirm
    # worker, ten=fair-queue tenant, gen=ruleset generation — a slow
    # request names every plane that served it
    lines.append("%-14s %10s %9s %9s %9s %9s %4s %4s %4s %-12s %s"
                 % ("req_id", "e2e_us", "queue", "prep", "scan",
                    "confirm", "lane", "wrk", "ten", "gen", "rules"))
    for e in ex[:20]:
        b = e.get("batch", {})

        def dim(key, e=e):
            v = e.get(key)
            return "-" if v is None or v == -1 else str(v)

        lines.append("%-14s %10d %9d %9d %9d %9d %4s %4s %4s %-12s %s"
                     % (str(e.get("request_id", "?"))[:14],
                        e.get("e2e_us", 0), e.get("queue_us", 0),
                        b.get("prep_us", 0), b.get("scan_us", 0),
                        b.get("confirm_us", 0),
                        dim("lane"), dim("worker"), dim("tenant"),
                        str(e.get("generation", "-") or "-")[:12],
                        ",".join(str(r) for r in
                                 e.get("rule_ids", [])[:4]) or "-"))
    if sidecar is not None:
        lines.append("")
        lines.append("sidecar hop (per-upstream EWMA, stamped sidecar-"
                     "side): pending=%s late=%s"
                     % (sidecar.get("pending"),
                        sidecar.get("late_responses")))
        for up in sidecar.get("upstreams") or []:
            lines.append("  %-28s ewma_ms=%.3f inflight=%s"
                         % (up.get("path", "?"), up.get("ewma_ms", 0.0),
                            up.get("inflight", 0)))
    return "\n".join(lines)


def render_timeline(trace: dict, max_cycles: int = 6,
                    width: int = 48) -> str:
    """Terminal Gantt for `dbg timeline` (ISSUE 12): per cycle, one bar
    row per recorded span — thread, span name, duration, and its
    position inside the cycle's window, so the cross-thread overlap
    structure (device busy vs confirm shares vs the next drain) is
    visible without leaving the terminal.  Input is the /debug/trace
    Chrome-trace JSON (the same bytes Perfetto loads)."""
    events = trace.get("traceEvents", [])
    if not trace.get("enabled", True) and not events:
        return "flight recorder disabled (--no-flight-recorder)"
    tnames = {e["tid"]: e["args"]["name"]
              for e in events if e.get("ph") == "M"
              and e.get("name") == "thread_name"}
    spans = [e for e in events if e.get("ph") == "X"
             and e.get("cat") == "serve"]
    by_cycle: dict = {}
    for s in spans:
        cyc = (s.get("args") or {}).get("cycle", 0)
        if cyc:
            by_cycle.setdefault(cyc, []).append(s)
    if not by_cycle:
        return ("no cycles recorded yet (no traffic, or the ring "
                "evicted them)")
    lines = []
    dropped = (trace.get("otherData") or {}).get("dropped", 0)
    if dropped:
        lines.append("NOTE: %d events evicted from the ring "
                     "(--trace-ring-kb raises the cap)" % dropped)
    for cyc in sorted(by_cycle)[-max_cycles:]:
        cspans = sorted(by_cycle[cyc], key=lambda s: s["ts"])
        w0 = min(s["ts"] for s in cspans)
        w1 = max(s["ts"] + s["dur"] for s in cspans)
        span_w = max(w1 - w0, 1.0)
        env = next((s for s in cspans if s["name"] == "cycle"), None)
        lines.append("cycle %d  (%.2f ms window%s)" % (
            cyc, span_w / 1000.0,
            ", %s requests" % env["args"].get("arg")
            if env is not None and env.get("args", {}).get("arg")
            else ""))
        for s in cspans:
            tname = tnames.get(s["tid"], str(s["tid"])).split(" ")[0]
            off = int((s["ts"] - w0) / span_w * width)
            ln = max(1, int(s["dur"] / span_w * width))
            bar = "." * off + "#" * min(ln, width - off)
            bar += "." * (width - len(bar))
            tag = s.get("args", {}).get("tag", 0)
            label = s["name"]
            if s["name"] in ("lane_launch", "device_busy",
                             "lane_collect"):
                label += "[%s]" % tag
            elif s["name"] == "confirm_share":
                label += "[w%s]" % tag
            lines.append("  %-22s %-16s %9dus |%s|"
                         % (tname, label, int(s["dur"]), bar))
    return "\n".join(lines)


def render_rules(stats: dict, health: dict, top: int = 20) -> str:
    """Terminal tables for `dbg rules` (ISSUE 3): the top rules by
    prefilter candidates with their confirm outcomes, the runtime
    dead-rule list, and the device-efficiency gauges."""
    lines = []
    eff = stats.get("efficiency") or {}
    dev = stats.get("device") or {}
    lines.append("ruleset %s  requests=%d  scan_impl=%s"
                 % (stats.get("version", "?"), stats.get("requests", 0),
                    dev.get("scan_impl", "?")))
    lines.append("efficiency: pad_waste=%s dispatch_fill=%s recompiles=%s"
                 % (eff.get("padding_waste_ratio"),
                    eff.get("dispatch_fill"),
                    eff.get("engine_recompiles")))
    lines.append("")
    qr = health.get("quick_reject") or {}
    if qr:
        lines.append("quick-reject: %s/%s rx rules carry literals "
                     "(skips=%s regex_evals=%s skip_rate=%s)"
                     % (qr.get("rules_with_literals"), qr.get("rx_rules"),
                        qr.get("skips"), qr.get("regex_evals"),
                        qr.get("skip_rate")))
    lines.append("%-8s %-7s %10s %10s %8s %8s %9s %10s %9s"
                 % ("rule_id", "family", "cand", "confirmed", "errors",
                    "fc_rate", "score_sum", "confirm_us", "qr_skips"))
    for r in (stats.get("rules") or [])[:top]:
        lines.append("%-8d %-7s %10d %10d %8d %8.3f %9d %10d %9d"
                     % (r["rule_id"], r["family"], r["candidates"],
                        r["confirmed"], r["confirm_errors"],
                        r["false_candidate_rate"], r["score_sum"],
                        r.get("confirm_us", 0), r.get("quick_rejects", 0)))
    dead = health.get("runtime_dead") or []
    lines.append("")
    lines.append("runtime-dead rules (%d):" % len(dead))
    for d in dead:
        lines.append("  %d  confirm_errors=%d  %s"
                     % (d["rule_id"], d["confirm_errors"],
                        d.get("reason", "")))
    for d in health.get("latent_dead") or []:
        lines.append("  %d  LATENT (no candidates yet)  %s"
                     % (d["rule_id"], d.get("reason", "")))
    nh = health.get("never_hit") or {}
    lines.append("never-hit: %s/%s rules over %s requests"
                 % (nh.get("count"), nh.get("total_rules"),
                    health.get("requests")))
    waste = health.get("top_false_candidates") or []
    if waste:
        lines.append("")
        lines.append("top confirm-CPU waste (false candidates):")
        for w in waste[:10]:
            lines.append("  %-8d %-7s wasted=%-8d fc_rate=%.3f"
                         % (w["rule_id"], w["family"],
                            w["wasted_confirms"],
                            w["false_candidate_rate"]))
    cost = health.get("top_expensive_confirms") or []
    if cost:
        lines.append("")
        lines.append("top confirm cost (cumulative, docs/CONFIRM_PLANE.md):")
        for w in cost[:10]:
            lines.append("  %-8d %-7s confirm_us=%-9d cand=%-6d "
                         "us/cand=%s qr_skips=%d"
                         % (w["rule_id"], w["family"], w["confirm_us"],
                            w["candidates"], w.get("us_per_candidate"),
                            w.get("quick_rejects", 0)))
    return "\n".join(lines)


def render_breaker(health: dict) -> str:
    """Terminal view for `dbg breaker`: the fail-safe plane's state
    out of /healthz's robustness block."""
    rb = health.get("robustness") or {}
    if not rb:
        return ("no robustness block in /healthz "
                "(server predates the fail-safe serve plane?)")
    brk = rb.get("breaker") or {}
    lad = rb.get("ladder") or {}
    lines = [
        "breaker: %s  trips=%s closes=%s probes=%s  last_trip=%s"
        % (brk.get("state", "?"), brk.get("trips"), brk.get("closes"),
           brk.get("probes"), brk.get("last_trip_reason") or "-"),
        "  consecutive_failures=%s/%s  cooldown_s=%s"
        % (brk.get("consecutive_failures"), brk.get("failure_threshold"),
           brk.get("cooldown_s")),
        "ladder:  level=%s (%s)  queue_delay_ewma_us=%s  steps=%s up/%s "
        "down"
        % (lad.get("level"), lad.get("mode"),
           lad.get("queue_delay_ewma_us"), lad.get("steps_up"),
           lad.get("steps_down")),
        "queue:   depth=%s/%s" % (rb.get("queue_depth"),
                                  rb.get("queue_cap")),
        "fallback: hangs=%s cpu_fallback_batches=%s watchdog_released=%s"
        % (rb.get("hangs"), rb.get("cpu_fallback_batches"),
           rb.get("watchdog_released")),
        "degraded_verdicts=%s" % rb.get("degraded_verdicts"),
    ]
    shed = rb.get("shed") or {}
    lines.append("shed:    %s"
                 % (", ".join("%s=%d" % kv for kv in sorted(shed.items()))
                    or "-"))
    lanes = rb.get("lanes") or []
    if len(lanes) > 1:
        # per-device lane plane (docs/MESH_SERVING.md): one row per
        # chip — where the capacity went when a breaker above is open
        lines.append("")
        lines.append("lanes:")
        lines.append("  %-4s %-14s %-9s %5s %5s %6s %8s %7s"
                     % ("lane", "device", "breaker", "trips", "hangs",
                        "errors", "requests", "fill"))
        for ln in lanes:
            brk_l = ln.get("breaker") or {}
            fill = ln.get("dispatch_fill")
            lines.append(
                "  %-4s %-14s %-9s %5s %5s %6s %8s %7s"
                % (ln.get("lane"), ln.get("device") or "-",
                   brk_l.get("state", "?"), brk_l.get("trips"),
                   ln.get("hangs"), ln.get("errors"),
                   ln.get("requests"),
                   ("%.3f" % fill) if fill is not None else "-"))
    return "\n".join(lines)


def render_tenants(st: dict) -> str:
    """Terminal view for `dbg tenants`: the tenant-isolation plane out
    of /tenants (docs/ROBUSTNESS.md "Tenant isolation") — fair-queue
    depths, per-tenant admission counters, quarantine state, and the
    top offenders sketch."""
    q = st.get("queue") or {}
    g = st.get("guard")
    lines = [
        "queue: depth=%s/%s  tenant_cap=%s  active_tenants=%s"
        % (q.get("depth"), q.get("cap"), q.get("tenant_cap"),
           q.get("active_tenants")),
    ]
    weights = q.get("weights") or {}
    if weights:
        lines.append("weights: %s"
                     % ", ".join("%s=%s" % kv
                                 for kv in sorted(weights.items())))
    if g is None:
        lines.append("tenant guard: DISABLED (--tenant-guard off) — "
                     "fair admission still applies")
        return "\n".join(lines)
    lines.append(
        "guard: policy=%s  tracked=%s/%s  quarantined=%s  "
        "(quarantines=%s releases=%s)"
        % (g.get("policy"), g.get("tracked"), g.get("max_tracked"),
           g.get("quarantined") or "-", g.get("quarantines"),
           g.get("releases")))
    lines.append(
        "budget: share>%s of a %ss window (min %s arrivals), "
        "%s window(s) confirm, dwell %ss, depth trigger %s"
        % (g.get("max_share"), g.get("window_s"),
           g.get("min_window_arrivals"), g.get("up_confirm_windows"),
           g.get("dwell_s"), g.get("depth_trigger")))
    rows = g.get("tenants") or []
    if rows:
        lines.append("")
        lines.append("%-8s %10s %8s %9s %9s %9s  %s"
                     % ("tenant", "admitted", "shed", "degraded",
                        "rate_rps", "shed_rps", "state"))
        depths = q.get("depths") or {}
        for r in rows[:20]:
            lines.append(
                "%-8s %10d %8d %9d %9.1f %9.1f  %s"
                % (r["tenant"], r["admitted"], r["shed"], r["degraded"],
                   r.get("rate_rps", 0.0), r.get("shed_rps", 0.0),
                   ("QUARANTINED" if r.get("quarantined") else
                    "q=%s" % depths.get(str(r["tenant"]), 0))))
    top = st.get("top_offenders") or []
    if top:
        sk = st.get("sketch") or {}
        lines.append("")
        lines.append("top offenders (shed+degraded; sketch %s/%s keys):"
                     % (sk.get("tracked"), sk.get("capacity")))
        for e in top[:10]:
            lines.append("  tenant %-8s count=%-8d (max_error=%d)"
                         % (e["key"], e["count"], e["max_error"]))
    return "\n".join(lines)


def render_faults(state: dict) -> str:
    """Terminal view for `dbg faults`: the active plan + counters."""
    if not state.get("active"):
        return "no fault plan active"
    plan = state.get("plan") or {}
    lines = ["fault plan (seed=%s):" % plan.get("seed")]
    lines.append("%-16s %7s %7s %9s %6s %9s %7s"
                 % ("site", "after", "times", "delay_s", "prob",
                    "arrivals", "fired"))
    for r in plan.get("rules") or []:
        lines.append("%-16s %7d %7s %9.3f %6.2f %9d %7d"
                     % (r["site"], r["after"],
                        r["times"] if r["times"] is not None else "inf",
                        r["delay_s"], r["prob"], r["arrivals"],
                        r["fired"]))
    return "\n".join(lines)


def render_rollout(st: dict) -> str:
    """Terminal view for `dbg rollout`: the guarded-rollout state
    machine out of /rollout (docs/ROBUSTNESS.md)."""
    if not st.get("enabled", True):
        return "no rollout controller attached (library batcher?)"
    sh = st.get("shadow") or {}
    diff = st.get("diff") or {}
    lines = [
        "rollout: %s  candidate=%s  incumbent=%s"
        % (st.get("state", "?"), st.get("candidate") or "-",
           st.get("incumbent") or "-"),
        "ramp:    step %s/%s  fraction=%s  served=%s/%s this step"
        % (st.get("step"), max(len(st.get("steps") or []) - 1, 0),
           st.get("fraction"), st.get("step_served"),
           st.get("step_min_requests")),
        "shadow:  %s  mirrored=%s compared=%s dropped=%s (sample=%s)"
        % ("on" if sh.get("active") else "off", sh.get("mirrored"),
           sh.get("compared"), sh.get("dropped"), sh.get("sample")),
        "diff:    %s"
        % (", ".join("%s=%d" % kv for kv in sorted(diff.items())) or "-"),
        "canary:  requests=%s failures=%s fail_open=%s"
        % (st.get("candidate_requests"), st.get("candidate_failures"),
           st.get("candidate_fail_open")),
        "history: promotions=%s rollbacks=%s%s"
        % (st.get("promotions"), st.get("rollbacks"),
           ("  last_rollback=%s" % st["rollback_reason"])
           if st.get("rollback_reason") else ""),
    ]
    rej = st.get("swap_rejected") or {}
    lines.append("rejected: %s"
                 % (", ".join("%s=%d" % kv for kv in sorted(rej.items()))
                    or "-"))
    if st.get("lkg_dir"):
        lines.append("lkg:     %s" % st["lkg_dir"])
    for ev in (st.get("history") or [])[-6:]:
        extras = {k: v for k, v in ev.items() if k not in ("ts", "event")}
        lines.append("  event: %-14s %s"
                     % (ev.get("event"),
                        " ".join("%s=%s" % kv for kv in extras.items())))
    return "\n".join(lines)


def render_scoring(st: dict) -> str:
    """Terminal view for `dbg scoring`: the learned scoring lane out of
    /scoring (docs/LEARNED_SCORING.md) — installed head, operating
    point, and the live fixed-vs-learned divergence counters."""
    if not st.get("active"):
        lines = ["scoring: FIXED CRS weights (no learned head installed)",
                 "  anomaly_threshold=%s  generation=%s"
                 % (st.get("anomaly_threshold"), st.get("generation"))]
        return "\n".join(lines)
    head = st.get("head") or {}
    diff = st.get("diff") or {}
    lines = [
        "scoring: LEARNED head %s  (fixed threshold=%s still exported)"
        % (head.get("version", "?"), st.get("anomaly_threshold")),
        "  threshold=%s  bias=%s  rules_in_head=%s  coverage=%s"
        % (head.get("threshold"), head.get("bias"),
           head.get("rules_in_head"), head.get("coverage")),
        "  bound to ruleset %s  (generation %s)"
        % (head.get("bound_ruleset"), st.get("generation")),
        "  divergence: %s"
        % (", ".join("%s=%d" % kv for kv in sorted(diff.items())) or
           "none observed"),
    ]
    prov = head.get("provenance") or {}
    base = prov.get("baseline") or {}
    if base:
        lines.append("  trained: dataset=%s  fp %s->%s  new_fn=%s"
                     % (prov.get("dataset", "?"),
                        (base.get("fixed") or {}).get("fp"),
                        (base.get("learned") or {}).get("fp"),
                        base.get("new_fn_vs_fixed")))
    tw = head.get("top_weights") or []
    if tw:
        lines.append("  top weights: %s"
                     % ", ".join("%s=%+.3f" % (w["rule_id"], w["weight"])
                                 for w in tw[:8]))
    return "\n".join(lines)


def render_drift(drift: dict, top: int = 20) -> str:
    """Terminal table for `dbg drift`: per-rule hit-rate deltas across
    the most recent hot reload, went-quiet rules first."""
    if not drift.get("rules") and drift.get("note"):
        return drift["note"]
    lines = ["drift %s -> %s  (requests %s -> %s)"
             % (drift.get("old_version", "?"),
                drift.get("new_version", "?"),
                drift.get("old_requests"), drift.get("new_requests"))]
    quiet = drift.get("went_quiet") or []
    lines.append("went quiet after reload (%d): %s"
                 % (len(quiet),
                    ", ".join(str(r) for r in quiet[:20]) or "-"))
    lines.append("")
    lines.append("%-8s %12s %12s %12s  %s"
                 % ("rule_id", "old_rate", "new_rate", "delta", "flag"))
    for r in (drift.get("rules") or [])[:top]:
        lines.append("%-8d %12.6f %12.6f %+12.6f  %s"
                     % (r["rule_id"], r["old_hit_rate"],
                        r["new_hit_rate"], r["delta"],
                        "QUIET" if r.get("went_quiet") else ""))
    added = drift.get("added_rules") or []
    removed = drift.get("removed_rules") or []
    if added or removed:
        lines.append("")
        lines.append("pack delta: +%d rules, -%d rules"
                     % (len(added), len(removed)))
    return "\n".join(lines)


def render_fleet(health: dict, slo: dict) -> str:
    """Terminal tables for `dbg fleet` (ISSUE 18): the node table,
    skew findings, and the SLO burn-rate table from the aggregator's
    /fleet/healthz + /fleet/slo."""
    lines = ["fleet: %s  (%d up, %d stale, %d scrape cycles)"
             % (health.get("status", "?"), health.get("nodes_up", 0),
                health.get("nodes_stale", 0),
                health.get("scrape_cycles", 0)), ""]
    lines.append("%-10s %-5s %-5s %-22s %10s %10s %8s %8s"
                 % ("node", "up", "stale", "generation", "requests",
                    "p99_us", "cf_share", "scr_ms"))
    for n in health.get("nodes", []):
        lines.append(
            "%-10s %-5s %-5s %-22s %10s %10s %8s %8s"
            % (n.get("name", "?"),
               "yes" if n.get("up") else "NO",
               "yes" if n.get("stale") else "-",
               (n.get("generation") or "-")[:22],
               ("%d" % n["requests_total"])
               if n.get("requests_total") is not None else "-",
               ("%.1f" % n["p99_e2e_us"])
               if n.get("p99_e2e_us") is not None else "-",
               ("%.2f" % n["confirm_share"])
               if n.get("confirm_share") is not None else "-",
               n.get("scrape_ms", "-")))
        if n.get("error"):
            lines.append("           error: %s" % n["error"])
    findings = health.get("skew_findings", [])
    lines.append("")
    if findings:
        lines.append("skew findings (%d):" % len(findings))
        for f in findings:
            lines.append("  [%s] %s: %s"
                         % (f.get("kind", "?"), f.get("node", "?"),
                            f.get("detail", "")))
    else:
        lines.append("skew findings: none")
    prof = health.get("merged_profile") or {}
    if "content_hash" in prof:
        lines.append("merged profile: %s (%s requests, %s rules)"
                     % (prof["content_hash"], prof.get("requests"),
                        prof.get("rules")))
    else:
        lines.append("merged profile: %s"
                     % (prof.get("error") or "unavailable"))
    lines.append("")
    lines.append("%-16s %-6s %-10s %10s %12s %12s"
                 % ("slo", "window", "verdict", "objective",
                    "burn", "error_rate"))
    for name, rec in sorted((slo.get("slos") or {}).items()):
        for wname, w in sorted(rec.get("windows", {}).items()):
            lines.append(
                "%-16s %-6s %-10s %10s %12s %12s"
                % (name, wname, rec.get("verdict", "?"),
                   rec.get("objective", "-"),
                   "-" if w.get("burn") is None else w["burn"],
                   "-" if w.get("error_rate") is None
                   else w["error_rate"]))
    lines.append("")
    lines.append("fleet SLO verdict: %s" % slo.get("verdict", "?"))
    return "\n".join(lines)


def render_fleetctl(journal: dict, lkg: dict,
                    daemon_tail: list) -> str:
    """Terminal view for `dbg fleetctl` (ISSUE 19): the fleet rollout
    journal (per-node stage + ack ledger), the fleet LKG pointer, and
    the retune daemon's last cycles — all read from the shared
    --lkg-dir, so it works with the control plane down (that is the
    point: this is the view an operator reads DURING an incident)."""
    lines = []
    if journal:
        lines.append("fleet rollout: %s  (wave at node %s)"
                     % (journal.get("state", "?"),
                        journal.get("node_idx", "?")))
        lines.append("candidate: %s   incumbent: %s"
                     % (journal.get("candidate") or "-",
                        journal.get("incumbent") or "-"))
        if journal.get("rollback_reason"):
            lines.append("last rollback: %s" % journal["rollback_reason"])
        lines.append("")
        acks = journal.get("acks") or {}
        lines.append("%-10s %-10s %-22s" % ("node", "stage", "acked"))
        for i, name in enumerate(journal.get("nodes") or []):
            idx = journal.get("node_idx", 0)
            stage = ("done" if name in acks
                     else "rolling" if i == idx
                     and journal.get("state") in ("canary", "promoting")
                     else "pending")
            lines.append("%-10s %-10s %-22s"
                         % (name, stage, acks.get(name, "-")))
    else:
        lines.append("fleet rollout: no journal (no wave has run)")
    lines.append("")
    if lkg:
        lines.append("fleet LKG: %s" % lkg.get("version", "?"))
        lines.append("  artifact: %s" % lkg.get("artifact", "?"))
        for name, ver in sorted((lkg.get("acks") or {}).items()):
            lines.append("  ack %-8s %s" % (name, ver))
    else:
        lines.append("fleet LKG: none written yet")
    lines.append("")
    if daemon_tail:
        last = daemon_tail[-1]
        lines.append("retune daemon: last cycle %s  (%s)"
                     % (last.get("result", "?"),
                        last.get("detail") or last.get("drift") or ""))
        lines.append("%-6s %-24s %s" % ("cycle", "result", "detail"))
        for rec in daemon_tail:
            lines.append("%-6s %-24s %s"
                         % (rec.get("cycle", "?"),
                            rec.get("result", "?"),
                            (rec.get("detail") or "")[:48]))
    else:
        lines.append("retune daemon: no ledger (daemon has not run)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.control.dbg")
    ap.add_argument("cmd",
                    choices=["conf", "health", "metrics", "latency",
                             "tenants", "ruleset", "acl", "rulecheck",
                             "concheck", "evadecheck", "rules", "drift",
                             "breaker", "faults", "rollout", "scoring",
                             "timeline", "fleet", "fleetctl"])
    ap.add_argument("--cycles", type=int, default=6,
                    help="timeline: how many recent cycles to render "
                         "(the Gantt view of /debug/trace)")
    ap.add_argument("--server", default="127.0.0.1:9901")
    ap.add_argument("--rules", default=None,
                    help="rulecheck: rules tree to analyze (default: "
                         "the bundled CRS tree)")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "notice", "info"],
                    help="rulecheck: gate severity for the exit code")
    ap.add_argument("--set", dest="set_json", default=None,
                    help="tenants: JSON tenant→tags table to push")
    ap.add_argument("--swap", default=None,
                    help="ruleset: checkpoint artifact path to hot-swap")
    ap.add_argument("--force", action="store_true",
                    help="ruleset: break-glass one-shot swap (skip the "
                         "guarded staged rollout)")
    ap.add_argument("--abort", action="store_true",
                    help="rollout: abort an in-flight staged rollout "
                         "(rolls back to the incumbent)")
    ap.add_argument("--paranoia", type=int, default=2)
    ap.add_argument("--sidecar", default=None,
                    help="latency: also scrape the native sidecar's "
                         "--status-port JSON at this host:port")
    ap.add_argument("--lkg-dir", default=None,
                    help="fleetctl: the shared fleet LKG dir (rollout "
                         "journal + LKG pointer + daemon ledger)")
    args = ap.parse_args(argv)

    if args.cmd == "fleetctl":
        # file-plane view: reads the shared --lkg-dir directly, no
        # serve process involved (works mid-incident by design)
        import os as _os

        from ingress_plus_tpu.control.fleetctl import (
            FLEET_JOURNAL, load_fleet_lkg)
        from ingress_plus_tpu.control.retuned import JOURNAL_NAME

        if not args.lkg_dir:
            ap.error("fleetctl needs --lkg-dir")
        journal = None
        jpath = _os.path.join(args.lkg_dir, FLEET_JOURNAL)
        if _os.path.exists(jpath):
            with open(jpath) as f:
                journal = json.load(f)
        lkg = load_fleet_lkg(args.lkg_dir)
        tail = []
        lpath = _os.path.join(args.lkg_dir, JOURNAL_NAME)
        if _os.path.exists(lpath):
            with open(lpath) as f:
                for line in f.read().splitlines()[-12:]:
                    try:
                        tail.append(json.loads(line))
                    except ValueError:
                        continue
        print(render_fleetctl(journal, lkg, tail))
        return 0

    if args.cmd in ("rulecheck", "concheck", "evadecheck"):
        # local analysis, no serve plane involved — delegate to the
        # analyzer CLI so dbg and `python -m ingress_plus_tpu.analysis`
        # render and gate identically
        from ingress_plus_tpu.analysis.__main__ import main as rc_main
        rc_args = ["--fail-on", args.fail_on]
        if args.cmd == "concheck":
            rc_args.append("--conc")
        else:
            if args.cmd == "evadecheck":
                rc_args.append("--evade")
            if args.rules:
                rc_args += ["--rules", args.rules]
        return rc_main(rc_args)

    try:
        if args.cmd == "rules":
            stats = json.loads(_call(args.server, "/rules/stats?n=64"))
            rules_health = json.loads(_call(args.server, "/rules/health"))
            out = render_rules(stats, rules_health)
        elif args.cmd == "drift":
            out = render_drift(json.loads(_call(args.server,
                                                "/rules/drift")))
        elif args.cmd == "breaker":
            out = render_breaker(json.loads(_call(args.server,
                                                  "/healthz")))
        elif args.cmd == "rollout":
            if args.abort:
                out = render_rollout(json.loads(_call(
                    args.server, "/rollout", {"action": "abort"})))
            else:
                out = render_rollout(json.loads(_call(args.server,
                                                      "/rollout")))
        elif args.cmd == "scoring":
            if args.swap:
                # staged scoring-head push (the admission gate answers;
                # --force = break-glass one-shot install)
                out = _call(args.server,
                            "/configuration/scoring"
                            + ("?mode=force" if args.force else ""),
                            {"path": args.swap}, timeout=300)
            else:
                out = render_scoring(json.loads(_call(args.server,
                                                      "/scoring")))
        elif args.cmd == "faults":
            if args.set_json is not None:
                # --set 'dispatch_hang:times=1' installs; --set '' clears
                out = render_faults(json.loads(_call(
                    args.server, "/faults", {"spec": args.set_json})))
            else:
                out = render_faults(json.loads(_call(args.server,
                                                     "/faults")))
        elif args.cmd == "fleet":
            # --server here is the AGGREGATOR (control/fleetobs.py),
            # default port 9911, not a serve node
            srv = args.server
            if srv == "127.0.0.1:9901":
                srv = "127.0.0.1:9911"
            out = render_fleet(
                json.loads(_call(srv, "/fleet/healthz")),
                json.loads(_call(srv, "/fleet/slo")))
        elif args.cmd == "timeline":
            trace = json.loads(_call(
                args.server, "/debug/trace?cycles=%d"
                % max(args.cycles, 1)))
            out = render_timeline(trace, max_cycles=max(args.cycles, 1))
        elif args.cmd == "latency":
            metrics = _call(args.server, "/metrics")
            slow = json.loads(_call(args.server, "/debug/slow"))
            sidecar = None
            if args.sidecar:
                sidecar = json.loads(_call(args.sidecar, "/"))
            out = render_latency(metrics, slow, sidecar)
        elif args.cmd == "conf":
            out = _call(args.server, "/configuration")
        elif args.cmd == "health":
            out = _call(args.server, "/healthz")
        elif args.cmd == "metrics":
            out = _call(args.server, "/metrics")
        elif args.cmd == "tenants":
            if args.set_json:
                out = _call(args.server, "/configuration/tenants",
                            json.loads(args.set_json))
            else:
                # the tenant-isolation plane (fair queue + flood
                # guard), not just the mask count — /configuration
                # still carries the latter
                out = render_tenants(json.loads(_call(args.server,
                                                      "/tenants")))
        elif args.cmd == "acl":
            if args.set_json:
                # push: {"acls": {name: {allow/deny/greylist: [cidr]}},
                #        "tenant_acl": {"0": name}, "default": name}
                out = _call(args.server, "/configuration/acl",
                            json.loads(args.set_json))
            else:
                out = _call(args.server, "/configuration")
        else:  # ruleset
            if not args.swap:
                print("ruleset requires --swap <artifact path>",
                      file=sys.stderr)
                return 2
            # the push responds only after the admission gate (staged)
            # or the full compile+swap (force) — minutes-grade, not 10s
            out = _call(args.server,
                        "/configuration/ruleset"
                        + ("?mode=force" if args.force else ""),
                        {"path": args.swap,
                         "paranoia_level": args.paranoia}, timeout=300)
    except (OSError, ValueError) as e:  # ValueError covers bad --set JSON
        print("error: %s" % e, file=sys.stderr)
        return 1
    print(out.strip())
    return 0


if __name__ == "__main__":
    sys.exit(main())
