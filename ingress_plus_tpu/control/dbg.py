"""dbg — inspection CLI for the serve loop's dynamic-config plane.

Reference: `cmd/dbg/main.go`† queries the controller's Lua unix-socket
endpoints (`/configuration/backends`, ...) to show the live dynamic
state.  Same idea against our HTTP plane:

    python -m ingress_plus_tpu.control.dbg conf     [--server host:port]
    python -m ingress_plus_tpu.control.dbg health
    python -m ingress_plus_tpu.control.dbg metrics
    python -m ingress_plus_tpu.control.dbg tenants --set '{"1": ["attack-sqli"]}'
    python -m ingress_plus_tpu.control.dbg ruleset --swap /path/artifact \
        [--paranoia 2]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _call(server: str, path: str, payload=None, timeout: float = 10) -> str:
    url = "http://%s%s" % (server, path)
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method="POST" if data else "GET",
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.control.dbg")
    ap.add_argument("cmd",
                    choices=["conf", "health", "metrics", "tenants",
                             "ruleset", "acl"])
    ap.add_argument("--server", default="127.0.0.1:9901")
    ap.add_argument("--set", dest="set_json", default=None,
                    help="tenants: JSON tenant→tags table to push")
    ap.add_argument("--swap", default=None,
                    help="ruleset: checkpoint artifact path to hot-swap")
    ap.add_argument("--paranoia", type=int, default=2)
    args = ap.parse_args(argv)

    try:
        if args.cmd == "conf":
            out = _call(args.server, "/configuration")
        elif args.cmd == "health":
            out = _call(args.server, "/healthz")
        elif args.cmd == "metrics":
            out = _call(args.server, "/metrics")
        elif args.cmd == "tenants":
            if args.set_json:
                out = _call(args.server, "/configuration/tenants",
                            json.loads(args.set_json))
            else:
                out = _call(args.server, "/configuration")
        elif args.cmd == "acl":
            if args.set_json:
                # push: {"acls": {name: {allow/deny/greylist: [cidr]}},
                #        "tenant_acl": {"0": name}, "default": name}
                out = _call(args.server, "/configuration/acl",
                            json.loads(args.set_json))
            else:
                out = _call(args.server, "/configuration")
        else:  # ruleset
            if not args.swap:
                print("ruleset requires --swap <artifact path>",
                      file=sys.stderr)
                return 2
            # the swap responds only after the new pipeline is compiled
            # and warm (zero serve gap) — minutes-grade, not 10s
            out = _call(args.server, "/configuration/ruleset",
                        {"path": args.swap,
                         "paranoia_level": args.paranoia}, timeout=300)
    except (OSError, ValueError) as e:  # ValueError covers bad --set JSON
        print("error: %s" % e, file=sys.stderr)
        return 1
    print(out.strip())
    return 0


if __name__ == "__main__":
    sys.exit(main())
