"""Fleet telemetry plane: scrape, merge, judge (ISSUE 18).

Every observability surface before this PR is per-process; ROADMAP
item 4 names the missing half precisely — "telemetry that aggregates".
``FleetObserver`` is that layer: a node registry + resilient scraper
over the per-node HTTP surfaces (``/metrics``, ``/healthz``,
``/rules/stats?format=profile``, ``/rules/drift``), aggregation with
per-kind semantics, and an SLO burn-rate engine on top of the merged
stream.  The shape mirrors the reference's postanalytics rollup (per-
node WAF telemetry merges before any cluster decision) and the
per-device→pool aggregation of the parallel-firewall decomposition
(arXiv:1312.4188).

Aggregation semantics (docs/OBSERVABILITY.md "Fleet telemetry"):

=============  =========================================================
metric kind    fleet semantics
=============  =========================================================
counter        SUM over reachable nodes — conservation guaranteed:
               the fleet value equals Σ per-node values by
               construction, and fleetgate/bench assert it against
               independently counted traffic
histogram      bucket-wise merge (``Histogram.merge``) — lossless
               because every node shares the fixed log2 bounds; a
               bounds mismatch is a *skew finding*, never a crash
gauge          min/max/mean rollup (``agg=`` label) + per-node detail
               (``node=`` label, emitted while the fleet is small
               enough to stay inside the cardinality budget)
info joints    value-1 label carriers (``*_info``) re-keyed as
               node-counts per label tuple and cross-checked: a node
               serving a stale pack generation is a first-class
               skew finding
=============  =========================================================

A node that fails its scrape is marked down (and *stale* if we ever
reached it), excluded from every rollup — conservation then holds
over the reachable subset, which the fault-matrix ``fleet_scrape``
scenario pins.  Skew findings cover generation skew, per-node e2e p99
outliers, and confirm-share outliers.

The aggregator serves ``/fleet/metrics``, ``/fleet/healthz``,
``/fleet/drift``, ``/fleet/slo``, and ``/fleet/profile`` (the merged
``MeasuredProfile`` canonical bytes — the artifact the continuous-
retune daemon consumes).  Transport is pluggable: real nodes scrape
over urllib HTTP; in-process ServeLoops (fleetgate, tests) scrape
through ``ServeLoop.http_get`` with zero sockets.
"""

from __future__ import annotations

import argparse
import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ingress_plus_tpu.compiler.profile import (
    MeasuredProfile, ProfileVersionError)
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils import promparse
from ingress_plus_tpu.utils.slo import DEFAULT_SLOS, SLO, SLOEngine
from ingress_plus_tpu.utils.trace import Histogram

__all__ = ["FleetObserver", "Node", "ScrapeError", "fetch_http"]

#: per-node detail gauges carry a node= label only while the fleet is
#: small enough to stay inside promlint's cardinality budget
MAX_NODE_DETAIL = 32

#: scrape paths pulled per node per cycle (one failure fails the node's
#: whole cycle — a half-scraped node is skew, not data)
SCRAPE_PATHS = ("/metrics", "/healthz", "/rules/stats?format=profile",
                "/rules/drift")

#: p99 outlier: a node pages when its e2e p99 exceeds the fleet median
#: by this factor AND by an absolute floor (a 3µs-vs-1µs "outlier" on
#: an idle fleet is noise, not skew)
P99_OUTLIER_FACTOR = 2.0
P99_OUTLIER_FLOOR_US = 1000.0

#: confirm-share outlier: flag a node whose confirm share of stage time
#: exceeds the fleet median by both this factor and absolute margin
CONFIRM_SHARE_FACTOR = 1.5
CONFIRM_SHARE_MARGIN = 0.10


class ScrapeError(RuntimeError):
    """One node's scrape cycle failed (transport error, non-2xx,
    injected fault) — the node goes down/stale, the cycle continues."""


# transport: (node, path) -> body bytes, raising ScrapeError on failure
Transport = Callable[[str], bytes]


def fetch_http(target: str, timeout_s: float = 3.0) -> Transport:
    """Default transport: GET http://target/path with a hard timeout."""
    import urllib.error
    import urllib.request

    def _fetch(path: str) -> bytes:
        url = "http://%s%s" % (target, path)
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                if not 200 <= r.status < 300:
                    raise ScrapeError("%s -> HTTP %d" % (url, r.status))
                return r.read()
        except ScrapeError:
            raise
        except Exception as e:
            raise ScrapeError("%s: %s" % (url, e)) from e
    return _fetch


def serve_loop_transport(serve) -> Transport:
    """In-process transport over ``ServeLoop.http_get`` — the zero-
    socket path fleetgate and the tests scrape through."""
    def _fetch(path: str) -> bytes:
        status, _ctype, body = serve.http_get(path)
        if not status.startswith("2"):
            raise ScrapeError("%s -> %s" % (path, status))
        return body
    return _fetch


@dataclass
class Node:
    """Registry entry + last-scrape state for one serve process."""

    name: str
    target: str = ""                  # host:port ("" = custom transport)
    transport: Optional[Transport] = None
    up: bool = False
    stale: bool = False               # reached before, unreachable now
    error: str = ""
    scrapes: int = 0
    failures: int = 0
    scrape_ms: float = 0.0
    exposition: Optional[promparse.Exposition] = None
    healthz: Dict = field(default_factory=dict)
    profile: Optional[MeasuredProfile] = None
    profile_raw: bytes = b""
    drift: Dict = field(default_factory=dict)

    def fetch(self, path: str) -> bytes:
        t = self.transport or fetch_http(self.target)
        return t(path)


class FleetObserver:
    """The aggregator: scrape every registered node, merge per metric
    kind, cross-check generations, feed the SLO engine, and serve the
    ``/fleet/*`` surfaces."""

    def __init__(self, slos: Tuple[SLO, ...] = DEFAULT_SLOS,
                 clock: Callable[[], float] = time.monotonic,
                 latency_stage: str = "e2e",
                 node_timeout_s: float = 5.0,
                 cycle_timeout_s: float = 15.0):
        self.nodes: List[Node] = []
        #: per-node scrape budget / whole-cycle bound (ISSUE 19): a
        #: node past its budget goes stale exactly like a refused one
        self.node_timeout_s = node_timeout_s
        self.cycle_timeout_s = cycle_timeout_s
        self.slo_engine = SLOEngine(slos, clock=clock)
        self.latency_stage = latency_stage
        self.scrape_cycles = 0
        self.scrape_errors = 0
        self._lock = threading.Lock()
        self._agg_lines: List[str] = []
        self._skew: List[Dict] = []
        self._counters: Dict[str, float] = {}
        self._per_node_counters: Dict[str, Dict[str, float]] = {}
        self._merged_profile: Optional[MeasuredProfile] = None
        self._profile_error: str = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd = None

    # -------------------------------------------------------- registry

    def add_node(self, name: str, target: str = "",
                 transport: Optional[Transport] = None) -> Node:
        if any(n.name == name for n in self.nodes):
            raise ValueError("duplicate node name %r" % name)
        if not target and transport is None:
            raise ValueError("node %r needs a target or a transport"
                             % name)
        node = Node(name=name, target=target, transport=transport)
        self.nodes.append(node)
        return node

    # -------------------------------------------------------- scraping

    @staticmethod
    def _fetch_node(node: Node) -> Dict[str, bytes]:
        """Pure fetch of every scrape path (worker thread).  Mutates
        NOTHING — a fetch abandoned past its budget can complete late
        without tearing node state a later cycle already rewrote."""
        return {"metrics": node.fetch("/metrics"),
                "healthz": node.fetch("/healthz"),
                "profile": node.fetch("/rules/stats?format=profile"),
                "drift": node.fetch("/rules/drift")}

    @staticmethod
    def _apply_node(node: Node, res: Dict[str, bytes],
                    ms: float) -> None:
        """Parse + install one node's fetched payloads (cycle thread)."""
        node.exposition = promparse.parse_exposition(
            res["metrics"].decode("utf-8", "replace"))
        try:
            node.healthz = json.loads(res["healthz"])
        except ValueError:
            node.healthz = {}
        node.profile_raw = res["profile"]
        try:
            node.profile = MeasuredProfile.from_json(
                res["profile"].decode("utf-8", "replace"))
        except (ValueError, KeyError):
            node.profile = None
        try:
            node.drift = json.loads(res["drift"])
        except ValueError:
            node.drift = {}
        node.scrape_ms = round(ms, 3)

    def scrape(self) -> Dict:
        """One scrape cycle over the registry, then re-aggregate and
        feed the SLO engine.  Node fetches run CONCURRENTLY, each with
        its own timeout budget, and the whole cycle is bounded — one
        hung node costs its own sample, never its siblings' (ISSUE 19).
        Fault sites still fire on the cycle thread in node order, so a
        seeded plan replays deterministically; stale accounting is
        unchanged.  Never raises on a node failure."""
        import concurrent.futures as cf

        deadline = time.monotonic() + self.cycle_timeout_s
        ex = cf.ThreadPoolExecutor(
            max_workers=max(1, min(len(self.nodes) or 1, 8)),
            thread_name_prefix="fleet-scrape")
        started: Dict[str, float] = {}
        futs: Dict[str, "cf.Future"] = {}
        injected: Dict[str, Exception] = {}
        for node in self.nodes:
            node.scrapes += 1
            try:
                # fault sites (utils/faults.py): every shape of scrape
                # failure, armed per node-scrape arrival so plans can
                # target the Nth node of the Nth cycle deterministically
                if faults.fire("scrape_timeout"):
                    raise ScrapeError("injected scrape timeout")
                if faults.fire("scrape_5xx"):
                    raise ScrapeError("injected scrape 5xx")
                if faults.fire("node_partition"):
                    raise ScrapeError("injected node partition")
            except ScrapeError as e:
                injected[node.name] = e
                continue
            started[node.name] = time.perf_counter()
            futs[node.name] = ex.submit(self._fetch_node, node)
        for node in self.nodes:
            err: Optional[Exception] = injected.get(node.name)
            if err is None:
                fut = futs.get(node.name)
                if fut is None:
                    continue
                budget = min(self.node_timeout_s,
                             max(0.0, deadline - time.monotonic()))
                try:
                    res = fut.result(timeout=budget)
                    self._apply_node(
                        node, res,
                        (time.perf_counter() - started[node.name]) * 1e3)
                    node.up = True
                    node.stale = False
                    node.error = ""
                    continue
                except cf.TimeoutError:
                    err = ScrapeError(
                        "scrape budget exceeded (%.1fs)" % budget)
                except Exception as e:   # noqa: BLE001 — resilience is
                    # the contract: one dying node must not stop the cycle
                    err = e
            node.failures += 1
            node.stale = node.up or node.stale
            node.up = False
            node.error = str(err)
            self.scrape_errors += 1
        ex.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            self.scrape_cycles += 1
            self._aggregate()
            self._feed_slos()
        return self.healthz()

    # ----------------------------------------------------- aggregation

    def _reachable(self) -> List[Node]:
        return [n for n in self.nodes
                if n.up and n.exposition is not None]

    def _aggregate(self) -> None:
        """Rebuild the aggregated exposition + skew findings from the
        last scrape of every reachable node.  Caller holds the lock."""
        nodes = self._reachable()
        skew: List[Dict] = []
        lines: List[str] = []
        counters: Dict[str, float] = {}
        per_node: Dict[str, Dict[str, float]] = {}

        # union of families over reachable nodes, deterministic order
        fam_names: List[str] = sorted(
            {name for n in nodes for name in n.exposition.families})
        for fname in fam_names:
            ftype = "untyped"
            fhelp = None
            for n in nodes:
                fam = n.exposition.families.get(fname)
                if fam is None:
                    continue
                if fam.type != "untyped":
                    ftype = fam.type
                if fhelp is None and fam.help:
                    fhelp = fam.help
            if ftype == "histogram":
                lines += self._merge_histogram(fname, fhelp, nodes, skew)
            elif ftype == "counter":
                lines += self._merge_counter(fname, fhelp, nodes,
                                             counters, per_node)
            else:
                lines += self._merge_gauge(fname, ftype, fhelp, nodes)

        lines += self._self_series()
        skew += self._generation_skew(nodes)
        skew += self._latency_skew(nodes)
        skew += self._confirm_share_skew(nodes)
        self._merge_profiles(nodes)

        self._agg_lines = lines
        self._skew = skew
        self._counters = counters
        self._per_node_counters = per_node

    @staticmethod
    def _fmt(v: float) -> str:
        if math.isnan(v):
            return "NaN"
        if float(v).is_integer() and abs(v) < 1e15:
            return "%d" % int(v)
        return repr(round(v, 9))

    def _merge_counter(self, fname: str, fhelp: Optional[str],
                       nodes: List[Node], counters: Dict[str, float],
                       per_node: Dict[str, Dict[str, float]]
                       ) -> List[str]:
        """SUM per labelset over reachable nodes — conservation by
        construction, with the per-node addends kept for the bench and
        gate to audit independently."""
        sums: Dict[str, Tuple[Dict[str, str], float]] = {}
        for n in nodes:
            fam = n.exposition.families.get(fname)
            if fam is None:
                continue
            node_total = 0.0
            for s in fam.samples:
                key = "%s|%s" % (s.name, promparse.group_key(s.labels))
                labels, cur = sums.get(key, (s.labels, 0.0))
                sums[key] = (labels, cur + s.value)
                node_total += s.value
            per_node.setdefault(fname, {})[n.name] = node_total
        counters[fname] = sum(v for _l, v in sums.values())
        if not sums:
            return []
        lines = ["# HELP %s %s" % (fname, fhelp or "fleet sum"),
                 "# TYPE %s counter" % fname]
        for key in sorted(sums):
            labels, val = sums[key]
            name = key.split("|", 1)[0]
            lab = "".join('%s="%s",' % kv
                          for kv in sorted(labels.items()))
            lines.append("%s%s %s"
                         % (name,
                            ("{%s}" % lab.rstrip(",")) if lab else "",
                            self._fmt(val)))
        return lines

    def _merge_histogram(self, fname: str, fhelp: Optional[str],
                         nodes: List[Node], skew: List[Dict]
                         ) -> List[str]:
        """Bucket-wise merge per labelset via Histogram.merge; a bounds
        mismatch books a skew finding and skips that labelset."""
        groups: Dict[str, List[Tuple[str, Dict]]] = {}
        for n in nodes:
            for key, rec in n.exposition.histogram_series(fname).items():
                groups.setdefault(key, []).append((n.name, rec))
        if not groups:
            return []
        lines = ["# HELP %s %s" % (fname, fhelp or "fleet merge"),
                 "# TYPE %s histogram" % fname]
        for key in sorted(groups):
            hists = []
            labels: Dict[str, str] = {}
            bad = False
            for node_name, rec in groups[key]:
                labels = rec["labels"]
                pts = rec["buckets"]
                if not pts or pts[-1][0] != math.inf:
                    bad = True
                    skew.append({
                        "kind": "histogram_shape", "node": node_name,
                        "detail": "%s{%s}: no +Inf bucket"
                                  % (fname, key)})
                    continue
                bounds = [int(le) for le, _v in pts[:-1]]
                try:
                    hists.append(Histogram.from_cumulative(
                        bounds, [v for _le, v in pts],
                        rec["sum"] or 0))
                except ValueError as e:
                    bad = True
                    skew.append({
                        "kind": "histogram_shape", "node": node_name,
                        "detail": "%s{%s}: %s" % (fname, key, e)})
            if not hists:
                continue
            try:
                merged = Histogram.merge(hists)
            except ValueError as e:
                skew.append({"kind": "histogram_bounds_mismatch",
                             "node": "*",
                             "detail": "%s{%s}: %s" % (fname, key, e)})
                continue
            if bad and not hists:
                continue
            lines += merged.prometheus(fname, labels or None)
        return lines

    def _merge_gauge(self, fname: str, ftype: str,
                     fhelp: Optional[str], nodes: List[Node]
                     ) -> List[str]:
        """min/max/mean rollup (+ per-node detail while small); info
        joints (``*_info``) become node-counts per label tuple."""
        if fname.endswith("_info"):
            return self._merge_info(fname, fhelp, nodes)
        groups: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}
        for n in nodes:
            fam = n.exposition.families.get(fname)
            if fam is None:
                continue
            for s in fam.samples:
                key = "%s|%s" % (s.name, promparse.group_key(s.labels))
                groups.setdefault(key, []).append(
                    (n.name, s.labels, s.value))
        if not groups:
            return []
        lines = ["# HELP %s %s" % (fname, fhelp or "fleet rollup"),
                 "# TYPE %s gauge" % fname]
        detail = len(nodes) <= MAX_NODE_DETAIL
        for key in sorted(groups):
            rows = groups[key]
            name = key.split("|", 1)[0]
            labels = rows[0][1]
            vals = [v for _n, _l, v in rows if not math.isnan(v)]
            base = "".join('%s="%s",' % kv
                           for kv in sorted(labels.items()))
            for agg, val in (("min", min(vals) if vals else math.nan),
                             ("max", max(vals) if vals else math.nan),
                             ("mean", (sum(vals) / len(vals))
                              if vals else math.nan)):
                lines.append('%s{%sagg="%s"} %s'
                             % (name, base, agg, self._fmt(val)))
            if detail:
                for node_name, _l, v in sorted(rows):
                    lines.append('%s{%snode="%s"} %s'
                                 % (name, base, node_name,
                                    self._fmt(v)))
        return lines

    def _merge_info(self, fname: str, fhelp: Optional[str],
                    nodes: List[Node]) -> List[str]:
        counts: Dict[str, Tuple[Dict[str, str], int]] = {}
        for n in nodes:
            fam = n.exposition.families.get(fname)
            if fam is None:
                continue
            for s in fam.samples:
                key = promparse.group_key(s.labels)
                labels, c = counts.get(key, (s.labels, 0))
                counts[key] = (labels, c + 1)
        if not counts:
            return []
        lines = ["# HELP %s %s (fleet: value = nodes serving this "
                 "label tuple)" % (fname, fhelp or "info joint"),
                 "# TYPE %s gauge" % fname]
        for key in sorted(counts):
            labels, c = counts[key]
            lab = "".join('%s="%s",' % kv
                          for kv in sorted(labels.items()))
            lines.append("%s%s %d"
                         % (fname,
                            ("{%s}" % lab.rstrip(",")) if lab else "",
                            c))
        return lines

    def _self_series(self) -> List[str]:
        """The aggregator's own health metrics."""
        up = sum(1 for n in self.nodes if n.up)
        stale = sum(1 for n in self.nodes if n.stale)
        return [
            "# HELP ipt_fleet_nodes registered fleet nodes",
            "# TYPE ipt_fleet_nodes gauge",
            "ipt_fleet_nodes %d" % len(self.nodes),
            "# HELP ipt_fleet_nodes_up nodes reachable at last scrape",
            "# TYPE ipt_fleet_nodes_up gauge",
            "ipt_fleet_nodes_up %d" % up,
            "# HELP ipt_fleet_nodes_stale nodes reached before but "
            "unreachable now (excluded from every rollup)",
            "# TYPE ipt_fleet_nodes_stale gauge",
            "ipt_fleet_nodes_stale %d" % stale,
            "# HELP ipt_fleet_scrape_cycles_total completed scrape "
            "cycles",
            "# TYPE ipt_fleet_scrape_cycles_total counter",
            "ipt_fleet_scrape_cycles_total %d" % self.scrape_cycles,
            "# HELP ipt_fleet_scrape_errors_total node scrapes that "
            "failed",
            "# TYPE ipt_fleet_scrape_errors_total counter",
            "ipt_fleet_scrape_errors_total %d" % self.scrape_errors,
        ]

    # ---------------------------------------------------- skew findings

    def _generation_skew(self, nodes: List[Node]) -> List[Dict]:
        """Cross-check ``ipt_ruleset_info`` version labels: nodes off
        the majority generation are skew (the exact condition a fleet
        rollout must converge away)."""
        versions: Dict[str, List[str]] = {}
        for n in nodes:
            v = n.exposition.value("ipt_ruleset_info")
            fam = n.exposition.families.get("ipt_ruleset_info")
            ver = ""
            if fam is not None and fam.samples:
                ver = fam.samples[0].labels.get("version", "")
            if v is not None and ver:
                versions.setdefault(ver, []).append(n.name)
        if len(versions) <= 1:
            return []
        majority = max(sorted(versions),
                       key=lambda v: (len(versions[v]), v))
        out = []
        for ver in sorted(versions):
            if ver == majority:
                continue
            for name in sorted(versions[ver]):
                out.append({
                    "kind": "generation_skew", "node": name,
                    "generation": ver,
                    "detail": "serving pack generation %r; fleet "
                              "majority is %r" % (ver, majority)})
        return out

    def _node_p99(self, n: Node) -> Optional[float]:
        series = n.exposition.histogram_series("ipt_stage_us")
        for rec in series.values():
            if rec["labels"].get("stage") != self.latency_stage:
                continue
            pts = rec["buckets"]
            if not pts or pts[-1][0] != math.inf or pts[-1][1] <= 0:
                return None
            bounds = [int(le) for le, _v in pts[:-1]]
            try:
                h = Histogram.from_cumulative(
                    bounds, [v for _le, v in pts], rec["sum"] or 0)
            except ValueError:
                return None
            return h.percentile(0.99)
        return None

    def _latency_skew(self, nodes: List[Node]) -> List[Dict]:
        p99s = [(n.name, self._node_p99(n)) for n in nodes]
        p99s = [(name, v) for name, v in p99s if v is not None]
        if len(p99s) < 3:
            return []
        med = sorted(v for _n, v in p99s)[len(p99s) // 2]
        out = []
        for name, v in sorted(p99s):
            if (v > med * P99_OUTLIER_FACTOR
                    and v - med > P99_OUTLIER_FLOOR_US):
                out.append({
                    "kind": "p99_outlier", "node": name,
                    "detail": "e2e p99 %.0fus vs fleet median %.0fus"
                              % (v, med)})
        return out

    @staticmethod
    def _confirm_share(n: Node) -> Optional[float]:
        exp = n.exposition
        parts = [exp.value("ipt_prep_us_sum"),
                 exp.value("ipt_engine_us_sum"),
                 exp.value("ipt_confirm_us_sum")]
        if any(p is None for p in parts):
            return None
        total = sum(parts)
        if total <= 0:
            return None
        return parts[2] / total

    def _confirm_share_skew(self, nodes: List[Node]) -> List[Dict]:
        shares = [(n.name, self._confirm_share(n)) for n in nodes]
        shares = [(name, v) for name, v in shares if v is not None]
        if len(shares) < 3:
            return []
        med = sorted(v for _n, v in shares)[len(shares) // 2]
        out = []
        for name, v in sorted(shares):
            if (v > med * CONFIRM_SHARE_FACTOR
                    and v - med > CONFIRM_SHARE_MARGIN):
                out.append({
                    "kind": "confirm_share_outlier", "node": name,
                    "detail": "confirm share %.2f vs fleet median %.2f"
                              % (v, med)})
        return out

    # ------------------------------------------------- profile merging

    def _merge_profiles(self, nodes: List[Node]) -> None:
        profs = [n.profile for n in nodes if n.profile is not None]
        if not profs:
            self._merged_profile = None
            self._profile_error = "no node profiles scraped"
            return
        try:
            self._merged_profile = MeasuredProfile.merge(profs)
            self._profile_error = ""
        except (ProfileVersionError, ValueError) as e:
            self._merged_profile = None
            self._profile_error = str(e)

    # ------------------------------------------------------ SLO feeding

    def _feed_slos(self) -> None:
        """Derive cumulative (good, total) per declared SLO from the
        merged counters and histogram and feed the engine.  Caller
        holds the lock."""
        nodes = self._reachable()
        req = self._counters.get("ipt_requests_total", 0.0)
        fail_open = self._counters.get("ipt_fail_open_total", 0.0)
        degraded = self._counters.get("ipt_degraded_verdicts_total",
                                      0.0)
        for s in self.slo_engine.slos:
            if s.kind == "availability" and s.tenant is None:
                good = max(req - fail_open - degraded, 0.0)
                self.slo_engine.observe(s.name, good, req)
            elif s.kind == "availability":
                good = total = 0.0
                for n in nodes:
                    t = n.exposition.counter_total(
                        "ipt_tenant_requests_total",
                        tenant=str(s.tenant))
                    d = n.exposition.counter_total(
                        "ipt_tenant_degraded_total",
                        tenant=str(s.tenant))
                    total += t
                    good += max(t - d, 0.0)
                self.slo_engine.observe(s.name, good, total)
            elif s.kind == "latency":
                good, total = self._latency_counts(nodes, s.budget_us)
                self.slo_engine.observe(s.name, good, total)

    def _latency_counts(self, nodes: List[Node], budget_us: int
                        ) -> Tuple[float, float]:
        """(requests under budget, requests) from the merged e2e
        histogram's cumulative buckets: good = cumulative count at the
        smallest bound >= budget (a conservative read — the bucket
        bound caps the true latency of everything it counts)."""
        good = total = 0.0
        for n in nodes:
            series = n.exposition.histogram_series("ipt_stage_us")
            for rec in series.values():
                if rec["labels"].get("stage") != self.latency_stage:
                    continue
                pts = rec["buckets"]
                if not pts or pts[-1][0] != math.inf:
                    continue
                total += pts[-1][1]
                g = 0.0
                for le, v in pts:
                    if le >= budget_us:
                        g = v
                        break
                good += g
        return good, total

    # ------------------------------------------------------- rendering

    def fleet_metrics(self) -> str:
        with self._lock:
            lines = list(self._agg_lines)
        lines += self.slo_engine.prometheus_lines()
        return "\n".join(lines) + "\n"

    def healthz(self) -> Dict:
        with self._lock:
            skew = list(self._skew)
            prof = self._merged_profile
            prof_err = self._profile_error
        node_rows = []
        for n in self.nodes:
            gen = ""
            if n.exposition is not None:
                fam = n.exposition.families.get("ipt_ruleset_info")
                if fam is not None and fam.samples:
                    gen = fam.samples[0].labels.get("version", "")
            p99 = self._node_p99(n) if n.exposition is not None else None
            share = (self._confirm_share(n)
                     if n.exposition is not None else None)
            req = (n.exposition.value("ipt_requests_total")
                   if n.exposition is not None else None)
            node_rows.append({
                "name": n.name, "target": n.target, "up": n.up,
                "stale": n.stale, "error": n.error,
                "generation": gen,
                "requests_total": req,
                "p99_e2e_us": round(p99, 1) if p99 is not None
                else None,
                "confirm_share": round(share, 4) if share is not None
                else None,
                "scrape_ms": n.scrape_ms,
                "scrapes": n.scrapes, "failures": n.failures,
            })
        return {
            "status": self.slo_engine.fleet_verdict(),
            "nodes": node_rows,
            "nodes_up": sum(1 for n in self.nodes if n.up),
            "nodes_stale": sum(1 for n in self.nodes if n.stale),
            "scrape_cycles": self.scrape_cycles,
            "scrape_errors": self.scrape_errors,
            "skew_findings": skew,
            "merged_profile": ({"content_hash": prof.content_hash(),
                                "requests": prof.requests,
                                "rules": len(prof.rules)}
                               if prof is not None
                               else {"error": prof_err}),
        }

    def fleet_drift(self) -> Dict:
        """Per-node drift reports + the fleet union of went-quiet rules
        with node attribution."""
        per_node: Dict[str, Dict] = {}
        quiet: Dict[str, List[str]] = {}
        for n in self.nodes:
            if not n.up or not n.drift:
                continue
            per_node[n.name] = n.drift
            for rec in (n.drift.get("went_quiet") or []):
                rid = str(rec.get("rule") if isinstance(rec, dict)
                          else rec)
                quiet.setdefault(rid, []).append(n.name)
        return {
            "nodes": per_node,
            "fleet_went_quiet": [
                {"rule": rid, "nodes": sorted(names)}
                for rid, names in sorted(quiet.items())],
        }

    def fleet_slo(self) -> Dict:
        return {
            "verdict": self.slo_engine.fleet_verdict(),
            "slos": self.slo_engine.burn_rates(),
        }

    def counters_snapshot(self) -> Tuple[Dict[str, float],
                                         Dict[str, Dict[str, float]]]:
        """(fleet counter sums, per-node addends) — the conservation
        audit surface fleetgate and bench check against independently
        counted traffic."""
        with self._lock:
            return dict(self._counters), {
                k: dict(v) for k, v in self._per_node_counters.items()}

    def merged_profile(self) -> Optional[MeasuredProfile]:
        with self._lock:
            return self._merged_profile

    # ------------------------------------------------------ HTTP plane

    def route(self, path: str) -> Tuple[str, str, bytes]:
        """Sync router for the /fleet/* surfaces (same (status, ctype,
        body) contract as ServeLoop._route_http)."""
        if path.startswith("/fleet/metrics"):
            return ("200 OK", "text/plain; version=0.0.4",
                    self.fleet_metrics().encode())
        if path.startswith("/fleet/healthz"):
            return ("200 OK", "application/json",
                    json.dumps(self.healthz()).encode())
        if path.startswith("/fleet/drift"):
            return ("200 OK", "application/json",
                    json.dumps(self.fleet_drift()).encode())
        if path.startswith("/fleet/slo"):
            return ("200 OK", "application/json",
                    json.dumps(self.fleet_slo()).encode())
        if path.startswith("/fleet/profile"):
            prof = self.merged_profile()
            if prof is None:
                return ("503 Service Unavailable", "application/json",
                        json.dumps({"error": self._profile_error
                                    or "no merged profile"}).encode())
            return ("200 OK", "application/json",
                    prof.to_json().encode())
        return ("404 Not Found", "application/json",
                json.dumps({"error": "unknown path %s" % path,
                            "routes": ["/fleet/metrics",
                                       "/fleet/healthz",
                                       "/fleet/drift", "/fleet/slo",
                                       "/fleet/profile"]}).encode())

    def serve_http(self, port: int = 0,
                   host: str = "127.0.0.1") -> int:
        """Expose the /fleet/* plane on a real TCP port (daemon
        thread); returns the bound port."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        obs = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:           # noqa: N802 (stdlib API)
                status, ctype, body = obs.route(self.path)
                self.send_response(int(status.split()[0]))
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:  # silence stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="fleetobs-http", daemon=True)
        t.start()
        return int(self._httpd.server_address[1])

    def start_scraping(self, interval_s: float = 5.0) -> None:
        """Background scrape loop (daemon thread)."""
        def _loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.scrape()
                except Exception:    # noqa: BLE001 — the loop survives
                    pass
        self._thread = threading.Thread(target=_loop,
                                        name="fleetobs-scraper",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet telemetry aggregator: scrape N serve "
                    "nodes, serve /fleet/*")
    ap.add_argument("--node", action="append", default=[],
                    metavar="NAME=HOST:PORT", required=False,
                    help="register a node (repeatable)")
    ap.add_argument("--port", type=int, default=9911,
                    help="aggregator HTTP port (0 = ephemeral)")
    ap.add_argument("--interval-s", type=float, default=5.0)
    ap.add_argument("--once", action="store_true",
                    help="scrape once, print /fleet/healthz, exit")
    args = ap.parse_args(argv)
    if not args.node:
        ap.error("at least one --node NAME=HOST:PORT is required")
    obs = FleetObserver()
    for spec in args.node:
        name, _, target = spec.partition("=")
        if not target:
            ap.error("--node must be NAME=HOST:PORT, got %r" % spec)
        obs.add_node(name, target=target)
    obs.scrape()
    if args.once:
        print(json.dumps(obs.healthz(), indent=2))
        return 0
    port = obs.serve_http(port=args.port)
    print("fleetobs: serving /fleet/* on 127.0.0.1:%d, scraping %d "
          "nodes every %.1fs" % (port, len(obs.nodes),
                                 args.interval_s))
    obs.start_scraping(args.interval_s)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        obs.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
