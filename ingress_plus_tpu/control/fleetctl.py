"""Fleet-staged rollout: the PR 5 state machine, across nodes.

One pack, N serve nodes (docs/SERVING.md "Fleet serving").  The single-
node RolloutController stages a candidate through shadow → canary →
ramp on ONE process; this module sequences those rollouts across the
fleet so a bad pack is caught by the cheapest possible blast radius:

1. **Central admission** — the candidate clears the static/compile/
   golden-replay gates ONCE, on the canary node.  A rejection here
   touches no traffic anywhere.
2. **Canary node** — the canary node's own staged rollout (shadow
   mirror, ramped canary lanes) runs to LIVE while every sibling keeps
   serving the incumbent.
3. **Node-by-node promote** — siblings admit the already-vetted pack
   one at a time.  Between promotions the fleet observer's skew
   findings act as tripwires: a node serving a generation that is
   neither incumbent nor candidate, or a fresh p99/confirm-share
   outlier on a just-promoted node, halts the wave.
4. **Fleet rollback** — ANY node rejecting (or a tripwire firing)
   rolls the WHOLE fleet back to the fleet LKG pointer: one artifact,
   one per-node ack ledger.  The journal is rewritten at every
   transition, so a controller that crashes mid-wave converges every
   node back to LKG at restart (``recover()``) — the fleet never stays
   split-brained between generations.

The fleet LKG pointer (``FLEET_LKG``) is separate from each node's own
LKG: it names the last pack that went live on EVERY node, plus which
version each node last acknowledged.  Writes are write-then-rename,
like control/rollout.py's per-node pointer.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from ingress_plus_tpu.control.rollout import (
    LIVE,
    REJECTED,
    ROLLED_BACK,
    RolloutController,
    RolloutRejected,
    persist_lkg,
)
from ingress_plus_tpu.utils import faults

FLEET_IDLE = "idle"
FLEET_ADMITTED = "admitted"
FLEET_CANARY = "canary"
FLEET_PROMOTING = "promoting"
FLEET_LIVE = "live"
FLEET_ROLLED_BACK = "rolled_back"

FLEET_STATES = (FLEET_IDLE, FLEET_ADMITTED, FLEET_CANARY,
                FLEET_PROMOTING, FLEET_LIVE, FLEET_ROLLED_BACK)

FLEET_LKG_POINTER = "FLEET_LKG"
FLEET_JOURNAL = "fleet_rollout.json"

#: skew kinds that halt a promotion wave when they name a node the wave
#: already touched (generation skew is handled separately — it is
#: EXPECTED mid-wave between promoted and pending nodes)
TRIPWIRE_KINDS = ("p99_outlier", "confirm_share_outlier")


def _atomic_write(path: Path, text: str) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def load_fleet_lkg(lkg_dir) -> Optional[dict]:
    """The fleet pointer: {"artifact", "version", "acks"} or None."""
    ptr = Path(lkg_dir) / FLEET_LKG_POINTER
    if not ptr.is_file():
        return None
    try:
        return json.loads(ptr.read_text())
    except (OSError, ValueError):
        return None


def load_fleet_lkg_pack(lkg_dir):
    """CompiledRuleset behind the fleet pointer, or None."""
    from ingress_plus_tpu.compiler.ruleset import CompiledRuleset

    meta = load_fleet_lkg(lkg_dir)
    if meta is None:
        return None
    try:
        faults.raise_if("lkg_corrupt")
        return CompiledRuleset.load(Path(lkg_dir) / meta["artifact"])
    except Exception:
        return None


class FleetNode:
    """fleetctl's handle on one serve node (in-process flavor): its
    batcher (for direct LKG convergence) and its RolloutController
    (for staged rollouts).  ``HttpFleetNode`` is the wire twin — same
    surface over /configuration/ruleset + /rollout."""

    def __init__(self, name: str, batcher, rollout: RolloutController):
        self.name = name
        self.batcher = batcher
        self.rollout = rollout

    @property
    def serving_version(self) -> str:
        return self.batcher.pipeline.ruleset.version

    def admit(self, ruleset=None, artifact_path=None,
              overrides=None) -> dict:
        return self.rollout.admit(ruleset=ruleset,
                                  artifact_path=artifact_path,
                                  overrides=overrides)

    def pump(self) -> None:
        self.rollout.tick()

    def state(self) -> str:
        return self.rollout.state

    def candidate_version(self) -> str:
        return self.rollout.status().get("candidate") or ""

    def failure_reason(self) -> str:
        ro = self.rollout
        return (ro.rollback_reason
                or (ro.last_admission or {}).get("reason", "")
                or ro.state)

    def abort(self, reason: str) -> bool:
        return self.rollout.abort(reason)

    def incumbent_pack(self):
        return self.batcher.pipeline.ruleset

    def converge_to(self, cr, artifact=None) -> bool:
        """Force-install ``cr`` (rollback/recovery path — the staged
        machinery is exactly what we're converging away from)."""
        if cr is None:
            return False
        if self.serving_version == cr.version:
            return True
        try:
            self.batcher.swap_ruleset(cr)
            return True
        except Exception:
            return False

    def status_brief(self) -> dict:
        st = self.rollout.status()
        return {"name": self.name,
                "generation": self.serving_version,
                "rollout_state": st["state"],
                "candidate": st["candidate"],
                "fraction": st["fraction"]}


class HttpFleetNode:
    """The wire twin of FleetNode for deployed fleets: staged rollouts
    ride POST /configuration/ruleset?mode=staged (artifact paths on a
    shared volume — deploy/ mounts the LKG dir fleet-wide), state rides
    GET /rollout, and LKG convergence is the break-glass ?mode=force
    swap.  Rulesets can only travel by artifact path here."""

    def __init__(self, name: str, target: str, timeout_s: float = 30.0):
        self.name = name
        self.target = target          # "host:port"
        self.timeout_s = timeout_s

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            "http://%s%s" % (self.target, path),
            data=(json.dumps(payload).encode()
                  if payload is not None else None),
            headers={"Content-Type": "application/json"},
            method=method)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:  # structured 4xx bodies
            try:
                return json.loads(e.read() or b"{}")
            except ValueError:
                return {"error": "http %d" % e.code}
        except (urllib.error.URLError, OSError) as e:
            # an unreachable node must surface as a node-level failure
            # (converge_failed / unreachable), never an exception — a
            # dead node is precisely when fleet_rollback runs, and it
            # promises "partial failures are reported, not raised"
            return {"error":
                    "unreachable: %s" % (getattr(e, "reason", None) or e)}

    @property
    def serving_version(self) -> str:
        st = self._call("GET", "/rollout")
        return str(st.get("incumbent", ""))

    def admit(self, ruleset=None, artifact_path=None,
              overrides=None) -> dict:
        if artifact_path is None:
            raise RolloutRejected(
                "load", "no_artifact", "",
                {"error": "HTTP nodes admit artifact paths only"})
        payload = {"path": str(artifact_path)}
        payload.update(overrides or {})
        rep = self._call("POST", "/configuration/ruleset?mode=staged",
                         payload)
        if rep.get("rejected") or rep.get("error"):
            raise RolloutRejected(
                rep.get("stage", "admit"),
                rep.get("reason", rep.get("error", "rejected")),
                str(artifact_path), rep)
        return rep

    def pump(self) -> None:
        pass  # the remote batcher ticks its own rollout

    def state(self) -> str:
        st = self._call("GET", "/rollout")
        if "state" not in st and st.get("error"):
            return "unreachable"
        return str(st.get("state", "idle"))

    def candidate_version(self) -> str:
        return str(self._call("GET", "/rollout").get("candidate") or "")

    def failure_reason(self) -> str:
        st = self._call("GET", "/rollout")
        return str(st.get("rollback_reason") or st.get("error")
                   or st.get("state", ""))

    def abort(self, reason: str) -> bool:
        return bool(self._call("POST", "/rollout",
                               {"action": "abort"}).get("aborted"))

    def incumbent_pack(self):
        return None  # pack bytes live on the node, not here

    def converge_to(self, cr, artifact=None) -> bool:
        if artifact is None:
            return False
        if cr is not None and self.serving_version == cr.version:
            return True
        rep = self._call("POST", "/configuration/ruleset?mode=force",
                         {"path": str(artifact)})
        return bool(rep.get("ruleset"))

    def status_brief(self) -> dict:
        st = self._call("GET", "/rollout")
        return {"name": self.name,
                "generation": st.get("incumbent"),
                "rollout_state": st.get("state", "unreachable"),
                "candidate": st.get("candidate"),
                "fraction": st.get("fraction")}


class FleetController:
    """Sequences per-node staged rollouts; owns the fleet LKG pointer,
    the per-node ack ledger, and the crash-recovery journal."""

    def __init__(self, nodes: List[FleetNode], lkg_dir,
                 observer=None,
                 traffic_pump: Optional[Callable[[FleetNode], None]]
                 = None):
        if not nodes:
            raise ValueError("a fleet needs at least one node")
        self.nodes = list(nodes)
        self.lkg_dir = Path(lkg_dir)
        self.lkg_dir.mkdir(parents=True, exist_ok=True)
        self.observer = observer       # FleetObserver | None (tripwires)
        self.traffic_pump = traffic_pump
        self.state = FLEET_IDLE
        self.candidate_version = ""
        self.incumbent_version = ""
        self.rollback_reason = ""
        self.rollbacks = 0
        self.fleet_promotions = 0
        self.acks: Dict[str, str] = {}     # node → acked pack version
        self.last_admission: Optional[dict] = None
        self.last_recovery: Optional[dict] = None
        self._idx = 0                      # node currently rolling
        self._candidate_src: dict = {}
        self._candidate_cr = None          # CompiledRuleset | None
        self._tripwire_seen: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------- ledger / journal

    @property
    def journal_path(self) -> Path:
        return self.lkg_dir / FLEET_JOURNAL

    def _write_journal(self) -> None:
        _atomic_write(self.journal_path, json.dumps({
            "state": self.state,
            "candidate": self.candidate_version,
            "incumbent": self.incumbent_version,
            "node_idx": self._idx,
            "acks": dict(self.acks),
            "rollback_reason": self.rollback_reason,
            "nodes": [n.name for n in self.nodes],
            "updated": time.time(),
        }, indent=1))

    def _write_fleet_lkg(self, cr) -> None:
        """Persist the pack + move the fleet pointer (atomic)."""
        base = persist_lkg(cr, self.lkg_dir)
        _atomic_write(self.lkg_dir / FLEET_LKG_POINTER, json.dumps({
            "artifact": base.name,
            "version": cr.version,
            "acks": {n.name: n.serving_version for n in self.nodes},
            "updated": time.time(),
        }))

    def _ensure_fleet_lkg(self) -> None:
        """Before the first wave ever: the incumbent IS the LKG (a
        rollback target must exist before anything can need one)."""
        if load_fleet_lkg(self.lkg_dir) is not None:
            return
        inc = self.nodes[0].incumbent_pack()
        if inc is not None:
            self._write_fleet_lkg(inc)

    # ------------------------------------------------------ admission

    def begin(self, ruleset=None, artifact_path=None,
              overrides: Optional[dict] = None) -> dict:
        """Central admission on the canary node.  Returns the admission
        report; a rejection leaves the fleet idle and untouched."""
        with self._lock:
            if self.state in (FLEET_ADMITTED, FLEET_CANARY,
                              FLEET_PROMOTING):
                raise RuntimeError("fleet rollout already in flight "
                                   "(state=%s)" % self.state)
            self.state = FLEET_ADMITTED
        self.incumbent_version = self.nodes[0].serving_version
        self.rollback_reason = ""
        self.acks = {}
        self._idx = 0
        self._tripwire_seen = set()
        self._candidate_src = {"ruleset": ruleset,
                               "artifact_path": artifact_path,
                               "overrides": overrides}
        self._candidate_cr = ruleset
        if ruleset is None and artifact_path is not None:
            try:
                from ingress_plus_tpu.compiler.ruleset import \
                    CompiledRuleset

                self._candidate_cr = CompiledRuleset.load(artifact_path)
            except Exception:
                self._candidate_cr = None
        self._ensure_fleet_lkg()
        try:
            report = self.nodes[0].admit(ruleset=ruleset,
                                         artifact_path=artifact_path,
                                         overrides=overrides)
        except RolloutRejected as e:
            with self._lock:
                self.state = FLEET_IDLE
            self.last_admission = {"ok": False, **e.report}
            self._write_journal()
            return self.last_admission
        self.candidate_version = self.nodes[0].candidate_version()
        self.last_admission = {"ok": True, **report}
        with self._lock:
            self.state = FLEET_CANARY
        self._write_journal()
        return self.last_admission

    # ------------------------------------------------------ the wave

    def _check_tripwires(self) -> Optional[str]:
        if self.observer is None:
            return None
        try:
            findings = self.observer.healthz().get("skew_findings") or []
        except Exception:
            return None
        touched = set(self.acks) | {self.nodes[self._idx].name
                                    if self._idx < len(self.nodes)
                                    else ""}
        expected = {self.incumbent_version, self.candidate_version}
        for f in findings:
            kind, node = f.get("kind"), f.get("node")
            key = (kind, node, f.get("detail"))
            if key in self._tripwire_seen:
                continue
            if kind in TRIPWIRE_KINDS and node in touched:
                self._tripwire_seen.add(key)
                return "%s:%s" % (kind, node)
            if kind == "generation_skew":
                # mid-wave incumbent/candidate split is the PLAN; a
                # generation outside that pair is an alien pack.  Only
                # the node's OWN generation decides (the detail string
                # also names the fleet majority, which almost always IS
                # incumbent or candidate — matching against it would
                # never flag the alien node)
                gen = f.get("generation")
                if gen is None:  # older observers: first %r in detail
                    m = re.match(r"serving pack generation '([^']*)'",
                                 f.get("detail", ""))
                    gen = m.group(1) if m else None
                if gen is not None and gen not in expected:
                    self._tripwire_seen.add(key)
                    return "alien_generation:%s" % node
        return None

    def poll(self) -> str:
        """Advance the wave one step.  Call from the control loop (the
        retune daemon / drill pump); traffic itself rides the nodes."""
        if self.state not in (FLEET_CANARY, FLEET_PROMOTING):
            return self.state
        tripped = self._check_tripwires()
        if tripped:
            self.fleet_rollback("skew_tripwire:" + tripped)
            return self.state
        node = self.nodes[self._idx]
        node.pump()
        st = node.state()
        if st in (REJECTED, ROLLED_BACK):
            self.fleet_rollback("node:%s:%s"
                                % (node.name, node.failure_reason()))
            return self.state
        if st != LIVE or node.serving_version != self.candidate_version:
            return self.state
        # node done: ack it, move the wave on
        self.acks[node.name] = self.candidate_version
        self._idx += 1
        if self._idx >= len(self.nodes):
            self._finalize()
            return self.state
        with self._lock:
            self.state = FLEET_PROMOTING
        self._write_journal()
        nxt = self.nodes[self._idx]
        try:
            nxt.admit(**self._candidate_src)
        except RolloutRejected as e:
            self.fleet_rollback("node:%s:admission:%s"
                                % (nxt.name, e.report.get("reason")))
        return self.state

    def _finalize(self) -> None:
        strays = [n.name for n in self.nodes
                  if n.serving_version != self.candidate_version]
        if strays:
            self.fleet_rollback("post_wave_divergence:%s"
                                % ",".join(strays))
            return
        cr = self._candidate_cr or self.nodes[0].incumbent_pack()
        if cr is not None:
            self._write_fleet_lkg(cr)
        with self._lock:
            self.state = FLEET_LIVE
            self.fleet_promotions += 1
        self._write_journal()

    def drive(self, deadline_s: float = 120.0) -> str:
        """Pump the wave to a terminal state (in-process harnesses: the
        traffic_pump supplies each node's rollout the traffic it needs
        to walk its ramp)."""
        deadline = time.monotonic() + deadline_s
        while (self.state in (FLEET_CANARY, FLEET_PROMOTING)
               and time.monotonic() < deadline):
            if self.traffic_pump is not None:
                self.traffic_pump(self.nodes[min(self._idx,
                                                 len(self.nodes) - 1)])
            self.poll()
        return self.state

    # ------------------------------------------------------ rollback

    def fleet_rollback(self, reason: str) -> dict:
        """Converge EVERY node to the fleet LKG: abort in-flight
        rollouts, force-install the LKG pack wherever the serving
        generation differs.  Partial failures are reported, not
        raised — a node that cannot converge is an operator page."""
        with self._lock:
            self.state = FLEET_ROLLED_BACK
            self.rollback_reason = reason
            self.rollbacks += 1
        lkg_cr = load_fleet_lkg_pack(self.lkg_dir)
        meta = load_fleet_lkg(self.lkg_dir)
        artifact = (self.lkg_dir / meta["artifact"]
                    if meta and meta.get("artifact") else None)
        per_node = {}
        for n in self.nodes:
            n.abort("fleet_rollback:" + reason)
            if lkg_cr is None and artifact is None:
                per_node[n.name] = "no_fleet_lkg"
                continue
            ok = n.converge_to(lkg_cr, artifact)
            per_node[n.name] = "converged" if ok else "converge_failed"
            if ok and lkg_cr is not None:
                self.acks[n.name] = lkg_cr.version
        self._write_journal()
        report = {"reason": reason, "nodes": per_node,
                  "lkg": getattr(lkg_cr, "version", None)}
        self.last_recovery = report
        return report

    # ------------------------------------------------------ recovery

    def recover(self) -> dict:
        """Crash-mid-wave convergence: if the journal says a rollout
        was in flight, every node converges to the fleet LKG before
        anything else happens.  Idempotent; safe to call at every
        startup."""
        try:
            journal = json.loads(self.journal_path.read_text())
        except (OSError, ValueError):
            return {"recovered": False, "why": "no journal"}
        if journal.get("state") not in (FLEET_ADMITTED, FLEET_CANARY,
                                        FLEET_PROMOTING,
                                        FLEET_ROLLED_BACK):
            return {"recovered": False,
                    "why": "journal state %r is terminal"
                           % journal.get("state")}
        lkg_cr = load_fleet_lkg_pack(self.lkg_dir)
        if lkg_cr is None:
            return {"recovered": False, "why": "no fleet LKG pack"}
        meta = load_fleet_lkg(self.lkg_dir)
        artifact = (self.lkg_dir / meta["artifact"]
                    if meta and meta.get("artifact") else None)
        per_node = {}
        for n in self.nodes:
            n.abort("fleet_recovery")
            ok = n.converge_to(lkg_cr, artifact)
            per_node[n.name] = "converged" if ok else "converge_failed"
            if ok:
                self.acks[n.name] = lkg_cr.version
        with self._lock:
            self.state = FLEET_IDLE
            self.candidate_version = ""
            self.rollback_reason = "recovered:%s" % journal.get("state")
        self._write_journal()
        report = {"recovered": True,
                  "from_state": journal.get("state"),
                  "lkg": lkg_cr.version, "nodes": per_node}
        self.last_recovery = report
        return report

    # ------------------------------------------------------ status

    def status(self) -> dict:
        with self._lock:
            idx = self._idx
            return {
                "state": self.state,
                "candidate": self.candidate_version or None,
                "incumbent": self.incumbent_version or None,
                "node_idx": idx,
                "rollbacks": self.rollbacks,
                "fleet_promotions": self.fleet_promotions,
                "rollback_reason": self.rollback_reason,
                "acks": dict(self.acks),
                "lkg": load_fleet_lkg(self.lkg_dir),
                "nodes": [{
                    **n.status_brief(),
                    "stage": ("done" if n.name in self.acks
                              else "rolling" if (i == idx and self.state
                                                 in (FLEET_CANARY,
                                                     FLEET_PROMOTING))
                              else "pending"),
                    "acked": self.acks.get(n.name),
                } for i, n in enumerate(self.nodes)],
            }


# ===================================================== node harness
# In-process fleet for drills/scenarios: each node is a real Batcher +
# ServeLoop with its UDS plane served from a background thread, so the
# front speaks to it over the actual wire — and ``kill()`` severs the
# listener AND every established connection, exactly like SIGKILL.


class NodeHarness:
    """One in-process serve node with a kill/revive switch."""

    def __init__(self, name: str, batcher, socket_path: str):
        from ingress_plus_tpu.serve.server import ServeLoop

        self.name = name
        self.batcher = batcher
        self.socket_path = socket_path
        self.serve = ServeLoop(batcher, socket_path=socket_path)
        self._loop = None
        self._stop_ev = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        import asyncio

        ready = threading.Event()

        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            stop = asyncio.Event()
            self._stop_ev = stop

            async def _main() -> None:
                await self.serve.start()
                ready.set()
                await stop.wait()
                for s in self.serve._servers:
                    s.close()
                self.serve._servers = []

            try:
                loop.run_until_complete(_main())
            finally:
                loop.close()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="node-" + self.name)
        self._thread.start()
        if not ready.wait(timeout=15):
            raise RuntimeError("node %s failed to start" % self.name)

    def kill(self) -> None:
        """Sever the node's wire presence (listener + live conns); the
        batcher stays warm so ``revive()`` is instant."""
        done = threading.Event()

        def _k() -> None:
            for s in self.serve._servers:
                s.close()
            self.serve._servers = []
            for w in list(self.serve._conn_writers):
                try:
                    w.transport.abort()
                except Exception:
                    pass
            done.set()

        if self._loop is not None:
            self._loop.call_soon_threadsafe(_k)
            done.wait(timeout=10)

    def revive(self) -> None:
        import asyncio

        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self.serve.start(),
                                               self._loop)
        fut.result(timeout=15)

    def close(self) -> None:
        if self._loop is not None and self._stop_ev is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop_ev.set)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.batcher.close()


def build_drill_fleet(n_nodes: int, lkg_dir,
                      socket_prefix: str = "/tmp/ipt-fdrill",
                      observer: bool = False, **batcher_kw):
    """N in-process drill nodes (incumbent pack) + a front over them +
    a FleetController wired with the drill traffic pump.  Returns
    (harnesses, front, fleet, obs) — caller owns teardown."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.control.rollout import (
        _DRILL_INCUMBENT, _drill_config, _drill_traffic)
    from ingress_plus_tpu.serve.front import BackendNode, FrontLoop
    from ingress_plus_tpu.utils.faults import _mk_batcher

    cr_inc = compile_ruleset(parse_seclang(_DRILL_INCUMBENT))
    harnesses = []
    fleet_nodes = []
    for i in range(n_nodes):
        b = _mk_batcher(cr=cr_inc, **batcher_kw)
        ro = RolloutController(b, _drill_config())
        b.rollout = ro
        h = NodeHarness("n%d" % i, b,
                        "%s-%d-%d.sock" % (socket_prefix, os.getpid(), i))
        h.start()
        harnesses.append(h)
        fleet_nodes.append(FleetNode(h.name, b, ro))

    obs = None
    if observer:
        from ingress_plus_tpu.control.fleetobs import (
            FleetObserver, serve_loop_transport)

        obs = FleetObserver()
        for h in harnesses:
            obs.add_node(h.name,
                         transport=serve_loop_transport(h.serve))

    backends = [BackendNode(
        name=h.name, socket_path=h.socket_path,
        probe=(lambda s=h.serve:
               s.http_get("/readyz")[0].startswith("200")))
        for h in harnesses]
    front = FrontLoop(backends,
                      "%s-%d-front.sock" % (socket_prefix, os.getpid()),
                      probe_interval_s=0.2)
    front.start_background()

    wave = [0]

    def _pump(node: FleetNode) -> None:
        wave[0] += 1
        _drill_traffic(node.batcher, 24, "fleet%d" % wave[0])

    fleet = FleetController(fleet_nodes, lkg_dir, observer=obs,
                            traffic_pump=_pump)
    return harnesses, front, fleet, obs


def run_fleet_drill(lkg_dir=None) -> dict:
    """Drive the whole fleet control plane end to end in one process —
    the ``fleetdrill`` CI gate (tools/lint.py --ci) asserts ``passed``:

    1. **front_kill** — a 3-node front wave with one node killed
       mid-send: zero verdict loss, no silent unblocked attacks;
    2. **fleet_live** — the good candidate admitted once centrally,
       canaried, promoted node by node to LIVE everywhere, fleet LKG
       written with every ack;
    3. **bad_pack_rejected** — the broken pack stopped at central
       admission, fleet untouched;
    4. **mid_wave_rollback** — a node failing mid-promote rolls the
       WHOLE fleet back to the fleet LKG;
    5. **daemon_cycle** — one forced retune-daemon cycle end to end:
       profile → four gates → fleet-staged rollout to LIVE.
    """
    import tempfile

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.control.rollout import (
        _DRILL_BROKEN, _DRILL_CANDIDATE, _DRILL_INCUMBENT)
    from ingress_plus_tpu.control.retuned import ROLLOUT_LIVE, RetuneDaemon
    from ingress_plus_tpu.utils import faults
    from ingress_plus_tpu.utils.faults import FaultPlan, _front_wave

    tmp = None
    if lkg_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="ipt-fleetdrill-")
        lkg_dir = tmp.name
    report: Dict[str, dict] = {}
    saved = faults.active()
    faults.clear()
    harnesses, front, fleet, obs = build_drill_fleet(
        3, lkg_dir, socket_prefix="/tmp/ipt-fleetdrill", observer=True)
    live = [(harnesses, front)]   # whichever build the finally must reap
    try:
        # --- leg 1: node killed mid-wave behind the front
        violations: List[str] = []
        faults.install(FaultPlan.from_spec("node_kill:times=1"))
        _front_wave(front, 32, "warm", violations)
        got = _front_wave(front, 64, "kill", violations,
                          kill=harnesses[1].kill)
        faults.clear()
        report["front_kill"] = {
            "ok": len(got) == 64 and not violations,
            "verdicts": len(got), "sent": 64,
            "violations": violations,
        }
        harnesses[1].revive()

        # --- leg 2: good pack to LIVE fleet-wide
        cr_good = compile_ruleset(parse_seclang(_DRILL_CANDIDATE))
        adm = fleet.begin(ruleset=cr_good)
        state = fleet.drive(deadline_s=120) if adm.get("ok") else fleet.state
        lkg = load_fleet_lkg(lkg_dir)
        report["fleet_live"] = {
            "ok": (state == FLEET_LIVE
                   and all(n.serving_version == cr_good.version
                           for n in fleet.nodes)
                   and bool(lkg) and lkg["version"] == cr_good.version
                   and len(lkg["acks"]) == len(fleet.nodes)),
            "state": state, "acks": dict(fleet.acks),
            "lkg": lkg and lkg["version"],
        }

        # --- leg 3: broken pack stopped at central admission
        cr_bad = compile_ruleset(parse_seclang(_DRILL_BROKEN))
        adm = fleet.begin(ruleset=cr_bad)
        report["bad_pack_rejected"] = {
            "ok": (not adm.get("ok") and fleet.state == FLEET_IDLE
                   and all(n.serving_version == cr_good.version
                           for n in fleet.nodes)),
            "stage": adm.get("stage"), "reason": adm.get("reason"),
        }

        # --- leg 4: mid-wave node failure → fleet rollback to LKG
        cr_inc = compile_ruleset(parse_seclang(_DRILL_INCUMBENT))
        adm = fleet.begin(ruleset=cr_inc)
        faults.install(FaultPlan.from_spec("swap_fail:after=1,times=1"))
        state = fleet.drive(deadline_s=120) if adm.get("ok") else fleet.state
        faults.clear()
        report["mid_wave_rollback"] = {
            "ok": (state == FLEET_ROLLED_BACK
                   and all(n.serving_version == cr_good.version
                           for n in fleet.nodes)),
            "state": state, "reason": fleet.rollback_reason,
        }

        # --- leg 5: one forced daemon cycle end to end.  Fresh fleet:
        # the kill/rollback legs above left REAL timing skew behind
        # (which the tripwires would rightly act on — that is their
        # job); the daemon leg proves the happy path on a steady-state
        # fleet like the one a deployed daemon watches.
        front.stop()
        for h in harnesses:
            h.close()
        live.clear()
        harnesses, front, fleet, obs = build_drill_fleet(
            3, os.path.join(lkg_dir, "daemon"),
            socket_prefix="/tmp/ipt-fleetdrill2", observer=True)
        live.append((harnesses, front))
        daemon = RetuneDaemon(obs, fleet, lkg_dir,
                              rules=parse_seclang(_DRILL_INCUMBENT),
                              min_interval_s=0.0, cooldown_s=0.0,
                              retune_kw={"corpus_n": 64, "ab": False,
                                         "staged": False})
        for node in fleet.nodes:      # an even profile on every node
            fleet.traffic_pump(node)
        obs.scrape()
        rec = daemon.cycle(force=True)
        report["daemon_cycle"] = {
            "ok": (rec["result"] == ROLLOUT_LIVE
                   and all(n.serving_version == rec.get("candidate")
                           for n in fleet.nodes)),
            "result": rec["result"], "detail": rec.get("detail", ""),
            "candidate": rec.get("candidate"),
            "gates": rec.get("gates"),
        }
        return {"passed": all(leg["ok"] for leg in report.values()),
                "legs": report}
    finally:
        faults.clear()
        if saved is not None:
            faults.install(saved)
        for hs, fr in live:
            fr.stop()
            for h in hs:
                h.close()
        if tmp is not None:
            tmp.cleanup()
