"""Sync controller: the `syncIngress` loop analog.

Reference flow (`internal/ingress/controller/nginx.go`†, SURVEY.md §3.2):

    informer event → build model → render template →
      IF only dynamic state changed: POST to the Lua endpoint (no reload)
      ELSE: nginx -t, diff, SIGHUP reload

Here the same decision, re-targeted:

- **render diff** → "reload" (the nginx shim must re-read directives);
- **tenant table change only** → "dynamic": POST the EP tenant rule-mask
  table to the serve loop's /configuration/tenants endpoint (the
  configuration.lua† unix-socket channel analog) — no reload, no serve
  gap;
- no change → "noop".

`tenant_masks` maps the model's tenant→rule-tags table onto the compiled
ruleset: tenant 0 always runs the full (paranoia-filtered) set; a tenant
with tags runs exactly the rules carrying ≥1 of its tags (per-tenant
verdict masks over one shared NFA — SURVEY.md §7 hard part #6: no
per-tenant recompilation).
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.model import (
    Configuration,
    build_configuration,
)
from ingress_plus_tpu.control.objects import ConfigMap, Ingress
from ingress_plus_tpu.control.template import render


MAX_TENANTS = 4096  # bounds the (T, R) mask allocation (config #4 is 256)


def validate_tenant_tags(raw) -> Dict[int, Tuple[str, ...]]:
    """Validate a tenant→rule-tags push payload (the
    ``/configuration/tenants`` body) into a canonical table — a
    structured reject instead of a silent truncation (ISSUE 10):

    - the payload must be a JSON object with at most ``MAX_TENANTS``
      entries (``tenant_masks`` silently drops ids past the bound, so
      an oversized push would install a partial table);
    - keys must be canonical base-10 tenant ids — ``"01"`` and ``"1"``
      would silently collapse into one mask row, last writer wins;
    - ids must sit in ``[0, MAX_TENANTS)``;
    - tag values must be lists of strings (a bare string iterates
      per-character into tags matching no rule → all-False mask →
      scan bypass)."""
    if not isinstance(raw, dict):
        raise ValueError("payload must be a JSON object")
    if len(raw) > MAX_TENANTS:
        raise ValueError(
            "too many tenants: %d entries > MAX_TENANTS=%d (the mask "
            "table would silently truncate)" % (len(raw), MAX_TENANTS))
    tags: Dict[int, Tuple[str, ...]] = {}
    for k, v in raw.items():
        if not isinstance(v, (list, tuple)) or not all(
                isinstance(t, str) for t in v):
            raise ValueError(
                "tenant %r: tag values must be lists of strings" % (k,))
        ks = k if isinstance(k, str) else str(k)
        try:
            t = int(ks)
        except (ValueError, TypeError):
            raise ValueError("tenant key %r is not an integer id" % (k,))
        if str(t) != ks:
            raise ValueError(
                "tenant key %r is not canonical (use %r — non-canonical "
                "keys silently collapse into one mask row)" % (k, str(t)))
        if not 0 <= t < MAX_TENANTS:
            raise ValueError("tenant ids must be in [0, %d)" % MAX_TENANTS)
        if t in tags:
            raise ValueError("duplicate tenant id %d" % t)
        tags[t] = tuple(v)
    return tags


def tenant_masks(cr: CompiledRuleset,
                 tenant_tags: Dict[int, Tuple[str, ...]]) -> np.ndarray:
    """(T, R) bool — row 0 = full ruleset (reserved, cannot be overridden);
    a tenant id NOT in the table also runs the full ruleset (all-True
    default): an unlisted tenant must never mean "scan nothing"."""
    ids = [t for t in tenant_tags if 0 < t < MAX_TENANTS]
    T = (max(ids) + 1) if ids else 1
    masks = np.ones((T, cr.n_rules), dtype=bool)
    rule_tags = [frozenset(m.rule.tags) for m in cr.rules]
    for t in ids:
        want = frozenset(tenant_tags[t])
        masks[t] = np.fromiter(
            (bool(want & rt) for rt in rule_tags), bool, cr.n_rules)
    return masks


@dataclass
class SyncResult:
    action: str                  # "reload" | "dynamic" | "noop"
    rendered: str
    configuration: Configuration
    pushed_tenants: bool = False
    pushed_acls: bool = False
    errors: List[str] = field(default_factory=list)


#: dynamic-push retry policy: bounded exponential backoff per channel
RETRY_BASE_S = 1.0
RETRY_MAX_S = 60.0


@dataclass
class _PushChannel:
    """Dirty-state tracking for one dynamic-push channel.

    A failed push used to leave ``last_*`` stale and hope a later sync
    re-diffed it — a push that kept failing was retried on EVERY tick
    (no backoff against a struggling serve loop), and a push=False tick
    in between silently marked it clean (the update was dropped until
    the next unrelated diff).  Now the desired payload is pinned here
    until it lands: every sync tick retries dirty channels whose backoff
    has elapsed, with the LATEST payload, converging regardless of what
    else changed in between."""

    path: str
    payload: object = None
    dirty: bool = False
    attempts: int = 0
    next_retry: float = 0.0    # monotonic deadline for the next attempt

    def mark(self, payload) -> None:
        if self.dirty and payload != self.payload:
            # intent changed mid-retry: push the NEW payload promptly —
            # the old backoff was earned by a stale body
            self.attempts = 0
            self.next_retry = 0.0
        self.payload = payload
        self.dirty = True


class SyncController:
    def __init__(self, global_config: Optional[GlobalConfig] = None,
                 serve_http: Optional[str] = None):
        self.global_config = global_config or GlobalConfig()
        self.serve_http = serve_http or self.global_config.sidecar_http
        self.last_rendered: Optional[str] = None
        self.last_tenants: Optional[Dict[int, Tuple[str, ...]]] = None
        self.last_acls: Optional[dict] = None
        self._channels: Dict[str, _PushChannel] = {
            "tenants": _PushChannel("/configuration/tenants"),
            "acl": _PushChannel("/configuration/acl"),
        }
        self._now = time.monotonic   # injectable clock (tests)

    def _post(self, path: str, obj) -> bool:
        url = "http://%s%s" % (self.serve_http, path)
        try:
            req = urllib.request.Request(
                url, data=json.dumps(obj).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except OSError:
            return False

    def _acl_payload(self, cfg: Configuration) -> dict:
        """wallarm-acl push body: ACL content from the ConfigMap tier
        (GlobalConfig.acls JSON), bindings from per-Ingress annotations.
        Bindings naming an ACL with no content are dropped with a model
        error (the serve endpoint would reject the whole push)."""
        try:
            specs = json.loads(self.global_config.acls) \
                if self.global_config.acls else {}
            if not isinstance(specs, dict):
                raise ValueError("acls must be a JSON object")
        except (ValueError, TypeError) as e:
            cfg.errors.append("acls configmap value: %s" % e)
            specs = {}
        binding = {}
        for t, name in sorted(cfg.tenant_acls().items()):
            if name in specs:
                binding[str(t)] = name
            else:
                cfg.errors.append(
                    "tenant %d: wallarm-acl %r has no list content" % (t, name))
        return {"acls": specs, "tenant_acl": binding}

    def flush_pending(self) -> Dict[str, bool]:
        """Attempt every dirty channel whose backoff has elapsed; the
        retry half of the sync tick (also callable from a bare timer).
        Returns {channel: landed} for the channels actually attempted."""
        out: Dict[str, bool] = {}
        now = self._now()
        for name, ch in self._channels.items():
            if not ch.dirty or now < ch.next_retry:
                continue
            ok = self._post(ch.path, ch.payload)
            out[name] = ok
            if ok:
                ch.dirty = False
                ch.attempts = 0
                ch.next_retry = 0.0
            else:
                ch.attempts += 1
                ch.next_retry = now + min(
                    RETRY_BASE_S * (2 ** (ch.attempts - 1)), RETRY_MAX_S)
        return out

    def retry_state(self) -> Dict[str, dict]:
        """Dirty/backoff snapshot per channel (status & tests)."""
        now = self._now()
        return {name: {"dirty": ch.dirty, "attempts": ch.attempts,
                       "retry_in_s": round(max(ch.next_retry - now, 0.0), 3)
                       if ch.dirty else 0.0}
                for name, ch in self._channels.items()}

    def sync(self, ingresses: List[Ingress],
             configmap: Optional[ConfigMap] = None,
             push: bool = True) -> SyncResult:
        if configmap is not None:
            self.global_config = GlobalConfig.from_configmap(configmap)
            self.serve_http = self.global_config.sidecar_http
        cfg = build_configuration(ingresses, self.global_config)
        text = render(cfg, self.global_config)
        tags = cfg.tenant_tags()
        acls = self._acl_payload(cfg)

        if text != self.last_rendered:
            action = "reload"
        elif tags != self.last_tenants or acls != self.last_acls:
            action = "dynamic"
        else:
            action = "noop"

        # diff → dirty channel (the desired payload is pinned on the
        # channel until it LANDS, so a failed push keeps converging on
        # subsequent ticks with bounded exponential backoff instead of
        # waiting for the next unrelated diff)
        if push:
            if tags != self.last_tenants:
                self._channels["tenants"].mark(
                    {str(t): list(v) for t, v in tags.items()})
            if acls != self.last_acls:
                self._channels["acl"].mark(acls)
        self.last_rendered = text
        self.last_tenants = tags
        self.last_acls = acls

        pushed = pushed_acls = False
        errors = []
        if push:
            attempted = self.flush_pending()
            pushed = attempted.get("tenants", False)
            pushed_acls = attempted.get("acl", False)
            for name, ok in attempted.items():
                if not ok:
                    ch = self._channels[name]
                    errors.append(
                        "%s push to %s failed (attempt %d, retry in %.0fs)"
                        % (name, self.serve_http, ch.attempts,
                           max(ch.next_retry - self._now(), 0.0)))
        return SyncResult(action=action, rendered=text, configuration=cfg,
                          pushed_tenants=pushed, pushed_acls=pushed_acls,
                          errors=list(cfg.errors)
                          + list(self.global_config.errors) + errors)
