"""Sync controller: the `syncIngress` loop analog.

Reference flow (`internal/ingress/controller/nginx.go`†, SURVEY.md §3.2):

    informer event → build model → render template →
      IF only dynamic state changed: POST to the Lua endpoint (no reload)
      ELSE: nginx -t, diff, SIGHUP reload

Here the same decision, re-targeted:

- **render diff** → "reload" (the nginx shim must re-read directives);
- **tenant table change only** → "dynamic": POST the EP tenant rule-mask
  table to the serve loop's /configuration/tenants endpoint (the
  configuration.lua† unix-socket channel analog) — no reload, no serve
  gap;
- no change → "noop".

`tenant_masks` maps the model's tenant→rule-tags table onto the compiled
ruleset: tenant 0 always runs the full (paranoia-filtered) set; a tenant
with tags runs exactly the rules carrying ≥1 of its tags (per-tenant
verdict masks over one shared NFA — SURVEY.md §7 hard part #6: no
per-tenant recompilation).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.model import (
    Configuration,
    build_configuration,
)
from ingress_plus_tpu.control.objects import ConfigMap, Ingress
from ingress_plus_tpu.control.template import render


MAX_TENANTS = 4096  # bounds the (T, R) mask allocation (config #4 is 256)


def tenant_masks(cr: CompiledRuleset,
                 tenant_tags: Dict[int, Tuple[str, ...]]) -> np.ndarray:
    """(T, R) bool — row 0 = full ruleset (reserved, cannot be overridden);
    a tenant id NOT in the table also runs the full ruleset (all-True
    default): an unlisted tenant must never mean "scan nothing"."""
    ids = [t for t in tenant_tags if 0 < t < MAX_TENANTS]
    T = (max(ids) + 1) if ids else 1
    masks = np.ones((T, cr.n_rules), dtype=bool)
    rule_tags = [frozenset(m.rule.tags) for m in cr.rules]
    for t in ids:
        want = frozenset(tenant_tags[t])
        masks[t] = np.fromiter(
            (bool(want & rt) for rt in rule_tags), bool, cr.n_rules)
    return masks


@dataclass
class SyncResult:
    action: str                  # "reload" | "dynamic" | "noop"
    rendered: str
    configuration: Configuration
    pushed_tenants: bool = False
    errors: List[str] = field(default_factory=list)


class SyncController:
    def __init__(self, global_config: Optional[GlobalConfig] = None,
                 serve_http: Optional[str] = None):
        self.global_config = global_config or GlobalConfig()
        self.serve_http = serve_http or self.global_config.sidecar_http
        self.last_rendered: Optional[str] = None
        self.last_tenants: Optional[Dict[int, Tuple[str, ...]]] = None

    def _push_tenants(self, tags: Dict[int, Tuple[str, ...]]) -> bool:
        body = json.dumps({str(t): list(v) for t, v in tags.items()})
        url = "http://%s/configuration/tenants" % self.serve_http
        try:
            req = urllib.request.Request(
                url, data=body.encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except OSError:
            return False

    def sync(self, ingresses: List[Ingress],
             configmap: Optional[ConfigMap] = None,
             push: bool = True) -> SyncResult:
        if configmap is not None:
            self.global_config = GlobalConfig.from_configmap(configmap)
            self.serve_http = self.global_config.sidecar_http
        cfg = build_configuration(ingresses, self.global_config)
        text = render(cfg, self.global_config)
        tags = cfg.tenant_tags()

        if text != self.last_rendered:
            action = "reload"
        elif tags != self.last_tenants:
            action = "dynamic"
        else:
            action = "noop"

        pushed = False
        if push and tags != self.last_tenants:
            pushed = self._push_tenants(tags)
            if not pushed:
                # leave last_tenants stale so the next sync retries the
                # push (a restarting serve loop must not be skipped as
                # "noop" forever)
                self.last_rendered = text
                return SyncResult(
                    action=action, rendered=text, configuration=cfg,
                    pushed_tenants=False,
                    errors=list(cfg.errors) + list(self.global_config.errors)
                    + ["tenant push to %s failed" % self.serve_http])
        self.last_rendered = text
        self.last_tenants = tags
        return SyncResult(action=action, rendered=text, configuration=cfg,
                          pushed_tenants=pushed,
                          errors=list(cfg.errors)
                          + list(self.global_config.errors))
