"""Sync controller: the `syncIngress` loop analog.

Reference flow (`internal/ingress/controller/nginx.go`†, SURVEY.md §3.2):

    informer event → build model → render template →
      IF only dynamic state changed: POST to the Lua endpoint (no reload)
      ELSE: nginx -t, diff, SIGHUP reload

Here the same decision, re-targeted:

- **render diff** → "reload" (the nginx shim must re-read directives);
- **tenant table change only** → "dynamic": POST the EP tenant rule-mask
  table to the serve loop's /configuration/tenants endpoint (the
  configuration.lua† unix-socket channel analog) — no reload, no serve
  gap;
- no change → "noop".

`tenant_masks` maps the model's tenant→rule-tags table onto the compiled
ruleset: tenant 0 always runs the full (paranoia-filtered) set; a tenant
with tags runs exactly the rules carrying ≥1 of its tags (per-tenant
verdict masks over one shared NFA — SURVEY.md §7 hard part #6: no
per-tenant recompilation).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.model import (
    Configuration,
    build_configuration,
)
from ingress_plus_tpu.control.objects import ConfigMap, Ingress
from ingress_plus_tpu.control.template import render


MAX_TENANTS = 4096  # bounds the (T, R) mask allocation (config #4 is 256)


def tenant_masks(cr: CompiledRuleset,
                 tenant_tags: Dict[int, Tuple[str, ...]]) -> np.ndarray:
    """(T, R) bool — row 0 = full ruleset (reserved, cannot be overridden);
    a tenant id NOT in the table also runs the full ruleset (all-True
    default): an unlisted tenant must never mean "scan nothing"."""
    ids = [t for t in tenant_tags if 0 < t < MAX_TENANTS]
    T = (max(ids) + 1) if ids else 1
    masks = np.ones((T, cr.n_rules), dtype=bool)
    rule_tags = [frozenset(m.rule.tags) for m in cr.rules]
    for t in ids:
        want = frozenset(tenant_tags[t])
        masks[t] = np.fromiter(
            (bool(want & rt) for rt in rule_tags), bool, cr.n_rules)
    return masks


@dataclass
class SyncResult:
    action: str                  # "reload" | "dynamic" | "noop"
    rendered: str
    configuration: Configuration
    pushed_tenants: bool = False
    pushed_acls: bool = False
    errors: List[str] = field(default_factory=list)


class SyncController:
    def __init__(self, global_config: Optional[GlobalConfig] = None,
                 serve_http: Optional[str] = None):
        self.global_config = global_config or GlobalConfig()
        self.serve_http = serve_http or self.global_config.sidecar_http
        self.last_rendered: Optional[str] = None
        self.last_tenants: Optional[Dict[int, Tuple[str, ...]]] = None
        self.last_acls: Optional[dict] = None

    def _post(self, path: str, obj) -> bool:
        url = "http://%s%s" % (self.serve_http, path)
        try:
            req = urllib.request.Request(
                url, data=json.dumps(obj).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except OSError:
            return False

    def _push_tenants(self, tags: Dict[int, Tuple[str, ...]]) -> bool:
        return self._post("/configuration/tenants",
                          {str(t): list(v) for t, v in tags.items()})

    def _acl_payload(self, cfg: Configuration) -> dict:
        """wallarm-acl push body: ACL content from the ConfigMap tier
        (GlobalConfig.acls JSON), bindings from per-Ingress annotations.
        Bindings naming an ACL with no content are dropped with a model
        error (the serve endpoint would reject the whole push)."""
        try:
            specs = json.loads(self.global_config.acls) \
                if self.global_config.acls else {}
            if not isinstance(specs, dict):
                raise ValueError("acls must be a JSON object")
        except (ValueError, TypeError) as e:
            cfg.errors.append("acls configmap value: %s" % e)
            specs = {}
        binding = {}
        for t, name in sorted(cfg.tenant_acls().items()):
            if name in specs:
                binding[str(t)] = name
            else:
                cfg.errors.append(
                    "tenant %d: wallarm-acl %r has no list content" % (t, name))
        return {"acls": specs, "tenant_acl": binding}

    def sync(self, ingresses: List[Ingress],
             configmap: Optional[ConfigMap] = None,
             push: bool = True) -> SyncResult:
        if configmap is not None:
            self.global_config = GlobalConfig.from_configmap(configmap)
            self.serve_http = self.global_config.sidecar_http
        cfg = build_configuration(ingresses, self.global_config)
        text = render(cfg, self.global_config)
        tags = cfg.tenant_tags()
        acls = self._acl_payload(cfg)

        if text != self.last_rendered:
            action = "reload"
        elif tags != self.last_tenants or acls != self.last_acls:
            action = "dynamic"
        else:
            action = "noop"

        pushed = pushed_acls = False
        errors = []
        if push and tags != self.last_tenants:
            pushed = self._push_tenants(tags)
            if not pushed:
                # leave last_tenants stale so the next sync retries the
                # push (a restarting serve loop must not be skipped as
                # "noop" forever)
                errors.append("tenant push to %s failed" % self.serve_http)
        if push and acls != self.last_acls:
            pushed_acls = self._post("/configuration/acl", acls)
            if not pushed_acls:
                errors.append("acl push to %s failed" % self.serve_http)
        self.last_rendered = text
        if push and not errors or not push:
            self.last_tenants = tags
            self.last_acls = acls
        elif pushed:           # tenants landed, acls did not
            self.last_tenants = tags
        elif pushed_acls:      # acls landed, tenants did not
            self.last_acls = acls
        return SyncResult(action=action, rendered=text, configuration=cfg,
                          pushed_tenants=pushed, pushed_acls=pushed_acls,
                          errors=list(cfg.errors)
                          + list(self.global_config.errors) + errors)
