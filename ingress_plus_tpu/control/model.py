"""Model builder: Ingress objects → `Configuration` → (template.py).

Reference: `getConfiguration`/`getBackendServers` in
`internal/ingress/controller/controller.go`† producing
`Configuration{Backends, Servers, Locations}` (`pkg/apis/ingress/
types.go`†).  Additions for the TPU backend:

- every Location carries its extracted DetectionConfig;
- Ingresses are assigned stable **tenant ids** (EP routing, SURVEY.md
  §2.4): the per-namespace rule-subset table that the serve loop's
  tenant masks consume (benchmark config #4, 256 Ingress objects).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ingress_plus_tpu.control.annotations import DetectionConfig, Extractor
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.objects import Backend, Ingress


@dataclass
class Location:
    path: str
    path_type: str
    backend: Backend
    detection: DetectionConfig
    ingress_key: str


@dataclass
class Server:
    hostname: str
    locations: List[Location] = field(default_factory=list)


@dataclass
class Configuration:
    servers: List[Server] = field(default_factory=list)
    # EP routing table: tenant id → (ingress key, rule-subset tags).
    # Tenant 0 is reserved for "full ruleset".
    tenants: Dict[int, Tuple[str, Tuple[str, ...]]] = field(
        default_factory=dict)
    errors: List[str] = field(default_factory=list)

    def tenant_tags(self) -> Dict[int, Tuple[str, ...]]:
        return {t: tags for t, (_, tags) in self.tenants.items()}

    def tenant_acls(self) -> Dict[int, str]:
        """tenant id → wallarm-acl name (the per-Ingress annotation
        carried to the serve loop's ACL binding — models/acl.py).  An
        Ingress's locations share its tenant id, so the first non-empty
        acl per tenant wins; conflicting names are a model error."""
        out: Dict[int, str] = {}
        errs = set()
        for server in self.servers:
            for loc in server.locations:
                det = loc.detection
                t = det.tenant
                if not det.acl:
                    continue
                if t in out and out[t] != det.acl:
                    key = (t, out[t], det.acl)
                    if key not in errs:
                        errs.add(key)
                        self.errors.append(
                            "tenant %d: conflicting wallarm-acl %r vs %r"
                            % (t, out[t], det.acl))
                    continue
                out[t] = det.acl
        return out


def _apply_globals(cfg: DetectionConfig, g: GlobalConfig) -> DetectionConfig:
    """Tier merge: ConfigMap sets the defaults annotations did not touch,
    and the override policy gates mode strengthening (the reference's
    wallarm-mode-allow-override semantics).  ``cfg.explicit`` separates an
    explicit `wallarm-mode: off` opt-out (honored) from the absent-
    annotation default (promoted to the cluster default)."""
    if (g.enable_detection and cfg.mode == "off"
            and "mode" not in cfg.explicit):
        cfg.mode = g.default_mode
    order = ("off", "monitoring", "safe_blocking", "block")
    if g.mode_allow_override == "off":
        cfg.mode = g.default_mode if g.enable_detection else "off"
    elif g.mode_allow_override == "strict":
        # annotations may only weaken, never strengthen
        if order.index(cfg.mode) > order.index(g.default_mode):
            cfg.mode = g.default_mode
    if ("detection_backend" not in cfg.explicit
            and g.detection_backend == "tpu"):
        cfg.detection_backend = "tpu"
    if cfg.anomaly_threshold == 0:
        cfg.anomaly_threshold = g.anomaly_threshold
    if cfg.paranoia_level == 0:
        cfg.paranoia_level = g.paranoia_level
    if not g.fail_open:
        cfg.fallback = False
    return cfg


def build_configuration(
    ingresses: List[Ingress],
    global_config: Optional[GlobalConfig] = None,
) -> Configuration:
    g = global_config or GlobalConfig()
    ex = Extractor(strict=False)
    out = Configuration()
    servers: Dict[str, Server] = {}

    # one extract per Ingress (extraction is not idempotent on ex.errors,
    # and the 256-Ingress config shouldn't pay double parse work)
    extracted = {ing.key: ex.extract(ing) for ing in ingresses}

    # stable tenant ids: sorted ingress keys, 1-based (0 = full ruleset)
    with_subset = sorted(
        key for key, det in extracted.items() if det.rule_subset)
    tenant_of = {key: i + 1 for i, key in enumerate(with_subset)}

    for ing in sorted(ingresses, key=lambda i: i.key):
        det = _apply_globals(extracted[ing.key], g)
        det.tenant = tenant_of.get(ing.key, 0)
        if det.tenant:
            out.tenants[det.tenant] = (ing.key, tuple(det.rule_subset))
        for rule in ing.rules:
            srv = servers.setdefault(rule.host, Server(hostname=rule.host))
            for p in rule.paths:
                srv.locations.append(Location(
                    path=p.path, path_type=p.path_type, backend=p.backend,
                    detection=det, ingress_key=ing.key))

    # deterministic output: hosts sorted, catch-all last; longest path
    # first within a server (nginx location-match order)
    for srv in servers.values():
        srv.locations.sort(key=lambda l: (-len(l.path), l.path))
    out.servers = sorted(
        servers.values(),
        key=lambda s: (s.hostname == "_", s.hostname))
    out.errors = ex.errors
    return out
