"""Control plane — the reference's Go controller re-shaped for the TPU
detection backend (SURVEY.md §2.1).

The reference stack is: k8s informers → annotation extraction → model
build (`Configuration{Servers, Locations}`) → template render (nginx.conf)
→ reload-vs-dynamic decision → data-plane update (SIGHUP or Lua endpoint
POST).  Files here mirror that pipeline one-to-one, minus the parts that
are pure kubernetes plumbing (informers/leader election), which need a
cluster, not a framework:

    objects.py      — minimal Ingress/ConfigMap object model
                      (pkg/apis/ingress/types.go† analog)
    annotations.py  — parser framework + wallarm/tpu annotation set
                      (internal/ingress/annotations/†)
    config.py       — global config tiers (controller/config/config.go†)
    model.py        — Ingress objects → Configuration model
                      (controller/controller.go† getConfiguration)
    template.py     — model → nginx.conf text incl. detection-backend
                      routing (controller/template/† + nginx.tmpl†)
    sync.py         — syncIngress analog: render, diff, reload-vs-dynamic,
                      push tenant table to the serve loop
                      (controller/nginx.go† + configuration.lua† channel)
    admission.py    — dry-run validation webhook (internal/admission/†)
    dbg.py          — inspection CLI (cmd/dbg/main.go†)
"""

from ingress_plus_tpu.control.annotations import Extractor  # noqa: F401
from ingress_plus_tpu.control.config import GlobalConfig  # noqa: F401
from ingress_plus_tpu.control.model import build_configuration  # noqa: F401
from ingress_plus_tpu.control.objects import ConfigMap, Ingress  # noqa: F401
from ingress_plus_tpu.control.template import render  # noqa: F401
