"""Deployment rendering — the packaging/operator tier (SURVEY.md §2.3 L6).

Reference: the wallarm-extended Helm chart† (controller Deployment +
wallarm sidecars + Tarantool postanalytics Deployment, driven by
``values.yaml`` ``controller.wallarm.*`` keys) and the pre-rendered static
manifests under ``deploy/static/``†.

This module is the same idea sized to the TPU framework: a typed values
object rendered into k8s manifests.  The pod layout it emits is the
architecture of SURVEY.md §3.3 (TPU variant):

    [ingress pod]        nginx + shim (unchanged data plane)
      └─ sidecar         native/sidecar (mux, balancer, fail-open SLO)
      └─ serve-loop × N  one per TPU chip, each on its own UDS
      └─ postanalytics   spool consolidator (the cron-sidecar analog)

The spool emptyDir is pod-local, so the consolidator MUST live in the
ingress pod (the reference runs its export cron as a controller-pod
sidecar for the same reason); the serve loops' in-process PostChannel +
spool plays the Tarantool-queue role, and a central collector — when one
exists — is reached via ``export_url``.

Manifests are YAML text rendered by template strings — the reference
renders Go templates to text the same way; no YAML library is needed (or
available) and the golden tests pin the output byte-for-byte
(tests/test_deploy.py, template_test.go† style).

``python -m ingress_plus_tpu.control.deploy [outdir]`` regenerates
``deploy/static/`` (the chart→static pipeline of the reference).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List


@dataclass
class DeployValues:
    """values.yaml analog — the operator-tunable surface."""

    namespace: str = "ingress-plus-tpu"
    name: str = "ipt"
    replicas: int = 2                    # ingress pods (DP over hosts)
    chips_per_host: int = 4              # serve loops per pod (1/chip)
    image: str = "ingress-plus-tpu:latest"
    balance: str = "rr"                  # rr | ewma | chash
    deadline_ms: int = 50
    status_port: int = 9902
    http_port: int = 9901                # serve loop 0's metrics/config
    mode: str = "block"
    rules_configmap: str = "ipt-rules"
    fail_open: bool = True
    batch_window_us: int = 500
    max_batch: int = 256
    spool_dir: str = "/var/spool/ipt"
    lkg_dir: str = "/var/lib/ipt/lkg"    # last-known-good pack store
    export_url: str = ""                 # postanalytics collector
    export_interval_s: float = 5.0
    # --- fleet tier (ISSUE 19, docs/SERVING.md "Fleet serving"): the
    # shared admission front + N detection replicas + the telemetry
    # aggregator + the continuous retune daemon in one pod.  0 fleet
    # nodes = the tier is not rendered (single-pod layout only).
    fleet_nodes: int = 3                 # replicas behind the front
    front_http_port: int = 9921          # front /metrics,/front/nodes
    fleet_http_port: int = 9911          # aggregator /fleet/*
    retune_min_interval_s: float = 600.0
    retune_cooldown_s: float = 1800.0
    tenants: Dict[int, List[str]] = field(default_factory=dict)


    @classmethod
    def from_yaml(cls, text: str) -> "DeployValues":
        """Parse the values file (deploy/values.yaml analog of the
        chart's values.yaml†).  A deliberately tiny YAML subset — flat
        ``key: value`` pairs plus one ``tenants:`` block mapping tenant
        id to a tag list — because no YAML library is available in the
        serve image and the operator surface is exactly DeployValues.
        Unknown keys are a hard error (a typo'd key silently keeping
        its default is how bad deploys ship)."""
        v = cls()
        fields = {f: type(getattr(v, f)) for f in v.__dataclass_fields__}
        in_tenants = False
        for ln, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].rstrip()
            if not line.strip():
                continue
            if in_tenants and (line.startswith("  ") or
                               line.startswith("\t")):
                key, _, val = line.strip().partition(":")
                try:
                    tid = int(key.strip())
                except ValueError:
                    raise ValueError("values.yaml:%d: tenant id %r is "
                                     "not an integer" % (ln, key))
                tags = [t.strip().strip("'\"")
                        for t in val.strip().strip("[]").split(",")
                        if t.strip()]
                v.tenants[tid] = tags
                continue
            in_tenants = False
            if line != line.lstrip():
                raise ValueError("values.yaml:%d: unexpected indent %r"
                                 % (ln, raw))
            key, sep, val = line.partition(":")
            key = key.strip().replace("-", "_")
            if not sep:
                raise ValueError("values.yaml:%d: expected key: value, "
                                 "got %r" % (ln, raw))
            if key == "tenants":
                in_tenants = True
                continue
            if key not in fields:
                raise ValueError("values.yaml:%d: unknown key %r "
                                 "(valid: %s)" % (ln, key,
                                                  ", ".join(sorted(fields))))
            val = val.strip().strip("'\"")
            ftype = fields[key]
            if ftype is bool:
                setattr(v, key, val.lower() in ("true", "1", "yes", "on"))
            elif ftype is int:
                setattr(v, key, int(val))
            elif ftype is float:
                setattr(v, key, float(val))
            else:
                setattr(v, key, val)
        return v


def _serve_socket(i: int) -> str:
    return "/run/ipt/serve-%d.sock" % i


def render_configmap(v: DeployValues) -> str:
    """Global-config ConfigMap (the ~200-key ConfigMap tier, ours)."""
    lines = [
        "apiVersion: v1",
        "kind: ConfigMap",
        "metadata:",
        "  name: %s-config" % v.name,
        "  namespace: %s" % v.namespace,
        "data:",
        "  enable-detection: \"true\"",
        "  detection-backend: \"tpu\"",
        "  default-mode: \"%s\"" % v.mode,
        "  fail-open: \"%s\"" % ("true" if v.fail_open else "false"),
        "  batch-window-us: \"%d\"" % v.batch_window_us,
        "  max-batch: \"%d\"" % v.max_batch,
        "  sidecar-socket: \"/run/ipt/detect.sock\"",
        "  detect-timeout-ms: \"%d\"" % v.deadline_ms,
    ]
    return "\n".join(lines) + "\n"


def render_deployment(v: DeployValues) -> str:
    """The ingress pod: nginx+shim container, native sidecar, N serve
    loops (one per chip) — the wallarm-sidecar-per-pod layout of the
    chart, TPU-shaped."""
    upstreams = ",".join(_serve_socket(i) for i in range(v.chips_per_host))
    out = [
        "apiVersion: apps/v1",
        "kind: Deployment",
        "metadata:",
        "  name: %s-controller" % v.name,
        "  namespace: %s" % v.namespace,
        "spec:",
        "  replicas: %d" % v.replicas,
        "  selector:",
        "    matchLabels: {app: %s-controller}" % v.name,
        "  template:",
        "    metadata:",
        "      labels: {app: %s-controller}" % v.name,
        "    spec:",
        "      volumes:",
        "        - name: ipt-run",
        "          emptyDir: {}",
        "        - name: ipt-rules",
        "          configMap: {name: %s}" % v.rules_configmap,
        "        - name: ipt-spool",
        "          emptyDir: {}",
        "        # last-known-good ruleset store (docs/ROBUSTNESS.md "
        "\"Guarded",
        "        # rollout\"): packs that reach LIVE persist here; a "
        "serve",
        "        # container restarting mid-rollout prefers this "
        "artifact over",
        "        # the ConfigMap rules tree",
        "        - name: ipt-lkg",
        "          emptyDir: {}",
        "      containers:",
        "        - name: controller",
        "          image: %s" % v.image,
        "          args: [\"/nginx-ingress-controller\"]",
        "          volumeMounts:",
        "            - {name: ipt-run, mountPath: /run/ipt}",
        "        - name: detect-sidecar",
        "          image: %s" % v.image,
        "          command:",
        "            - /usr/local/bin/ipt-sidecar",
        "            - --listen",
        "            - /run/ipt/detect.sock",
        "            - --upstream",
        "            - %s" % upstreams,
        "            - --balance",
        "            - %s" % v.balance,
        "            - --deadline-ms",
        "            - \"%d\"" % v.deadline_ms,
        "            - --status-port",
        "            - \"%d\"" % v.status_port,
        "          volumeMounts:",
        "            - {name: ipt-run, mountPath: /run/ipt}",
    ]
    for i in range(v.chips_per_host):
        out += [
            "        - name: serve-%d" % i,
            "          image: %s" % v.image,
            "          command:",
            "            - python",
            "            - -m",
            "            - ingress_plus_tpu.serve",
            "            - --socket",
            "            - %s" % _serve_socket(i),
            "            - --mode",
            "            - %s" % v.mode,
            "            - --rules-dir",
            "            - /etc/ipt/rules",
            "            - --max-batch",
            "            - \"%d\"" % v.max_batch,
            "            - --max-delay-us",
            "            - \"%d\"" % v.batch_window_us,
            "            - --http-port",
            "            - \"%d\"" % (v.http_port + i),
            "            - --spool-dir",
            "            - %s" % v.spool_dir,
            "            - --lkg-dir",
            "            - %s" % v.lkg_dir,
            "          env:",
            "            - {name: TPU_VISIBLE_CHIPS, value: \"%d\"}" % i,
            "          resources:",
            "            limits: {google.com/tpu: 1}",
            "          livenessProbe:",
            "            httpGet: {path: /healthz, port: %d}"
            % (v.http_port + i),
            "            initialDelaySeconds: 30",
            "            periodSeconds: 5",
            "          # readiness is split from liveness "
            "(docs/ROBUSTNESS.md):",
            "          # /readyz goes 503 while the dispatch breaker "
            "is open or the",
            "          # brownout ladder is above full detection, "
            "pulling the pod",
            "          # from rotation instead of routing traffic "
            "into a brownout",
            "          readinessProbe:",
            "            httpGet: {path: /readyz, port: %d}"
            % (v.http_port + i),
            "            initialDelaySeconds: 10",
            "            periodSeconds: 3",
            "          volumeMounts:",
            "            - {name: ipt-run, mountPath: /run/ipt}",
            "            - {name: ipt-rules, mountPath: /etc/ipt/rules}",
            "            - {name: ipt-spool, mountPath: %s}" % v.spool_dir,
            "            - {name: ipt-lkg, mountPath: %s}" % v.lkg_dir,
        ]
    # postanalytics consolidator — shares the pod's spool emptyDir (a
    # separate Deployment could never see it; emptyDir is pod-local)
    out += [
        "        - name: postanalytics",
        "          image: %s" % v.image,
        "          command:",
        "            - python",
        "            - -m",
        "            - ingress_plus_tpu.post.export",
        "            - --spool-dir",
        "            - %s" % v.spool_dir,
        "            - --interval-s",
        "            - \"%g\"" % v.export_interval_s,
    ]
    if v.export_url:
        out += [
            "            - --url",
            "            - %s" % v.export_url,
        ]
    out += [
        "          volumeMounts:",
        "            - {name: ipt-spool, mountPath: %s}" % v.spool_dir,
    ]
    return "\n".join(out) + "\n"


def render_service(v: DeployValues) -> str:
    out = [
        "apiVersion: v1",
        "kind: Service",
        "metadata:",
        "  name: %s-metrics" % v.name,
        "  namespace: %s" % v.namespace,
        "spec:",
        "  selector: {app: %s-controller}" % v.name,
        "  ports:",
        "    - {name: sidecar-status, port: %d}" % v.status_port,
    ]
    for i in range(v.chips_per_host):
        out.append("    - {name: serve-%d-http, port: %d}"
                   % (i, v.http_port + i))
    return "\n".join(out) + "\n"


def _fleet_socket(i: int) -> str:
    return "/run/ipt/fleet-%d.sock" % i


def render_fleet(v: DeployValues) -> str:
    """The fleet pod (ISSUE 19): N detection replicas behind ONE
    shared admission front, the telemetry aggregator scraping all of
    them, and the continuous retune daemon closing the loop.  Every
    replica carries its own /readyz readiness probe (the front stops
    routing to an unready node before k8s does); the front's own
    readiness is 503-when-zero-nodes-up, so the Service only pulls the
    POD when the whole fleet inside is dark — one dead replica is a
    capacity event, not a service event."""
    # fleet replicas' HTTP planes start well clear of the single-pod
    # tier (http_port..+chips) AND the aggregator/front ports (99xx)
    node_port = v.http_port + 40
    backends = ",".join("n%d=%s@127.0.0.1:%d"
                        % (i, _fleet_socket(i), node_port + i)
                        for i in range(v.fleet_nodes))
    out = [
        "apiVersion: apps/v1",
        "kind: Deployment",
        "metadata:",
        "  name: %s-fleet" % v.name,
        "  namespace: %s" % v.namespace,
        "spec:",
        "  replicas: 1",
        "  selector:",
        "    matchLabels: {app: %s-fleet}" % v.name,
        "  template:",
        "    metadata:",
        "      labels: {app: %s-fleet}" % v.name,
        "    spec:",
        "      volumes:",
        "        - name: ipt-run",
        "          emptyDir: {}",
        "        - name: ipt-rules",
        "          configMap: {name: %s}" % v.rules_configmap,
        "        # ONE shared LKG dir for the whole fleet: the fleet",
        "        # rollout journal, the FLEET_LKG pointer, and the",
        "        # retune daemon's cycle ledger all live here — a node",
        "        # (or the daemon) restarting mid-rollout converges to",
        "        # this, not to whatever it was serving",
        "        - name: ipt-fleet-lkg",
        "          emptyDir: {}",
        "      containers:",
    ]
    for i in range(v.fleet_nodes):
        out += [
            "        - name: serve-%d" % i,
            "          image: %s" % v.image,
            "          command:",
            "            - python",
            "            - -m",
            "            - ingress_plus_tpu.serve",
            "            - --socket",
            "            - %s" % _fleet_socket(i),
            "            - --mode",
            "            - %s" % v.mode,
            "            - --rules-dir",
            "            - /etc/ipt/rules",
            "            - --max-batch",
            "            - \"%d\"" % v.max_batch,
            "            - --max-delay-us",
            "            - \"%d\"" % v.batch_window_us,
            "            - --http-port",
            "            - \"%d\"" % (node_port + i),
            "            - --lkg-dir",
            "            - %s" % v.lkg_dir,
            "          env:",
            "            - {name: TPU_VISIBLE_CHIPS, value: \"%d\"}" % i,
            "          resources:",
            "            limits: {google.com/tpu: 1}",
            "          livenessProbe:",
            "            httpGet: {path: /healthz, port: %d}"
            % (node_port + i),
            "            initialDelaySeconds: 30",
            "            periodSeconds: 5",
            "          readinessProbe:",
            "            httpGet: {path: /readyz, port: %d}"
            % (node_port + i),
            "            initialDelaySeconds: 10",
            "            periodSeconds: 3",
            "          volumeMounts:",
            "            - {name: ipt-run, mountPath: /run/ipt}",
            "            - {name: ipt-rules, mountPath: /etc/ipt/rules}",
            "            - {name: ipt-fleet-lkg, mountPath: %s}" % v.lkg_dir,
        ]
    out += [
        "        # the shared admission front (serve/front.py): one",
        "        # listener, least-loaded routing, retry-on-connect,",
        "        # half-open canary re-admission; when EVERY node is",
        "        # down it serves the fail-open verdict itself",
        "        - name: front",
        "          image: %s" % v.image,
        "          command:",
        "            - python",
        "            - -m",
        "            - ingress_plus_tpu.serve",
        "            - --front",
        "            - --socket",
        "            - /run/ipt/front.sock",
        "            - --http-port",
        "            - \"%d\"" % v.front_http_port,
    ]
    for i in range(v.fleet_nodes):
        out += [
            "            - --backend",
            "            - n%d=%s@127.0.0.1:%d"
            % (i, _fleet_socket(i), node_port + i),
        ]
    out += [
        "          readinessProbe:",
        "            # 503 only when ZERO backends are up: one dead",
        "            # replica must not pull the pod from rotation",
        "            httpGet: {path: /readyz, port: %d}" % v.front_http_port,
        "            initialDelaySeconds: 5",
        "            periodSeconds: 3",
        "          volumeMounts:",
        "            - {name: ipt-run, mountPath: /run/ipt}",
        "        - name: fleet-aggregator",
        "          image: %s" % v.image,
        "          command:",
        "            - python",
        "            - -m",
        "            - ingress_plus_tpu.control.fleetobs",
        "            - --port",
        "            - \"%d\"" % v.fleet_http_port,
        "            - --interval-s",
        "            - \"%g\"" % v.export_interval_s,
    ]
    for i in range(v.fleet_nodes):
        out += [
            "            - --node",
            "            - n%d=127.0.0.1:%d" % (i, node_port + i),
        ]
    out += [
        "          readinessProbe:",
        "            httpGet: {path: /fleet/healthz, port: %d}"
        % v.fleet_http_port,
        "            initialDelaySeconds: 5",
        "            periodSeconds: 5",
        "        # the continuous retune daemon (control/retuned.py):",
        "        # watches /fleet/drift, retunes through the four",
        "        # gates, hands the winner to the fleet-staged rollout",
        "        - name: retune-daemon",
        "          image: %s" % v.image,
        "          command:",
        "            - python",
        "            - -m",
        "            - ingress_plus_tpu.control.retuned",
        "            - --fleet-url",
        "            - 127.0.0.1:%d" % v.fleet_http_port,
        "            - --lkg-dir",
        "            - %s" % v.lkg_dir,
        "            - --min-interval-s",
        "            - \"%g\"" % v.retune_min_interval_s,
        "            - --cooldown-s",
        "            - \"%g\"" % v.retune_cooldown_s,
    ]
    for i in range(v.fleet_nodes):
        out += [
            "            - --node",
            "            - n%d=127.0.0.1:%d" % (i, node_port + i),
        ]
    out += [
        "          volumeMounts:",
        "            - {name: ipt-fleet-lkg, mountPath: %s}" % v.lkg_dir,
        "---",
        "apiVersion: v1",
        "kind: Service",
        "metadata:",
        "  name: %s-fleet" % v.name,
        "  namespace: %s" % v.namespace,
        "spec:",
        "  selector: {app: %s-fleet}" % v.name,
        "  ports:",
        "    - {name: front-http, port: %d}" % v.front_http_port,
        "    - {name: fleet-http, port: %d}" % v.fleet_http_port,
    ]
    # the fleet replicas' HTTP planes are scraped pod-locally by the
    # aggregator; only the rollups leave the pod
    return "\n".join(out) + "\n"


def render_all(v: DeployValues) -> Dict[str, str]:
    """filename → manifest text (the chart's template set)."""
    out = {
        "configmap.yaml": render_configmap(v),
        "deployment.yaml": render_deployment(v),
        "service.yaml": render_service(v),
    }
    if v.fleet_nodes > 0:
        out["fleet.yaml"] = render_fleet(v)
    return out


def write_static(outdir: str | Path,
                 values: DeployValues | None = None) -> List[str]:
    """Regenerate the static manifests (deploy/static analog)."""
    outdir = Path(outdir)
    outdir.mkdir(parents=True, exist_ok=True)
    v = values or DeployValues()
    written = []
    for name, text in render_all(v).items():
        (outdir / name).write_text(text)
        written.append(name)
    return sorted(written)


if __name__ == "__main__":
    import argparse

    repo = Path(__file__).resolve().parents[2]
    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.control.deploy")
    ap.add_argument("outdir", nargs="?",
                    default=str(repo / "deploy" / "static"))
    ap.add_argument("--values", default=str(repo / "deploy" / "values.yaml"),
                    help="values file driving the render (the chart's "
                         "values.yaml analog)")
    args = ap.parse_args()
    values = None
    if Path(args.values).exists():
        values = DeployValues.from_yaml(Path(args.values).read_text())
        print("values: %s" % args.values)
    for f in write_static(args.outdir, values):
        print("wrote %s" % f)
