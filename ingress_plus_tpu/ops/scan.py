"""Batched bitap scan — jnp/XLA implementation.

The recurrence per byte (uint32, element-wise over words — see
compiler/bitap.py for why no cross-word carries exist):

    S' = ((S << 1) | INIT) & B[byte]
    M' = M | (S' & FINAL)

Shapes: tokens (B, L) int32 in [0, 255] (padded with any value), lengths
(B,) int32, state/match (B, W) uint32.  Padded steps are identity on both S
and M (masked select), so a row's final state is exactly the state after its
``length`` real bytes — the property the streaming chunk chain relies on.

Design notes (TPU-first):
- `lax.scan` over the time axis with the batch×words update vectorized on
  the VPU; `unroll` amortizes loop overhead.
- The 256×W byte table is gathered per step with `jnp.take` — on TPU this
  compiles to a dynamic-gather from VMEM (the table is ~256×258×4B ≈ 264KB).
- Everything is static-shaped; jit caches one executable per (B, L, W).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ingress_plus_tpu.compiler.bitap import BitapTables


@jax.tree_util.register_pytree_node_class
@dataclass
class ScanTables:
    """Device-resident scan tables (a pytree, so it jits as an argument —
    ruleset hot-swap is just passing new arrays, no recompilation).

    ``byte_planes`` is the byte table split into 4 uint8 planes stored as
    bf16 (values 0..255 are exact in bf16): the TPU path fetches B[byte]
    for a whole batch as ``onehot(bytes) @ byte_planes`` — one MXU matmul —
    because per-lane dynamic gather is slow on TPU."""

    byte_table: jax.Array   # (256, W) uint32
    byte_planes: jax.Array  # (256, 4W) bfloat16 — plane-major [b0|b1|b2|b3]
    init_mask: jax.Array    # (W,) uint32
    final_mask: jax.Array   # (W,) uint32
    # ---- byte-class compression (Hyperscan-style): the 256 byte rows
    # collapse to k distinct classes (k≈75 on the CRS corpus).  Class
    # index k is the reserved DEAD class (all-zero reach) used as padding,
    # which makes per-step validity masks unnecessary: once a row runs
    # into padding its state dies and its match mask is stable.
    byte_class: Optional[jax.Array] = None   # (257,) int32: byte→class,
                                             #   [256] = dead class k
    class_table: Optional[jax.Array] = None  # (k+1, W) uint32
    # ---- class-pair stride (one W-word gather per TWO bytes):
    #   S2 = ((S<<2) | (I<<1) | I) & R'[c1,c2]
    #   R'[c1,c2] = ((T[c1]<<1) | I) & T[c2]
    # (exact: expanding ((S<<2)|(I<<1)|I) & ((T1<<1)|I) & T2 reproduces
    # the two-step shift-and because every cross term is absorbed by the
    # unconditional I coverage of initial states).  Odd-position match
    # ends are collected via FA[c1] = T[c1] & final.
    pair_reach: Optional[jax.Array] = None   # ((k+1)^2, W) uint32
    pair_final: Optional[jax.Array] = None   # (k+1, W) uint32: T[c] & F

    @classmethod
    def from_bitap(cls, t: BitapTables, classes: bool = True
                   ) -> "ScanTables":
        bt = t.byte_table.astype(np.uint32)
        planes = np.concatenate(
            [((bt >> (8 * k)) & 0xFF).astype(np.float32) for k in range(4)],
            axis=1,
        )
        fields = dict(
            byte_table=jnp.asarray(bt),
            byte_planes=jnp.asarray(planes, dtype=jnp.bfloat16),
            init_mask=jnp.asarray(t.init_mask, dtype=jnp.uint32),
            final_mask=jnp.asarray(t.final_mask, dtype=jnp.uint32),
        )
        if classes:
            byte_class, T, pair_reach, pair_final, k = \
                build_class_pair_tables(bt, t.init_mask, t.final_mask)
            fields.update(
                byte_class=jnp.asarray(byte_class),
                class_table=jnp.asarray(T),
                pair_reach=jnp.asarray(pair_reach),
                pair_final=jnp.asarray(pair_final),
            )
        return cls(**fields)

    @property
    def n_words(self) -> int:
        return self.byte_table.shape[1]

    @property
    def n_classes(self) -> int:
        """Real classes (excluding the dead padding class)."""
        return self.class_table.shape[0] - 1

    def tree_flatten(self):
        return (self.byte_table, self.byte_planes, self.init_mask,
                self.final_mask, self.byte_class, self.class_table,
                self.pair_reach, self.pair_final), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def build_class_pair_tables(byte_table: np.ndarray, init_mask: np.ndarray,
                            final_mask: np.ndarray,
                            k_pad: Optional[int] = None,
                            uniq_inv=None):
    """Byte-class compression + folded pair recurrence tables — the ONE
    construction shared by the single-chip tables (ScanTables.from_bitap)
    and the per-shard sharded tables (parallel/shard.py), so the
    recurrence can never diverge between paths (round-4 review).

    Returns (byte_class (257,), class_table (K+1, W), pair_reach
    ((K+1)^2, W), pair_final (K+1, W), k) as numpy; the DEAD class (zero
    reach) sits at index K = ``k_pad or k`` and byte_class[256] maps to
    it.  ``k_pad`` ≥ k pads the class axis (sharded paths need a uniform
    K across shards); padding rows keep all-zero reach.  ``uniq_inv``
    lets a caller that already ran the axis-0 unique (the sharded k_max
    pre-pass) hand the (uniq, inv) pair in instead of paying it twice."""
    bt = byte_table.astype(np.uint32)
    if uniq_inv is None:
        uniq, inv = np.unique(bt, axis=0, return_inverse=True)
    else:
        uniq, inv = uniq_inv
    inv = np.asarray(inv).ravel()  # numpy <2.0 returns (256, 1), axis=0
    k = int(uniq.shape[0])
    K = k_pad if k_pad is not None else k
    if K < k:
        raise ValueError("k_pad=%d < actual class count %d" % (K, k))
    T = np.zeros((K + 1, bt.shape[1]), np.uint32)
    T[:k] = uniq
    byte_class = np.concatenate(
        [inv.astype(np.int32), np.asarray([K], np.int32)])
    init = init_mask.astype(np.uint32)[None, None, :]
    pair = ((T[:, None, :] << np.uint32(1)) | init) & T[None, :, :]
    pair_reach = pair.reshape((K + 1) * (K + 1), -1)
    pair_final = T & final_mask.astype(np.uint32)[None, :]
    return byte_class, T, pair_reach, pair_final, k


def classes_for(byte_class: jax.Array, tokens: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """(B, L) byte rows → (B, L) class ids with padding (pos ≥ length)
    mapped to the DEAD class via the 256 sentinel — the one byte→class
    mapping shared by scan_pairs and the Pallas pair kernel so the
    dead-class convention cannot diverge between them (round-4 review)."""
    L = tokens.shape[1]
    toks = jnp.where(
        jnp.arange(L, dtype=jnp.int32)[None, :]
        < lengths.astype(jnp.int32)[:, None],
        jnp.asarray(tokens).astype(jnp.int32), jnp.int32(256))
    return jnp.take(byte_class, toks, axis=0).astype(jnp.int32)


def _reach_take(tables: ScanTables, bytes_t: jax.Array) -> jax.Array:
    """B[byte] via dynamic gather — fast on CPU, slow on TPU."""
    return jnp.take(tables.byte_table, bytes_t, axis=0)


def _reach_onehot(tables: ScanTables, bytes_t: jax.Array) -> jax.Array:
    """B[byte] via one-hot × byte-plane matmul — rides the MXU.

    onehot (B, 256) bf16 @ planes (256, 4W) bf16 → f32, exact for values
    ≤255; the four uint8 planes are recombined with shifts/ors."""
    B = bytes_t.shape[0]
    W = tables.n_words
    onehot = (bytes_t[:, None] == jnp.arange(256, dtype=jnp.int32)[None, :])
    planes = jnp.dot(onehot.astype(jnp.bfloat16), tables.byte_planes,
                     preferred_element_type=jnp.float32)
    p = planes.astype(jnp.uint32).reshape(B, 4, W)
    return (p[:, 0] | (p[:, 1] << jnp.uint32(8))
            | (p[:, 2] << jnp.uint32(16)) | (p[:, 3] << jnp.uint32(24)))


def scan_bytes(
    tables: ScanTables,
    tokens: jax.Array,   # (B, L) int32/uint8
    lengths: jax.Array,  # (B,) int32
    state: Optional[jax.Array] = None,  # (B, W) uint32 — streaming carry
    match: Optional[jax.Array] = None,  # (B, W) uint32 — sticky accumulator
    unroll: int = 8,
    gather: str = "auto",  # "take" | "onehot" | "auto"
) -> Tuple[jax.Array, jax.Array]:
    """Scan a batch of byte rows; returns (match, state) after each row's
    ``length`` bytes.  Pass the returned ``state``/``match`` back in for the
    next chunk of the same streams (benchmark config #5)."""
    B, L = tokens.shape
    W = tables.n_words
    if state is None:
        state = jnp.zeros((B, W), dtype=jnp.uint32)
    if match is None:
        match = jnp.zeros((B, W), dtype=jnp.uint32)
    # Benchmarked on TPU v5e (full 1.4k-rule corpus, W=291, B=1024, L=1024,
    # K=65 in-dispatch amortized — see utils/microbench.py for why naive
    # timing lies here): take ≈ 200 MB/s, onehot ≈ 100 MB/s.  XLA lowers
    # the (256, W) row gather acceptably, so "take" is the default.
    if gather == "auto":
        gather = "take"
    reach_fn = _reach_take if gather == "take" else _reach_onehot

    tokens_t = jnp.transpose(tokens.astype(jnp.int32))  # (L, B): scan axis first
    steps = jnp.arange(L, dtype=jnp.int32)
    lengths = lengths.astype(jnp.int32)

    init = tables.init_mask[None, :]
    final = tables.final_mask[None, :]

    def step(carry, xs):
        S, M = carry
        bytes_t, t = xs
        reach = reach_fn(tables, bytes_t)  # (B, W)
        S_new = ((S << jnp.uint32(1)) | init) & reach
        valid = (t < lengths)[:, None]  # (B, 1)
        S = jnp.where(valid, S_new, S)
        M = jnp.where(valid, M | (S_new & final), M)
        return (S, M), None

    (state, match), _ = jax.lax.scan(
        step, (state, match), (tokens_t, steps), unroll=unroll
    )
    return match, state


@functools.partial(jax.jit, static_argnames=("unroll", "gather"))
def scan_bytes_jit(tables, tokens, lengths, state=None, match=None,
                   unroll: int = 8, gather: str = "auto"):
    return scan_bytes(tables, tokens, lengths, state, match, unroll, gather)


def scan_pairs(
    tables: ScanTables,
    tokens: jax.Array,   # (B, L) int32/uint8, L even
    lengths: jax.Array,  # (B,) int32
    state: Optional[jax.Array] = None,
    match: Optional[jax.Array] = None,
    unroll: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Class-pair-stride scan: L/2 steps, ONE (B, W) reach gather per TWO
    bytes (see ScanTables.pair_reach for the folded recurrence) plus one
    small (B, W) gather for odd-position match ends.  Returns the same
    (match, state) as ``scan_bytes``, with one contract difference: rows
    shorter than L are padded with the DEAD class, so their returned
    ``state`` is zero, not the state after ``length`` bytes — use this
    path for request scans (only ``match`` is consumed) and equal-length
    chunk waves, NOT for carrying state across ragged streaming chunks.
    """
    B, L = tokens.shape
    if L % 2:
        raise ValueError("scan_pairs needs even L (pad_rows rounds to 128)")
    W = tables.n_words
    if state is None:
        state = jnp.zeros((B, W), dtype=jnp.uint32)
    if match is None:
        match = jnp.zeros((B, W), dtype=jnp.uint32)
    k1 = tables.class_table.shape[0]  # k + 1 (dead class last)

    # byte → class, with padding mapped to the dead class (reach 0): the
    # scan needs no per-step validity selects at all
    cls = classes_for(tables.byte_class, tokens, lengths)  # (B, L)
    c1 = jnp.transpose(cls[:, 0::2])                      # (L/2, B)
    c2 = jnp.transpose(cls[:, 1::2])
    pair_idx = c1 * jnp.int32(k1) + c2

    I = tables.init_mask[None, :]
    IOR = (I << jnp.uint32(1)) | I
    final = tables.final_mask[None, :]

    def step(carry, xs):
        S, M = carry
        pidx, cc1 = xs
        R = jnp.take(tables.pair_reach, pidx, axis=0)     # (B, W)
        FA1 = jnp.take(tables.pair_final, cc1, axis=0)    # (B, W)
        M = M | (((S << jnp.uint32(1)) | I) & FA1)        # ends at byte 1
        S = ((S << jnp.uint32(2)) | IOR) & R
        M = M | (S & final)                               # ends at byte 2
        return (S, M), None

    (state, match), _ = jax.lax.scan(
        step, (state, match), (pair_idx, c1), unroll=unroll)
    return match, state


@functools.partial(jax.jit, static_argnames=("unroll",))
def scan_pairs_jit(tables, tokens, lengths, state=None, match=None,
                   unroll: int = 8):
    return scan_pairs(tables, tokens, lengths, state, match, unroll)


def scan_bytes_reference(tables: ScanTables, data: bytes) -> np.ndarray:
    """Single-row convenience wrapper (numpy in/out) for tests/debugging."""
    if len(data) == 0:
        return np.zeros((tables.n_words,), dtype=np.uint32)
    tokens = jnp.asarray(np.frombuffer(data, dtype=np.uint8)[None, :])
    lengths = jnp.asarray([len(data)], dtype=jnp.int32)
    match, _ = scan_bytes(tables, tokens, lengths)
    return np.asarray(match[0])


def pad_rows(rows: list, max_len: Optional[int] = None, round_to: int = 128
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side helper: pack variable-length byte strings into a padded
    (B, L) uint8 matrix + lengths.  L is rounded up to ``round_to`` so jit
    sees few distinct shapes (length-bucketing happens in serve/batcher)."""
    if not rows:
        return np.zeros((0, round_to), np.uint8), np.zeros((0,), np.int32)
    L = max_len or max(1, max(len(r) for r in rows))
    L = ((L + round_to - 1) // round_to) * round_to
    out = np.zeros((len(rows), L), dtype=np.uint8)
    lengths = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        r = r[:L]
        out[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        lengths[i] = len(r)
    return out, lengths
