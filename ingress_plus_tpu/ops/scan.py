"""Batched bitap scan — jnp/XLA implementation.

The recurrence per byte (uint32, element-wise over words — see
compiler/bitap.py for why no cross-word carries exist):

    S' = ((S << 1) | INIT) & B[byte]
    M' = M | (S' & FINAL)

Shapes: tokens (B, L) int32 in [0, 255] (padded with any value), lengths
(B,) int32, state/match (B, W) uint32.  Padded steps are identity on both S
and M (masked select), so a row's final state is exactly the state after its
``length`` real bytes — the property the streaming chunk chain relies on.

Design notes (TPU-first):
- `lax.scan` over the time axis with the batch×words update vectorized on
  the VPU; `unroll` amortizes loop overhead.
- The 256×W byte table is gathered per step with `jnp.take` — on TPU this
  compiles to a dynamic-gather from VMEM (the table is ~256×258×4B ≈ 264KB).
- Everything is static-shaped; jit caches one executable per (B, L, W).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ingress_plus_tpu.compiler.bitap import BitapTables


@jax.tree_util.register_pytree_node_class
@dataclass
class ScanTables:
    """Device-resident scan tables (a pytree, so it jits as an argument —
    ruleset hot-swap is just passing new arrays, no recompilation)."""

    byte_table: jax.Array  # (256, W) uint32
    init_mask: jax.Array   # (W,) uint32
    final_mask: jax.Array  # (W,) uint32

    @classmethod
    def from_bitap(cls, t: BitapTables) -> "ScanTables":
        return cls(
            byte_table=jnp.asarray(t.byte_table, dtype=jnp.uint32),
            init_mask=jnp.asarray(t.init_mask, dtype=jnp.uint32),
            final_mask=jnp.asarray(t.final_mask, dtype=jnp.uint32),
        )

    @property
    def n_words(self) -> int:
        return self.byte_table.shape[1]

    def tree_flatten(self):
        return (self.byte_table, self.init_mask, self.final_mask), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def scan_bytes(
    tables: ScanTables,
    tokens: jax.Array,   # (B, L) int32/uint8
    lengths: jax.Array,  # (B,) int32
    state: Optional[jax.Array] = None,  # (B, W) uint32 — streaming carry
    match: Optional[jax.Array] = None,  # (B, W) uint32 — sticky accumulator
    unroll: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Scan a batch of byte rows; returns (match, state) after each row's
    ``length`` bytes.  Pass the returned ``state``/``match`` back in for the
    next chunk of the same streams (benchmark config #5)."""
    B, L = tokens.shape
    W = tables.n_words
    if state is None:
        state = jnp.zeros((B, W), dtype=jnp.uint32)
    if match is None:
        match = jnp.zeros((B, W), dtype=jnp.uint32)

    tokens_t = jnp.transpose(tokens.astype(jnp.int32))  # (L, B): scan axis first
    steps = jnp.arange(L, dtype=jnp.int32)
    lengths = lengths.astype(jnp.int32)

    init = tables.init_mask[None, :]
    final = tables.final_mask[None, :]

    def step(carry, xs):
        S, M = carry
        bytes_t, t = xs
        reach = jnp.take(tables.byte_table, bytes_t, axis=0)  # (B, W)
        S_new = ((S << jnp.uint32(1)) | init) & reach
        valid = (t < lengths)[:, None]  # (B, 1)
        S = jnp.where(valid, S_new, S)
        M = jnp.where(valid, M | (S_new & final), M)
        return (S, M), None

    (state, match), _ = jax.lax.scan(
        step, (state, match), (tokens_t, steps), unroll=unroll
    )
    return match, state


@functools.partial(jax.jit, static_argnames=("unroll",))
def scan_bytes_jit(tables, tokens, lengths, state=None, match=None, unroll: int = 8):
    return scan_bytes(tables, tokens, lengths, state, match, unroll)


def scan_bytes_reference(tables: ScanTables, data: bytes) -> np.ndarray:
    """Single-row convenience wrapper (numpy in/out) for tests/debugging."""
    if len(data) == 0:
        return np.zeros((tables.n_words,), dtype=np.uint32)
    tokens = jnp.asarray(np.frombuffer(data, dtype=np.uint8)[None, :])
    lengths = jnp.asarray([len(data)], dtype=jnp.int32)
    match, _ = scan_bytes(tables, tokens, lengths)
    return np.asarray(match[0])


def pad_rows(rows: list, max_len: Optional[int] = None, round_to: int = 128
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side helper: pack variable-length byte strings into a padded
    (B, L) uint8 matrix + lengths.  L is rounded up to ``round_to`` so jit
    sees few distinct shapes (length-bucketing happens in serve/batcher)."""
    if not rows:
        return np.zeros((0, round_to), np.uint8), np.zeros((0,), np.int32)
    L = max_len or max(1, max(len(r) for r in rows))
    L = ((L + round_to - 1) // round_to) * round_to
    out = np.zeros((len(rows), L), dtype=np.uint8)
    lengths = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        r = r[:L]
        out[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        lengths[i] = len(r)
    return out, lengths
