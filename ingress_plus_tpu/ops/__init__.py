"""TPU scan kernels: the per-byte automaton hot loop (SURVEY.md §3.3 #2).

Two interchangeable implementations of the same bitap recurrence
(compiler/bitap.py):

- ``scan.py``         — pure jnp/XLA: `lax.scan` over byte steps, gather for
  the byte table.  Runs anywhere (CPU tests, TPU), is the reference
  implementation, and is what multi-chip sharding wraps.
- ``pallas_scan.py``  — hand-written Pallas TPU kernel: MXU one-hot reach
  precompute into VMEM scratch + serial VPU shift-AND chain with state
  resident in VMEM and early exit on ragged tiles.

Measured on v5e (full 1.4k-rule corpus, W=291, see utils/microbench.py):
XLA `take` ≈ 200 MB/s, Pallas ≈ 163 MB/s (TB=256, CL=8) — both near
VPU-bound on the (B, W) recurrence; XLA's gather lowering wins, so
``scan.py`` is the serving default and the kernel is kept as the
hand-scheduled alternative (it wins on ragged batches via early exit).

Both expose scan(tokens, lengths, state) → (match, state) so streaming
chunked bodies (benchmark config #5) carry the NFA state vector across
calls — the framework's sequence-parallel analog (SURVEY.md §5).
"""

from ingress_plus_tpu.ops.scan import (  # noqa: F401
    ScanTables,
    scan_bytes,
    scan_bytes_reference,
)
