"""TPU scan kernels: the per-byte automaton hot loop (SURVEY.md §3.3 #2).

Two interchangeable implementations of the same bitap recurrence
(compiler/bitap.py):

- ``scan.py``         — pure jnp/XLA: `lax.scan` over byte steps, gather for
  the byte table.  Runs anywhere (CPU tests, TPU), is the reference
  implementation, and is what multi-chip sharding wraps.
- ``pallas_scan.py``  — hand-written Pallas TPU kernel: byte table resident
  in VMEM, grid over batch tiles, double-buffered HBM→VMEM byte streaming.

Both expose scan(tokens, lengths, state) → (match, state) so streaming
chunked bodies (benchmark config #5) carry the NFA state vector across
calls — the framework's sequence-parallel analog (SURVEY.md §5).
"""

from ingress_plus_tpu.ops.scan import (  # noqa: F401
    ScanTables,
    scan_bytes,
    scan_bytes_reference,
)
