"""Pallas TPU kernel for the batched bitap scan.

Same contract as ops/scan.py::scan_bytes — this is the hand-scheduled
version of the hot loop (the reference's per-byte libproton automaton scan,
SURVEY.md §3.3 hot loop #2).  What the kernel does that the XLA lax.scan
lowering can't:

- **Decoupled gather.** The serial dependency (S' depends on S) forces one
  step per input byte, and XLA re-gathers B[byte] from the (256, W) table
  inside every step.  Here the reach masks for a whole CL-byte chunk are
  computed up front on the MXU — one-hot(bytes) @ byte-planes in bf16
  (values ≤255 are exact) — and the serial chain then runs as pure VPU
  element-wise ops against VMEM scratch.
- **Early exit on ragged batches.** The serial loop bound is the *tile's*
  max row length (read on-chip), so a tile of short rows skips its padded
  tail entirely; XLA's scan always walks the full padded length.
- **State residency.** (state, match) live in the output VMEM blocks across
  the whole length axis (grid dim 1 is sequential), so HBM sees each token
  byte once and each state word twice.

Mosaic note: in-kernel reshapes like (TB, CL)→(CL·TB, 1) are unsupported
shape casts, so the position-major column layout is produced *outside* the
kernel by XLA (cheap fused transpose) and block-indexed directly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ingress_plus_tpu.ops.scan import ScanTables


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _scan_kernel(toks_pm_ref, lens_ref, planes_ref, init_ref, final_ref,
                 state_in_ref, match_in_ref, match_ref, state_ref,
                 reach_ref, *, CL: int, TB: int, MR: int, Wp: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        state_ref[:] = state_in_ref[:]
        match_ref[:] = match_in_ref[:]

    t_max = jnp.max(lens_ref[:])      # tile's longest row
    t_rem = t_max - k * CL            # bytes of real work left in this chunk

    @pl.when(t_rem > 0)
    def _():
        # ---- stage 1: reach masks for every (position, row) via MXU ------
        # toks_pm rows are position-major: row t*TB + r  ⇒  byte t of row r.
        lanes = jax.lax.broadcasted_iota(jnp.int32, (MR, 256), 1)
        for j in range(CL * TB // MR):
            @pl.when(j * (MR // TB) < t_rem)
            def _():
                sub = toks_pm_ref[pl.ds(j * MR, MR), :]       # (MR, 1)
                onehot = (sub == lanes).astype(jnp.bfloat16)
                planes = jnp.dot(onehot, planes_ref[:],
                                 preferred_element_type=jnp.float32)
                p = planes.astype(jnp.int32)
                reach = (p[:, 0 * Wp:1 * Wp]
                         | (p[:, 1 * Wp:2 * Wp] << 8)
                         | (p[:, 2 * Wp:3 * Wp] << 16)
                         | (p[:, 3 * Wp:4 * Wp] << 24))
                reach_ref[pl.ds(j * MR, MR), :] = reach

        # ---- stage 2: serial shift-AND chain on the VPU ------------------
        init = init_ref[:]                                    # (1, Wp)
        final = final_ref[:]
        lens = lens_ref[:]                                    # (TB, 1)

        def step(t, carry):
            S, M = carry
            reach = reach_ref[pl.ds(t * TB, TB), :]           # (TB, Wp)
            S_new = ((S << 1) | init) & reach
            valid = (k * CL + t) < lens                       # (TB, 1)
            S = jnp.where(valid, S_new, S)
            M = jnp.where(valid, M | (S_new & final), M)
            return (S, M)

        S, M = jax.lax.fori_loop(0, jnp.minimum(CL, t_rem), step,
                                 (state_ref[:], match_ref[:]))
        state_ref[:] = S
        match_ref[:] = M


@functools.partial(
    jax.jit, static_argnames=("TB", "CL", "MR", "interpret"))
def _pallas_scan(tokens, lengths, planes, init, final, state, match,
                 TB: int, CL: int, MR: int, interpret: bool):
    """tokens (B, L) int32 padded to tile multiples; lengths (B, 1) int32;
    state/match (B, Wp) int32.  Returns (match, state), (B, Wp) int32."""
    B, L = tokens.shape
    Wp = init.shape[1]
    nb, nk = B // TB, L // CL

    # position-major column: row ((i*nk + k)*CL + t)*TB + r = byte t of
    # batch row i*TB+r in chunk k — one fused XLA transpose, no in-kernel
    # reshapes (unsupported shape casts in Mosaic).
    toks_pm = (tokens.reshape(nb, TB, nk, CL)
               .transpose(0, 2, 3, 1)
               .reshape(nb * nk * CL * TB, 1))

    kernel = functools.partial(_scan_kernel, CL=CL, TB=TB, MR=MR, Wp=Wp)
    out_m, out_s = pl.pallas_call(
        kernel,
        grid=(nb, nk),
        in_specs=[
            pl.BlockSpec((CL * TB, 1), lambda i, k, nk=nk: (i * nk + k, 0),
                         memory_space=pltpu.VMEM),       # tokens (pos-major)
            pl.BlockSpec((TB, 1), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # lengths
            pl.BlockSpec((256, 4 * Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # byte planes
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # init
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # final
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # state carry in
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # match carry in
        ],
        out_specs=[
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # match
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # state
        ],
        scratch_shapes=[pltpu.VMEM((CL * TB, Wp), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(toks_pm, lengths, planes, init, final, state, match)
    return out_m, out_s


class PallasScanner:
    """Caches the padded/packed device tables for repeated kernel calls
    (serving + bench reuse one instance; hot-swap = build a new one)."""

    def __init__(self, tables: ScanTables, TB: int = 64, CL: int = 32,
                 MR: int = 256):
        W = tables.n_words
        Wp = _round_up(max(W, 128), 128)
        self.W, self.Wp, self.TB, self.CL = W, Wp, TB, CL
        self.MR = min(MR, CL * TB)
        # stage 1 writes reach rows in MR-row blocks and gates each block
        # by position — misaligned tilings would leave scratch rows stale
        # and silently corrupt the NFA state, so reject them loudly
        if TB % 8 or (CL * TB) % self.MR or self.MR % TB:
            raise ValueError(
                "invalid tiling: need TB %% 8 == 0, MR %% TB == 0 and "
                "(CL*TB) %% MR == 0; got TB=%d CL=%d MR=%d"
                % (TB, CL, self.MR))
        bt = np.zeros((256, Wp), np.uint32)
        bt[:, :W] = np.asarray(tables.byte_table)
        self.planes = jnp.asarray(np.concatenate(
            [((bt >> (8 * k)) & 0xFF).astype(np.float32) for k in range(4)],
            axis=1), jnp.bfloat16)
        init = np.zeros((1, Wp), np.int32)
        init[0, :W] = np.asarray(tables.init_mask).view(np.int32)
        final = np.zeros((1, Wp), np.int32)
        final[0, :W] = np.asarray(tables.final_mask).view(np.int32)
        self.init, self.final = jnp.asarray(init), jnp.asarray(final)

    def __call__(self, tokens, lengths, state=None, match=None,
                 interpret: bool = False):
        """scan_bytes contract: returns (match, state) as (B, W) uint32."""
        B, L = tokens.shape
        TB, CL, W, Wp = self.TB, self.CL, self.W, self.Wp
        Bp = _round_up(max(B, TB), TB)
        Lp = _round_up(max(L, CL), CL)

        def as_i32(x):
            x = jnp.asarray(x)
            return (jax.lax.bitcast_convert_type(x, jnp.int32)
                    if x.dtype == jnp.uint32 else x.astype(jnp.int32))

        tok_p = jnp.zeros((Bp, Lp), jnp.int32).at[:B, :L].set(
            jnp.asarray(tokens).astype(jnp.int32))
        len_p = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(
            jnp.asarray(lengths).astype(jnp.int32))
        sin = jnp.zeros((Bp, Wp), jnp.int32)
        if state is not None:
            sin = sin.at[:B, :W].set(as_i32(state))
        min_ = jnp.zeros((Bp, Wp), jnp.int32)
        if match is not None:
            min_ = min_.at[:B, :W].set(as_i32(match))

        out_m, out_s = _pallas_scan(
            tok_p, len_p, self.planes, self.init, self.final, sin, min_,
            TB=TB, CL=CL, MR=self.MR, interpret=interpret)
        to_u32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
        return to_u32(out_m[:B, :W]), to_u32(out_s[:B, :W])


def pallas_scan_bytes(
    tables: ScanTables,
    tokens: jax.Array,
    lengths: jax.Array,
    state: Optional[jax.Array] = None,
    match: Optional[jax.Array] = None,
    TB: int = 64,
    CL: int = 32,
    MR: int = 256,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot convenience wrapper (table packing not cached); equivalence
    with scan_bytes is asserted bit-for-bit in tests/test_pallas_scan.py."""
    return PallasScanner(tables, TB=TB, CL=CL, MR=MR)(
        tokens, lengths, state, match, interpret=interpret)
