"""Pallas TPU kernel for the batched bitap scan.

Same contract as ops/scan.py::scan_bytes — this is the hand-scheduled
version of the hot loop (the reference's per-byte libproton automaton scan,
SURVEY.md §3.3 hot loop #2).  What the kernel does that the XLA lax.scan
lowering can't:

- **Decoupled gather.** The serial dependency (S' depends on S) forces one
  step per input byte, and XLA re-gathers B[byte] from the (256, W) table
  inside every step.  Here the reach masks for a whole CL-byte chunk are
  computed up front on the MXU — one-hot(bytes) @ byte-planes in bf16
  (values ≤255 are exact) — and the serial chain then runs as pure VPU
  element-wise ops against VMEM scratch.
- **Early exit on ragged batches.** The serial loop bound is the *tile's*
  max row length (read on-chip), so a tile of short rows skips its padded
  tail entirely; XLA's scan always walks the full padded length.
- **State residency.** (state, match) live in the output VMEM blocks across
  the whole length axis (grid dim 1 is sequential), so HBM sees each token
  byte once and each state word twice.

Mosaic note: in-kernel reshapes like (TB, CL)→(CL·TB, 1) are unsupported
shape casts, so the position-major column layout is produced *outside* the
kernel by XLA (cheap fused transpose) and block-indexed directly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax-version compat: CompilerParams was TPUCompilerParams on older
# pallas (same fields); resolve once at import
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

from ingress_plus_tpu.ops.scan import ScanTables, classes_for, scan_pairs_jit


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _scan_kernel(toks_pm_ref, lens_ref, planes_ref, init_ref, final_ref,
                 state_in_ref, match_in_ref, match_ref, state_ref,
                 reach_ref, *, CL: int, TB: int, MR: int, Wp: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        state_ref[:] = state_in_ref[:]
        match_ref[:] = match_in_ref[:]

    t_max = jnp.max(lens_ref[:])      # tile's longest row
    t_rem = t_max - k * CL            # bytes of real work left in this chunk

    @pl.when(t_rem > 0)
    def _():
        # ---- stage 1: reach masks for every (position, row) via MXU ------
        # toks_pm rows are position-major: row t*TB + r  ⇒  byte t of row r.
        lanes = jax.lax.broadcasted_iota(jnp.int32, (MR, 256), 1)
        for j in range(CL * TB // MR):
            @pl.when(j * (MR // TB) < t_rem)
            def _():
                sub = toks_pm_ref[pl.ds(j * MR, MR), :]       # (MR, 1)
                onehot = (sub == lanes).astype(jnp.bfloat16)
                planes = jnp.dot(onehot, planes_ref[:],
                                 preferred_element_type=jnp.float32)
                p = planes.astype(jnp.int32)
                reach = (p[:, 0 * Wp:1 * Wp]
                         | (p[:, 1 * Wp:2 * Wp] << 8)
                         | (p[:, 2 * Wp:3 * Wp] << 16)
                         | (p[:, 3 * Wp:4 * Wp] << 24))
                reach_ref[pl.ds(j * MR, MR), :] = reach

        # ---- stage 2: serial shift-AND chain on the VPU ------------------
        init = init_ref[:]                                    # (1, Wp)
        final = final_ref[:]
        lens = lens_ref[:]                                    # (TB, 1)

        def step(t, carry):
            S, M = carry
            reach = reach_ref[pl.ds(t * TB, TB), :]           # (TB, Wp)
            S_new = ((S << 1) | init) & reach
            valid = (k * CL + t) < lens                       # (TB, 1)
            S = jnp.where(valid, S_new, S)
            M = jnp.where(valid, M | (S_new & final), M)
            return (S, M)

        S, M = jax.lax.fori_loop(0, jnp.minimum(CL, t_rem), step,
                                 (state_ref[:], match_ref[:]))
        state_ref[:] = S
        match_ref[:] = M


@functools.partial(
    jax.jit, static_argnames=("TB", "CL", "MR", "interpret"))
def _pallas_scan(tokens, lengths, planes, init, final, state, match,
                 TB: int, CL: int, MR: int, interpret: bool):
    """tokens (B, L) int32 padded to tile multiples; lengths (B, 1) int32;
    state/match (B, Wp) int32.  Returns (match, state), (B, Wp) int32."""
    B, L = tokens.shape
    Wp = init.shape[1]
    nb, nk = B // TB, L // CL

    # position-major column: row ((i*nk + k)*CL + t)*TB + r = byte t of
    # batch row i*TB+r in chunk k — one fused XLA transpose, no in-kernel
    # reshapes (unsupported shape casts in Mosaic).
    toks_pm = (tokens.reshape(nb, TB, nk, CL)
               .transpose(0, 2, 3, 1)
               .reshape(nb * nk * CL * TB, 1))

    kernel = functools.partial(_scan_kernel, CL=CL, TB=TB, MR=MR, Wp=Wp)
    out_m, out_s = pl.pallas_call(
        kernel,
        grid=(nb, nk),
        in_specs=[
            pl.BlockSpec((CL * TB, 1), lambda i, k, nk=nk: (i * nk + k, 0),
                         memory_space=pltpu.VMEM),       # tokens (pos-major)
            pl.BlockSpec((TB, 1), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # lengths
            pl.BlockSpec((256, 4 * Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # byte planes
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # init
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),       # final
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # state carry in
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),       # match carry in
        ],
        out_specs=[
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # match
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # state
        ],
        scratch_shapes=[pltpu.VMEM((CL * TB, Wp), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(toks_pm, lengths, planes, init, final, state, match)
    return out_m, out_s


class PallasScanner:
    """Caches the padded/packed device tables for repeated kernel calls
    (serving + bench reuse one instance; hot-swap = build a new one)."""

    def __init__(self, tables: ScanTables, TB: int = 64, CL: int = 32,
                 MR: int = 256):
        W = tables.n_words
        Wp = _round_up(max(W, 128), 128)
        self.W, self.Wp, self.TB, self.CL = W, Wp, TB, CL
        self.MR = min(MR, CL * TB)
        # stage 1 writes reach rows in MR-row blocks and gates each block
        # by position — misaligned tilings would leave scratch rows stale
        # and silently corrupt the NFA state, so reject them loudly
        if TB % 8 or (CL * TB) % self.MR or self.MR % TB:
            raise ValueError(
                "invalid tiling: need TB %% 8 == 0, MR %% TB == 0 and "
                "(CL*TB) %% MR == 0; got TB=%d CL=%d MR=%d"
                % (TB, CL, self.MR))
        bt = np.zeros((256, Wp), np.uint32)
        bt[:, :W] = np.asarray(tables.byte_table)
        self.planes = jnp.asarray(np.concatenate(
            [((bt >> (8 * k)) & 0xFF).astype(np.float32) for k in range(4)],
            axis=1), jnp.bfloat16)
        init = np.zeros((1, Wp), np.int32)
        init[0, :W] = np.asarray(tables.init_mask).view(np.int32)
        final = np.zeros((1, Wp), np.int32)
        final[0, :W] = np.asarray(tables.final_mask).view(np.int32)
        self.init, self.final = jnp.asarray(init), jnp.asarray(final)

    def __call__(self, tokens, lengths, state=None, match=None,
                 interpret: bool = False):
        """scan_bytes contract: returns (match, state) as (B, W) uint32."""
        B, L = tokens.shape
        TB, CL, W, Wp = self.TB, self.CL, self.W, self.Wp
        Bp = _round_up(max(B, TB), TB)
        Lp = _round_up(max(L, CL), CL)

        def as_i32(x):
            x = jnp.asarray(x)
            return (jax.lax.bitcast_convert_type(x, jnp.int32)
                    if x.dtype == jnp.uint32 else x.astype(jnp.int32))

        tok_p = jnp.zeros((Bp, Lp), jnp.int32).at[:B, :L].set(
            jnp.asarray(tokens).astype(jnp.int32))
        len_p = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(
            jnp.asarray(lengths).astype(jnp.int32))
        sin = jnp.zeros((Bp, Wp), jnp.int32)
        if state is not None:
            sin = sin.at[:B, :W].set(as_i32(state))
        min_ = jnp.zeros((Bp, Wp), jnp.int32)
        if match is not None:
            min_ = min_.at[:B, :W].set(as_i32(match))

        out_m, out_s = _pallas_scan(
            tok_p, len_p, self.planes, self.init, self.final, sin, min_,
            TB=TB, CL=CL, MR=self.MR, interpret=interpret)
        to_u32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
        return to_u32(out_m[:B, :W]), to_u32(out_s[:B, :W])


def pallas_scan_bytes(
    tables: ScanTables,
    tokens: jax.Array,
    lengths: jax.Array,
    state: Optional[jax.Array] = None,
    match: Optional[jax.Array] = None,
    TB: int = 64,
    CL: int = 32,
    MR: int = 256,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot convenience wrapper (table packing not cached); equivalence
    with scan_bytes is asserted bit-for-bit in tests/test_pallas_scan.py."""
    return PallasScanner(tables, TB=TB, CL=CL, MR=MR)(
        tokens, lengths, state, match, interpret=interpret)


# ---------------------------------------------------------------------------
# Class-pair Pallas kernel (round 4, VERDICT item #8)
# ---------------------------------------------------------------------------
#
# Why the byte kernel lost its own bake-off (pallas ≈ 254k vs pair ≈ 357k
# req/s on v5e): its serial VPU chain runs one shift-AND step per BYTE,
# while the XLA pair impl runs one per BYTE PAIR.  At W≈500+ (Wp 640
# lanes) the chain dominates, so the hand kernel's better gather couldn't
# make up a 2× step-count handicap.  This kernel takes BOTH wins:
#
# - **Pair chain.**  The serial loop consumes two bytes per step using the
#   same folded recurrence as ops/scan.py::scan_pairs —
#       pairR = ((R1 << 1) | I) & R2
#       M    |= ((S << 1) | I) & (R1 & final)      (ends at odd byte)
#       S     = ((S << 2) | (I<<1) | I) & pairR
#       M    |= S & final                          (ends at even byte)
#   where R1/R2 are the two bytes' single-byte reach rows.  Expanding the
#   fold reproduces two shift-AND steps exactly (see ScanTables notes).
# - **Class-compressed MXU gather.**  Bytes are mapped to Hyperscan-style
#   byte classes OUTSIDE the kernel (tiny 257-entry XLA gather); stage 1
#   one-hots over K1 ≤ 256 classes instead of 256 raw bytes, so the MXU
#   matmul contracts over the (usually much smaller) class count.
# - **Cross-chunk overlap.**  reach scratch is DOUBLE-BUFFERED: iteration
#   k first issues the MXU stage for chunk k+1 into buffer (k+1)%2 (its
#   tokens come from a second, shifted BlockSpec view of the same array),
#   then runs the serial chain of chunk k from buffer k%2.  The two
#   stages touch disjoint buffers, so Mosaic is free to run chunk k+1's
#   matmuls under chunk k's VPU chain instead of serializing them.
#
# Dead-class padding (index K-1 has all-zero reach) replaces per-step
# validity masks, exactly like scan_pairs: a padded row's state dies and
# its match is stable, so the chain needs no lens compares at all.  The
# state contract therefore matches scan_pairs, NOT scan_bytes: rows
# shorter than L return state 0 — use for request scans and equal-length
# chunk waves (match is what serving consumes).


def _pair_kernel(cls_pm_ref, cls_nx_ref, lens_ref, planes_ref, init_ref,
                 final_ref, state_in_ref, match_in_ref, match_ref,
                 state_ref, reach0_ref, reach1_ref, *, CL: int, TB: int,
                 MR: int, Wp: int, K1p: int, NK: int):
    k = pl.program_id(1)
    even = (k % 2) == 0     # chunk k's reach lives in buf (k%2); the two
                            # buffers are separate scratch refs so all
                            # ref indexing stays static under Mosaic

    @pl.when(k == 0)
    def _():
        state_ref[:] = state_in_ref[:]
        match_ref[:] = match_in_ref[:]

    t_max = jnp.max(lens_ref[:])
    lanes = jax.lax.broadcasted_iota(jnp.int32, (MR, K1p), 1)

    def stage1(tok_ref, buf_ref, rem):
        """Reach rows for one whole chunk into ``buf_ref`` (MXU).

        The guard rounds ``rem`` UP TO EVEN: the chain's last pair reads
        position rem itself when rem is odd (its R2 — a dead-class
        padding byte whose computed reach is all-zero), so that row MUST
        be freshly computed; guarding on bare ``rem`` left it stale from
        two chunks earlier and fabricated matches (round-4 review repro:
        TB=8/MR=8, 49-byte row)."""
        rem_even = ((rem + 1) // 2) * 2
        for j in range(CL * TB // MR):
            @pl.when(j * (MR // TB) < rem_even)
            def _():
                sub = tok_ref[pl.ds(j * MR, MR), :]           # (MR, 1)
                onehot = (sub == lanes).astype(jnp.bfloat16)
                planes = jnp.dot(onehot, planes_ref[:],
                                 preferred_element_type=jnp.float32)
                p = planes.astype(jnp.int32)
                reach = (p[:, 0 * Wp:1 * Wp]
                         | (p[:, 1 * Wp:2 * Wp] << 8)
                         | (p[:, 2 * Wp:3 * Wp] << 16)
                         | (p[:, 3 * Wp:4 * Wp] << 24))
                buf_ref[pl.ds(j * MR, MR), :] = reach

    # prime buffer 0 with chunk 0's reach on the first grid step
    @pl.when(k == 0)
    def _():
        stage1(cls_pm_ref, reach0_ref, t_max)

    # issue chunk k+1's MXU work FIRST (into the other buffer) — program
    # order ahead of the chain, disjoint buffer, so Mosaic may overlap it
    # under the serial VPU chain of chunk k
    nx_rem = t_max - (k + 1) * CL

    @pl.when((k + 1 < NK) & (nx_rem > 0) & even)
    def _():
        stage1(cls_nx_ref, reach1_ref, nx_rem)

    @pl.when((k + 1 < NK) & (nx_rem > 0) & jnp.logical_not(even))
    def _():
        stage1(cls_nx_ref, reach0_ref, nx_rem)

    # ... then run chunk k's serial pair chain from its own buffer
    t_rem = t_max - k * CL

    def chain(buf_ref):
        init = init_ref[:]                                    # (1, Wp)
        final = final_ref[:]
        ior = (init << 1) | init

        def step(t, carry):
            S, M = carry
            R1 = buf_ref[pl.ds((2 * t) * TB, TB), :]
            R2 = buf_ref[pl.ds((2 * t + 1) * TB, TB), :]
            pairR = ((R1 << 1) | init) & R2
            M = M | (((S << 1) | init) & (R1 & final))
            S = ((S << 2) | ior) & pairR
            M = M | (S & final)
            return (S, M)

        n_pairs = (jnp.minimum(CL, t_rem) + 1) // 2
        S, M = jax.lax.fori_loop(0, n_pairs, step,
                                 (state_ref[:], match_ref[:]))
        state_ref[:] = S
        match_ref[:] = M

    @pl.when((t_rem > 0) & even)
    def _():
        chain(reach0_ref)

    @pl.when((t_rem > 0) & jnp.logical_not(even))
    def _():
        chain(reach1_ref)


@functools.partial(
    jax.jit, static_argnames=("TB", "CL", "MR", "interpret"))
def _pallas_pair_scan(cls_tokens, lengths, planes, init, final, state,
                      match, TB: int, CL: int, MR: int, interpret: bool):
    """cls_tokens (B, L) int32 CLASS indices (dead class = K1-1) padded to
    tile multiples; otherwise the _pallas_scan contract."""
    B, L = cls_tokens.shape
    Wp = init.shape[1]
    K1p = planes.shape[0]
    nb, nk = B // TB, L // CL

    toks_pm = (cls_tokens.reshape(nb, TB, nk, CL)
               .transpose(0, 2, 3, 1)
               .reshape(nb * nk * CL * TB, 1))

    kernel = functools.partial(_pair_kernel, CL=CL, TB=TB, MR=MR, Wp=Wp,
                               K1p=K1p, NK=nk)
    blk = CL * TB
    out_m, out_s = pl.pallas_call(
        kernel,
        grid=(nb, nk),
        in_specs=[
            pl.BlockSpec((blk, 1), lambda i, k, nk=nk: (i * nk + k, 0),
                         memory_space=pltpu.VMEM),   # chunk k classes
            # chunk k+1's classes (clamped at the last chunk): feeds the
            # double-buffered prefetch stage
            pl.BlockSpec((blk, 1),
                         lambda i, k, nk=nk: (
                             i * nk + jnp.minimum(k + 1, nk - 1), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),   # lengths
            pl.BlockSpec((K1p, 4 * Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),   # class planes
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Wp), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),   # state carry in
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),   # match carry in
        ],
        out_specs=[
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, Wp), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # match
            jax.ShapeDtypeStruct((B, Wp), jnp.int32),    # state
        ],
        scratch_shapes=[pltpu.VMEM((blk, Wp), jnp.int32),
                        pltpu.VMEM((blk, Wp), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(toks_pm, toks_pm, lengths, planes, init, final, state, match)
    return out_m, out_s


def check_pair_tiling(TB: int, CL: int, MR: int) -> int:
    """Validate the (TB, CL, MR) tile config; returns the clamped MR."""
    MR = min(MR, CL * TB)
    if TB % 8 or CL % 2 or (CL * TB) % MR or MR % TB:
        raise ValueError(
            "invalid tiling: need TB %% 8 == 0, CL even, MR %% TB == 0 "
            "and (CL*TB) %% MR == 0; got TB=%d CL=%d MR=%d"
            % (TB, CL, MR))
    return MR


def pack_pair_tables(class_table: np.ndarray, init_mask: np.ndarray,
                     final_mask: np.ndarray):
    """Pad + plane-split class tables into the _pallas_pair_scan input
    layout — the ONE packing shared by PallasPairScanner (single chip)
    and ShardedEngine's per-shard pallas2 path.

    class_table (K1, W) uint32 with the DEAD class (all-zero reach)
    LAST; init/final (W,) uint32.  Returns (planes (K1p, 4*Wp) float32
    — byte planes of the uint32 words, exact in bf16 since every value
    <= 255; init (1, Wp) int32; final (1, Wp) int32; K1p; Wp), padded to
    the kernel's 128-lane tiles with all-zero (dead) rows."""
    K1, W = class_table.shape
    Wp = _round_up(max(W, 128), 128)
    K1p = _round_up(max(K1, 128), 128)
    ct = np.zeros((K1p, Wp), np.uint32)
    ct[:K1, :W] = np.asarray(class_table)
    planes = np.concatenate(
        [((ct >> (8 * j)) & 0xFF).astype(np.float32) for j in range(4)],
        axis=1)
    init = np.zeros((1, Wp), np.int32)
    init[0, :W] = np.asarray(init_mask).view(np.int32)
    final = np.zeros((1, Wp), np.int32)
    final[0, :W] = np.asarray(final_mask).view(np.int32)
    return planes, init, final, K1p, Wp


class PallasPairScanner:
    """Class-pair Pallas kernel with cached packed tables.

    Same call contract as PallasScanner, with scan_pairs' state caveat
    (dead-class padding: short rows return state 0)."""

    def __init__(self, tables: ScanTables, TB: int = 64, CL: int = 16,
                 MR: int = 256):
        if tables.byte_class is None:
            raise ValueError("tables built without byte classes")
        W = tables.n_words
        planes, init, final, K1p, Wp = pack_pair_tables(
            np.asarray(tables.class_table), np.asarray(tables.init_mask),
            np.asarray(tables.final_mask))
        self.W, self.Wp, self.TB, self.CL, self.K1p = W, Wp, TB, CL, K1p
        self.MR = check_pair_tiling(TB, CL, MR)
        self.planes = jnp.asarray(planes, jnp.bfloat16)
        self.init, self.final = jnp.asarray(init), jnp.asarray(final)
        self.byte_class = tables.byte_class        # (257,) int32
        self.dead = int(tables.class_table.shape[0]) - 1

    def __call__(self, tokens, lengths, state=None, match=None,
                 interpret: bool = False):
        B, L = tokens.shape
        TB, CL, W, Wp = self.TB, self.CL, self.W, self.Wp
        Bp = _round_up(max(B, TB), TB)
        Lp = _round_up(max(L, CL), CL)

        def as_i32(x):
            x = jnp.asarray(x)
            return (jax.lax.bitcast_convert_type(x, jnp.int32)
                    if x.dtype == jnp.uint32 else x.astype(jnp.int32))

        lengths = jnp.asarray(lengths).astype(jnp.int32)
        # byte → class with padding mapped to the dead class (tiny XLA
        # gather; the kernel then one-hots over classes, not bytes) —
        # the SAME mapping scan_pairs uses (ops/scan.py classes_for)
        cls = classes_for(self.byte_class, tokens, lengths)
        cls_p = jnp.full((Bp, Lp), self.dead, jnp.int32).at[:B, :L].set(cls)
        len_p = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(lengths)
        sin = jnp.zeros((Bp, Wp), jnp.int32)
        if state is not None:
            sin = sin.at[:B, :W].set(as_i32(state))
        min_ = jnp.zeros((Bp, Wp), jnp.int32)
        if match is not None:
            min_ = min_.at[:B, :W].set(as_i32(match))

        out_m, out_s = _pallas_pair_scan(
            cls_p, len_p, self.planes, self.init, self.final, sin, min_,
            TB=TB, CL=CL, MR=self.MR, interpret=interpret)
        to_u32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
        return to_u32(out_m[:B, :W]), to_u32(out_s[:B, :W])


# ---------------------------------------------------------------------------
# Raw-byte fused kernel (ISSUE 13: "make the device path real")
# ---------------------------------------------------------------------------
#
# The pallas2 host contract still made the caller prep CLASS arrays: an
# eager (257,)-LUT gather (classes_for), eager padding ops, and an int32
# upcast — per dispatch, on the host/default-device boundary.  The
# Hyperflex observation (arXiv:2512.07123) is that for a shift-and NFA
# packed across vector lanes, any byte-level pre-mapping composes into
# the per-byte reach fetch: planes_byte[b] == planes_class[byte_class[b]]
# by construction, so a kernel that one-hots RAW byte values over 257
# rows (256 bytes + one dead padding index) computes bit-identical reach
# rows with NO host-side class mapping at all.  The host ships the uint8
# request bytes and the lengths — a memcpy — and everything else
# (dead-index padding select, position-major transpose, the MXU reach
# matmuls, the lane-packed pair chain) lives in ONE device program.
#
# The MXU price: the one-hot contraction runs over K1p = 384 padded rows
# instead of the pack's K1p (128 on the bundled pack) — 3x the stage-1
# matmul flops.  That stage overlaps the serial VPU chain (the pair
# kernel's double-buffered prefetch), so the trade buys host-prep and
# transfer volume with idle MXU cycles.  Measured truth lives in
# `utils/microbench --scan`; parity is CI-gated (tools/lint.py
# devicegate) in interpret mode.

#: the reserved dead padding index of the raw-byte planes (row 256 has
#: all-zero reach — a padded position kills its lane's state and leaves
#: the sticky match stable, exactly the scan_pairs dead-class contract)
DEAD_BYTE = 256


def pack_byte_pair_tables(byte_table: np.ndarray, init_mask: np.ndarray,
                          final_mask: np.ndarray):
    """pack_pair_tables on the RAW byte axis: 257 rows (byte values +
    the dead padding index LAST), padded to the kernel's 128-lane tiles
    (K1p = 384).  The byte→class LUT is gone — it composes into the
    planes (planes[b] = class_planes[byte_class[b]])."""
    W = byte_table.shape[1]
    bt = np.zeros((DEAD_BYTE + 1, W), np.uint32)
    bt[:256] = np.asarray(byte_table)
    return pack_pair_tables(bt, init_mask, final_mask)


@functools.partial(
    jax.jit, static_argnames=("TB", "CL", "MR", "interpret"))
def _fused_byte_scan(tokens, lengths, planes, init, final, state, match,
                     TB: int, CL: int, MR: int, interpret: bool):
    """Raw-byte fused device program: tokens (B, L) uint8 RAW request
    bytes, lengths (B,) int32, state/match (B, W) uint32.  The
    ragged/padding handling is one elementwise select (position >=
    length → DEAD_BYTE) that XLA fuses into the position-major
    transpose; the Mosaic pair kernel then needs no validity compares
    at all.  Returns (match, state) as (B, W) uint32."""
    B, L = tokens.shape
    W = state.shape[1]
    Wp = init.shape[1]
    Bp = _round_up(max(B, TB), TB)
    Lp = _round_up(max(L, CL), CL)
    lengths = lengths.reshape(B)
    toks = jnp.where(
        jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None],
        tokens.astype(jnp.int32), jnp.int32(DEAD_BYTE))
    cls_p = jnp.full((Bp, Lp), DEAD_BYTE, jnp.int32).at[:B, :L].set(toks)
    len_p = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(lengths)

    def as_i32p(x):
        x = jax.lax.bitcast_convert_type(x, jnp.int32)
        return jnp.zeros((Bp, Wp), jnp.int32).at[:B, :W].set(x)

    out_m, out_s = _pallas_pair_scan(
        cls_p, len_p, planes, init, final, as_i32p(state), as_i32p(match),
        TB=TB, CL=CL, MR=MR, interpret=interpret)
    to_u32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
    return to_u32(out_m[:B, :W]), to_u32(out_s[:B, :W])


class PallasByteScanner:
    """Raw-byte fused scanner — serving name ``pallas3`` (ISSUE 13,
    docs/SCAN_KERNEL.md "Device path").

    Contract: uint8 request bytes + lengths IN, (match, state) uint32
    OUT; byte→reach mapping, ragged/padding handling and the
    lane-packed pair chain all execute inside one device program, so
    the host path per dispatch approaches a memcpy (see the module
    comment above for the design and its MXU trade).

    Backend dispatch: on TPU backends the Mosaic kernel compiles; on
    CPU (or ``mode="reference"``) the SAME math runs as the XLA
    class-pair lowering (``scan_pairs`` — bit-identical by the plane
    composition identity, pinned by tests/test_pallas_scan.py and the
    ``devicegate`` CI gate), so ``--scan-impl pallas3`` serves
    everywhere and the first real-TPU run is a flag flip, not a
    porting project.  ``interpret=True`` forces the Mosaic interpreter
    (the parity-test path).

    State contract = scan_pairs (dead padding): rows shorter than L
    return state 0 — request scans and equal-length chunk waves, NOT
    ragged streaming carries (streams keep the byte path)."""

    def __init__(self, tables: ScanTables, TB: int = 64, CL: int = 16,
                 MR: int = 256):
        if tables.pair_reach is None:
            raise ValueError(
                "tables built without byte classes (the reference "
                "lowering needs the pair tables)")
        W = tables.n_words
        planes, init, final, K1p, Wp = pack_byte_pair_tables(
            np.asarray(tables.byte_table), np.asarray(tables.init_mask),
            np.asarray(tables.final_mask))
        self.W, self.Wp, self.TB, self.CL, self.K1p = W, Wp, TB, CL, K1p
        self.MR = check_pair_tiling(TB, CL, MR)
        self.planes = jnp.asarray(planes, jnp.bfloat16)
        self.init, self.final = jnp.asarray(init), jnp.asarray(final)
        #: reference-lowering twin (a pytree — passed as a jit ARGUMENT
        #: so nothing constant-folds, the BENCH_r02 lesson)
        self.tables = tables
        self.device = None   # for_device() replicas record their chip

    # ------------------------------------------------------- placement

    def for_device(self, device):
        """Replica with the packed tables placed on ``device`` via the
        NamedSharding idiom (SNIPPETS.md [3]): a one-device mesh with a
        replicated PartitionSpec pins this lane's copy to its own chip,
        so N serve lanes dispatch the kernel concurrently — the
        ``tables_for`` sigpack-replication story (docs/MESH_SERVING.md)
        now covers the Pallas path too."""
        import copy

        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        sh = NamedSharding(Mesh(np.asarray([device]), ("lane",)),
                           PartitionSpec())
        new = copy.copy(self)
        new.planes = jax.device_put(self.planes, sh)
        new.init = jax.device_put(self.init, sh)
        new.final = jax.device_put(self.final, sh)
        new.tables = jax.device_put(self.tables, sh)
        new.device = device
        return new

    # ------------------------------------------------------- exec keys

    def _use_kernel(self) -> bool:
        """Mosaic compiles only on TPU platforms ("axon" = this rig's
        remote-TPU PJRT plugin); everywhere else the reference lowering
        serves (pallas_call without interpret would raise on CPU)."""
        return jax.default_backend() in ("tpu", "axon")

    def exec_shape(self, B: int, L: int) -> Tuple[int, int]:
        """The executable-keying shape of one (B, L) dispatch: the
        Mosaic kernel keys on the TILE-padded rectangle (several host
        bucket shapes share one executable), the reference lowering on
        the exact shape.  The pipeline recompile gauge reads this so
        pallas3 serving counts real compiles, not phantom ones."""
        if self._use_kernel():
            return (_round_up(max(B, self.TB), self.TB),
                    _round_up(max(L, self.CL), self.CL))
        return (B, L)

    # --------------------------------------------------------- dispatch

    def __call__(self, tokens, lengths, state=None, match=None,
                 interpret: bool = False, mode: str = "auto"):
        """scan_bytes-shaped call: returns (match, state) (B, W) uint32.

        ``mode``: "auto" = Mosaic kernel on TPU backends, reference XLA
        lowering elsewhere; "kernel" forces the pallas_call (compiled,
        or Mosaic-interpreted with interpret=True); "reference" forces
        the XLA lowering."""
        tokens = jnp.asarray(tokens)
        B, L = tokens.shape
        W = self.W
        lengths = jnp.asarray(lengths).astype(jnp.int32).reshape(B)
        if mode == "auto":
            mode = "kernel" if (interpret or self._use_kernel()) \
                else "reference"

        def as_u32(x):
            if x is None:
                return jnp.zeros((B, W), jnp.uint32)
            x = jnp.asarray(x)
            return (x if x.dtype == jnp.uint32
                    else jax.lax.bitcast_convert_type(x, jnp.uint32))

        state, match = as_u32(state), as_u32(match)
        if mode == "reference":
            if L % 2:
                # the pair fold consumes two bytes per step; one extra
                # column is past every row's length, so classes_for
                # maps it to the dead class — math unchanged
                tokens = jnp.pad(tokens, ((0, 0), (0, 1)))
            return scan_pairs_jit(self.tables, tokens, lengths,
                                  state, match)
        return _fused_byte_scan(
            tokens, lengths, self.planes, self.init, self.final,
            state, match, TB=self.TB, CL=self.CL, MR=self.MR,
            interpret=interpret)
