"""ingress_plus_tpu — TPU-native WAF detection framework.

A brand-new framework with the capabilities of wallarm/ingress-plus
(Wallarm's ingress-nginx WAF fork), re-designed TPU-first:

- ``compiler/``  — ruleset compiler: SecLang (ModSecurity CRS) / signature
  packs → mandatory-factor extraction → bit-parallel shift-and (bitap) NFA
  tables.  The analog of the reference's closed-source libproton compiled
  ruleset (proton.db) and of libmodsecurity's SecLang engine
  (reference: internal C engines, see SURVEY.md §2.2).
- ``ops/``       — JAX/XLA + Pallas TPU kernels for the batched byte-stream
  scan (the reference's per-byte automaton hot loop, SURVEY.md §3.3).
- ``models/``    — detection models: prefilter NFA + per-class verdict heads,
  strict-grammar SQLi/XSS confirm (libdetection analog), ML scorer.
- ``parallel/``  — device-mesh sharding: DP (batch), TP (ruleset shards),
  EP (tenant routing), SP (streaming halo exchange) via shard_map + XLA
  collectives over ICI (SURVEY.md §2.4).
- ``serve/``     — dispatcher/serve loop: batching, fail-open, ruleset
  hot-swap, metrics (the nginx-module/sidecar boundary, SURVEY.md §3.3).
- ``control/``   — control-plane analog: annotations, global config,
  template rendering (SURVEY.md §2.1).
- ``rules/``     — bundled CRS-v3-shaped rule corpus + signature packs
  (authored for this project; provenance in rules/README.md).
"""

__version__ = "0.1.0"
