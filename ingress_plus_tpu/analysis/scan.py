"""Lexical directive scanner: rules tree → positioned directive stream.

The SecLang parser (compiler/seclang.py) resolves control flow while it
loads — which is exactly why it cannot *report* on it: a skipped rule
never becomes a ``Rule``, a dangling marker is silently survived.  The
analyzers instead walk this raw, position-preserving directive stream
(file + line per directive, chain structure, action dicts) and re-derive
the control/dataflow properties independently, so findings can say
*where* the problem is authored.

Reuses only the seclang lexer primitives (tokenizer, action splitter) —
the semantics under audit are re-derived here, not imported.
"""

from __future__ import annotations

import glob as _glob
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ingress_plus_tpu.compiler.seclang import (
    _logical_lines_numbered,
    _parse_actions,
    _phase_key,
    _split_directive,
)


@dataclass
class Directive:
    """One logical SecLang directive with its source position."""

    kind: str                 # "SecRule" | "SecAction" | "SecMarker" | ...
    tokens: List[str]
    file: str
    line: int                 # 1-based first line of the logical line
    actions: Dict[str, List[str]] = field(default_factory=dict)

    # -- SecRule/SecAction conveniences -------------------------------
    @property
    def rule_id(self) -> int:
        try:
            return int(self.actions.get("id", ["0"])[0] or 0)
        except ValueError:
            return 0

    @property
    def phase(self) -> str:
        return _phase_key(self.actions)

    @property
    def is_chain_link_opener(self) -> bool:
        return "chain" in self.actions

    @property
    def skip_marker(self) -> Optional[str]:
        v = self.actions.get("skipAfter")
        return v[0].strip().strip("'\"") if v else None

    @property
    def setvars(self) -> List[str]:
        return [v.strip("'\"") for v in self.actions.get("setvar", []) if v]

    @property
    def targets_txt(self) -> str:
        return self.tokens[1] if self.kind == "SecRule" and \
            len(self.tokens) > 1 else ""

    @property
    def op_txt(self) -> str:
        return self.tokens[2] if self.kind == "SecRule" and \
            len(self.tokens) > 2 else ""

    def operator(self) -> Tuple[bool, str, str]:
        """(negate, operator, argument) — mirrors the parser's split."""
        op = self.op_txt
        negate = False
        if op.startswith("!@"):
            negate, op = True, op[1:]
        if op.startswith("@"):
            parts = op.split(None, 1)
            return negate, parts[0][1:], parts[1] if len(parts) > 1 else ""
        if op.startswith("!"):
            return True, "rx", op[1:]
        return negate, "rx", op


@dataclass
class FileScan:
    path: str
    directives: List[Directive]
    #: directive index of an ``Include`` → the FileScans it pulled in,
    #: in glob order — the topology the parser's skip regions follow
    #: (a region survives INTO an included file and is cleared after it)
    includes: Dict[int, List["FileScan"]] = field(default_factory=dict)


def scan_file(path: Path) -> FileScan:
    directives: List[Directive] = []
    for lineno, line in _logical_lines_numbered(path.read_text()):
        try:
            tokens = _split_directive(line)
        except ValueError:
            continue  # the parser raises on these; not this pass's job
        if not tokens:
            continue
        kind = tokens[0]
        actions: Dict[str, List[str]] = {}
        if kind == "SecRule" and len(tokens) > 3:
            actions = _parse_actions(tokens[3])
        elif kind == "SecAction" and len(tokens) > 1:
            actions = _parse_actions(tokens[1])
        directives.append(Directive(kind=kind, tokens=tokens,
                                    file=str(path), line=lineno,
                                    actions=actions))
    return FileScan(path=str(path), directives=directives)


def scan_tree(path: str | Path) -> List[FileScan]:
    """Scan a rules tree in load order: a directory scans its sorted
    ``*.conf`` files; a file is scanned and its ``Include`` directives
    followed (sorted glob expansion, cycle-proof) — the same traversal
    load_seclang_dir performs."""
    p = Path(path)
    seen: set = set()
    out: List[FileScan] = []

    def visit(conf: Path) -> "FileScan | None":
        key = str(conf.resolve())
        if key in seen or not conf.is_file():
            return None
        seen.add(key)
        fs = scan_file(conf)
        out.append(fs)
        for i, d in enumerate(fs.directives):
            if d.kind != "Include" or len(d.tokens) < 2:
                continue
            pat = d.tokens[1]
            root = Path(pat) if Path(pat).is_absolute() else conf.parent / pat
            matches = ([Path(m) for m in sorted(_glob.glob(str(root)))]
                       if any(c in pat for c in "*?[") else [root])
            for m in matches:
                child = visit(m)
                if child is not None:
                    fs.includes.setdefault(i, []).append(child)
        return fs

    if p.is_dir():
        for conf in sorted(p.glob("*.conf")):
            visit(conf)
    else:
        visit(p)
    return out


def root_scans(scans: List[FileScan]) -> List[FileScan]:
    """The load-order entry files (those not pulled in by an Include) —
    the starting points for any walk that follows the include topology."""
    included = {id(c) for fs in scans
                for children in fs.includes.values() for c in children}
    return [fs for fs in scans if id(fs) not in included]


def iter_load_order(scans: List[FileScan]):
    """Yield ``(file_scan, directive)`` in the parser's ACTUAL load
    order: entry files in sequence, descending into Include'd files at
    the Include directive's position (a flat per-file walk would order
    a parent's post-Include directives before the included ones —
    review finding: that inverted read/write order across Includes)."""
    def walk(fs: FileScan):
        for idx, d in enumerate(fs.directives):
            yield fs, d
            if d.kind == "Include":
                for child in fs.includes.get(idx, []):
                    yield from walk(child)

    for fs in root_scans(scans):
        yield from walk(fs)


def static_tx_env(scans: List[FileScan]
                  ) -> Tuple[Dict[str, str], Dict[str, Directive]]:
    """(env, conditional_writes) mirroring the parser's TX-env fold
    semantics (compiler/seclang.py): SecActions fold in load order; a
    SecRule folds when its own condition resolves statically TRUE
    against the env so far, is ignored when FALSE, and otherwise
    INVALIDATES the names it writes (request-dependent).  Chain-carried
    setvars always invalidate.  ``conditional_writes`` maps each
    request-dependently-written name to its first writing directive —
    names folded from statically-true rules are NOT in it.

    Known divergence from the parser: taken skip regions are not
    simulated here, so a setvar inside a skipped interval still
    classifies — acceptable for reporting (the reachability sweep
    re-evaluates regions itself)."""
    from ingress_plus_tpu.compiler.seclang import (
        _fold_tx_assignments,
        _invalidate_tx_names,
        _static_skip_condition,
    )
    env: Dict[str, str] = {}
    cond: Dict[str, Directive] = {}
    in_chain = False
    cur_fs: Optional[FileScan] = None
    for fs, d in iter_load_order(scans):
        if fs is not cur_fs:
            cur_fs = fs
            in_chain = False   # the parser's chain state is per file
        if d.kind == "SecAction":
            _fold_tx_assignments(env, d.setvars)
            continue
        if d.kind != "SecRule":
            continue
        is_link = in_chain
        # a chain continues while each link carries "chain"
        in_chain = d.is_chain_link_opener
        if not d.setvars:
            continue
        if is_link or d.is_chain_link_opener:
            verdict = None        # conjunction: never static here
        else:
            negate, op, arg = d.operator()
            verdict = _static_skip_condition(d.targets_txt, negate,
                                             op, arg, env)
        if verdict is True:
            _fold_tx_assignments(env, d.setvars)
        elif verdict is None:
            for name in _invalidate_tx_names(env, d.setvars):
                cond.setdefault(name, d)
        # verdict False: the rule never fires — env untouched
    return env, cond


def rule_positions(scans: List[FileScan]) -> Dict[int, Tuple[str, int]]:
    """rule id → (file, line) for findings that only know the id."""
    out: Dict[int, Tuple[str, int]] = {}
    for fs in scans:
        for d in fs.directives:
            if d.kind in ("SecRule", "SecAction") and d.rule_id:
                out.setdefault(d.rule_id, (d.file, d.line))
    return out
