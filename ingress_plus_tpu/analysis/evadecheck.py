"""evadecheck — static evasion-closure analyzer (docs/ANALYSIS.md).

The third analyzer next to rulecheck/concheck.  rulecheck certifies the
prefilter never loses a match OF THE BYTES IT SCANS; evadecheck asks the
question ROADMAP item 5 leaves open: are those bytes the ones an
attacker must send?  For each compiled rule it statically decides
whether detection is CLOSED under the modeled evasion families — the
re-encodings a payload survives on its way to the backend sink — by
diffing three artifacts the compiler already produces: the rule's
SecLang transform chain, the serve-path normalizer's decode set
(serve/normalize.py scan variants; ARGS is pre-decoded exactly once),
and the regex AST / mandatory-literal factors.

Check classes (stable dotted ids):

  evade.transform-closure   a rule scans a RAW byte stream (REQUEST_URI,
                            REQUEST_HEADERS) with no decode transform in
                            its chain: a %XX-encoded payload never folds
                            back to the pattern's bytes on any scanned
                            variant (the 944130 escape).  Also flags
                            html-entity blindness for XSS-tagged markup
                            literals.
  evade.literal-fragility   every mandatory quick-reject literal
                            (models/confirm.py derive_quick_reject)
                            contains a severable gap: a space an inline
                            comment (/**/, SQL sinks) or an alternate
                            whitespace byte can occupy while the chain
                            neutralizes neither.  Long factors near the
                            pack window are surfaced (info) as chunk-
                            boundary seams for item 3's windowed scan.
  evade.case-hole           a letter-keyword pattern matched case-
                            sensitively (no t:lowercase, no inline
                            (?i)): mixed-case spelling evades while the
                            sink stays case-insensitive.
  evade.anchor-hazard       every path through the pattern starts at ^ —
                            on scanned streams the attacker owns the
                            prefix, so padding defeats the anchor.

Runtime twin: utils/evasion.py ``mutation_harness`` replays the golden
corpus re-encoded per mutation family through ``detect_cpu_only`` and
reports per-family retention + per-escape rule attribution.  Pass its
escapes to ``run_evadecheck(escapes=...)`` and any static finding whose
rule appears in a runtime escape of the matching family is CORROBORATED:
severity escalates to error and the finding message names the escaping
request.  Statically-found weaknesses that no mutation reaches stay at
their static severity and live in the reasoned baseline
(analysis/evadecheck-baseline.json).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set

from ingress_plus_tpu.analysis.findings import Baseline, Finding, Report

#: mutation family → the static check class its escapes corroborate
FAMILY_CHECK = {
    "url": "evade.transform-closure",
    "html": "evade.transform-closure",
    "unicode": "evade.transform-closure",
    "comment": "evade.literal-fragility",
    "whitespace": "evade.literal-fragility",
    "case": "evade.case-hole",
    "split": "evade.anchor-hazard",
}

#: text-matching operators whose argument describes payload bytes an
#: attacker can re-encode (heuristic detectors model their own decoding)
_TEXT_OPS = {"rx", "pm", "pmf", "pmFromFile", "contains", "containsWord",
             "streq", "beginsWith", "endsWith"}

#: factors at/over this length will straddle a chunk boundary once item
#: 3's windowed scanning lands (MAX_FACTOR_LEN is 32; seams open well
#: before that)
_CHUNK_SEAM_LEN = 24


#: protocol wire tokens: the engine maps them into the uri scan stream,
#: but no backend ever decodes them — the method/protocol field IS the
#: raw token, so encoding it breaks the request, not the detection
_WIRE_TOKEN_BASES = {"REQUEST_METHOD", "REQUEST_PROTOCOL"}


def _raw_bases(rule) -> Set[str]:
    return {t.strip().lstrip("&!").split(":", 1)[0].upper()
            for t in (rule.raw_targets or ()) if t.strip()}


def _wire_token_only(rule) -> bool:
    bases = _raw_bases(rule)
    return bool(bases) and bases <= _WIRE_TOKEN_BASES


def _rule_chain_transforms(rule) -> Set[str]:
    """Union of transforms over the rule and its chained links — a decode
    anywhere in the chain covers the shared MATCHED_VAR re-tests."""
    t: Set[str] = set()
    link = rule
    while link is not None:
        t |= set(link.transforms)
        link = getattr(link, "chain", None)
    return t


def _letter_runs(text: str, n: int = 3) -> bool:
    """True when ``text`` contains a run of >= n letters — a keyword an
    attacker can respell in mixed case (two-letter hex fragments like
    ``%df`` don't count)."""
    streak = 0
    for c in text:
        streak = streak + 1 if c.isalpha() else 0
        if streak >= n:
            return True
    return False


def _all_paths_start_anchored(node) -> bool:
    """True iff every string matched by the pattern must begin at ``^``.

    Conservative: unknown node shapes return False (no finding)."""
    from ingress_plus_tpu.compiler import regex_ast as R

    if isinstance(node, R.Anchor):
        return node.kind in ("^", "A")
    if isinstance(node, R.Concat):
        for part in node.parts:
            if isinstance(part, R.Anchor) and part.kind in ("^", "A"):
                return True
            if isinstance(part, R.Repeat) and part.min == 0:
                continue  # skippable prefix — look further
            return _all_paths_start_anchored(part)
        return False
    if isinstance(node, R.Alt):
        return all(_all_paths_start_anchored(b) for b in node.options)
    return False


def _check_transform_closure(meta) -> List[Finding]:
    rule = meta.rule
    if rule.operator not in _TEXT_OPS or getattr(rule, "negate", False):
        return []
    t = _rule_chain_transforms(rule)
    out: List[Finding] = []

    # raw-stream decode gap: URI bytes arrive percent-encoded, the
    # backend's router decodes them, and NOTHING decodes them before
    # this rule's variant (ARGS alone is pre-decoded once by the serve
    # path).  Headers are deliberately out of scope — no backend
    # url-decodes header bytes, so an encoded header is a broken attack,
    # not an evasion (the same carrier model as utils/evasion.py).
    # Patterns that themselves match encoded forms ('%' in the
    # argument) are exempt — encoding detectors by design; so are
    # wire-token rules (REQUEST_METHOD et al. are never decoded).
    from ingress_plus_tpu.compiler.ruleset import _DECODE_TRANSFORMS
    if "uri" in rule.targets and not (t & _DECODE_TRANSFORMS) \
            and "%" not in rule.argument and not _wire_token_only(rule):
        out.append(Finding(
            check="evade.transform-closure", severity="warning",
            rule_id=rule.rule_id, subject="missing-url-decode",
            message="scans the raw uri with no urlDecode-family "
                    "transform: a %XX-encoded path never matches on "
                    "any scanned variant while the backend router "
                    "decodes it"))

    # html-entity blindness: an XSS markup literal ('<'-shaped) without
    # htmlEntityDecode anywhere — &#x3c;script decodes at the browser
    # sink but never on the scanned rows.
    from ingress_plus_tpu.compiler.ruleset import _HTML_TRANSFORMS
    if "attack-xss" in rule.tags and "<" in rule.argument \
            and rule.operator in ("rx", "contains", "pm", "pmf",
                                  "pmFromFile") \
            and not (t & _HTML_TRANSFORMS):
        out.append(Finding(
            check="evade.transform-closure", severity="notice",
            rule_id=rule.rule_id, subject="missing-html-decode",
            message="XSS markup literal without htmlEntityDecode: "
                    "entity-encoded markup (&#x3c;script) decodes at the "
                    "browser but not on the scanned rows"))
    return out


def _check_literal_fragility(meta) -> List[Finding]:
    from ingress_plus_tpu.compiler.ruleset import (
        _COMMENT_TRANSFORMS,
        _WS_COLLAPSE,
    )
    from ingress_plus_tpu.models.confirm import derive_quick_reject

    rule = meta.rule
    out: List[Finding] = []
    t = _rule_chain_transforms(rule)

    gapped: List[bytes] = []
    if rule.operator == "rx":
        qr = derive_quick_reject(rule.argument,
                                 bool(meta.confirm.get("fold")))
        if qr and all(b" " in lit for lit in qr):
            gapped = list(qr)
    elif rule.operator in ("pm", "pmf", "pmFromFile"):
        words = meta.confirm.get("words") or []
        enc = [w.encode("utf-8", "surrogateescape") for w in words]
        if enc and all(b" " in w for w in enc):
            gapped = enc

    if gapped:
        sample = gapped[0].decode("utf-8", "replace")
        if "attack-sqli" in rule.tags and not (t & _COMMENT_TRANSFORMS):
            out.append(Finding(
                check="evade.literal-fragility", severity="warning",
                rule_id=rule.rule_id, subject="comment-severable",
                message="every mandatory literal spans a space (e.g. "
                        "%r) and no comment transform folds /**/ back "
                        "to whitespace: an inline comment severs the "
                        "match in a SQL sink" % sample))
        if not (t & _WS_COLLAPSE):
            out.append(Finding(
                check="evade.literal-fragility", severity="notice",
                rule_id=rule.rule_id, subject="whitespace-severable",
                message="every mandatory literal spans a literal space "
                        "(e.g. %r) with no whitespace-collapse "
                        "transform: tab/newline separators sever the "
                        "match" % sample))

    # chunk-boundary seam: a mandatory factor this long WILL straddle a
    # window edge under item 3's chunked scanning
    if meta.has_prefilter and rule.operator == "rx":
        qr = derive_quick_reject(rule.argument,
                                 bool(meta.confirm.get("fold")))
        longest = max((len(lit) for lit in qr or ()), default=0)
        if longest >= _CHUNK_SEAM_LEN:
            out.append(Finding(
                check="evade.literal-fragility", severity="info",
                rule_id=rule.rule_id, subject="chunk-window",
                message="mandatory literal of %d bytes will straddle "
                        "chunk boundaries under windowed scanning "
                        "(ROADMAP item 3) unless windows overlap by at "
                        "least that length" % longest))
    return out


def _check_case_hole(meta) -> List[Finding]:
    rule = meta.rule
    if rule.operator not in ("rx", "contains", "containsWord", "streq",
                             "beginsWith", "endsWith"):
        return []  # pm-family ops fold unconditionally at compile
    if meta.confirm.get("fold") or _wire_token_only(rule):
        return []  # HTTP methods/protocol are case-sensitive tokens
    if rule.operator == "rx" and "(?i" in rule.argument:
        return []
    if not _letter_runs(rule.argument):
        return []
    return [Finding(
        check="evade.case-hole", severity="notice",
        rule_id=rule.rule_id, subject="case-sensitive-keyword",
        message="letter keyword matched case-sensitively (no "
                "t:lowercase, no inline (?i)): mixed-case spelling "
                "evades while most sinks stay case-insensitive")]


def _check_anchor_hazard(meta) -> List[Finding]:
    from ingress_plus_tpu.compiler.regex_ast import (
        RegexUnsupported,
        parse_regex,
    )

    rule = meta.rule
    if rule.operator not in ("rx", "beginsWith"):
        return []
    # only where the attacker owns the matched value's prefix: args and
    # body values.  uri rows start at the request line's fixed framing,
    # header rows at the header NAME, and scalar rules (REQUEST_METHOD)
    # anchor a value the attacker must produce whole — padding is
    # impossible or self-defeating in all three.
    scanned = set(rule.targets) & {"args", "body"}
    if not scanned or _wire_token_only(rule):
        return []
    if rule.operator == "beginsWith":
        anchored = True
    else:
        try:
            ast = parse_regex(rule.argument,
                              ignorecase=bool(meta.confirm.get("fold")))
        except (RegexUnsupported, RecursionError):
            return []
        anchored = _all_paths_start_anchored(ast)
    if not anchored:
        return []
    return [Finding(
        check="evade.anchor-hazard", severity="notice",
        rule_id=rule.rule_id, subject="start-anchored",
        message="every match path starts at ^ but the attacker owns "
                "the %s prefix: benign padding defeats the anchor"
                % "/".join(sorted(scanned)))]


def _corroborate(findings: List[Finding],
                 escapes: Sequence[Dict]) -> int:
    """Escalate static findings confirmed by runtime escapes.

    An escape corroborates a finding when the finding's rule was among
    the rules that detected the BASE request and the escape's mutation
    family maps to the finding's check class — the mutation removed
    exactly the signal the static check called fragile."""
    by_key: Dict = {}
    for e in escapes:
        check = FAMILY_CHECK.get(e.get("family", ""))
        for rid in e.get("base_rule_ids", ()):
            by_key.setdefault((check, int(rid)), []).append(e)
    n = 0
    for f in findings:
        hits = by_key.get((f.check, f.rule_id))
        if not hits:
            continue
        e = hits[0]
        f.severity = "error"
        f.message += (" [CORROBORATED: %s-family mutation of %s escaped "
                      "detection]" % (e.get("family"),
                                      e.get("request_id", "?")))
        n += 1
    return n


#: default suppression baseline, next to this module (concheck layout)
BASELINE = Path(__file__).resolve().parent / "evadecheck-baseline.json"


def run_evadecheck(rules_path: Optional[str | Path] = None,
                   baseline_path: Optional[str | Path] = "auto",
                   compiled=None,
                   escapes: Optional[Sequence[Dict]] = None) -> Report:
    """Run the evasion-closure checks over a rules tree.

    ``escapes`` takes ``mutation_harness`` escape records (any families,
    flattened, each dict carrying ``family``) for corroboration.
    ``compiled`` skips recompilation (dbg / gate paths)."""
    from ingress_plus_tpu.analysis import BUNDLED_RULES
    from ingress_plus_tpu.analysis.scan import rule_positions, scan_tree
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import load_seclang_dir

    rules_path = Path(rules_path) if rules_path is not None else \
        BUNDLED_RULES
    if not rules_path.exists():
        raise OSError("rules tree %s does not exist — an empty audit "
                      "would report a misleading clean pass" % rules_path)
    if compiled is None:
        compiled = compile_ruleset(load_seclang_dir(rules_path))

    findings: List[Finding] = []
    for meta in compiled.rules:
        findings += _check_transform_closure(meta)
        findings += _check_literal_fragility(meta)
        findings += _check_case_hole(meta)
        findings += _check_anchor_hazard(meta)

    corroborated = _corroborate(findings, escapes or ())

    # source positions + path relativization (rulecheck convention:
    # reports must not embed machine-specific absolute paths)
    scans = scan_tree(rules_path)
    pos = rule_positions(scans)
    rel_bases = [Path.cwd(),
                 rules_path if rules_path.is_dir() else rules_path.parent]

    def _rel(p: str) -> str:
        for base in rel_bases:
            try:
                return str(Path(p).resolve().relative_to(base.resolve()))
            except ValueError:
                continue
        return p

    for f in findings:
        if not f.file and f.rule_id in pos:
            f.file, f.line = pos[f.rule_id]
        if f.file:
            f.file = _rel(f.file)

    resolved_baseline = ""
    if baseline_path == "auto":
        baseline_path = BASELINE if BASELINE.is_file() else None
    if baseline_path is not None:
        bl = Baseline.load(baseline_path)
        bl.apply(findings)
        resolved_baseline = bl.path

    return Report(
        findings=findings,
        rules_path=_rel(str(rules_path)),
        baseline_path=_rel(resolved_baseline) if resolved_baseline else "",
        n_rules=compiled.n_rules,
        pack_version=compiled.version,
        tool="evadecheck",
        meta={"corroborated": corroborated,
              "escapes_seen": len(escapes or ())},
    )
