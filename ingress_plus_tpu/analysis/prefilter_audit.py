"""Prefilter-soundness audit: static per-rule proof of the gate property.

``utils/prefilter_gate.py`` proves *by measurement* that the TPU bitap
prefilter never loses a confirm-stage match.  This module proves it
*statically, per rule*: it decodes the packed bitap tables back into
byte-class sequences (independent de-packing — a packing bug shows up
here, not just a derivation bug) and certifies, against a fresh
derivation from the rule's regex AST, that every string the rule can
match contains a substring matching one of the rule's packed factor
alternatives.  Certification logic:

    covered(d, G)   — class sequence d contains a window classwise
                      inside some alternative g of G (so every string
                      matching d contains a string matching g)
    certify(node,G) — exact when the node's language enumerates within
                      a bound; otherwise decomposes: any concat part (or
                      contiguous enumerable run of parts) certifying G
                      certifies the concat; an alternation certifies iff
                      every option does; Repeat(min>=1) via its body.

Squash/path scan lanes re-derive the compiler's factor-rewrite contract
independently: derived sequences are fragmented at ambiguous positions
(classes partially inside SQUASH_BYTES / path separators) with fully
deletable positions removed, and a factor must cover a window of some
fragment of EVERY alternative.

Rules without factors are classified (negated, non-scan operator,
degraded regex, unscannable target, destructive transform) so the
"silently falls to confirm-only" set is explicit; an rx rule with no
structural reason whose AST yields a certifiable factor group is a
coverage gap (the compiler left prefilter power on the table).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ingress_plus_tpu.analysis.findings import Finding
from ingress_plus_tpu.compiler.bitap import BitapTables
from ingress_plus_tpu.compiler.regex_ast import (
    Alt,
    Anchor,
    Concat,
    Lit,
    Repeat,
    RegexUnsupported,
    parse_regex,
)

ClassSeq = Tuple[frozenset, ...]

#: enumeration bound — deliberately wider than the compiler's
#: MAX_ALTERNATIVES=64 so every group the compiler derived from an
#: enumerable (sub)language is re-derivable here
ENUM_CAP = 256
MAX_REPEAT_ENUM = 8
#: mirrors compiler MIN_GROUP_BITS: below this a derivable group is too
#: weak to call its absence a coverage gap
GAP_MIN_BITS = 6.0
WEAK_BITS = 6.0

# independent copies of the compiler's lane byte sets (ruleset.py);
# divergence between these and the compiler's is itself a bug the
# cross-check would surface as uncertified factors
_SQUASH = frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B,
                     0x5C, 0x27, 0x22, 0x5E])
_PATH_SEP = frozenset([0x2F, 0x5C])

_FACTOR_OPS = {"rx", "pm", "pmf", "pmFromFile", "contains", "containsWord",
               "streq", "beginsWith", "endsWith"}
_HEURISTIC_OPS = {"detectSQLi", "detectXSS"}


def seq_bits(seq: ClassSeq) -> float:
    return sum(math.log2(256.0 / max(1, len(c))) for c in seq)


# ------------------------------------------------------------- de-packing


def decode_factors(tables: BitapTables) -> List[ClassSeq]:
    """Reconstruct every packed factor's byte-class sequence from the
    device tables (byte_table bit columns), independently of the
    compiler's packing bookkeeping."""
    out: List[ClassSeq] = []
    bt = tables.byte_table
    for f in range(tables.n_factors):
        w = int(tables.factor_word[f])
        fin = int(tables.factor_bit[f])
        length = int(tables.factor_len[f])
        start = fin - length + 1
        col = bt[:, w]
        seq = []
        for j in range(start, fin + 1):
            members = np.nonzero((col >> np.uint32(j)) & np.uint32(1))[0]
            seq.append(frozenset(int(b) for b in members))
        out.append(tuple(seq))
    return out


def rule_factor_groups(tables: BitapTables) -> Dict[int, List[int]]:
    """rule index → packed factor indices (CSR inversion)."""
    out: Dict[int, List[int]] = {}
    indptr = tables.factor_rule_indptr
    for f in range(tables.n_factors):
        for r in tables.factor_rule_ids[indptr[f]:indptr[f + 1]]:
            out.setdefault(int(r), []).append(f)
    return out


def table_consistency(tables: BitapTables) -> List[str]:
    """Structural invariants of the packed tables (start bit in INIT,
    final bit in FINAL, factor ranges inside their word)."""
    problems = []
    for f in range(tables.n_factors):
        w = int(tables.factor_word[f])
        fin = int(tables.factor_bit[f])
        length = int(tables.factor_len[f])
        start = fin - length + 1
        if not (0 <= start <= fin < 32):
            problems.append("factor %d: bit range [%d,%d] outside word"
                            % (f, start, fin))
            continue
        if not (int(tables.init_mask[w]) >> start) & 1:
            problems.append("factor %d: start bit %d missing from "
                            "init_mask[%d]" % (f, start, w))
        if not (int(tables.final_mask[w]) >> fin) & 1:
            problems.append("factor %d: final bit %d missing from "
                            "final_mask[%d]" % (f, fin, w))
    return problems


# ----------------------------------------------------- language machinery


def enum_language(node, cap: int = ENUM_CAP) -> Optional[List[ClassSeq]]:
    """Bounded exact enumeration of the class sequences ``node``
    matches; None when unbounded or past ``cap``."""
    if isinstance(node, Lit):
        return [(node.chars,)]
    if isinstance(node, Anchor):
        return [()]
    if isinstance(node, Alt):
        out: List[ClassSeq] = []
        for opt in node.options:
            sub = enum_language(opt, cap)
            if sub is None:
                return None
            out.extend(sub)
            if len(out) > cap:
                return None
        return list(dict.fromkeys(out))
    if isinstance(node, Concat):
        acc: List[ClassSeq] = [()]
        for part in node.parts:
            sub = enum_language(part, cap)
            if sub is None:
                return None
            acc = [a + b for a in acc for b in sub]
            if len(acc) > cap:
                return None
        return acc
    if isinstance(node, Repeat):
        if node.max is None or node.max > MAX_REPEAT_ENUM:
            return None
        base = enum_language(node.node, cap)
        if base is None:
            return None
        out = []
        piece: List[ClassSeq] = [()]
        for k in range(node.max + 1):
            if k >= node.min:
                out.extend(piece)
                if len(out) > cap:
                    return None
            if k < node.max:
                piece = [a + b for a in piece for b in base]
                if len(piece) > cap:
                    return None
        return list(dict.fromkeys(out))
    raise TypeError("unknown AST node %r" % (node,))


def lane_fragments(seq: ClassSeq, squash: bool,
                   path_split: bool) -> List[ClassSeq]:
    """A derived sequence's surviving contiguous fragments in the rule's
    scan lane.  Fully deletable positions vanish (neighbors adjacent in
    the squashed stream); ambiguously deletable / path-separator-capable
    positions are barriers a factor window cannot span."""
    if not squash and not path_split:
        return [seq]
    frags: List[List[frozenset]] = [[]]
    for cls in seq:
        if squash and cls <= _SQUASH:
            continue
        barrier = (squash and bool(cls & _SQUASH)) or \
                  (path_split and bool(cls & _PATH_SEP))
        if barrier:
            frags.append([])
        else:
            frags[-1].append(cls)
    return [tuple(f) for f in frags]


def covered(d: ClassSeq, group: Sequence[ClassSeq]) -> bool:
    """Does some window of ``d`` sit classwise inside some alternative
    of ``group`` (⇒ every string matching d contains a group match)?"""
    for g in group:
        L = len(g)
        if L == 0 or L > len(d):
            continue
        for off in range(len(d) - L + 1):
            if all(d[off + i] <= g[i] for i in range(L)):
                return True
    return False


def _enum_certifies(seqs: List[ClassSeq], group: Sequence[ClassSeq],
                    squash: bool, path_split: bool) -> bool:
    for d in seqs:
        if not any(covered(f, group)
                   for f in lane_fragments(d, squash, path_split)):
            return False
    return True


def certify(node, group: Sequence[ClassSeq], squash: bool = False,
            path_split: bool = False) -> bool:
    """True iff every match of ``node`` provably contains (in the rule's
    scan lane) a substring matching ``group``.  False = NOT certified
    (may still be sound — but the static proof failed, which for
    compiler-produced groups means a compiler bug)."""
    seqs = enum_language(node)
    if seqs is not None:
        return _enum_certifies(seqs, group, squash, path_split)
    if isinstance(node, Repeat):
        return node.min >= 1 and certify(node.node, group, squash,
                                         path_split)
    if isinstance(node, Alt):
        return all(certify(opt, group, squash, path_split)
                   for opt in node.options)
    if isinstance(node, Concat):
        # contiguous runs of enumerable parts form exactly-known
        # sub-languages that appear contiguously in every match
        run: List[ClassSeq] = [()]
        for part in node.parts:
            sub = enum_language(part)
            if sub is not None and len(sub) * len(run) <= ENUM_CAP:
                run = [a + b for a in run for b in sub]
                continue
            if run != [()] and _enum_certifies(run, group, squash,
                                               path_split):
                return True
            if sub is not None:
                # product overflowed the cap: the part still starts a
                # fresh run of its own (review finding: dropping it
                # produced false uncertified errors on sound groups)
                run = sub
            else:
                run = [()]
                if certify(part, group, squash, path_split):
                    return True
        return run != [()] and _enum_certifies(run, group, squash,
                                               path_split)
    return False


def derive_group(node, squash: bool = False,
                 path_split: bool = False) -> Optional[List[ClassSeq]]:
    """Independently derive a usable mandatory factor group, used to
    distinguish 'no factor exists' from 'compiler missed one'.
    Deliberately simpler than the compiler's extractor — a None here is
    conservative (no coverage-gap warning), never wrong."""
    seqs = enum_language(node)
    if seqs is not None:
        group: List[ClassSeq] = []
        for d in seqs:
            frags = [f for f in lane_fragments(d, squash, path_split) if f]
            if not frags:
                return None
            best = max(frags, key=seq_bits)
            # trim uninformative edges, clamp to a bitap word
            lo, hi = 0, len(best)
            while lo < hi and len(best[lo]) == 256:
                lo += 1
            while hi > lo and len(best[hi - 1]) == 256:
                hi -= 1
            best = best[lo:hi][:32]
            if not best:
                return None
            group.append(best)
        group = list(dict.fromkeys(group))
        if 0 < len(group) <= 64 and \
                min(seq_bits(g) for g in group) >= GAP_MIN_BITS:
            return group
        return None
    if isinstance(node, Repeat):
        if node.min >= 1:
            return derive_group(node.node, squash, path_split)
        return None
    if isinstance(node, Concat):
        for part in node.parts:
            g = derive_group(part, squash, path_split)
            if g is not None:
                return g
        return None
    if isinstance(node, Alt):
        combined: List[ClassSeq] = []
        for opt in node.options:
            g = derive_group(opt, squash, path_split)
            if g is None:
                return None
            combined.extend(g)
        combined = list(dict.fromkeys(combined))
        return combined if len(combined) <= 64 else None
    return None


def _lit_seq(text: str, fold: bool) -> ClassSeq:
    seq = []
    for b in text.encode("utf-8", "surrogateescape"):
        s = {b}
        if fold:
            if 0x41 <= b <= 0x5A:
                s.add(b + 0x20)
            elif 0x61 <= b <= 0x7A:
                s.add(b - 0x20)
        seq.append(frozenset(s))
    return tuple(seq)


# ------------------------------------------------------------- the audit


def _confirm_only_reason(meta) -> Optional[str]:
    """Structural reason a rule compiles with no prefilter, or None."""
    c = meta.confirm
    if c.get("negate"):
        return "negated operator (absence has no factors)"
    if c["op"] in _HEURISTIC_OPS:
        return None
    if c["op"] not in _FACTOR_OPS:
        return "non-scan operator @%s" % c["op"]
    if "regex_unsupported" in c:
        return "regex outside the NFA subset (%s)" % c["regex_unsupported"]
    # imported, not copied: these sets ARE the compiler policy being
    # classified — a copy would mis-report a future always-confirm
    # transform as a coverage gap (review finding)
    from ingress_plus_tpu.compiler.ruleset import (
        _COMMENT_TRANSFORMS,
        _UNMODELED_DECODE_TRANSFORMS,
    )
    transforms = set(c.get("transforms", []))
    if transforms & _COMMENT_TRANSFORMS:
        return "comment transforms rewrite text no scan variant models"
    if transforms & _UNMODELED_DECODE_TRANSFORMS:
        return "decode transform no scan variant models"
    from ingress_plus_tpu.compiler.seclang import NON_SCANNED_SCALAR_BASES
    bases = {t.strip().lstrip("&!").split(":", 1)[0].upper()
             for t in c.get("raw_targets", []) if t.strip()}
    if bases & NON_SCANNED_SCALAR_BASES:
        return "target text never appears in a scanned stream"
    if not meta.rule.targets:
        return "no scannable target (rule abstains)"
    return None


def audit_prefilter(metas, tables: BitapTables) -> List[Finding]:
    """The check-class-1 entry point: cross-check every rule's packed
    factors against an independent derivation from its operator AST.

    ``metas`` is CompiledRuleset.rules (RuleMeta sequence) and
    ``tables`` the matching BitapTables."""
    findings: List[Finding] = []
    for problem in table_consistency(tables):
        findings.append(Finding(
            check="prefilter.table-corrupt", severity="error",
            message="packed table invariant violated: %s" % problem,
            subject=problem.split(":")[0]))

    decoded = decode_factors(tables)
    by_rule = rule_factor_groups(tables)

    for meta in metas:
        rid = meta.rule.rule_id
        c = meta.confirm
        op = c["op"]
        group = [decoded[f] for f in by_rule.get(meta.index, [])]
        squash = meta.variant in (3, 4, 5)
        path_split = bool(set(c.get("transforms", []))
                          & {"normalizePath", "normalisePath",
                             "normalizePathWin"})

        if group:
            if op in _HEURISTIC_OPS:
                findings.append(Finding(
                    check="prefilter.heuristic-trigger", severity="info",
                    rule_id=rid, subject=op,
                    message="@%s gate uses heuristic trigger factors; "
                            "soundness vs the strict-grammar detector is "
                            "pinned by tests, not statically provable"
                            % op))
                continue
            ok, detail = _certify_rule(c, group, squash, path_split)
            if not ok:
                findings.append(Finding(
                    check="prefilter.uncertified", severity="error",
                    rule_id=rid, subject=op,
                    message="packed factor group could not be certified "
                            "mandatory for the rule's pattern%s — the "
                            "prefilter may lose confirm-stage matches"
                            % (" (%s)" % detail if detail else "")))
            else:
                bits = min(seq_bits(g) for g in group)
                if bits < WEAK_BITS:
                    findings.append(Finding(
                        check="prefilter.weak-factor", severity="notice",
                        rule_id=rid,
                        message="weakest factor alternative carries only "
                                "%.1f bits (<%.0f): the prefilter fires "
                                "on most traffic for this rule"
                                % (bits, WEAK_BITS)))
            continue

        # ---- no packed factors: classify the confirm-only fall-through
        reason = _confirm_only_reason(meta)
        if reason is not None:
            findings.append(Finding(
                check="prefilter.confirm-only", severity="info",
                rule_id=rid,
                message="no prefilter, evaluated exactly on CPU for "
                        "every applicable request: %s" % reason))
            continue
        if op == "rx":
            try:
                ast = parse_regex(c.get("arg", ""),
                                  ignorecase=bool(c.get("fold")))
            except RegexUnsupported:
                continue  # compiler stores regex_unsupported; handled above
            g = derive_group(ast, squash, path_split)
            if g is not None and certify(ast, g, squash, path_split):
                findings.append(Finding(
                    check="prefilter.coverage-gap", severity="warning",
                    rule_id=rid,
                    message="compiled always-confirm although a "
                            "certifiable mandatory factor group exists "
                            "(%d alternatives, >=%.1f bits) — compiler "
                            "left prefilter power unused"
                            % (len(g), min(seq_bits(s) for s in g))))
            else:
                findings.append(Finding(
                    check="prefilter.confirm-only", severity="info",
                    rule_id=rid,
                    message="no prefilter: no mandatory factor is "
                            "derivable from the pattern"))
        else:
            findings.append(Finding(
                check="prefilter.confirm-only", severity="info",
                rule_id=rid,
                message="no prefilter factors for @%s" % op))
    return findings


def _certify_rule(confirm: Dict, group: List[ClassSeq], squash: bool,
                  path_split: bool) -> Tuple[bool, str]:
    """Certify one rule's packed group against its operator semantics."""
    op = confirm["op"]
    fold = bool(confirm.get("fold"))
    if op == "rx":
        try:
            ast = parse_regex(confirm.get("arg", ""), ignorecase=fold)
        except RegexUnsupported as e:
            return False, "pattern unparsable at audit time: %s" % e
        if certify(ast, group, squash, path_split):
            return True, ""
        return False, "regex language not covered"
    if op in ("pm", "pmf", "pmFromFile"):
        arg = confirm.get("arg", "")
        words = confirm.get("words") or \
            (arg.split("\n") if "\n" in arg else arg.split())
        for w in words:
            if not w.strip():
                continue
            d = _lit_seq(w.strip(), fold=True)
            if not any(covered(f, group)
                       for f in lane_fragments(d, squash, path_split)):
                return False, "phrase %r not covered" % w.strip()
        return True, ""
    if op in ("contains", "containsWord", "streq", "beginsWith",
              "endsWith"):
        d = _lit_seq(confirm.get("arg", ""), fold)
        if any(covered(f, group)
               for f in lane_fragments(d, squash, path_split)):
            return True, ""
        return False, "literal argument not covered"
    if op == "within":
        # @within inverts containment: the VARIABLE must occur inside
        # the argument, so arbitrarily short variable values match and
        # no factor is mandatory — any packed factor is unsound
        return False, "@within has no mandatory factor (variable ⊆ " \
                      "argument; short values escape any factor)"
    return False, "no certification procedure for @%s" % op
