"""Regex hazard checks (check class 4): static ReDoS lint + degraded
constructs, over the CONFIRM-lane patterns.

The confirm stage evaluates the original PCRE with Python ``re`` — a
backtracking engine — on attacker-controlled bytes, so catastrophic
backtracking is a real availability hazard there (the TPU scan lane is
linear-time by construction and immune).  Checks, on the parsed AST:

  regex.redos-nested-quantifier   (error)   an unbounded repeat whose
      body contains another unbounded repeat AND whose iterations can
      abut ambiguously (first/last byte classes of the body overlap):
      the (a+)+ shape — exponential backtracking on a miss
  regex.redos-overlapping-alternation (warning) an unbounded repeat over
      an alternation with intersecting option languages ((a|a)*,
      (a|ab)*): exponential path multiplicity
  regex.redos-adjacent-quantifiers (notice) two adjacent unbounded
      repeats with overlapping byte classes (\\s*\\s*, .*.*): O(n²)
      backtracking — tolerated, surfaced
  regex.degraded-construct        (notice)  pattern uses constructs the
      factor compiler cannot model (lookaround, backreferences, ...):
      the rule silently runs confirm-only on every applicable request
  regex.confirm-unparsable        (error)   the pattern does not compile
      in the confirm engine (Python ``re``) either: ConfirmRule holds
      rx=None and abstains forever — the rule is silently DEAD (the
      941300 shlex-halved-backslash shape this check first caught)
"""

from __future__ import annotations

import re as _re

from typing import Iterable, List, Set, Tuple

from ingress_plus_tpu.analysis.findings import Finding
from ingress_plus_tpu.compiler.regex_ast import (
    Alt,
    Anchor,
    Concat,
    Lit,
    Repeat,
    RegexUnsupported,
    parse_regex,
)

#: a bounded repeat this large backtracks like an unbounded one
_LARGE = 16


def _unbounded(r: Repeat) -> bool:
    return r.max is None or r.max >= _LARGE


def _first_classes(node) -> Tuple[Set[int], bool]:
    """(possible first bytes, nullable)."""
    if isinstance(node, Lit):
        return set(node.chars), False
    if isinstance(node, Anchor):
        return set(), True
    if isinstance(node, Repeat):
        first, nullable = _first_classes(node.node)
        return first, nullable or node.min == 0
    if isinstance(node, Alt):
        first: Set[int] = set()
        nullable = False
        for o in node.options:
            f, n = _first_classes(o)
            first |= f
            nullable = nullable or n
        return first, nullable
    if isinstance(node, Concat):
        first = set()
        for p in node.parts:
            f, n = _first_classes(p)
            first |= f
            if not n:
                return first, False
        return first, True
    return set(), True


def _last_classes(node) -> Tuple[Set[int], bool]:
    if isinstance(node, Concat):
        last: Set[int] = set()
        for p in reversed(node.parts):
            f, n = _last_classes(p)
            last |= f
            if not n:
                return last, False
        return last, True
    if isinstance(node, Alt):
        last = set()
        nullable = False
        for o in node.options:
            f, n = _last_classes(o)
            last |= f
            nullable = nullable or n
        return last, nullable
    if isinstance(node, Repeat):
        last, nullable = _last_classes(node.node)
        return last, nullable or node.min == 0
    if isinstance(node, Lit):
        return set(node.chars), False
    return set(), True


def _walk(node) -> Iterable:
    yield node
    if isinstance(node, Concat):
        for p in node.parts:
            yield from _walk(p)
    elif isinstance(node, Alt):
        for o in node.options:
            yield from _walk(o)
    elif isinstance(node, Repeat):
        yield from _walk(node.node)


def _alphabet(node) -> Set[int]:
    out: Set[int] = set()
    for n in _walk(node):
        if isinstance(n, Lit):
            out |= n.chars
    return out


def _ambiguous_inner_repeat(body) -> bool:
    """Is there an unbounded repeat inside ``body`` whose alphabet
    overlaps what can ADJOIN it — the bytes following/preceding it
    within an iteration, or (wrapping past nullable tails) the body's
    own first bytes from the next outer iteration?  That overlap lets
    the repeat absorb bytes the decomposition also needs elsewhere, so
    one string splits into exponentially many iteration decompositions
    ((a+)+ yes; (?:[^,]{0,64},)+ no — the separator disambiguates).
    Only the FOLLOW side creates this: a fixed predecessor is matched
    before the repeat ever starts (variable predecessors are the
    adjacent-quantifiers check's domain)."""
    first_b, _ = _first_classes(body)

    def rec(node, follow: Set[int]) -> bool:
        if isinstance(node, Repeat):
            if _unbounded(node) and _alphabet(node.node) & follow:
                return True
            return rec(node.node, follow)
        if isinstance(node, Alt):
            return any(rec(o, follow) for o in node.options)
        if isinstance(node, Concat):
            parts = node.parts
            for k, p in enumerate(parts):
                f: Set[int] = set()
                i = k + 1
                while i < len(parts):
                    fc, nullable = _first_classes(parts[i])
                    f |= fc
                    if not nullable:
                        break
                    i += 1
                else:
                    f |= follow      # everything after is nullable: wrap
                if rec(p, f):
                    return True
            return False
        return False

    # the wrap-around context: after the body ends, the next outer
    # iteration begins with the body's own first bytes
    return rec(body, first_b)


def _langs_overlap(a, b, cap: int = 32) -> bool:
    """Can options a and b match a common string (bounded check)?
    Classwise: same length + positionwise intersection, or one a
    classwise-intersecting prefix of the other."""
    from ingress_plus_tpu.analysis.prefilter_audit import enum_language
    la = enum_language(a, cap)
    lb = enum_language(b, cap)
    if la is None or lb is None:
        return False  # conservative: no finding on unenumerable options
    for sa in la:
        for sb in lb:
            short, long_ = (sa, sb) if len(sa) <= len(sb) else (sb, sa)
            if all(short[i] & long_[i] for i in range(len(short))):
                return True
    return False


def hazards_for_pattern(ast) -> List[Tuple[str, str]]:
    """(check, detail) hazard list for one parsed pattern."""
    out: List[Tuple[str, str]] = []
    for node in _walk(ast):
        if not isinstance(node, Repeat) or not _unbounded(node):
            continue
        body = node.node
        if _ambiguous_inner_repeat(body):
            out.append((
                "regex.redos-nested-quantifier",
                "unbounded repeat of a body with an inner unbounded "
                "repeat whose alphabet overlaps its iteration "
                "boundary ((a+)+ shape)"))
            continue
        alts = [body] if isinstance(body, Alt) else \
            [n for n in _walk(body) if isinstance(n, Alt)]
        flagged = False
        for alt in alts:
            opts = alt.options
            for i in range(len(opts)):
                for j in range(i + 1, len(opts)):
                    if _langs_overlap(opts[i], opts[j]):
                        out.append((
                            "regex.redos-overlapping-alternation",
                            "alternation options under an unbounded "
                            "repeat can match the same string"))
                        flagged = True
                        break
                if flagged:
                    break
            if flagged:
                break

    for node in _walk(ast):
        if not isinstance(node, Concat):
            continue
        parts = [p for p in node.parts if not isinstance(p, Anchor)]
        for a, b in zip(parts, parts[1:]):
            if isinstance(a, Repeat) and isinstance(b, Repeat) and \
                    _unbounded(a) and _unbounded(b):
                last, _ = _last_classes(a)
                first, _ = _first_classes(b)
                if last & first:
                    out.append((
                        "regex.redos-adjacent-quantifiers",
                        "adjacent unbounded repeats over overlapping "
                        "byte classes (O(n²) backtracking)"))
    return out


def _iter_rx_confirms(metas):
    """Yield (rule_id, confirm_dict, where) for every rx evaluation the
    confirm stage performs — leaders and chain links."""
    for meta in metas:
        yield meta.rule.rule_id, meta.confirm, "rule"
        for k, link in enumerate(meta.confirm.get("chain", [])):
            yield meta.rule.rule_id, link, "chain link %d" % (k + 1)


def check_regex_hazards(metas) -> List[Finding]:
    findings: List[Finding] = []
    seen: set = set()
    for rid, confirm, where in _iter_rx_confirms(metas):
        if confirm.get("op") != "rx":
            continue
        arg = confirm.get("arg", "")
        key = (rid, where, arg)
        if key in seen:
            continue
        seen.add(key)
        try:
            # the confirm stage compiles the byte form of the pattern
            # (models/confirm.py ConfirmRule); a failure there means the
            # rule abstains on every request — dead, not degraded
            _re.compile(arg.encode("utf-8", "surrogateescape"))
        except _re.error as e:
            findings.append(Finding(
                check="regex.confirm-unparsable", severity="error",
                rule_id=rid, subject=where,
                message="pattern does not compile in the confirm "
                        "engine (%s): %s abstains on every request — "
                        "the rule is silently dead" % (e, where)))
            continue
        if "regex_unsupported" in confirm:
            findings.append(Finding(
                check="regex.degraded-construct", severity="notice",
                rule_id=rid, subject=where,
                message="pattern uses a construct the factor compiler "
                        "cannot model (%s): %s runs confirm-only on "
                        "every applicable request"
                        % (confirm["regex_unsupported"], where)))
            # hazards are still analyzable only if the AST parses; it
            # does not for unsupported constructs — Python re evaluates
            # them, so note the blind spot and move on
            continue
        try:
            ast = parse_regex(arg, ignorecase=bool(confirm.get("fold")))
        except RegexUnsupported as e:
            findings.append(Finding(
                check="regex.degraded-construct", severity="notice",
                rule_id=rid, subject=where,
                message="pattern unparsable at audit time (%s); ReDoS "
                        "lint blind for %s" % (e, where)))
            continue
        for check, detail in dict.fromkeys(hazards_for_pattern(ast)):
            sev = {"regex.redos-nested-quantifier": "error",
                   "regex.redos-overlapping-alternation": "warning",
                   "regex.redos-adjacent-quantifiers": "notice"}[check]
            findings.append(Finding(
                check=check, severity=sev, rule_id=rid, subject=where,
                message="%s — confirm-lane backtracking hazard in %s"
                        % (detail, where)))
    return findings
