"""TX / setvar dataflow checks (check class 3).

Collects every TX write (``setvar:tx.NAME=...``), read (``TX:NAME``
target, ``%{tx.NAME}`` macro) and engine-consumed name across the tree
in load order, then reports:

  tx.read-before-write   (warning) a TX variable is read but never
                                   written anywhere (or only written
                                   later in load order) — stale-name
                                   reads abstain at best, compare
                                   against garbage at worst
  tx.dead-write          (notice)  a setvar target nothing ever reads
  tx.threshold-unreachable (error) the compiled blocking threshold
                                   exceeds the sum of every rule's
                                   possible anomaly contribution — the
                                   949-style blocking rule can never fire
  tx.anomaly-never-evaluated (warning) rules contribute anomaly score
                                   but no threshold rule consumes it
  tx.conditional-setvar-skip (warning) a skipAfter condition reads a TX
                                   variable that a *conditional* SecRule
                                   writes: the parser abstains (keeps
                                   rules active) because the write is
                                   request-dependent — make it a
                                   SecAction if it is really static
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from ingress_plus_tpu.analysis.findings import Finding
from ingress_plus_tpu.analysis.scan import (
    FileScan,
    iter_load_order,
    static_tx_env,
)

#: names the COMPILER itself consumes from the static TX env
_ENGINE_READ = {
    "inbound_anomaly_score_threshold", "outbound_anomaly_score_threshold",
    "detection_paranoia_level", "paranoia_level",
    "blocking_paranoia_level", "executing_paranoia_level",
    "critical_anomaly_score", "error_anomaly_score",
    "warning_anomaly_score", "notice_anomaly_score",
}
#: names with compiler-provided defaults (readable without any write)
_DEFAULTED = {
    "critical_anomaly_score", "error_anomaly_score",
    "warning_anomaly_score", "notice_anomaly_score",
}
#: the anomaly accumulator family is consumed by the compiled score
#: matmul + threshold resolution even when no directive reads it back
_ANOMALY = re.compile(r"(^|_)anomaly_score(_pl\d)?$")

_MACRO = re.compile(r"%\{tx\.([a-zA-Z0-9_.]+)\}", re.IGNORECASE)


def _tx_reads(d) -> List[Tuple[str, bool]]:
    """``(name_or_pattern, is_regex)`` TX reads of this directive:
    TX: targets (incl. the CRS ``TX:/^prefix_/`` regex-selector shape —
    review finding: treating those as literal names produced false
    read-before-write AND dead-write findings) + %{tx.*} macros in the
    operator argument and every action value."""
    reads: List[Tuple[str, bool]] = []
    if d.kind == "SecRule":
        for t in d.targets_txt.split("|"):
            t = t.strip().lstrip("&!")
            if t.upper().startswith("TX:"):
                sel = t.split(":", 1)[1].strip()
                if sel.startswith("/"):
                    reads.append((sel.strip("/").lower(), True))
                else:
                    reads.append((sel.lower(), False))
        _, _, arg = d.operator()
        reads.extend((m.lower(), False) for m in _MACRO.findall(arg))
    for vals in d.actions.values():
        for v in vals:
            reads.extend((m.lower(), False)
                         for m in _MACRO.findall(v or ""))
    return reads


def _tx_writes(d) -> List[str]:
    """TX names this directive writes (delete form included — a delete
    is a write for dataflow purposes), via the parser's shared setvar
    normalization."""
    from ingress_plus_tpu.compiler.seclang import _classify_setvar
    out = []
    for sv in d.setvars:
        key, kind, _value = _classify_setvar(sv)
        if kind is not None:
            out.append(key)
    return out


def check_tx_dataflow(scans: List[FileScan], anomaly_threshold=None,
                      max_anomaly_sum: int = 0,
                      explicit_anomaly: bool = False) -> List[Finding]:
    findings: List[Finding] = []

    writes: Dict[str, Tuple[int, str, int]] = {}  # first write wins
    reads: List[Tuple[str, int, object]] = []
    skip_cond_reads: List[Tuple[str, int, object]] = []
    order_of: Dict[int, int] = {}     # id(directive) → load order
    any_capture = False
    order = 0
    # the include-following iterator, NOT a flat per-file walk: load
    # order interleaves at the Include point (review finding: flat
    # order inverted read/write positions across Includes)
    for _fs, d in iter_load_order(scans):
        if d.kind not in ("SecRule", "SecAction"):
            continue
        order += 1
        order_of[id(d)] = order
        if "capture" in d.actions:
            any_capture = True
        for name in _tx_writes(d):
            if name not in writes:
                writes[name] = (order, d.file, d.line)
        for name, is_regex in _tx_reads(d):
            reads.append((name, is_regex, order, d))
        if d.skip_marker is not None and d.kind == "SecRule":
            for t in d.targets_txt.split("|"):
                t = t.strip().lstrip("&!")
                if t.upper().startswith("TX:"):
                    skip_cond_reads.append(
                        (t.split(":", 1)[1].strip().lower(), order, d))

    # request-dependent writes only: a SecRule whose condition resolves
    # statically true FOLDS like a SecAction (the parser's semantics —
    # review finding: flagging those produced a factually wrong
    # "rules stay active" warning on trees the parser statically skips)
    _, conditional_writes = static_tx_env(scans)

    reported: set = set()
    for name, is_regex, order_r, d in reads:
        if name in reported:
            continue
        if is_regex:
            # regex selector: satisfied by ANY matching write; no
            # positional check (the selector deliberately ranges over
            # names written all over the tree)
            try:
                pat = re.compile(name)
            except re.error:
                continue
            if not any(pat.search(w) for w in writes):
                reported.add(name)
                findings.append(Finding(
                    check="tx.read-before-write", severity="warning",
                    rule_id=d.rule_id, subject="tx:/%s/" % name,
                    file=d.file, line=d.line,
                    message="TX selector /%s/ matches no variable ever "
                            "written in the tree (stale or typo'd "
                            "pattern?)" % name))
            continue
        if name.isdigit():
            if not any_capture:
                reported.add(name)
                findings.append(Finding(
                    check="tx.read-before-write", severity="warning",
                    rule_id=d.rule_id, subject="tx.%s" % name,
                    file=d.file, line=d.line,
                    message="capture variable tx.%s is read but no rule "
                            "in the tree uses the capture action" % name))
            continue
        if name in _DEFAULTED:
            continue
        w = writes.get(name)
        if w is None:
            reported.add(name)
            findings.append(Finding(
                check="tx.read-before-write", severity="warning",
                rule_id=d.rule_id, subject="tx.%s" % name,
                file=d.file, line=d.line,
                message="tx.%s is read but never written anywhere in "
                        "the tree (stale or typo'd name?)" % name))
        elif w[0] > order_r:
            reported.add(name)
            findings.append(Finding(
                check="tx.read-before-write", severity="warning",
                rule_id=d.rule_id, subject="tx.%s" % name,
                file=d.file, line=d.line,
                message="tx.%s is read before its first write (%s:%d "
                        "in load order)" % (name, w[1], w[2])))

    read_names = {name for name, is_regex, _, _ in reads if not is_regex}
    read_patterns = []
    for name, is_regex, _, _ in reads:
        if is_regex:
            try:
                read_patterns.append(re.compile(name))
            except re.error:
                pass
    for name, (order_w, file, line) in sorted(writes.items()):
        if name in read_names or name in _ENGINE_READ or \
                _ANOMALY.search(name) or \
                any(p.search(name) for p in read_patterns):
            continue
        findings.append(Finding(
            check="tx.dead-write", severity="notice",
            subject="tx.%s" % name, file=file, line=line,
            message="tx.%s is written but nothing (directive or engine) "
                    "ever reads it" % name))

    for name, order_r, d in skip_cond_reads:
        w = conditional_writes.get(name)
        # only a conditional write the parser has already seen at the
        # read point makes the condition abstain; a later write leaves
        # the static resolution intact (review finding: flagging those
        # claimed "rules stay active" for tiers the parser skips)
        if w is not None and order_of.get(id(w), order_r + 1) < order_r:
            findings.append(Finding(
                check="tx.conditional-setvar-skip", severity="warning",
                rule_id=d.rule_id, subject="tx.%s" % name,
                file=d.file, line=d.line,
                message="skipAfter condition reads tx.%s, which the "
                        "conditional SecRule %s writes: the write is "
                        "request-dependent, so the jump never resolves "
                        "statically (rules stay active); use SecAction "
                        "for static configuration" % (name,
                                                      w.rule_id or "?")))

    scored = max_anomaly_sum
    if anomaly_threshold is not None and scored and \
            anomaly_threshold > scored:
        findings.append(Finding(
            check="tx.threshold-unreachable", severity="error",
            subject="anomaly_threshold",
            message="blocking threshold %d exceeds the sum of every "
                    "rule's possible anomaly contribution (%d): anomaly "
                    "blocking can never fire"
                    % (anomaly_threshold, scored)))
    # only trees that OPT INTO anomaly mode (explicit setvar
    # increments) are expected to carry a 949-style threshold rule —
    # severity-fallback scores exist on every rule and the engine has a
    # default threshold, so warning on their absence alone was a false
    # positive on every plain block-action tree
    if anomaly_threshold is None and explicit_anomaly:
        findings.append(Finding(
            check="tx.anomaly-never-evaluated", severity="warning",
            subject="anomaly_threshold",
            message="rules carry explicit anomaly-score setvar "
                    "increments but the tree has no 949-style "
                    "threshold rule: the engine falls back to its "
                    "default threshold instead of the CRS-configured "
                    "one"))
    return findings
