"""rulecheck — static analyzer for compiled rulesets.

Runs five check classes over the parsed SecLang tree, the regex ASTs
and the compiled sigpack (see docs/ANALYSIS.md for the full catalog):

  1. prefilter-soundness audit   (analysis/prefilter_audit.py)
  2. control-flow reachability   (analysis/reach.py)
  3. TX / setvar dataflow        (analysis/txflow.py)
  4. regex hazards / ReDoS       (analysis/redos.py)
  5. transform-lane consistency  (analysis/lanecheck.py)

Entry points: ``run_rulecheck()`` (library), ``python -m
ingress_plus_tpu.analysis`` (CLI, text/JSON/SARIF), ``dbg rulecheck``
(control/dbg.py), ``tools/lint.py --ci`` (the CI gate: zero unsuppressed
error-severity findings on the bundled CRS tree).

The package also hosts ``concheck`` — the concurrency static analyzer
over the serve-plane SOURCES (analysis/concheck.py + threadmap.py,
docs/ANALYSIS.md "Concurrency analysis"): ``run_concheck()``,
``python -m ingress_plus_tpu.analysis --conc``, ``dbg concheck``, and
its own ``concheck`` gate in ``tools/lint.py --ci`` — and
``evadecheck``, the evasion-closure analyzer (analysis/evadecheck.py,
docs/ANALYSIS.md "Evasion analysis"): ``run_evadecheck()``,
``python -m ingress_plus_tpu.analysis --evade``, ``dbg evadecheck``,
and the ``evasiongate`` gate (static findings + the utils/evasion.py
mutation-harness retention floor).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import numpy as np

from ingress_plus_tpu.analysis.findings import (  # noqa: F401 (public API)
    Baseline,
    BaselineError,
    Finding,
    Report,
    SEVERITIES,
)
from ingress_plus_tpu.analysis.concheck import (  # noqa: F401 (public API)
    run_concheck,
)
from ingress_plus_tpu.analysis.evadecheck import (  # noqa: F401 (public API)
    run_evadecheck,
)
from ingress_plus_tpu.analysis.lanecheck import check_lanes
from ingress_plus_tpu.analysis.prefilter_audit import audit_prefilter
from ingress_plus_tpu.analysis.reach import check_reachability
from ingress_plus_tpu.analysis.redos import check_regex_hazards
from ingress_plus_tpu.analysis.scan import rule_positions, scan_tree
from ingress_plus_tpu.analysis.txflow import check_tx_dataflow

#: the bundled CRS-shaped tree — the default audit subject and the CI
#: gate's target; its accepted-findings baseline ships next to it as
#: rulecheck-baseline.json (resolved by run_rulecheck's "auto" mode)
BUNDLED_RULES = Path(__file__).resolve().parent.parent / "rules" / "crs"


def run_rulecheck(rules_path: Optional[str | Path] = None,
                  baseline_path: Optional[str | Path] = "auto",
                  compiled=None) -> Report:
    """Run every analyzer over a rules tree.

    ``baseline_path="auto"`` picks ``<rules>/rulecheck-baseline.json``
    when present; ``None`` disables suppression.  ``compiled`` may pass
    a pre-built CompiledRuleset to skip recompilation (dbg paths)."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import load_seclang_dir

    rules_path = Path(rules_path) if rules_path is not None else \
        BUNDLED_RULES
    if not rules_path.exists():
        raise OSError("rules tree %s does not exist — an empty audit "
                      "would report a misleading clean pass" % rules_path)
    if compiled is None:
        compiled = compile_ruleset(load_seclang_dir(rules_path))

    scans = scan_tree(rules_path)
    findings = []
    findings += audit_prefilter(compiled.rules, compiled.tables)
    findings += check_reachability(scans)
    def _has_anomaly_setvars() -> bool:
        for m in compiled.rules:
            link = m.rule
            while link is not None:
                if any("anomaly_score" in sv.partition("=")[0].lower()
                       for sv in link.setvars):
                    return True
                link = link.chain
        return False

    findings += check_tx_dataflow(
        scans,
        anomaly_threshold=compiled.anomaly_threshold,
        max_anomaly_sum=int(np.sum(compiled.rule_score)),
        explicit_anomaly=_has_anomaly_setvars())
    findings += check_regex_hazards(compiled.rules)
    findings += check_lanes(compiled.rules)

    # attach source positions to findings that only know their rule id,
    # then relativize paths: reports and SARIF must not embed
    # machine-specific absolute paths (review finding: GitHub code
    # scanning cannot map absolute URIs, and checked-in reports diffed
    # per checkout location)
    pos = rule_positions(scans)
    rel_bases = [Path.cwd(),
                 rules_path if rules_path.is_dir() else rules_path.parent]

    def _rel(p: str) -> str:
        for base in rel_bases:
            try:
                return str(Path(p).resolve().relative_to(base.resolve()))
            except ValueError:
                continue
        return p

    for f in findings:
        if not f.file and f.rule_id in pos:
            f.file, f.line = pos[f.rule_id]
        if f.file:
            f.file = _rel(f.file)

    resolved_baseline = ""
    if baseline_path == "auto":
        # an entry-config FILE keeps its baseline next to it (review
        # finding: <file>/rulecheck-baseline.json is never a file, so
        # accepted findings silently re-gated)
        base_dir = rules_path.parent if rules_path.is_file() else rules_path
        cand = base_dir / "rulecheck-baseline.json"
        baseline_path = cand if cand.is_file() else None
    if baseline_path is not None:
        bl = Baseline.load(baseline_path)
        bl.apply(findings)
        resolved_baseline = bl.path

    return Report(
        findings=findings,
        rules_path=_rel(str(rules_path)),
        baseline_path=_rel(resolved_baseline) if resolved_baseline else "",
        n_rules=compiled.n_rules,
        pack_version=compiled.version,
        reduction=getattr(compiled, "reduction", None),
    )
