"""concheck — concurrency static analysis of the serve plane
(docs/ANALYSIS.md "Concurrency analysis").

rulecheck (PR 2) is the static twin of a measured property of the
RULESET; concheck is the static twin of a measured property of the
SERVE PLANE: thread safety.  Four check classes over the AST of the
serve-plane sources (analysis/threadmap.py SERVE_PLANE_FILES):

1. **Thread-boundary map** — the declared registry of thread entry
   points (threadmap.THREAD_ROOTS) + conservative reachability, so
   every function knows which threads can execute it.  A
   ``threading.Thread(target=...)`` whose target is not a registered
   entry is itself a finding (``conc.unregistered-thread``).
2. **Guarded-by inference + unguarded mutations** — infer which
   attributes are only ever touched under a lock (``with self._lock``
   regions, propagated interprocedurally through always-locked call
   sites), then flag attributes MUTATED from two or more thread roots
   (a concurrent root — N lane workers, arbitrary submit callers —
   counts alone) where at least one mutation site is unguarded: the
   exact PR 10 bug class.  Container mutations (dict/set/list resize —
   the "changed size during iteration" crash class) and
   mixed-discipline attributes (guarded in one method, bare in
   another) are errors; plain lost-update counters are warnings.
3. **Lock-order graph** — nested-acquisition edges across all modules
   (syntactic nesting + locks inherited through always-locked call
   sites); any cycle is ``conc.lock-order-cycle`` (deadlock risk).
4. **Thread-lifecycle lint** — non-daemon worker threads,
   ``join()`` without a timeout on a worker/warmer thread, silent
   except-pass handlers inside thread entry loops, and blocking
   queue-consumer loops without the abandon/replace None-sentinel
   pattern (serve/lanes.py LaneWorker is the reference discipline).

Intentional lock-free fast paths are annotatable inline::

    self.hits += 1   # concheck: ok telemetry-grade; GIL-atomic int add

An annotated site suppresses its finding with the reason carried into
the report (like a baseline entry, but next to the code it justifies).
The checked-in baseline (analysis/concheck-baseline.json) covers the
class-level patterns a per-line annotation cannot express — e.g.
single-owner handoff objects whose happens-before edge is a
``LanePending.wait``.

False-positive posture: the call graph and receiver typing are
conservative (over-approximate reachability, under-approximate guard
inference), so concheck over-reports rather than under-reports; the
baseline + annotations are the pressure valve, and both require a
reason.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ingress_plus_tpu.analysis.findings import Baseline, Finding, Report
from ingress_plus_tpu.analysis.threadmap import (
    _AMBIENT_METHODS,
    _mro_method,
    FunctionInfo,
    ModuleMap,
    ThreadMap,
    _expr_chain,
    build_thread_map,
    chain_type,
    resolve_callees,
    resolve_local_types,
)

#: container-method names that mutate the receiver
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "add", "insert", "remove",
    "discard", "pop", "popleft", "clear", "update", "setdefault",
    "rotate", "sort", "reverse", "offer",
})

#: builtins whose single-argument call is an atomic C-level snapshot of
#: the argument under the GIL — the documented safe-read idiom
#: (``dict(live)`` / ``list(live)``), never an iteration race
_SNAPSHOT_FNS = frozenset({"list", "dict", "tuple", "set", "frozenset",
                           "len", "sorted", "sum", "min", "max"})

_ANNOT_RE = re.compile(r"#\s*concheck:\s*ok\b[:\s]*(.*)")

LockId = Tuple[str, str]          # (class name or "?", attr name)


@dataclass
class Access:
    owner: str                    # class name
    attr: str
    kind: str                     # read|iterate|escape|assign|augassign|container
    func: str                     # function key
    file: str
    line: int
    locks: FrozenSet[LockId]      # syntactic locks held at the site


@dataclass
class _FuncScan:
    accesses: List[Access] = field(default_factory=list)
    #: lock acquisitions: (lock_id, syntactic locks held, line)
    acquisitions: List[Tuple[LockId, FrozenSet[LockId], int]] = \
        field(default_factory=list)
    #: callsites: (callee key, syntactic locks held)
    callsites: List[Tuple[str, FrozenSet[LockId]]] = \
        field(default_factory=list)
    #: thread ctor sites: (target key or None, daemon, line)
    thread_ctors: List[Tuple[Optional[str], bool, int]] = \
        field(default_factory=list)
    #: join() calls with no timeout on thread-typed receivers: lines
    naked_joins: List[int] = field(default_factory=list)


class _Scanner:
    """One function's AST walk: accesses with lock context, lock
    acquisitions, call sites, and the lifecycle raw material."""

    def __init__(self, mm: ModuleMap, fi: FunctionInfo):
        self.mm = mm
        self.fi = fi
        self.local_types = resolve_local_types(mm, fi)
        self.out = _FuncScan()
        self.callees_cache = resolve_callees(mm, fi, self.local_types)
        self._reads: Set[Tuple[str, str]] = set()
        self._writes: Set[Tuple[str, str]] = set()
        # locals holding objects CONSTRUCTED in this function: they are
        # thread-local until published — accesses through them are not
        # shared-state accesses (fresh-object exemption, local half)
        self._fresh: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                chain = _expr_chain(node.value.func)
                if chain is not None and len(chain) == 1 \
                        and chain[0] in mm.classes:
                    self._fresh.add(node.targets[0].id)

    # ------------------------------------------------------ resolution

    def _owner_attr(self, node) -> Optional[Tuple[str, str]]:
        """Resolve an Attribute node to (owner class, attr) when the
        receiver's class is one of ours."""
        if not isinstance(node, ast.Attribute):
            return None
        chain = _expr_chain(node)
        if chain is None or len(chain) < 2:
            return None
        recv, attr = chain[:-1], chain[-1]
        if attr.startswith("__"):
            return None
        if recv[0] in self._fresh:
            return None
        t = chain_type(self.mm, self.fi, recv, self.local_types)
        if t is not None and t[0] == "cls" and t[1] in self.mm.classes:
            return (t[1], attr)
        return None

    def _lock_id(self, expr) -> Optional[LockId]:
        chain = _expr_chain(expr)
        if chain is None or len(chain) < 2:
            return None
        recv, attr = chain[:-1], chain[-1]
        t = chain_type(self.mm, self.fi, recv, self.local_types)
        if t is not None and t[0] == "cls" and t[1] in self.mm.classes:
            at = self.mm.classes[t[1]].attr_types.get(attr)
            if at is not None:
                if at[0] == "lock":
                    return (t[1], attr)
                if at[0] == "cond":
                    return (t[1], at[1])
        if "lock" in attr.lower() or attr in ("_not_empty", "_not_full"):
            owner = t[1] if (t is not None and t[0] == "cls") else "?"
            return (owner, attr)
        return None

    def _container_typed(self, owner: str, attr: str) -> bool:
        at = self.mm.classes[owner].attr_types.get(attr)
        return at is not None and at[0] in ("dict", "list", "set",
                                            "listof")

    # ----------------------------------------------------------- walk

    def scan(self) -> _FuncScan:
        body = self.fi.node.body
        for stmt in body:
            self._visit(stmt, frozenset())
        # RMW promotion: a plain assign to an attr this function also
        # READS is a read-modify-write (the Ewma.update shape), not an
        # atomic rebind
        for a in self.out.accesses:
            if a.kind == "assign" and (a.owner, a.attr) in self._reads:
                a.kind = "augassign"
        return self.out

    def _record(self, owner_attr, kind: str, node,
                locks: FrozenSet[LockId]) -> None:
        owner, attr = owner_attr
        self.out.accesses.append(Access(
            owner=owner, attr=attr, kind=kind, func=self.fi.key,
            file=self.fi.file, line=getattr(node, "lineno", 0),
            locks=locks))
        if kind in ("assign", "augassign", "container"):
            self._writes.add((owner, attr))
        else:
            self._reads.add((owner, attr))

    def _visit(self, node, locks: FrozenSet[LockId]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(locks)
            for item in node.items:
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    self.out.acquisitions.append(
                        (lid, locks, node.lineno))
                    inner.add(lid)
                else:
                    self._visit_expr(item.context_expr, locks)
            inner_f = frozenset(inner)
            for stmt in node.body:
                self._visit(stmt, inner_f)
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                self._visit_target(tgt, locks)
            self._visit_expr(node.value, locks)
            return
        if isinstance(node, ast.AugAssign):
            self._visit_target(node.target, locks, aug=True)
            self._visit_expr(node.value, locks)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    oa = self._owner_attr(tgt.value)
                    if oa is not None:
                        self._record(oa, "container", tgt, locks)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                oa = self._owner_attr(node.value)
                if oa is not None and self._container_typed(*oa):
                    self._record(oa, "escape", node, locks)
                else:
                    self._visit_expr(node.value, locks)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            oa = self._owner_attr(node.iter)
            if oa is not None:
                self._record(oa, "iterate", node.iter, locks)
            else:
                self._visit_expr(node.iter, locks)
            for stmt in node.body + node.orelse:
                self._visit(stmt, locks)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested defs/lambdas merge into the enclosing function,
            # WITH the enclosing lock context at their definition site
            # (closures handed across threads are covered by the
            # declared registry, not by pretending they are calls)
            body = node.body if isinstance(node.body, list) \
                else [ast.Expr(value=node.body)]
            for stmt in body:
                self._visit(stmt, locks)
            return
        if isinstance(node, ast.Expr):
            self._visit_expr(node.value, locks)
            return
        # generic statement: visit children as statements/expressions
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, locks)
            else:
                self._visit(child, locks)

    def _visit_target(self, tgt, locks: FrozenSet[LockId],
                      aug: bool = False) -> None:
        if isinstance(tgt, ast.Attribute):
            oa = self._owner_attr(tgt)
            if oa is not None:
                self._record(oa, "augassign" if aug else "assign",
                             tgt, locks)
            return
        if isinstance(tgt, ast.Subscript):
            oa = self._owner_attr(tgt.value)
            if oa is not None:
                self._record(oa, "container", tgt, locks)
            else:
                self._visit_expr(tgt.value, locks)
            self._visit_expr(tgt.slice, locks)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._visit_target(el, locks, aug=aug)

    def _visit_expr(self, node, locks: FrozenSet[LockId]) -> None:
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, locks)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                oa = self._owner_attr(gen.iter)
                if oa is not None:
                    self._record(oa, "iterate", gen.iter, locks)
                else:
                    self._visit_expr(gen.iter, locks)
                for cond in gen.ifs:
                    self._visit_expr(cond, locks)
            if isinstance(node, ast.DictComp):
                self._visit_expr(node.key, locks)
                self._visit_expr(node.value, locks)
            else:
                self._visit_expr(node.elt, locks)
            return
        if isinstance(node, ast.Attribute):
            oa = self._owner_attr(node)
            if oa is not None:
                self._record(oa, "read", node, locks)
            else:
                self._visit_expr(node.value, locks)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node, locks)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, locks)

    def _visit_call(self, node: ast.Call, locks: FrozenSet[LockId]) -> None:
        f = node.func
        # snapshot builtins: dict(x)/list(x)... is an atomic copy
        if isinstance(f, ast.Name) and f.id in _SNAPSHOT_FNS \
                and len(node.args) == 1:
            oa = self._owner_attr(node.args[0])
            if oa is not None:
                self._record(oa, "read", node.args[0], locks)
            else:
                self._visit_expr(node.args[0], locks)
            return
        if isinstance(f, ast.Attribute):
            # mutating container method on a RAW-container attribute —
            # a method call on a class-typed attr (SlowRing.offer,
            # Ewma.update) is that class's business, analyzed there
            oa = self._owner_attr(f.value)
            if oa is not None and f.attr in _MUTATORS \
                    and self._container_typed(*oa):
                self._record(oa, "container", node, locks)
            elif oa is not None:
                self._record(oa, "read", f.value, locks)
            else:
                self._visit_expr(f.value, locks)
            # thread lifecycle raw material
            if f.attr == "join":
                self._check_join(node, f)
            chain = _expr_chain(f)
            if chain is not None and len(chain) >= 2 \
                    and chain[-2:] == ("threading", "Thread"):
                self._record_thread_ctor(node)
        # record resolved callsites for guard propagation — EXCEPT calls
        # on freshly constructed locals: a method running on an object
        # this function just built is not a shared-state entry, and its
        # bare lock context must not poison the callee's inferred guard
        fresh_recv = False
        if isinstance(f, ast.Attribute):
            rchain = _expr_chain(f.value)
            fresh_recv = bool(rchain) and rchain[0] in self._fresh
        if not fresh_recv:
            for callee in self._resolve_one_call(node):
                self.out.callsites.append((callee, locks))
        for arg in node.args:
            self._visit_expr(arg, locks)
        for kw in node.keywords:
            self._visit_expr(kw.value, locks)

    def _resolve_one_call(self, node: ast.Call) -> Set[str]:
        mm, fi = self.mm, self.fi
        f = node.func
        out: Set[str] = set()
        if isinstance(f, ast.Name):
            if f.id in mm.classes:
                k = mm.classes[f.id].methods.get("__init__")
                if k:
                    out.add(k)
            out.update(mm.func_by_name.get(f.id, ()))
        elif isinstance(f, ast.Attribute):
            chain = _expr_chain(f.value)
            meth = f.attr
            t = chain_type(mm, fi, chain, self.local_types) \
                if chain else None
            if t is not None and t[0] == "cls" and t[1] in mm.classes:
                k = _mro_method(mm, t[1], meth)
                if k:
                    out.add(k)
                return out
            if chain == ("self",) and fi.cls is not None:
                k = _mro_method(mm, fi.cls, meth)
                if k:
                    out.add(k)
                return out
            if meth not in _AMBIENT_METHODS:
                out.update(mm.method_index.get(meth, ()))
        return out

    def _record_thread_ctor(self, node: ast.Call) -> None:
        daemon = False
        target_key: Optional[str] = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            if kw.arg == "target":
                chain = _expr_chain(kw.value)
                if chain is not None:
                    if chain[0] == "self" and len(chain) == 2 \
                            and self.fi.cls is not None:
                        target_key = _mro_method(self.mm, self.fi.cls,
                                                 chain[1])
                    elif len(chain) == 1:
                        keys = self.mm.func_by_name.get(chain[0], ())
                        target_key = keys[0] if keys else None
        self.out.thread_ctors.append((target_key, daemon, node.lineno))

    def _check_join(self, node: ast.Call, f: ast.Attribute) -> None:
        has_timeout = bool(node.args) or any(
            kw.arg == "timeout" for kw in node.keywords)
        if has_timeout:
            return
        chain = _expr_chain(f.value)
        t = chain_type(self.mm, self.fi, chain, self.local_types) \
            if chain else None
        if t is not None and t[0] in ("thread", "listof_thread"):
            self.out.naked_joins.append(node.lineno)


# ------------------------------------------------------------ analysis


def _annotations(mm: ModuleMap) -> Dict[Tuple[str, int], str]:
    """``# concheck: ok <reason>`` inline suppressions by (file, line)."""
    out: Dict[Tuple[str, int], str] = {}
    for rel, lines in mm.sources.items():
        for i, line in enumerate(lines, start=1):
            m = _ANNOT_RE.search(line)
            if m:
                out[(rel, i)] = m.group(1).strip() or "annotated ok"
    return out


def _propagate_guards(mm: ModuleMap, scans: Dict[str, _FuncScan],
                      root_entries: Set[str],
                      reachable: Optional[Set[str]] = None
                      ) -> Dict[str, FrozenSet[LockId]]:
    """Locks every call path provably holds when entering each function
    (intersection over call sites; thread entries start bare).  Under-
    approximates on purpose: an unknown call site contributes the empty
    set only if it exists — functions nobody calls inherit nothing.
    Call sites inside thread-UNREACHABLE functions are ignored: a
    library-only caller cannot race anything, so its bare context must
    not veto the serve plane's consistent locking."""
    callers: Dict[str, List[Tuple[str, FrozenSet[LockId]]]] = {}
    for key, scan in scans.items():
        if reachable is not None and key not in reachable:
            continue
        for callee, locks in scan.callsites:
            callers.setdefault(callee, []).append((key, locks))
    inherited: Dict[str, FrozenSet[LockId]] = {
        k: frozenset() for k in scans}
    for _ in range(4):                      # small fixpoint
        changed = False
        for key in scans:
            if key in root_entries:
                continue
            sites = callers.get(key)
            if not sites:
                continue
            acc: Optional[Set[LockId]] = None
            for caller, locks in sites:
                eff = set(locks) | set(inherited.get(caller,
                                                     frozenset()))
                acc = eff if acc is None else (acc & eff)
            new = frozenset(acc or ())
            if new != inherited[key]:
                inherited[key] = new
                changed = True
        if not changed:
            break
    return inherited


def _fmt_lock(lid: LockId) -> str:
    return "%s.%s" % lid


def _find_cycles(edges: Set[Tuple[LockId, LockId]]) -> List[List[LockId]]:
    graph: Dict[LockId, Set[LockId]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[LockId]] = []
    seen_cycles: Set[Tuple[LockId, ...]] = set()
    state: Dict[LockId, int] = {}

    def dfs(n: LockId, path: List[LockId]) -> None:
        state[n] = 1
        path.append(n)
        for m in sorted(graph.get(n, ())):
            if state.get(m, 0) == 1:
                cyc = path[path.index(m):] + [m]
                key = tuple(sorted(cyc[:-1]))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
            elif state.get(m, 0) == 0:
                dfs(m, path)
        path.pop()
        state[n] = 2

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            dfs(n, [])
    return cycles


@dataclass
class ConcScan:
    """The analyzer's intermediate product (exposed for tests and the
    threadmap doc generator)."""

    tmap: ThreadMap
    accesses: List[Access]
    inherited: Dict[str, FrozenSet[LockId]]
    lock_edges: Set[Tuple[LockId, LockId]]
    scans: Dict[str, _FuncScan]


def scan_concurrency(root: Optional[Path] = None,
                     tmap: Optional[ThreadMap] = None) -> ConcScan:
    if tmap is None:
        tmap = build_thread_map(root)
    mm = tmap.mm
    scans: Dict[str, _FuncScan] = {}
    for key, fi in mm.functions.items():
        scans[key] = _Scanner(mm, fi).scan()
    root_entries = {e for r in tmap.roots for e in r.entries}
    inherited = _propagate_guards(mm, scans, root_entries,
                                  reachable=set(tmap.reach))
    # effective lock context = syntactic + inherited; build the final
    # access list and the lock-order edge set
    accesses: List[Access] = []
    edges: Set[Tuple[LockId, LockId]] = set()
    for key, scan in scans.items():
        if _is_ctor(key):
            # an object under construction is thread-local: nothing a
            # constructor touches on self is shared yet
            continue
        inh = inherited.get(key, frozenset())
        for a in scan.accesses:
            if inh:
                a.locks = a.locks | inh
            accesses.append(a)
        for lid, held, _line in scan.acquisitions:
            for h in set(held) | set(inh):
                if h != lid:
                    edges.add((h, lid))
    return ConcScan(tmap=tmap, accesses=accesses, inherited=inherited,
                    lock_edges=edges, scans=scans)


def _is_ctor(key: str) -> bool:
    name = key.rsplit(".", 1)[-1]
    return name in ("__init__", "__post_init__", "__new__")


def check_concurrency(cs: ConcScan) -> List[Finding]:
    """Produce the findings from one scan (annotations applied by the
    caller via ``apply_annotations``)."""
    tmap = cs.tmap
    findings: List[Finding] = []

    # ---- 2. guarded-by inference + unguarded mutations + escapes
    by_attr: Dict[Tuple[str, str], List[Access]] = {}
    for a in cs.accesses:
        by_attr.setdefault((a.owner, a.attr), []).append(a)
    for (owner, attr), accs in sorted(by_attr.items()):
        # only mutations in thread-REACHABLE functions count: a
        # library-only mutator cannot race anything in the serve plane
        muts = [a for a in accs
                if a.kind in ("assign", "augassign", "container")
                and not _is_ctor(a.func)
                and tmap.roots_of(a.func)]
        if muts:
            mut_roots: Set[str] = set()
            for a in muts:
                mut_roots |= tmap.roots_of(a.func)
            unguarded = [a for a in muts if not a.locks]
            guarded = [a for a in muts if a.locks]
            if unguarded and tmap.is_concurrent(mut_roots):
                container = any(a.kind == "container" for a in unguarded)
                mixed = bool(guarded)
                rmw = any(a.kind == "augassign" for a in unguarded)
                if container or mixed:
                    sev = "error"
                elif rmw:
                    sev = "warning"
                else:
                    sev = "notice"   # atomic rebind: torn-free under GIL
                site = unguarded[0]
                kinds = sorted({a.kind for a in unguarded})
                msg = ("%s.%s mutated without a lock (%s) from "
                       "thread roots {%s}; %d unguarded site(s)"
                       % (owner, attr, "/".join(kinds),
                          ",".join(sorted(mut_roots)), len(unguarded)))
                if mixed:
                    locks = sorted({_fmt_lock(lid) for a in guarded
                                    for lid in a.locks})
                    msg += ("; other sites guard it with %s — mixed "
                            "discipline" % ", ".join(locks))
                findings.append(Finding(
                    check="conc.unguarded-mutation", severity=sev,
                    message=msg, subject="%s.%s" % (owner, attr),
                    file=site.file, line=site.line))
        # live-view escapes: the attr is lock-guarded somewhere, still
        # MUTATED after construction, and a method returns/iterates it
        # bare — the quarantined_ids() class
        guard_locks = {lid for a in accs for lid in a.locks}
        if guard_locks and muts:
            for a in accs:
                if a.kind in ("escape", "iterate") and not a.locks:
                    roots = tmap.roots_of(a.func)
                    other_roots = {r for m in accs if m is not a
                                   for r in tmap.roots_of(m.func)}
                    if not roots or not (roots | other_roots):
                        continue
                    if not tmap.is_concurrent(roots | other_roots):
                        continue
                    verb = ("returns a live reference to"
                            if a.kind == "escape" else "iterates")
                    findings.append(Finding(
                        check="conc.live-view-escape", severity="error",
                        message="%s %s %s.%s, which is guarded by %s "
                                "elsewhere — a concurrent resize "
                                "races the consumer (snapshot under "
                                "the lock instead)"
                                % (a.func.split("::")[-1], verb, owner,
                                   attr,
                                   ", ".join(sorted(_fmt_lock(g)
                                                    for g in
                                                    guard_locks))),
                        subject="%s.%s" % (owner, attr),
                        file=a.file, line=a.line))

    # ---- 3. lock-order cycles
    for cyc in _find_cycles(cs.lock_edges):
        findings.append(Finding(
            check="conc.lock-order-cycle", severity="error",
            message="lock-order cycle: %s — two threads taking these "
                    "in opposite order deadlock"
                    % " -> ".join(_fmt_lock(l) for l in cyc),
            subject=" -> ".join(_fmt_lock(l) for l in cyc)))

    # ---- 1b/4. thread lifecycle
    root_entries = {e for r in tmap.roots for e in r.entries}
    for key, scan in cs.scans.items():
        fi = tmap.mm.functions[key]
        for target_key, daemon, line in scan.thread_ctors:
            if not daemon:
                findings.append(Finding(
                    check="conc.thread-no-daemon", severity="warning",
                    message="thread created without daemon=True in %s "
                            "— a wedged worker blocks interpreter exit"
                            % key.split("::")[-1],
                    subject=key.split("::")[-1], file=fi.file,
                    line=line))
            if target_key is not None and target_key not in root_entries:
                findings.append(Finding(
                    check="conc.unregistered-thread", severity="warning",
                    message="thread target %s is not a registered "
                            "entry in analysis/threadmap.THREAD_ROOTS "
                            "— the thread map no longer covers this "
                            "plane" % target_key,
                    subject=target_key, file=fi.file, line=line))
        for line in scan.naked_joins:
            findings.append(Finding(
                check="conc.join-no-timeout", severity="warning",
                message="join() without a timeout in %s — a wedged "
                        "worker (native code cannot be interrupted) "
                        "hangs the caller forever; bound the join and "
                        "abandon, like serve/lanes.py"
                        % key.split("::")[-1],
                subject=key.split("::")[-1], file=fi.file, line=line))
        if key in root_entries:
            findings += _lint_root_body(tmap.mm, fi)
    return findings


def _lint_root_body(mm: ModuleMap, fi: FunctionInfo) -> List[Finding]:
    """Lifecycle lint applied to thread entry functions only."""
    out: List[Finding] = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.ExceptHandler):
            # queue.Empty / TimeoutError idle-poll handlers are the
            # normal shape of a timeout-driven consumer loop, not a
            # swallowed death
            tname = None
            if node.type is not None:
                tchain = _expr_chain(node.type)
                tname = tchain[-1] if tchain else None
            if tname in ("Empty", "TimeoutError", "Full"):
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue))
                   for s in node.body):
                out.append(Finding(
                    check="conc.silent-worker-death", severity="warning",
                    message="except handler in thread entry %s "
                            "swallows the exception with no counter — "
                            "a dying worker is invisible "
                            "(ipt_thread_uncaught_total is the "
                            "pattern)" % fi.name,
                    subject="%s.%s" % (fi.cls or fi.file, fi.name),
                    file=fi.file, line=node.lineno))
    # blocking queue-consumer loop without the None-sentinel discipline
    has_sentinel = any(
        isinstance(n, ast.Compare)
        and any(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops)
        and any(isinstance(c, ast.Constant) and c.value is None
                for c in n.comparators)
        for n in ast.walk(fi.node))
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.While):
            continue
        for call in ast.walk(node):
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "get" \
                    and not call.args \
                    and not any(kw.arg == "timeout"
                                for kw in call.keywords):
                chain = _expr_chain(call.func.value)
                if chain and chain[0] == "self" and not has_sentinel:
                    out.append(Finding(
                        check="conc.no-abandon-sentinel",
                        severity="notice",
                        message="%s blocks on %s.get() with no "
                                "timeout and no None-sentinel exit — "
                                "the owner cannot abandon/replace a "
                                "wedged worker (LaneWorker discipline)"
                                % (fi.name, ".".join(chain)),
                        subject="%s.%s" % (fi.cls or fi.file, fi.name),
                        file=fi.file, line=call.lineno))
                    break
    return out


def apply_annotations(findings: List[Finding],
                      notes: Dict[Tuple[str, int], str],
                      cs: ConcScan) -> None:
    """Inline ``# concheck: ok`` suppression: a finding is suppressed
    when EVERY site that produced it is annotated (for attr findings:
    every unguarded mutating / escaping site of that subject).  An
    annotation counts when it sits on the access line or the line
    directly above it (the comment-above-the-statement style)."""
    def note_for(file: str, line: int) -> Optional[str]:
        return notes.get((file, line)) or notes.get((file, line - 1))

    site_index: Dict[str, List[Access]] = {}
    for a in cs.accesses:
        site_index.setdefault("%s.%s" % (a.owner, a.attr), []).append(a)
    for f in findings:
        if f.check == "conc.unguarded-mutation":
            sites = [a for a in site_index.get(f.subject, ())
                     if a.kind in ("assign", "augassign", "container")
                     and not a.locks and not _is_ctor(a.func)
                     and cs.tmap.roots_of(a.func)]
            keys = [(a.file, a.line) for a in sites]
        else:
            keys = [(f.file, f.line)]
        reasons = [note_for(*k) for k in keys]
        if reasons and all(r is not None for r in reasons):
            f.suppressed = True
            f.suppress_reason = reasons[0] + " (inline)"


#: default baseline shipped next to the analyzer
BASELINE_PATH = Path(__file__).resolve().parent / "concheck-baseline.json"


def run_concheck(root: Optional[Path] = None,
                 baseline_path="auto") -> Report:
    """Run the concurrency analyzer over the serve-plane sources.

    ``baseline_path="auto"`` uses analysis/concheck-baseline.json;
    ``None`` disables baseline suppression (inline annotations always
    apply — they live in the code they justify)."""
    cs = scan_concurrency(root)
    findings = check_concurrency(cs)
    apply_annotations(findings, _annotations(cs.tmap.mm), cs)
    resolved = ""
    if baseline_path == "auto":
        baseline_path = BASELINE_PATH if BASELINE_PATH.is_file() else None
    if baseline_path is not None:
        bl = Baseline.load(baseline_path)
        bl.apply([f for f in findings if not f.suppressed])
        resolved = bl.path
    n_locks = len({lid for e in cs.lock_edges for lid in e})
    return Report(
        findings=findings,
        rules_path="serve-plane",
        baseline_path=resolved,
        tool="concheck",
        meta={
            "files": sorted(cs.tmap.mm.files),
            "functions": len(cs.tmap.mm.functions),
            "thread_roots": cs.tmap.registry_json(),
            "lock_order_edges": sorted(
                "%s -> %s" % (_fmt_lock(a), _fmt_lock(b))
                for a, b in cs.lock_edges),
            "locks_in_order_graph": n_locks,
        })
