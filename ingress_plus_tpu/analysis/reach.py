"""Control-flow reachability checks (check class 2).

Re-derives the parser's skipAfter/SecMarker semantics over the raw
directive stream and reports what the parser survives silently:

  flow.dangling-marker   (error)   skipAfter names a marker that never
                                   appears later in the same file — the
                                   region silently extends to EOF and
                                   drops every same-phase rule after it
  flow.marker-splits-chain (error) a SecMarker lands between a chain
                                   leader and its continuation links —
                                   a jump to it would tear the chain
  flow.unreachable-paranoia (warning) a rule is inside a skip region
                                   whose condition holds at EVERY
                                   paranoia level 1–4: no deployment
                                   setting can ever activate it
  flow.bad-paranoia-tag  (warning) paranoia-level/N tag outside 1–4
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ingress_plus_tpu.analysis.findings import Finding
from ingress_plus_tpu.analysis.scan import FileScan, root_scans
from ingress_plus_tpu.compiler.seclang import (
    _fold_tx_assignments,
    _invalidate_tx_names,
    _static_skip_condition,
)

#: TX names the CRS family uses to carry the deployment paranoia level —
#: reachability is evaluated with each of these forced to PL 1..4
_PARANOIA_VARS = ("detection_paranoia_level", "paranoia_level",
                  "blocking_paranoia_level", "executing_paranoia_level")

_PL_TAG = re.compile(r"paranoia-level/(\d+)")


def _simulate_skipped(scans: List[FileScan], pl: int,
                      base_tx: Optional[Dict[str, str]]) -> Dict[int, object]:
    """Walk the whole tree IN LOAD ORDER under trial paranoia level
    ``pl``, mirroring the parser's skip semantics: conditions evaluate
    against the env at their load point (review finding: an end-state
    env both missed real skips and invented false ones), setvars
    fold/invalidate as they execute, and skip regions follow the
    Include topology — a region survives INTO an included file (whose
    markers can close it) and is cleared after each included file, like
    the parser's `_skip_state["skips"] = []` at Include boundaries.
    Returns skipped Directives keyed by id()."""
    env: Dict[str, str] = dict(base_tx or {})
    tainted: set = set()   # paranoia vars invalidated (request-dependent)

    def force() -> None:
        # the trial PL is the deployment knob being swept: it overrides
        # whatever the tree's own SecActions assign — unless a
        # request-dependent write made the variable unknowable
        for name in _PARANOIA_VARS:
            if name in tainted:
                env.pop(name, None)
            else:
                env[name] = str(pl)

    def invalidate(setvars) -> None:
        for name in _invalidate_tx_names(env, setvars):
            if name in _PARANOIA_VARS:
                tainted.add(name)

    force()
    skipped: Dict[int, object] = {}

    def walk(fs: FileScan, active: List[Tuple[str, str]]) -> None:
        in_chain = False
        skip_chain = False
        for idx, d in enumerate(fs.directives):
            if d.kind == "Include":
                for child in fs.includes.get(idx, []):
                    walk(child, active)
                    del active[:]   # parser clears after each include
                continue
            if d.kind == "SecMarker":
                name = d.tokens[1].strip().strip("'\"") \
                    if len(d.tokens) > 1 else ""
                active[:] = [r for r in active if r[0] != name]
                continue
            if d.kind not in ("SecRule", "SecAction"):
                continue
            is_link = False
            if d.kind == "SecRule":
                is_link = in_chain
                in_chain = d.is_chain_link_opener
            if is_link:
                if skip_chain:
                    skipped[id(d)] = d
                    if not d.is_chain_link_opener:
                        skip_chain = False
                else:
                    invalidate(d.setvars)   # conjunction-conditioned
                    force()
                continue
            if any(ph == d.phase for _m, ph in active):
                skipped[id(d)] = d
                if d.kind == "SecRule" and d.is_chain_link_opener:
                    skip_chain = True
                continue
            if d.kind == "SecAction":
                # actions execute, then an unconditional jump (if any)
                _fold_tx_assignments(env, d.setvars)
                force()
                if d.skip_marker is not None:
                    active.append((d.skip_marker, d.phase))
                continue
            if d.is_chain_link_opener:
                invalidate(d.setvars)       # chain leader: never static
                force()
                continue
            negate, op, arg = d.operator()
            verdict = _static_skip_condition(d.targets_txt, negate, op,
                                             arg, env)
            if d.skip_marker is not None and verdict is True:
                _fold_tx_assignments(env, d.setvars)  # before the jump
                force()
                active.append((d.skip_marker, d.phase))
                continue
            if d.skip_marker is not None and verdict is False:
                continue                    # inert control rule
            if verdict is True:
                _fold_tx_assignments(env, d.setvars)
            elif verdict is None:
                invalidate(d.setvars)
            force()

    for fs in root_scans(scans):
        walk(fs, [])    # fresh regions per entry file (parser behavior)
    return skipped


def _marker_reachable(fs: FileScan, i: int, marker: str) -> bool:
    """Can a region opened at directive ``i`` of ``fs`` meet its marker
    before the parser clears it?  Forward in the same file; across an
    Include, only the FIRST included file's prefix counts — the parser
    clears skip regions after each included file (review finding: a
    marker in the Include'd file is NOT dangling)."""
    for j in range(i + 1, len(fs.directives)):
        d = fs.directives[j]
        if d.kind == "SecMarker" and len(d.tokens) > 1 and \
                d.tokens[1].strip().strip("'\"") == marker:
            return True
        if d.kind == "Include":
            children = fs.includes.get(j, [])
            if children:
                return _marker_reachable(children[0], -1, marker)
    return False


def _chain_spans(fs: FileScan) -> List[Tuple[int, int]]:
    """(leader_idx, last_link_idx) spans of SecRule chains."""
    spans = []
    i, n = 0, len(fs.directives)
    while i < n:
        d = fs.directives[i]
        if d.kind == "SecRule" and d.is_chain_link_opener:
            j = i + 1
            while j < n:
                dj = fs.directives[j]
                if dj.kind != "SecRule":
                    j += 1
                    continue
                if not dj.is_chain_link_opener:
                    break
                j += 1
            spans.append((i, min(j, n - 1)))
            i = j + 1
        else:
            i += 1
    return spans


def check_reachability(scans: List[FileScan],
                       base_tx: Optional[Dict[str, str]] = None
                       ) -> List[Finding]:
    findings: List[Finding] = []

    # the paranoia sweep: a rule skipped under EVERY trial PL is
    # unreachable by any deployment setting
    skipped_at: Dict[int, list] = {}
    for pl in (1, 2, 3, 4):
        for key, dj in _simulate_skipped(scans, pl, base_tx).items():
            skipped_at.setdefault(key, [dj, set()])[1].add(pl)
    for dj, pls in skipped_at.values():
        if len(pls) != 4:
            continue
        if dj.kind == "SecRule" and dj.skip_marker is None:
            findings.append(Finding(
                check="flow.unreachable-paranoia",
                severity="warning", rule_id=dj.rule_id,
                file=dj.file, line=dj.line,
                message="rule is skipped at every paranoia level 1-4: "
                        "no deployment setting ever activates it"))

    for fs in scans:
        markers_at = [i for i, d in enumerate(fs.directives)
                      if d.kind == "SecMarker"]
        marker_names = {
            i: fs.directives[i].tokens[1].strip().strip("'\"")
            for i in markers_at if len(fs.directives[i].tokens) > 1}

        for i, d in enumerate(fs.directives):
            marker = d.skip_marker
            if marker is not None and d.kind in ("SecRule", "SecAction"):
                if not _marker_reachable(fs, i, marker):
                    findings.append(Finding(
                        check="flow.dangling-marker", severity="error",
                        rule_id=d.rule_id, subject=marker,
                        file=d.file, line=d.line,
                        message="skipAfter:%s meets no SecMarker before "
                                "the region is cleared: a taken jump "
                                "silently skips same-phase rules to the "
                                "end of the file (or first Include)"
                                % marker))
            if d.kind == "SecRule":
                for t in d.actions.get("tag", []):
                    m = _PL_TAG.search(t)
                    if m and not (1 <= int(m.group(1)) <= 4):
                        findings.append(Finding(
                            check="flow.bad-paranoia-tag",
                            severity="warning", rule_id=d.rule_id,
                            subject=t.strip("'\""),
                            file=d.file, line=d.line,
                            message="paranoia-level/%s is outside 1-4: "
                                    "the paranoia mask can never enable "
                                    "this rule" % m.group(1)))

        for leader, last in _chain_spans(fs):
            split = [j for j in markers_at if leader < j <= last]
            if split:
                d = fs.directives[leader]
                findings.append(Finding(
                    check="flow.marker-splits-chain", severity="error",
                    rule_id=d.rule_id,
                    subject=marker_names.get(split[0], "?"),
                    file=d.file, line=fs.directives[split[0]].line,
                    message="SecMarker '%s' lands inside the chain of "
                            "rule %s: a jump to it would run a partial "
                            "chain" % (marker_names.get(split[0], "?"),
                                       d.rule_id or "?")))

    return findings
