"""Thread-boundary map of the serve plane (docs/ANALYSIS.md
"Concurrency analysis").

The serve plane is a genuinely concurrent system: a dispatch thread, N
per-device lane workers, M confirm workers, a watchdog monitor, an
oversized-body side worker, the rollout shadow/admission threads, the
postanalytics exporter, and every thread that calls ``Batcher.submit``
all execute against shared batcher/pipeline/guard state.  PRs 7-10 each
needed a manual review pass to find the cross-thread mutations; this
module makes the boundary DECLARED and machine-checked instead:

* :data:`THREAD_ROOTS` is the authoritative registry of thread entry
  points.  Every entry is hand-declared because thread boundaries in
  this codebase are invisible to a call graph — work crosses onto a
  lane/confirm worker as a closure through ``LaneWorker.submit``, so the
  functions those closures call are declared as entries of the worker
  root, not discovered.
* :func:`build_thread_map` parses the serve-plane sources (no imports,
  pure AST), builds a conservative call graph, and computes for every
  function the set of thread roots that can reach it.  ``concheck``
  consumes this to decide which attribute mutations are genuinely
  multi-threaded.

The call graph is deliberately over-approximate (attribute calls
resolve by method name when the receiver type cannot be inferred): for
"which threads can execute this function" an over-approximation errs
toward reporting more sharing, never less — the safe direction for a
race analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: package root (ingress_plus_tpu/) — analysis targets are relative to it
PACKAGE_ROOT = Path(__file__).resolve().parent.parent

#: the serve-plane sources concheck audits (ISSUE 11 target set).
#: serve/stream.py is deliberately OUT: StreamState handles are poisoned
#: cross-thread by design (documented bool-write-atomic contract) and
#: live entirely inside the dispatch thread's happens-before chain.
#: serve/websocket.py is IN for its shared-state touches (it mutates
#: pipeline stats), but ServeLoop._handle_conn is NOT a registered
#: root: per-connection WSStream/stream state is owned by the single
#: asyncio event-loop thread, and rooting the handler would flag every
#: per-connection field as shared — the boundary model is batcher-and-
#: below, where the real threads live.
SERVE_PLANE_FILES: Tuple[str, ...] = (
    "serve/batcher.py",
    "serve/lanes.py",
    "serve/server.py",
    "serve/websocket.py",
    "models/pipeline.py",
    "models/confirm_plane.py",
    "models/confirm.py",
    "models/tenant_guard.py",
    "models/rule_stats.py",
    "control/rollout.py",
    "utils/trace.py",
    "post/counters.py",
    "post/topk.py",
    "post/queue.py",
    "post/channel.py",
    "post/export.py",
    "post/aggregate.py",
    "post/brute.py",
)


@dataclass(frozen=True)
class ThreadRoot:
    """One declared thread entry point class.

    ``entries`` are ``"relpath::Qualname"`` keys (``Class.method`` or a
    module-level function).  ``concurrent=True`` means two or more OS
    threads may execute this root SIMULTANEOUSLY (N lane workers, M
    confirm workers, arbitrary submit callers) — a single concurrent
    root is therefore already a data-race boundary on its own."""

    name: str
    entries: Tuple[str, ...]
    concurrent: bool
    description: str


#: The authoritative thread map of the serve plane.  Adding a thread to
#: the codebase without registering it here is itself a finding
#: (``conc.unregistered-thread`` — concheck cross-checks every
#: ``threading.Thread(target=...)`` site against these entries).
THREAD_ROOTS: Tuple[ThreadRoot, ...] = (
    ThreadRoot(
        name="dispatch",
        entries=("serve/batcher.py::Batcher._run",
                 "serve/batcher.py::Batcher._run_mesh"),
        concurrent=False,
        description="the ipt-batcher dispatch thread: drains admission, "
                    "launches/collects device cycles, resolves verdict "
                    "futures (sole owner of stream state and the mesh "
                    "double buffer)"),
    ThreadRoot(
        name="lane_worker",
        entries=("serve/lanes.py::LaneWorker._run",
                 # closures cross onto the worker via LaneWorker.submit:
                 # these are the functions the dispatch thread wraps in
                 # lambdas and hands over (serve/batcher.py lane.call)
                 "models/pipeline.py::DetectionPipeline.detect_strict",
                 "models/pipeline.py::DetectionPipeline.detect_tenant_degraded",
                 "serve/batcher.py::Batcher._stream_step"),
        concurrent=True,
        description="ipt-device-N per-chip dispatch workers (one per "
                    "lane; zombies may linger after an abandon)"),
    ThreadRoot(
        name="confirm_worker",
        entries=("models/confirm_plane.py::confirm_one",),
        concurrent=True,
        description="ipt-confirm-N sharded confirm workers "
                    "(--confirm-workers > 1); shares arrive as closures "
                    "through ConfirmPool.submit"),
    ThreadRoot(
        name="watchdog",
        entries=("serve/batcher.py::Batcher._watch",),
        concurrent=False,
        description="ipt-watchdog monitor: releases a wedged cycle's "
                    "futures fail-open, drains the queue while the "
                    "dispatcher is stuck"),
    ThreadRoot(
        name="oversized",
        entries=("serve/batcher.py::Batcher._run_oversized",),
        concurrent=False,
        description="ipt-oversized side worker: inflates and "
                    "chunk-scans oversized bodies off the batch path"),
    ThreadRoot(
        name="shadow",
        entries=("control/rollout.py::RolloutController._shadow_run",),
        concurrent=False,
        description="ipt-shadow rollout mirror: replays sampled live "
                    "traffic through the candidate generation"),
    ThreadRoot(
        name="rollout_admission",
        entries=("control/rollout.py::RolloutController.admit",
                 "control/rollout.py::RolloutController.admit_scoring",
                 "control/rollout.py::RolloutController.abort",
                 "control/rollout.py::RolloutController.close"),
        concurrent=False,
        description="staged-rollout admission: runs on an HTTP executor "
                    "thread (ServeLoop run_in_executor), builds and "
                    "gates the candidate generation"),
    ThreadRoot(
        name="exporter",
        entries=("post/export.py::Exporter._run",
                 "post/export.py::RulesetWatcher._run"),
        concurrent=False,
        description="postanalytics exporter + artifact watcher threads"),
    ThreadRoot(
        name="submit",
        entries=("serve/batcher.py::Batcher.submit",
                 "serve/batcher.py::Batcher.begin_stream",
                 "serve/batcher.py::Batcher.feed_chunk",
                 "serve/batcher.py::Batcher.finish_stream",
                 "serve/batcher.py::Batcher.abort_stream"),
        concurrent=True,
        description="admission callers: the asyncio event loop in "
                    "production, arbitrary threads in benches/tests — "
                    "Batcher.submit is a declared thread-safe API "
                    "(models/tenant_guard.py contract)"),
    ThreadRoot(
        name="control",
        entries=("serve/batcher.py::Batcher.swap_ruleset",
                 "serve/batcher.py::Batcher.set_tenant_tags",
                 "serve/batcher.py::Batcher.set_scoring_head",
                 "serve/batcher.py::Batcher.reset_latency_observations",
                 "serve/batcher.py::Batcher.warm_lanes",
                 "serve/batcher.py::Batcher.close",
                 # the HTTP POST handlers run their mutations on
                 # executor threads (run_in_executor) — two concurrent
                 # POSTs are two threads
                 "serve/server.py::ServeLoop._route_http"),
        concurrent=True,
        description="control-plane mutations (hot swap, tenant tables, "
                    "scoring head, bench resets, HTTP POST handlers): "
                    "HTTP executor threads and the ipt-swapwarm-N "
                    "ephemeral warmers they fan out"),
    ThreadRoot(
        name="scrape",
        entries=("serve/server.py::ServeLoop._metrics_text",
                 "models/tenant_guard.py::TenantGuard.snapshot",
                 "models/tenant_guard.py::TenantGuard.brief",
                 "models/tenant_guard.py::TenantGuard.counters",
                 "models/rule_stats.py::RuleStats.health",
                 "models/rule_stats.py::RuleStats.rules_json",
                 "control/rollout.py::RolloutController.status",
                 "post/channel.py::PostChannel.status"),
        concurrent=True,
        description="status/metrics readers: /metrics, /healthz, "
                    "/tenants, /rules/*, dbg — read-only views that "
                    "must snapshot, never hold live references"),
)


# --------------------------------------------------------------- parsing


@dataclass
class FunctionInfo:
    """One analyzed function (nested defs and lambdas are merged into
    their enclosing function — a closure's body executes with the
    enclosing lexical context, and the declared registry covers the
    cases where it actually runs on another thread)."""

    key: str                       # "relpath::Qual.name"
    file: str
    cls: Optional[str]
    name: str
    lineno: int
    node: ast.AST = None           # type: ignore[assignment]
    calls: List[tuple] = field(default_factory=list)
    bases: Tuple[str, ...] = ()


@dataclass
class ClassInfo:
    name: str
    file: str
    bases: Tuple[str, ...]
    methods: Dict[str, str] = field(default_factory=dict)  # name -> key
    #: attr name -> type descriptor:
    #:   ("cls", "Name") | ("listof", "Name") | ("lock",) |
    #:   ("cond", lock_attr) | ("thread", daemon) | ("queue",) | None
    attr_types: Dict[str, tuple] = field(default_factory=dict)


@dataclass
class ModuleMap:
    """Everything the analyzers need from the parsed tree."""

    files: Dict[str, ast.Module]
    sources: Dict[str, List[str]]
    functions: Dict[str, FunctionInfo]
    classes: Dict[str, ClassInfo]          # class name -> info (last wins)
    func_by_name: Dict[str, List[str]]     # bare name -> keys
    method_index: Dict[str, List[str]]     # method name -> keys


def _call_name(node: ast.Call):
    """Classify a call target for conservative resolution."""
    f = node.func
    if isinstance(f, ast.Name):
        return ("name", f.id)
    if isinstance(f, ast.Attribute):
        recv = f.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            return ("self", f.attr)
        return ("attr", _expr_chain(recv), f.attr)
    return None


def _expr_chain(node) -> Optional[Tuple[str, ...]]:
    """``self.a.b`` → ("self", "a", "b"); ``x.y`` → ("x", "y");
    anything non-chain → None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    if isinstance(node, ast.Subscript):
        inner = _expr_chain(node.value)
        if inner is not None:
            return inner + ("[]",)
    return None


#: method names too generic to resolve by name alone (dict/list/str
#: builtins and same-name methods on unrelated classes shadow them) —
#: resolved only through an inferred receiver type
_AMBIENT_METHODS = frozenset({
    "get", "put", "update", "items", "keys", "values", "append", "pop",
    "popleft", "appendleft", "add", "remove", "discard", "clear",
    "extend", "sort", "join", "start", "wait", "set", "copy", "index",
    "count", "read", "write", "split", "strip", "encode", "decode",
    "format", "setdefault", "mkdir", "exists", "is_set", "close",
    "insert", "sum", "mean", "any", "all", "release", "acquire",
    "rotate", "result", "done", "cancel", "tolist", "astype", "send",
    "recv", "fileno", "flush", "match", "search", "group", "lower",
    "upper", "startswith", "endswith", "replace", "partition",
    # same-name methods on unrelated in-scope classes (Histogram vs
    # LoadController observe, Batcher vs LaneWorker submit, the many
    # snapshot()/reset()/record() views): by-name resolution here
    # manufactures cross-class reachability out of thin air
    "submit", "observe", "snapshot", "record", "reset", "status",
    "drain", "fire", "feed", "swap_ruleset",
})

_CTOR_TYPES = {
    ("threading", "Lock"): ("lock",),
    ("threading", "RLock"): ("lock",),
    ("queue", "Queue"): ("queue",),
    ("deque",): ("list",),
    ("collections", "deque"): ("list",),
    ("defaultdict",): ("dict",),
    ("collections", "defaultdict"): ("dict",),
}


def _infer_ctor(node) -> Optional[tuple]:
    """Type descriptor for a ``self.x = <expr>`` RHS."""
    if isinstance(node, ast.Call):
        chain = _expr_chain(node.func)
        if chain is None:
            return None
        if chain in _CTOR_TYPES:
            return _CTOR_TYPES[chain]
        if len(chain) == 1 and (chain[0],) in _CTOR_TYPES:
            return _CTOR_TYPES[(chain[0],)]
        if chain == ("threading", "Condition"):
            if node.args:
                arg = _expr_chain(node.args[0])
                if arg and arg[0] == "self" and len(arg) == 2:
                    return ("cond", arg[1])
            return ("lock",)
        if chain == ("threading", "Thread"):
            daemon = False
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(kw.value,
                                                     ast.Constant):
                    daemon = bool(kw.value.value)
            return ("thread", daemon)
        if chain == ("named_lock",) or chain[-1] == "named_lock":
            return ("lock",)
        if len(chain) == 1 and chain[0][:1].isupper():
            return ("cls", chain[0])
    if isinstance(node, ast.ListComp) and isinstance(node.elt, ast.Call):
        c = _expr_chain(node.elt.func)
        if c and len(c) >= 2 and c[-2:] == ("threading", "Thread"):
            return ("listof_thread",)
        if c and len(c) == 1 and c[0][:1].isupper():
            return ("listof", c[0])
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return ("dict",)
    if isinstance(node, (ast.List, ast.ListComp)):
        return ("list",)
    if isinstance(node, (ast.Set, ast.SetComp)):
        return ("set",)
    return None


def parse_tree(root: Optional[Path] = None,
               files: Sequence[str] = SERVE_PLANE_FILES) -> ModuleMap:
    """Parse the target files into the shared module map (pure AST — the
    analyzer must run in CI without importing jax-heavy modules)."""
    root = Path(root) if root is not None else PACKAGE_ROOT
    mm = ModuleMap(files={}, sources={}, functions={}, classes={},
                   func_by_name={}, method_index={})
    for rel in files:
        p = root / rel
        if not p.is_file():
            continue
        src = p.read_text()
        tree = ast.parse(src, filename=str(p))
        mm.files[rel] = tree
        mm.sources[rel] = src.splitlines()
        _index_module(mm, rel, tree)
    _collect_calls(mm)
    return mm


def _index_module(mm: ModuleMap, rel: str, tree: ast.Module) -> None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            bases = tuple(b.id for b in node.bases
                          if isinstance(b, ast.Name))
            # last wins, explicitly: a same-named class in a later file
            # REPLACES the earlier entry (merging two classes' methods
            # into one ClassInfo would mis-attribute accesses silently)
            ci = ClassInfo(name=node.name, file=rel, bases=bases)
            mm.classes[node.name] = ci
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = "%s::%s.%s" % (rel, node.name, item.name)
                    fi = FunctionInfo(key=key, file=rel, cls=node.name,
                                      name=item.name, lineno=item.lineno,
                                      node=item, bases=bases)
                    mm.functions[key] = fi
                    ci.methods[item.name] = key
                    mm.method_index.setdefault(item.name, []).append(key)
                    _infer_attr_types(ci, item)
            # dataclass field annotations: ``x: Dict[...] = field(...)``
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and \
                        isinstance(item.target, ast.Name):
                    ci.attr_types.setdefault(
                        item.target.id,
                        _annotation_type(item.annotation))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = "%s::%s" % (rel, node.name)
            fi = FunctionInfo(key=key, file=rel, cls=None,
                              name=node.name, lineno=node.lineno,
                              node=node)
            mm.functions[key] = fi
            mm.func_by_name.setdefault(node.name, []).append(key)


def _annotation_type(ann) -> Optional[tuple]:
    """Type descriptor from an annotation node.  Handles ``Optional[X]``
    (unwraps), string annotations ("Batcher"), containers, and plain
    in-scope class names."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.split(".")[-1].split("[")[0]
        return ("cls", name) if name[:1].isupper() else None
    if isinstance(ann, ast.Subscript):
        chain = _expr_chain(ann.value)
        tail = chain[-1] if chain else ""
        if tail == "Optional":
            return _annotation_type(ann.slice)
        if tail in ("Dict", "dict", "DefaultDict"):
            return ("dict",)
        if tail in ("List", "list", "Deque", "deque"):
            return ("list",)
        if tail in ("Set", "set", "FrozenSet"):
            return ("set",)
        return None
    chain = _expr_chain(ann)
    if chain is None:
        return None
    tail = chain[-1]
    if tail in ("Dict", "dict", "DefaultDict"):
        return ("dict",)
    if tail in ("List", "list", "Deque", "deque"):
        return ("list",)
    if tail in ("Set", "set"):
        return ("set",)
    if tail in ("Lock", "RLock"):
        return ("lock",)
    if tail[:1].isupper() and tail not in (
            "Tuple", "Sequence", "Iterable", "Callable", "Any",
            "Union", "Optional", "Mapping", "Type", "Future"):
        return ("cls", tail)
    return None


def _infer_attr_types(ci: ClassInfo, fn: ast.AST) -> None:
    """Record ``self.x = <typed expr>`` assignments (any method — most
    live in __init__) plus param-annotation propagation
    (``def __init__(self, pipeline: DetectionPipeline)`` +
    ``self.pipeline = pipeline``)."""
    ann: Dict[str, tuple] = {}
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + \
            list(args.kwonlyargs):
        if a.annotation is not None:
            t = _annotation_type(a.annotation)
            if t is not None and t[0] == "cls":
                ann[a.arg] = t
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                t = _infer_ctor(node.value)
                if t is None and isinstance(node.value, ast.Name):
                    t = ann.get(node.value.id)
                if t is not None:
                    ci.attr_types.setdefault(tgt.attr, t)


def _collect_calls(mm: ModuleMap) -> None:
    for fi in mm.functions.values():
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                c = _call_name(node)
                if c is not None:
                    fi.calls.append(c)


# ---------------------------------------------------------- resolution


def resolve_local_types(mm: ModuleMap, fi: FunctionInfo) -> Dict[str, tuple]:
    """Best-effort local-variable type map for one function: parameters
    by annotation, ``x = self.attr`` / ``x = self.a.b`` chains through
    the class attr-type table, ``x = ClassName(...)``, and loop vars
    over list-of-class locals."""
    out: Dict[str, tuple] = {}
    args = fi.node.args
    for a in list(args.posonlyargs) + list(args.args) + \
            list(args.kwonlyargs):
        if a.annotation is not None:
            t = _annotation_type(a.annotation)
            if t is not None and t[0] == "cls" and t[1] in mm.classes:
                out[a.arg] = t
    for _ in range(2):   # two passes: aliases of aliases
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                t = _infer_ctor(node.value)
                if t is None:
                    chain = _expr_chain(node.value)
                    if chain is not None:
                        t = chain_type(mm, fi, chain, out)
                if t is not None:
                    out.setdefault(name, t)
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                chain = _expr_chain(node.iter)
                if chain is not None:
                    t = chain_type(mm, fi, chain, out)
                    if t is not None and t[0] == "listof":
                        out.setdefault(node.target.id, ("cls", t[1]))
                    elif t is not None and t[0] == "listof_thread":
                        out.setdefault(node.target.id,
                                       ("thread", False))
    return out


def chain_type(mm: ModuleMap, fi: FunctionInfo,
               chain: Tuple[str, ...],
               local_types: Dict[str, tuple]) -> Optional[tuple]:
    """Resolve an attribute chain to a type descriptor."""
    if not chain:
        return None
    head, rest = chain[0], chain[1:]
    if head == "self":
        if fi.cls is None:
            return None
        t: Optional[tuple] = ("cls", fi.cls)
    else:
        t = local_types.get(head)
    for part in rest:
        if t is None:
            return None
        if part == "[]":
            t = ("cls", t[1]) if t[0] == "listof" else None
            continue
        if t[0] != "cls" or t[1] not in mm.classes:
            return None
        t = mm.classes[t[1]].attr_types.get(part)
    return t


def _mro_method(mm: ModuleMap, cls: str, name: str) -> Optional[str]:
    seen = set()
    stack = [cls]
    while stack:
        c = stack.pop(0)
        if c in seen or c not in mm.classes:
            continue
        seen.add(c)
        ci = mm.classes[c]
        if name in ci.methods:
            return ci.methods[name]
        stack.extend(ci.bases)
    return None


def resolve_callees(mm: ModuleMap, fi: FunctionInfo,
                    local_types: Optional[Dict[str, tuple]] = None
                    ) -> Set[str]:
    """Function keys this function may call (conservative)."""
    if local_types is None:
        local_types = resolve_local_types(mm, fi)
    out: Set[str] = set()
    for call in fi.calls:
        if call[0] == "name":
            name = call[1]
            if name in mm.classes:      # constructor
                k = _mro_method(mm, name, "__init__")
                if k:
                    out.add(k)
            out.update(mm.func_by_name.get(name, ()))
        elif call[0] == "self":
            if fi.cls is not None:
                k = _mro_method(mm, fi.cls, call[1])
                if k:
                    out.add(k)
                    continue
            out.update(mm.func_by_name.get(call[1], ()))
        elif call[0] == "attr":
            chain, meth = call[1], call[2]
            t = chain_type(mm, fi, chain, local_types) if chain else None
            if t is not None and t[0] == "cls":
                k = _mro_method(mm, t[1], meth)
                if k:
                    out.add(k)
                continue
            if meth not in _AMBIENT_METHODS:
                out.update(mm.method_index.get(meth, ()))
    return out


# -------------------------------------------------------- reachability


@dataclass
class ThreadMap:
    """roots + per-function reachability: the product concheck (and the
    docs) consume."""

    roots: Tuple[ThreadRoot, ...]
    #: function key -> set of root names that can execute it
    reach: Dict[str, Set[str]]
    mm: ModuleMap

    def roots_of(self, key: str) -> Set[str]:
        return self.reach.get(key, set())

    def is_concurrent(self, names: Set[str]) -> bool:
        """True when ``names`` implies two threads can run at once:
        two distinct roots, or one root that is itself concurrent."""
        if len(names) >= 2:
            return True
        by = {r.name: r for r in self.roots}
        return any(by[n].concurrent for n in names if n in by)

    def registry_json(self) -> List[dict]:
        return [{"name": r.name, "concurrent": r.concurrent,
                 "entries": list(r.entries),
                 "description": r.description}
                for r in self.roots]


def build_thread_map(root: Optional[Path] = None,
                     roots: Tuple[ThreadRoot, ...] = THREAD_ROOTS,
                     mm: Optional[ModuleMap] = None) -> ThreadMap:
    if mm is None:
        mm = parse_tree(root)
    # constructor edges are EXCLUDED from reachability: an object under
    # construction is thread-local until published, so a root reaching
    # ``ClassName(...)`` does not make that class's __init__-time
    # mutations shared (fresh-object exemption, interprocedural half)
    callees: Dict[str, Set[str]] = {
        k: {c for c in resolve_callees(mm, fi)
            if not c.endswith(".__init__")}
        for k, fi in mm.functions.items()}
    reach: Dict[str, Set[str]] = {}
    for r in roots:
        frontier = [e for e in r.entries if e in mm.functions]
        seen: Set[str] = set()
        while frontier:
            k = frontier.pop()
            if k in seen:
                continue
            seen.add(k)
            reach.setdefault(k, set()).add(r.name)
            frontier.extend(callees.get(k, ()))
    return ThreadMap(roots=roots, reach=reach, mm=mm)
