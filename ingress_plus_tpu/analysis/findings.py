"""Finding model, suppression baseline, and output renderers for rulecheck.

A *finding* is one machine-checked statement about the compiled ruleset
(see docs/ANALYSIS.md for the check catalog).  Severities gate CI:

    error    — a soundness/correctness hole (prefilter can lose a match,
               control flow drops rules ModSecurity would run, a blocking
               threshold that can never fire).  CI fails on unsuppressed
               errors.
    warning  — likely authoring bug or silent degradation worth a human
               look (read-before-write TX, coverage gap).
    notice   — measurable-but-accepted weakness (weak factor, polynomial
               backtracking shape).
    info     — by-design behavior surfaced for visibility (confirm-only
               rules, heuristic trigger groups).

The suppression baseline is a checked-in JSON list of accepted findings
("this limitation is known, here is why"); a suppressed finding still
appears in reports (``suppressed: true``) but never gates.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

SEVERITIES = ("error", "warning", "notice", "info")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

#: SARIF has no "notice"/"info" split at the level granularity we use
_SARIF_LEVEL = {"error": "error", "warning": "warning",
                "notice": "note", "info": "note"}


@dataclass
class Finding:
    """One rulecheck result.

    ``check`` is the stable dotted id (e.g. ``flow.dangling-marker``);
    ``subject`` is the non-rule anchor (marker name, TX variable,
    transform name) used for suppression matching when ``rule_id`` alone
    is ambiguous or absent.
    """

    check: str
    severity: str
    message: str
    rule_id: int = 0
    subject: str = ""
    file: str = ""
    line: int = 0
    suppressed: bool = False
    suppress_reason: str = ""

    def sort_key(self):
        return (_SEV_RANK.get(self.severity, len(SEVERITIES)),
                self.check, self.rule_id, self.subject)

    def to_dict(self) -> Dict:
        d = {"check": self.check, "severity": self.severity,
             "message": self.message}
        if self.rule_id:
            d["rule_id"] = self.rule_id
        if self.subject:
            d["subject"] = self.subject
        if self.file:
            d["file"] = self.file
        if self.line:
            d["line"] = self.line
        if self.suppressed:
            d["suppressed"] = True
            d["suppress_reason"] = self.suppress_reason
        return d


class BaselineError(Exception):
    pass


@dataclass
class Baseline:
    """Accepted-findings list.  An entry matches a finding when the
    ``check`` ids are equal AND every anchor the entry names (rule_id,
    subject, class, file) matches — an entry with only ``check`` set
    accepts the whole class, which is deliberate for by-design info
    classes.  ``class`` matches the owner part of a dotted subject
    (``ConfirmResult.confirmed`` → ``ConfirmResult``) — concheck's
    class-level suppression for single-owner handoff objects."""

    entries: List[Dict] = field(default_factory=list)
    path: str = ""

    @classmethod
    def load(cls, path: str | Path) -> "Baseline":
        p = Path(path)
        try:
            data = json.loads(p.read_text())
        except (OSError, ValueError) as e:
            raise BaselineError("cannot read baseline %s: %s" % (p, e))
        entries = data.get("suppressions", data) if isinstance(data, dict) \
            else data
        if not isinstance(entries, list):
            raise BaselineError("baseline %s: expected a list" % p)
        for e in entries:
            if not isinstance(e, dict) or "check" not in e or \
                    not e.get("reason"):
                raise BaselineError(
                    "baseline %s: every entry needs 'check' and a "
                    "one-line 'reason': %r" % (p, e))
        return cls(entries=entries, path=str(p))

    def match(self, f: Finding) -> Optional[Dict]:
        for e in self.entries:
            if e["check"] != f.check:
                continue
            if "rule_id" in e and int(e["rule_id"]) != f.rule_id:
                continue
            if "subject" in e and e["subject"] != f.subject:
                continue
            if "class" in e and \
                    e["class"] != f.subject.partition(".")[0]:
                continue
            if "file" in e and e["file"] != Path(f.file).name:
                continue
            return e
        return None

    def apply(self, findings: List[Finding]) -> None:
        for f in findings:
            e = self.match(f)
            if e is not None:
                f.suppressed = True
                f.suppress_reason = e["reason"]


@dataclass
class Report:
    """The full analyzer run: findings + provenance."""

    findings: List[Finding]
    rules_path: str = ""
    baseline_path: str = ""
    n_rules: int = 0
    pack_version: str = ""
    #: which analyzer produced this report ("rulecheck" | "concheck" |
    #: "evadecheck") — renderers brand their headers/driver from it
    tool: str = "rulecheck"
    #: tool-specific provenance (concheck: analyzed files, the thread
    #: -root registry, the lock-order edge list)
    meta: Optional[Dict] = None
    #: approximate-merge provenance of the audited pack (compiler
    #: ReductionReport dict; None = exact compile).  The prefilter audit
    #: certifies soundness THROUGH the reduction (widened/truncated
    #: factors still cover every derivation), but an operator reading
    #: the report must be able to see what was merged and at what
    #: estimated candidate cost.
    reduction: Optional[Dict] = None

    def counts(self, suppressed: bool = False) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            if f.suppressed == suppressed:
                out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def gating(self, fail_on: str = "error") -> List[Finding]:
        """Unsuppressed findings at or above ``fail_on`` severity."""
        rank = _SEV_RANK[fail_on]
        return [f for f in self.findings
                if not f.suppressed and _SEV_RANK[f.severity] <= rank]

    # ------------------------------------------------------------ renderers

    def to_json(self) -> str:
        out = {
            "tool": self.tool,
            "rules_path": self.rules_path,
            "baseline": self.baseline_path,
            "n_rules": self.n_rules,
            "pack_version": self.pack_version,
            "reduction": self.reduction,
            "counts": self.counts(),
            "suppressed_counts": self.counts(suppressed=True),
            "findings": [f.to_dict()
                         for f in sorted(self.findings,
                                         key=Finding.sort_key)],
        }
        if self.meta is not None:
            out["meta"] = self.meta
        return json.dumps(out, indent=2, sort_keys=False) + "\n"

    def to_text(self) -> str:
        if self.tool == "concheck":
            m = self.meta or {}
            lines = ["concheck: %d functions over %d files, "
                     "%d thread roots"
                     % (m.get("functions", 0), len(m.get("files", ())),
                        len(m.get("thread_roots", ())))]
        elif self.tool == "evadecheck":
            m = self.meta or {}
            lines = ["evadecheck: %d rules, pack %s, "
                     "%d corroborated by runtime escapes"
                     % (self.n_rules, self.pack_version or "?",
                        m.get("corroborated", 0))]
        else:
            lines = ["rulecheck: %d rules, pack %s" %
                     (self.n_rules, self.pack_version or "?")]
        active = [f for f in self.findings if not f.suppressed]
        for f in sorted(active, key=Finding.sort_key):
            loc = Path(f.file).name if f.file else "-"
            if f.line:
                loc += ":%d" % f.line
            anchor = str(f.rule_id) if f.rule_id else (f.subject or "-")
            lines.append("%-8s %-28s %-22s %-10s %s"
                         % (f.severity, f.check, loc, anchor, f.message))
        c = self.counts()
        sup = sum(self.counts(suppressed=True).values())
        lines.append("%d error, %d warning, %d notice, %d info"
                     " (%d suppressed by baseline)"
                     % (c["error"], c["warning"], c["notice"], c["info"],
                        sup))
        return "\n".join(lines) + "\n"

    def to_sarif(self) -> str:
        """SARIF 2.1.0, one run, one rule descriptor per check id —
        minimal but valid for GitHub code-scanning upload."""
        by_check: Dict[str, str] = {}
        results = []
        for f in sorted(self.findings, key=Finding.sort_key):
            by_check.setdefault(f.check, f.severity)
            res: Dict = {
                "ruleId": f.check,
                "level": _SARIF_LEVEL[f.severity],
                "message": {"text": f.message},
            }
            if f.file:
                loc: Dict = {"artifactLocation": {"uri": f.file}}
                if f.line:
                    loc["region"] = {"startLine": f.line}
                res["locations"] = [{"physicalLocation": loc}]
            if f.suppressed:
                res["suppressions"] = [{
                    "kind": "external",
                    "justification": f.suppress_reason,
                }]
            results.append(res)
        sarif = {
            "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                        "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {
                    "name": self.tool,
                    "informationUri": "docs/ANALYSIS.md",
                    "version": "1.0.0",
                    "rules": [{"id": cid,
                               "defaultConfiguration":
                                   {"level": _SARIF_LEVEL[sev]}}
                              for cid, sev in sorted(by_check.items())],
                }},
                "results": results,
            }],
        }
        return json.dumps(sarif, indent=2) + "\n"
