"""Transform-lane consistency checks (check class 5).

Every rule is scanned on ONE normalization variant of each stream; the
confirm stage applies the rule's exact transform chain.  The contract
(compiler/ruleset.py module docstring) is that the scan lane's
normalization never deletes bytes the rule's own chain keeps — the PR-1
`t:urlDecodeUni` double-decode fix was one instance of this class; the
round-3 942170 htmlEntityDecode factor loss was another.  This module
lints the whole class statically:

  lane.variant-mismatch  (error)  the compiled scan variant differs
      from the variant the rule's transform chain implies (independent
      re-derivation) — the rule scans text its transforms don't produce
  lane.unmodeled-decode  (error)  a rule KEEPS prefilter factors while
      its chain has a decode transform no scan variant applies
      (base64Decode/hexDecode/jsDecode/cssDecode): encoded payloads
      never contain the factor bytes, so the prefilter loses matches
  lane.comment-transform (error)  same, for comment-rewrite transforms
  lane.unknown-transform (warning) transform name the confirm stage
      does not implement — apply_transforms silently skips it, so the
      rule matches UN-transformed text (typo lint)
  lane.noop-transform    (notice)  documented no-op approximations
      (utf8toUnicode)
"""

from __future__ import annotations

from typing import List

from ingress_plus_tpu.analysis.findings import Finding

#: independent copy of the variant-assignment contract; divergence from
#: compiler/ruleset.py _rule_variant IS the finding
_WS_COLLAPSE = {"compressWhitespace", "removeWhitespace", "cmdLine"}
_HTML = {"htmlEntityDecode"}
_DECODE = {"urlDecode", "urlDecodeUni", "jsDecode", "cssDecode",
           "hexDecode", "base64Decode"}
_UNMODELED_DECODE = {"base64Decode", "hexDecode", "jsDecode", "cssDecode"}
_COMMENT = {"replaceComments", "removeCommentsChar"}
_NOOP = {"utf8toUnicode"}


def expected_variant(transforms) -> int:
    t = set(transforms)
    if t & _WS_COLLAPSE:
        if t & _HTML:
            return 4
        if t & _DECODE:
            return 5
        return 3
    if t & _HTML:
        return 2
    if t & _DECODE:
        return 1
    return 0


def check_lanes(metas) -> List[Finding]:
    findings: List[Finding] = []
    known = _known_transforms()
    for meta in metas:
        rid = meta.rule.rule_id
        transforms = list(meta.confirm.get("transforms", []))
        exp = expected_variant(transforms)
        got = int(meta.confirm.get("variant", meta.variant))
        if exp != got:
            findings.append(Finding(
                check="lane.variant-mismatch", severity="error",
                rule_id=rid, subject="variant %d != expected %d"
                                     % (got, exp),
                message="rule compiled onto scan variant %d but its "
                        "transform chain %r implies variant %d: the "
                        "prefilter scans text the confirm semantics "
                        "never see" % (got, transforms, exp)))
        if meta.has_prefilter:
            bad = set(transforms) & _UNMODELED_DECODE
            if bad:
                findings.append(Finding(
                    check="lane.unmodeled-decode", severity="error",
                    rule_id=rid, subject=",".join(sorted(bad)),
                    message="rule keeps prefilter factors while its "
                            "chain decodes with %s, which no scan "
                            "variant models: encoded payloads bypass "
                            "the prefilter" % ", ".join(sorted(bad))))
            bad = set(transforms) & _COMMENT
            if bad:
                findings.append(Finding(
                    check="lane.comment-transform", severity="error",
                    rule_id=rid, subject=",".join(sorted(bad)),
                    message="rule keeps prefilter factors while its "
                            "chain rewrites comments (%s), which no "
                            "scan variant models"
                            % ", ".join(sorted(bad))))
        # transform-name lint covers chain links too (they confirm with
        # their own chains)
        chains = [transforms] + [
            list(link.get("transforms", []))
            for link in meta.confirm.get("chain", [])]
        seen: set = set()
        for tlist in chains:
            for name in tlist:
                if name in seen:
                    continue
                seen.add(name)
                if name in _NOOP:
                    findings.append(Finding(
                        check="lane.noop-transform", severity="notice",
                        rule_id=rid, subject=name,
                        message="t:%s is a documented no-op "
                                "approximation here (docs/SECLANG.md)"
                                % name))
                elif name not in known:
                    findings.append(Finding(
                        check="lane.unknown-transform", severity="warning",
                        rule_id=rid, subject=name,
                        message="t:%s is not implemented by the confirm "
                                "stage and is silently skipped — the "
                                "rule matches un-transformed text "
                                "(typo?)" % name))
    return findings


def _known_transforms() -> set:
    from ingress_plus_tpu.models.confirm import TRANSFORMS
    return set(TRANSFORMS) | {"none"}
