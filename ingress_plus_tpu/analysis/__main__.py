"""CLI: ``python -m ingress_plus_tpu.analysis``.

    python -m ingress_plus_tpu.analysis                    # bundled tree
    python -m ingress_plus_tpu.analysis --rules path/ --format sarif
    python -m ingress_plus_tpu.analysis --format json --output reports/RULECHECK.json
    python -m ingress_plus_tpu.analysis --conc             # concurrency analyzer
    python -m ingress_plus_tpu.analysis --conc --fail-on error

Exit code 0 when no unsuppressed finding reaches ``--fail-on`` severity
(default: error) — the CI gate contract.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ingress_plus_tpu.analysis import (
    BaselineError,
    SEVERITIES,
    run_concheck,
    run_rulecheck,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.analysis")
    ap.add_argument("--conc", action="store_true",
                    help="run concheck (the serve-plane concurrency "
                         "analyzer) instead of rulecheck")
    ap.add_argument("--rules", default=None,
                    help="rules tree (directory of *.conf, or an entry "
                         "config); default: the bundled CRS tree")
    ap.add_argument("--format", choices=["text", "json", "sarif"],
                    default="text")
    ap.add_argument("--baseline", default="auto",
                    help="suppression baseline JSON; 'auto' (default) "
                         "uses <rules>/rulecheck-baseline.json (or "
                         "analysis/concheck-baseline.json with --conc), "
                         "'none' disables suppression")
    ap.add_argument("--fail-on", choices=list(SEVERITIES),
                    default="error",
                    help="exit nonzero when an unsuppressed finding of "
                         "this severity (or worse) exists")
    ap.add_argument("--output", default=None,
                    help="also write the rendered report to this path")
    args = ap.parse_args(argv)

    baseline = None if args.baseline == "none" else args.baseline
    if args.conc:
        try:
            report = run_concheck(baseline_path=baseline)
        except (OSError, BaselineError, SyntaxError) as e:
            print("concheck: %s" % e, file=sys.stderr)
            return 2
        out = {"text": report.to_text, "json": report.to_json,
               "sarif": report.to_sarif}[args.format]()
        if args.output:
            Path(args.output).parent.mkdir(parents=True, exist_ok=True)
            Path(args.output).write_text(out)
        print(out, end="")
        gating = report.gating(args.fail_on)
        if gating:
            print("concheck: %d unsuppressed finding(s) at or above "
                  "severity %r" % (len(gating), args.fail_on),
                  file=sys.stderr)
            return 1
        return 0

    from ingress_plus_tpu.compiler.seclang import SecLangError

    try:
        report = run_rulecheck(rules_path=args.rules,
                               baseline_path=baseline)
    except (OSError, BaselineError, SecLangError) as e:
        print("rulecheck: %s" % e, file=sys.stderr)
        return 2

    out = {"text": report.to_text, "json": report.to_json,
           "sarif": report.to_sarif}[args.format]()
    if args.output:
        Path(args.output).parent.mkdir(parents=True, exist_ok=True)
        Path(args.output).write_text(out)
    print(out, end="")

    gating = report.gating(args.fail_on)
    if gating:
        print("rulecheck: %d unsuppressed finding(s) at or above "
              "severity %r" % (len(gating), args.fail_on),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
