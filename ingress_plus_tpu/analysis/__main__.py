"""CLI: ``python -m ingress_plus_tpu.analysis``.

    python -m ingress_plus_tpu.analysis                    # bundled tree
    python -m ingress_plus_tpu.analysis --rules path/ --format sarif
    python -m ingress_plus_tpu.analysis --format json --output reports/RULECHECK.json
    python -m ingress_plus_tpu.analysis --conc             # concurrency analyzer
    python -m ingress_plus_tpu.analysis --evade            # evasion-closure analyzer
    python -m ingress_plus_tpu.analysis --evade --fail-on warning

All three analyzers share one convention: ``--fail-on`` severity grammar,
text/JSON/SARIF renderers (findings.py), and the exit-code contract —
0 when no unsuppressed finding reaches ``--fail-on`` severity (default:
error), 1 when one does, 2 on operational error (unreadable tree or
baseline).  The CI gates in tools/lint.py ride on exactly this contract.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ingress_plus_tpu.analysis import (
    BaselineError,
    SEVERITIES,
    run_concheck,
    run_evadecheck,
    run_rulecheck,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.analysis")
    which = ap.add_mutually_exclusive_group()
    which.add_argument("--conc", action="store_true",
                       help="run concheck (the serve-plane concurrency "
                            "analyzer) instead of rulecheck")
    which.add_argument("--evade", action="store_true",
                       help="run evadecheck (the evasion-closure "
                            "analyzer) instead of rulecheck")
    ap.add_argument("--rules", default=None,
                    help="rules tree (directory of *.conf, or an entry "
                         "config); default: the bundled CRS tree "
                         "(ignored by --conc)")
    ap.add_argument("--format", choices=["text", "json", "sarif"],
                    default="text")
    ap.add_argument("--baseline", default="auto",
                    help="suppression baseline JSON; 'auto' (default) "
                         "resolves the analyzer's checked-in baseline "
                         "(<rules>/rulecheck-baseline.json, "
                         "analysis/concheck-baseline.json, "
                         "analysis/evadecheck-baseline.json), "
                         "'none' disables suppression")
    ap.add_argument("--fail-on", choices=list(SEVERITIES),
                    default="error",
                    help="exit nonzero when an unsuppressed finding of "
                         "this severity (or worse) exists")
    ap.add_argument("--output", default=None,
                    help="also write the rendered report to this path")
    args = ap.parse_args(argv)

    from ingress_plus_tpu.compiler.seclang import SecLangError

    baseline = None if args.baseline == "none" else args.baseline
    if args.conc:
        tool, run = "concheck", lambda: run_concheck(
            baseline_path=baseline)
    elif args.evade:
        tool, run = "evadecheck", lambda: run_evadecheck(
            rules_path=args.rules, baseline_path=baseline)
    else:
        tool, run = "rulecheck", lambda: run_rulecheck(
            rules_path=args.rules, baseline_path=baseline)

    try:
        report = run()
    except (OSError, BaselineError, SecLangError, SyntaxError) as e:
        print("%s: %s" % (tool, e), file=sys.stderr)
        return 2

    out = {"text": report.to_text, "json": report.to_json,
           "sarif": report.to_sarif}[args.format]()
    if args.output:
        Path(args.output).parent.mkdir(parents=True, exist_ok=True)
        Path(args.output).write_text(out)
    print(out, end="")

    gating = report.gating(args.fail_on)
    if gating:
        print("%s: %d unsuppressed finding(s) at or above severity %r"
              % (tool, len(gating), args.fail_on), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
