"""Prometheus exposition hygiene lint (ISSUE 12 satellite).

The /metrics exposition grew hand-rolled across 11 PRs; nothing ever
checked it against the conventions scrapers and dashboards assume.
This checker parses one text-format scrape and enforces:

* **naming** — every metric carries the ``ipt_`` namespace prefix;
  counters end in ``_total`` (or ``_sum``/``_count`` — the cumulative
  microsecond counters like ``ipt_batch_us_sum`` predate this lint and
  follow the histogram-component convention);
* **metadata** — every emitted series has a ``# TYPE`` line, and every
  ``# TYPE`` a ``# HELP`` (the serve loop guarantees the pair via
  ``server._with_help``; the lint guards hand-added lines that bypass
  it);
* **bounded cardinality** — no label (other than ``le``) may exceed
  ``series_cap`` distinct values: the ``bounded_counter_series``
  budget is 30 + the "other" fold, so a per-rule or per-tenant series
  slipping into the exposition unfolded fails on its FIRST scrape, not
  after a dashboard dies;
* **histogram shape** — ``_bucket`` series carry ``le``, include
  ``+Inf``, and the cumulative counts are monotonic;
* **values parse** — every sample value is a float (NaN allowed: the
  efficiency gauges are NaN until the first dispatch by design).

``check_exposition`` returns finding strings (empty = clean); the
``promlint`` gate in tools/lint.py scrapes an in-process ServeLoop
after real traffic so the tenant/family folds are actually exercised.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Set, Tuple

#: bounded_counter_series caps at 30 verbatim + "other"; lanes and
#: stages are small closed sets.  Anything past this is an unbounded
#: label escaping the budget.
DEFAULT_SERIES_CAP = 40

_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')
_META_RE = re.compile(
    r"^# (?P<kind>TYPE|HELP) (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\s+(?P<rest>.*))?$")

#: suffixes that resolve a series back to its declared metric family
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")

#: counter naming: _total is the convention; _sum/_count are accepted
#: for cumulative histogram-component counters (documented above)
_COUNTER_SUFFIXES = ("_total", "_sum", "_count")


def _base_name(name: str, types: Dict[str, str]) -> str:
    """Resolve a series name to the declared metric it samples
    (histogram/summary components strip their suffix)."""
    if name in types:
        return name
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in types:
            return name[: -len(suf)]
    return name


def check_exposition(text: str,
                     prefix: str = "ipt_",
                     series_cap: int = DEFAULT_SERIES_CAP) -> List[str]:
    findings: List[str] = []
    types: Dict[str, str] = {}
    helps: Set[str] = set()
    #: (metric, label) -> distinct values
    label_values: Dict[Tuple[str, str], Set[str]] = {}
    #: histogram buckets: (metric, non-le labelset) -> [(le, value)]
    buckets: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    seen_series: Set[str] = set()

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            m = _META_RE.match(line)
            if m is None:
                findings.append("line %d: malformed comment %r"
                                % (lineno, line[:60]))
                continue
            if m.group("kind") == "TYPE":
                types[m.group("name")] = (m.group("rest") or "").strip()
            else:
                helps.add(m.group("name"))
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            findings.append("line %d: unparsable series line %r"
                            % (lineno, line[:60]))
            continue
        name = m.group("name")
        seen_series.add(name)
        try:
            val = float(m.group("value"))
        except ValueError:
            findings.append("line %d: %s value %r is not a float"
                            % (lineno, name, m.group("value")))
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        base = _base_name(name, types)
        for k, v in labels.items():
            if k == "le":
                continue
            label_values.setdefault((base, k), set()).add(v)
        if name.endswith("_bucket"):
            le = labels.get("le")
            if le is None:
                findings.append("line %d: %s has no le label"
                                % (lineno, name))
            else:
                key = (base, ",".join(
                    "%s=%s" % kv for kv in sorted(labels.items())
                    if kv[0] != "le"))
                lev = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(key, []).append((lev, val))

    # naming + metadata per declared or sampled metric family
    for name in sorted(seen_series):
        base = _base_name(name, types)
        if not name.startswith(prefix):
            findings.append("%s: missing the %s namespace prefix"
                            % (name, prefix))
        if base not in types:
            findings.append("%s: series has no # TYPE line" % name)
    for base, mtype in sorted(types.items()):
        if base not in helps:
            findings.append("%s: # TYPE without # HELP" % base)
        if mtype == "counter" and not base.endswith(_COUNTER_SUFFIXES):
            findings.append(
                "%s: TYPE counter but name lacks a _total/_sum/_count "
                "suffix" % base)

    # bounded cardinality: the first offender is the finding (the gate
    # fails fast — an unbounded per-rule/per-tenant series is a scrape
    # bomb, not a style nit)
    for (base, label), values in sorted(label_values.items()):
        if len(values) > series_cap:
            findings.append(
                "%s{%s=}: %d distinct label values (cap %d) — an "
                "unbounded series escaped the bounded_counter_series "
                "fold" % (base, label, len(values), series_cap))

    # histogram shape: +Inf present, cumulative counts monotonic
    for (base, labelset), pts in sorted(buckets.items()):
        pts.sort(key=lambda p: p[0])
        if not pts or pts[-1][0] != math.inf:
            findings.append("%s{%s}: histogram without a +Inf bucket"
                            % (base, labelset))
        vals = [v for _, v in pts]
        if any(b < a for a, b in zip(vals, vals[1:])):
            findings.append("%s{%s}: non-monotonic cumulative bucket "
                            "counts" % (base, labelset))
    return findings
