"""Prometheus exposition hygiene lint (ISSUE 12 satellite).

The /metrics exposition grew hand-rolled across 11 PRs; nothing ever
checked it against the conventions scrapers and dashboards assume.
This checker decodes one text-format scrape (via the shared
``utils/promparse`` parser — the same decode path the fleet
aggregator merges through, so what the lint accepts is exactly what
the fleet plane can aggregate) and enforces:

* **naming** — every metric carries the ``ipt_`` namespace prefix;
  counters end in ``_total`` (or ``_sum``/``_count`` — the cumulative
  microsecond counters like ``ipt_batch_us_sum`` predate this lint and
  follow the histogram-component convention);
* **metadata** — every emitted series has a ``# TYPE`` line, and every
  ``# TYPE`` a ``# HELP`` (the serve loop guarantees the pair via
  ``server._with_help``; the lint guards hand-added lines that bypass
  it);
* **bounded cardinality** — no label (other than ``le``) may exceed
  ``series_cap`` distinct values: the ``bounded_counter_series``
  budget is 30 + the "other" fold, so a per-rule or per-tenant series
  slipping into the exposition unfolded fails on its FIRST scrape, not
  after a dashboard dies;
* **aggregation safety** (ISSUE 18) — counters and gauges must be
  summable across instances: a node-unique label (``instance``,
  ``host``, ``pid``, ...) on a per-node exposition makes the fleet
  sum double-count identity instead of traffic.  ``fleet=True``
  relaxes the check for the labels the aggregator itself adds
  deliberately (``node=`` per-node detail, ``agg=`` rollups — bounded
  by fleet size, which the cardinality cap still polices);
* **histogram shape** — ``_bucket`` series carry ``le``, include
  ``+Inf``, and the cumulative counts are monotonic;
* **values parse** — every sample value is a float (NaN allowed: the
  efficiency gauges are NaN until the first dispatch by design).

``check_exposition`` returns finding strings (empty = clean); the
``promlint`` gate in tools/lint.py scrapes an in-process ServeLoop
after real traffic so the tenant/family folds are actually exercised,
and the ``fleetgate`` gate runs the same check (``fleet=True``) over
the aggregated ``/fleet/metrics`` exposition.
"""

from __future__ import annotations

import math
from typing import Dict, List, Set, Tuple

from ingress_plus_tpu.utils.promparse import (
    base_name, group_key, parse_exposition)

#: bounded_counter_series caps at 30 verbatim + "other"; lanes and
#: stages are small closed sets.  Anything past this is an unbounded
#: label escaping the budget.
DEFAULT_SERIES_CAP = 40

#: counter naming: _total is the convention; _sum/_count are accepted
#: for cumulative histogram-component counters (documented above)
_COUNTER_SUFFIXES = ("_total", "_sum", "_count")

#: labels that identify the emitting node rather than the traffic —
#: a counter/gauge split on one cannot be summed across the fleet
NODE_IDENTITY_LABELS = ("instance", "node", "host", "hostname",
                       "pod", "pid")

#: labels the fleet aggregator adds on purpose (per-node detail +
#: rollup axis); only legitimate on the AGGREGATED exposition
_FLEET_LABELS = ("node", "agg")


def check_exposition(text: str,
                     prefix: str = "ipt_",
                     series_cap: int = DEFAULT_SERIES_CAP,
                     fleet: bool = False) -> List[str]:
    exp = parse_exposition(text)
    findings: List[str] = list(exp.errors)
    types = exp.types
    helps = set(exp.helps)
    #: (metric, label) -> distinct values
    label_values: Dict[Tuple[str, str], Set[str]] = {}
    #: histogram buckets: (metric, non-le labelset) -> [(le, value)]
    buckets: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    seen_series: Set[str] = set()

    for s in exp.samples:
        seen_series.add(s.name)
        base = base_name(s.name, types)
        for k, v in s.labels.items():
            if k == "le":
                continue
            label_values.setdefault((base, k), set()).add(v)
        if s.name.endswith("_bucket"):
            le = s.labels.get("le")
            if le is None:
                findings.append("line %d: %s has no le label"
                                % (s.lineno, s.name))
            else:
                key = (base, group_key(s.labels))
                lev = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(key, []).append((lev, s.value))

    # naming + metadata per declared or sampled metric family
    for name in sorted(seen_series):
        base = base_name(name, types)
        if not name.startswith(prefix):
            findings.append("%s: missing the %s namespace prefix"
                            % (name, prefix))
        if base not in types:
            findings.append("%s: series has no # TYPE line" % name)
    for base, mtype in sorted(types.items()):
        if base not in helps:
            findings.append("%s: # TYPE without # HELP" % base)
        if mtype == "counter" and not base.endswith(_COUNTER_SUFFIXES):
            findings.append(
                "%s: TYPE counter but name lacks a _total/_sum/_count "
                "suffix" % base)

    # bounded cardinality: the first offender is the finding (the gate
    # fails fast — an unbounded per-rule/per-tenant series is a scrape
    # bomb, not a style nit)
    for (base, label), values in sorted(label_values.items()):
        if len(values) > series_cap:
            findings.append(
                "%s{%s=}: %d distinct label values (cap %d) — an "
                "unbounded series escaped the bounded_counter_series "
                "fold" % (base, label, len(values), series_cap))

    # aggregation safety (ISSUE 18): counters/gauges keyed by node
    # identity cannot be summed across the fleet — the merge would
    # count nodes, not traffic.  Histograms are exempt (their le axis
    # merges bucket-wise); the aggregator's own node=/agg= labels are
    # legitimate only on the aggregated exposition (fleet=True).
    for (base, label), _values in sorted(label_values.items()):
        if label not in NODE_IDENTITY_LABELS:
            continue
        if fleet and label in _FLEET_LABELS:
            continue
        if types.get(base) == "histogram":
            continue
        findings.append(
            "%s{%s=}: node-identity label breaks cross-instance "
            "aggregation (counters/gauges must be summable across "
            "the fleet)" % (base, label))

    # histogram shape: +Inf present, cumulative counts monotonic
    for (base, labelset), pts in sorted(buckets.items()):
        pts.sort(key=lambda p: p[0])
        if not pts or pts[-1][0] != math.inf:
            findings.append("%s{%s}: histogram without a +Inf bucket"
                            % (base, labelset))
        vals = [v for _, v in pts]
        if any(b < a for a, b in zip(vals, vals[1:])):
            findings.append("%s{%s}: non-monotonic cumulative bucket "
                            "counts" % (base, labelset))
    return findings
