"""Brute-force / enumeration rate detection — the wruby `brute-detect`†
script analog (SURVEY.md §2.3).

The reference's cron script scans the postanalytics DB for high-rate
request streams against auth-ish endpoints and raises "brute" attacks;
its sibling heuristic raises "dirbust" (forced browsing) when one
source fans out over many distinct paths.  Here both detectors run
inside the exporter drain (same cadence position: off the hot path,
over queued hits) using sliding windows keyed per application (tenant)
and source:

* ``brute``  — per (tenant, client, path): ≥ threshold requests to one
  auth-shaped path inside the window.  Consumes ALL hits (attack or
  not — credential stuffing is mostly *clean* requests at high rate),
  which is why Hit records are enqueued for every request when a
  PostChannel is active, not only for attacks.
* ``dirbust`` — per (tenant, client): ≥ threshold DISTINCT paths inside
  the window (scanner/wordlist sweeps; auth-shaped or not).

Emitted attacks carry evidence in ``sample_points`` (the matched-points
analog for rate detections: the window, the count, the path) so the
attack export tells the operator exactly what tripped, like a rule hit
does.  Thresholds are deployment-configurable (serve CLI:
``--brute-threshold``/``--brute-window-s``/``--dirbust-threshold``).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Sequence, Tuple

from ingress_plus_tpu.post.queue import Hit
from ingress_plus_tpu.post.aggregate import Attack

# path substrings that mark an auth-shaped target (the reference keys on
# configured "protected" endpoints; this default list mirrors its docs)
AUTH_MARKERS = ("login", "signin", "sign-in", "auth", "password", "passwd",
                "session", "token", "register", "wp-login")


def _path_key(uri: str) -> str:
    path = uri.split("?", 1)[0].lower()
    return path[:128]


def is_auth_path(uri: str) -> bool:
    p = _path_key(uri)
    return any(m in p for m in AUTH_MARKERS)


@dataclass
class BruteConfig:
    window_s: float = 60.0
    threshold: int = 25        # requests per window per (tenant,client,path)
    auth_only: bool = True     # rate-watch only auth-shaped paths
    #: forced-browsing sweep: distinct paths per (tenant, client) window;
    #: 0 disables the dirbust detector
    dirbust_threshold: int = 50
    dirbust_window_s: float = 60.0


class BruteDetector:
    def __init__(self, config: BruteConfig | None = None):
        self.config = config or BruteConfig()
        self._windows: Dict[Tuple[int, str, str], Deque[float]] = {}
        #: dirbust state: per (tenant, client) deque of (ts, path) plus
        #: an incremental path→count map so the distinct-path count is
        #: O(1) per hit (review finding: rebuilding the set per hit made
        #: the exporter drain O(n²) against a single chatty client)
        self._sweeps: Dict[Tuple[int, str], Deque[Tuple[float, str]]] = {}
        self._sweep_counts: Dict[Tuple[int, str], Dict[str, int]] = {}
        # keys already reported this window, so one burst → one attack
        self._reported: Dict[tuple, float] = {}

    def observe(self, hits: Sequence[Hit]) -> List[Attack]:
        """Feed a drained batch of hits; returns newly detected brute /
        dirbust attacks (one per offending key per window)."""
        cfg = self.config
        out: List[Attack] = []
        for hit in hits:
            out.extend(self._observe_brute(hit, cfg))
            if cfg.dirbust_threshold > 0:
                out.extend(self._observe_dirbust(hit, cfg))
        self._gc(time.time())
        return out

    def _observe_brute(self, hit: Hit, cfg: BruteConfig) -> List[Attack]:
        if cfg.auth_only and not is_auth_path(hit.uri):
            return []
        path = _path_key(hit.uri)
        key = (hit.tenant, hit.client, path)
        dq = self._windows.setdefault(key, deque())
        dq.append(hit.ts)
        while dq and hit.ts - dq[0] > cfg.window_s:
            dq.popleft()
        if len(dq) < cfg.threshold:
            return []
        last = self._reported.get(("b",) + key, -1e18)
        if hit.ts - last <= cfg.window_s:
            return []
        self._reported[("b",) + key] = hit.ts
        atk = Attack(tenant=hit.tenant, client=hit.client,
                     attack_class="brute", first_ts=dq[0], last_ts=hit.ts)
        atk.count = len(dq)
        atk.sample_uris = [hit.uri[:256]]
        atk.sample_request_ids = [hit.request_id]
        # rate evidence in the matched-points shape the export already
        # carries for rule hits (rule_id 0 = heuristic, not a rule)
        atk.sample_points = [{
            "rule_id": 0, "var": "RATE:%s" % path,
            "value": "%d requests in %.0fs from %s"
                     % (len(dq), cfg.window_s, hit.client)}]
        return [atk]

    def _observe_dirbust(self, hit: Hit, cfg: BruteConfig) -> List[Attack]:
        key = (hit.tenant, hit.client)
        dq = self._sweeps.setdefault(key, deque())
        counts = self._sweep_counts.setdefault(key, {})
        path = _path_key(hit.uri)
        dq.append((hit.ts, path))
        counts[path] = counts.get(path, 0) + 1
        while dq and hit.ts - dq[0][0] > cfg.dirbust_window_s:
            _ts, old = dq.popleft()
            c = counts.get(old, 0) - 1
            if c <= 0:
                counts.pop(old, None)
            else:
                counts[old] = c
        distinct = len(counts)
        if distinct < cfg.dirbust_threshold:
            return []
        last = self._reported.get(("d",) + key, -1e18)
        if hit.ts - last <= cfg.dirbust_window_s:
            return []
        self._reported[("d",) + key] = hit.ts
        atk = Attack(tenant=hit.tenant, client=hit.client,
                     attack_class="dirbust", first_ts=dq[0][0],
                     last_ts=hit.ts)
        # count = DISTINCT paths (what crossed dirbust_threshold), not
        # total window hits — a chatty client re-fetching each path
        # would otherwise export an inflated sweep size (ADVICE r05)
        atk.count = distinct
        atk.sample_uris = sorted(counts)[:Attack.MAX_SAMPLES]
        atk.sample_request_ids = [hit.request_id]
        atk.sample_points = [{
            "rule_id": 0, "var": "SWEEP",
            "value": "%d distinct paths in %.0fs from %s"
                     % (distinct, cfg.dirbust_window_s, hit.client)}]
        return [atk]

    def _gc(self, now: float) -> None:
        """Bound memory: drop idle windows (no hit for 2 windows)."""
        dead = [k for k, dq in self._windows.items()
                if not dq or now - dq[-1] > 2 * self.config.window_s]
        for k in dead:
            self._windows.pop(k, None)
            self._reported.pop(("b",) + k, None)
        dead2 = [k for k, dq in self._sweeps.items()
                 if not dq or now - dq[-1][0]
                 > 2 * self.config.dirbust_window_s]
        for k in dead2:
            self._sweeps.pop(k, None)
            self._sweep_counts.pop(k, None)
            self._reported.pop(("d",) + k, None)
