"""Brute-force rate detection — the wruby `brute-detect`† script analog
(SURVEY.md §2.3).

The reference's cron script scans the postanalytics DB for high-rate
request streams against auth-ish endpoints and raises "brute" attacks.
Here the detector runs inside the exporter drain (same cadence position:
off the hot path, over queued hits) using per-(tenant, client, path-key)
sliding windows.  It consumes ALL hits (attack or not — brute force is
mostly *clean* requests at high rate), which is why Hit records are
enqueued for every request when a PostChannel is active, not only for
attacks.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Sequence, Tuple

from ingress_plus_tpu.post.queue import Hit
from ingress_plus_tpu.post.aggregate import Attack

# path substrings that mark an auth-shaped target (the reference keys on
# configured "protected" endpoints; this default list mirrors its docs)
AUTH_MARKERS = ("login", "signin", "sign-in", "auth", "password", "passwd",
                "session", "token", "register", "wp-login")


def _path_key(uri: str) -> str:
    path = uri.split("?", 1)[0].lower()
    return path[:128]


def is_auth_path(uri: str) -> bool:
    p = _path_key(uri)
    return any(m in p for m in AUTH_MARKERS)


@dataclass
class BruteConfig:
    window_s: float = 60.0
    threshold: int = 25        # requests per window per (tenant,client,path)
    auth_only: bool = True     # rate-watch only auth-shaped paths


class BruteDetector:
    def __init__(self, config: BruteConfig | None = None):
        self.config = config or BruteConfig()
        self._windows: Dict[Tuple[int, str, str], Deque[float]] = {}
        # keys already reported this window, so one burst → one attack
        self._reported: Dict[Tuple[int, str, str], float] = {}

    def observe(self, hits: Sequence[Hit]) -> List[Attack]:
        """Feed a drained batch of hits; returns newly detected brute
        attacks (class "brute", one per offending key per window)."""
        cfg = self.config
        out: List[Attack] = []
        for hit in hits:
            if cfg.auth_only and not is_auth_path(hit.uri):
                continue
            key = (hit.tenant, hit.client, _path_key(hit.uri))
            dq = self._windows.setdefault(key, deque())
            dq.append(hit.ts)
            while dq and hit.ts - dq[0] > cfg.window_s:
                dq.popleft()
            if len(dq) >= cfg.threshold:
                last = self._reported.get(key, -1e18)
                if hit.ts - last > cfg.window_s:
                    self._reported[key] = hit.ts
                    atk = Attack(tenant=hit.tenant, client=hit.client,
                                 attack_class="brute", first_ts=dq[0],
                                 last_ts=hit.ts)
                    atk.count = len(dq)
                    atk.sample_uris = [hit.uri[:256]]
                    atk.sample_request_ids = [hit.request_id]
                    out.append(atk)
        self._gc(time.time())
        return out

    def _gc(self, now: float) -> None:
        """Bound memory: drop idle windows (no hit for 2 windows)."""
        dead = [k for k, dq in self._windows.items()
                if not dq or now - dq[-1] > 2 * self.config.window_s]
        for k in dead:
            self._windows.pop(k, None)
            self._reported.pop(k, None)
