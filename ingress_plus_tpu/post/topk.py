"""Top-K heavy hitters — the space-saving sketch (Metwally et al.,
"Efficient computation of frequent and top-k elements in data streams").

The postanalytics plane wants "which paths / tenants are drawing the
attacks" without keeping a counter per distinct key — a scanner sweep
generates unbounded distinct URIs, so an exact dict is exactly the
unbounded-cardinality hazard the NodeCounters caps exist to prevent.
The sketch keeps at most ``capacity`` tracked keys: an untracked key
evicts the current minimum and INHERITS its count (the classic
over-estimate; the inherited amount is kept per entry as ``max_error``
so consumers see the bound, not a false precision).

Guarantees (from the paper): any key with true frequency greater than
the minimum tracked count is in the sketch, and every reported count
over-estimates by at most that entry's ``max_error``.

Served under ``/wallarm-status`` as ``top_attacked`` (post/channel.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ingress_plus_tpu.utils.trace import named_lock


class SpaceSaving:
    """Bounded top-K counter sketch.  O(capacity) eviction scan on a
    miss-while-full — capacity is small (default 32), and offers happen
    once per attack verdict, not per request."""

    def __init__(self, capacity: int = 32):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._counts: Dict[str, int] = {}
        self._error: Dict[str, int] = {}
        self._lock = named_lock("SpaceSaving._lock")

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, inc: int = 1) -> None:
        with self._lock:
            if key in self._counts:
                self._counts[key] += inc
                return
            if len(self._counts) < self.capacity:
                self._counts[key] = inc
                self._error[key] = 0
                return
            victim = min(self._counts, key=self._counts.__getitem__)
            floor = self._counts.pop(victim)
            self._error.pop(victim, None)
            # the newcomer inherits the evicted minimum: its true count
            # is somewhere in (inc, floor + inc] — floor is the error
            self._counts[key] = floor + inc
            self._error[key] = floor

    def summary(self) -> dict:
        """Sketch occupancy metadata — capacity vs tracked keys and the
        total tracked mass — so status views (``/tenants``,
        ``/wallarm-status``) can render ``items()`` next to the bound
        they were computed under instead of implying exactness."""
        with self._lock:
            return {"capacity": self.capacity,
                    "tracked": len(self._counts),
                    "total": sum(self._counts.values())}

    def items(self, n: Optional[int] = None) -> List[dict]:
        """Tracked keys, count-descending: ``{key, count, max_error}``
        — ``count`` may over-estimate by up to ``max_error``."""
        with self._lock:
            rows = sorted(self._counts.items(),
                          key=lambda kv: (-kv[1], kv[0]))
            return [{"key": k, "count": c,
                     "max_error": self._error.get(k, 0)}
                    for k, c in (rows[:n] if n else rows)]
