"""Export + ruleset-sync loops — the cron sidecar analog (SURVEY.md §3.4).

``Exporter`` is the `export-attacks`/`export-counters`† cadence: a
background thread that periodically drains the HitQueue, folds hits into
attacks (aggregate.py), runs brute detection, and delivers them to a sink.
The reference POSTs to the Wallarm cloud over HTTPS; this build has zero
egress, so the default sink is an append-only jsonl spool directory, with
an optional HTTP hook for a reachable collector.  Delivery failure never
raises into the serve path — failed batches are re-spooled and counted.

``RulesetWatcher`` is the `sync-node`† analog: the reference cron pulls a
fresh proton.db and hot-swaps the engine's ruleset.  Here: watch a
directory for compiled-ruleset artifacts (compiler/ruleset.py save()
format, `<name>.iptr/` with meta.json) newer than the running version and
POST the serve loop's ``/configuration/ruleset`` endpoint, which performs
the double-buffered on-device swap with no serve gap.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Callable, List, Optional

from ingress_plus_tpu.post.aggregate import aggregate_attacks
from ingress_plus_tpu.post.brute import BruteDetector
from ingress_plus_tpu.post.queue import HitQueue
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.trace import EV_EXPORT, flight


class Exporter:
    def __init__(
        self,
        queue: HitQueue,
        spool_dir: Optional[str] = None,
        http_url: Optional[str] = None,
        interval_s: float = 5.0,
        gap_s: float = 60.0,
        brute: Optional[BruteDetector] = None,
        max_drain: int = 100_000,
        on_export: Optional[Callable[[List[dict]], None]] = None,
        backoff_max_s: float = 300.0,
        max_spool_bytes: int = 256 << 20,
        jitter_seed: int = 0,
    ):
        self.queue = queue
        self.spool_dir = Path(spool_dir) if spool_dir else None
        self.http_url = http_url
        self.interval_s = interval_s
        self.gap_s = gap_s
        self.brute = brute
        self.max_drain = max_drain
        #: delivered-records hook (PostChannel feeds NodeCounters so
        #: brute/dirbust events show in /wallarm-status per application)
        self.on_export = on_export
        self.exported_attacks = 0
        self.export_errors = 0
        # failure backoff (docs/ROBUSTNESS.md): a down collector used to
        # be re-hit on the fixed interval forever — retries now back off
        # exponentially with jitter up to a ceiling, and delivery
        # success snaps back to the base interval
        self.backoff_max_s = backoff_max_s
        self.consecutive_failures = 0
        self.backoff_s = 0.0   # the currently applied backoff (status)
        self._rng = random.Random(jitter_seed)
        # spool bound: a long collector outage must not fill the disk —
        # oldest spool files are dropped (and counted) to fit the cap
        self.max_spool_bytes = max_spool_bytes
        self.spool_dropped_files = 0
        self.spool_dropped_bytes = 0
        self.spool_dropped_records = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.spool_dir:
            self.spool_dir.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------ core

    def flush_once(self) -> int:
        """One export cycle; returns number of attacks delivered."""
        hits = self.queue.drain(self.max_drain)
        if not hits:
            return 0
        attacks = aggregate_attacks(hits, gap_s=self.gap_s)
        if self.brute is not None:
            attacks.extend(self.brute.observe(hits))
        if not attacks:
            return 0
        records = [a.to_dict() for a in attacks]
        ok = self._deliver(records)
        if ok:
            self.consecutive_failures = 0
            self.exported_attacks += len(records)
            if self.on_export is not None:
                try:
                    self.on_export(records)
                except Exception:
                    pass   # counters are best-effort, never break export
            return len(records)
        self.export_errors += 1
        self.consecutive_failures += 1
        return 0

    def next_wait_s(self) -> float:
        """Sleep until the next export attempt: the base interval while
        healthy; exponential backoff with jitter (x[1.0, 1.5)) and a
        hard ceiling after consecutive delivery failures — a down
        collector is probed ever more gently, and the jitter keeps a
        fleet of nodes from re-hitting it in lockstep."""
        if not self.consecutive_failures:
            return self.interval_s
        base = min(self.interval_s * (2 ** (self.consecutive_failures - 1)),
                   self.backoff_max_s)
        return min(base * (1.0 + 0.5 * self._rng.random()),
                   self.backoff_max_s)

    def _enforce_spool_bound(self, incoming: int, keep: Path) -> bool:
        """Drop-oldest spool files until ``incoming`` more bytes fit
        under ``max_spool_bytes`` (the current writer's own file is
        dropped last).  False = the batch cannot fit even after
        dropping everything else — the caller skips the write and
        counts the records."""
        if self.max_spool_bytes <= 0 or self.spool_dir is None:
            return True
        try:
            files = []
            for f in self.spool_dir.glob("attacks*"):
                if f.is_file():
                    st = f.stat()
                    files.append((st.st_mtime, st.st_size, f))
        except OSError:
            return True
        total = sum(sz for _, sz, _ in files)
        if total + incoming <= self.max_spool_bytes:
            return True
        # oldest first; the live file we are about to append to goes last
        files.sort(key=lambda t: (t[2] == keep, t[0]))
        for _, sz, f in files:
            if total + incoming <= self.max_spool_bytes:
                break
            try:
                f.unlink()
            except OSError:
                continue
            total -= sz
            self.spool_dropped_files += 1
            self.spool_dropped_bytes += sz
        return total + incoming <= self.max_spool_bytes

    def _deliver(self, records: List[dict]) -> bool:
        delivered = False
        if self.spool_dir is not None:
            try:
                # one spool file per exporter process: the rendered
                # Deployment mounts a single spool emptyDir into N serve
                # containers, so a shared attacks.jsonl would interleave
                # buffered appends and tear lines.  Keyed by pid there is
                # exactly one writer per file.
                path = self.spool_dir / ("attacks.%d.jsonl" % os.getpid())
                payload = "".join(json.dumps(r) + "\n" for r in records)
                if self._enforce_spool_bound(len(payload), path):
                    with path.open("a") as f:
                        f.write(payload)
                    delivered = True
                else:
                    # the batch alone exceeds the bound: counted loss,
                    # never unbounded disk
                    self.spool_dropped_records += len(records)
            except OSError:
                pass
        if self.http_url:
            try:
                # export_5xx fault site (utils/faults.py): a collector
                # answering 5xx raises exactly like a dead one
                faults.raise_if("export_5xx")
                req = urllib.request.Request(
                    self.http_url, data=json.dumps(records).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=5).read()
                delivered = True
            except Exception:
                # cloud unreachable: spool already has the data (if
                # configured); otherwise count the loss, never raise
                pass
        return delivered

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ipt-exporter", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        wait = self.interval_s
        flight.register_thread("exporter")
        while not self._stop.wait(wait):
            flight.begin(EV_EXPORT, cycle=0)
            try:
                self.flush_once()
            except Exception:
                self.export_errors += 1
                self.consecutive_failures += 1
            finally:
                flight.end(EV_EXPORT, cycle=0)
            wait = self.next_wait_s()
            self.backoff_s = wait if self.consecutive_failures else 0.0

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        try:
            self.flush_once()
        except Exception:
            self.export_errors += 1


class RulesetWatcher:
    """Poll ``artifact_dir`` for compiled-ruleset artifacts and hot-swap
    the serve loop when a version not yet running appears.

    Artifact layout (compiler/ruleset.py save()): ``<dir>/<name>.npz`` +
    ``<dir>/<name>.json`` whose JSON carries a content-hash ``version``.
    Newest meta mtime wins.  The swap itself
    is the serve loop's job (double-buffered device puts); this watcher
    only triggers it — exactly the reference's cron→module split.
    """

    def __init__(self, artifact_dir: str, serve_http: str,
                 interval_s: float = 10.0,
                 poster: Optional[Callable[[str, dict], dict]] = None):
        self.artifact_dir = Path(artifact_dir)
        self.serve_http = serve_http  # host:port
        self.interval_s = interval_s
        self.current_version: Optional[str] = None
        self.swaps = 0
        self.errors = 0
        # versions the serve loop REJECTED (guarded-rollout admission
        # gate 4xx, control/rollout.py): re-pushing one would re-run the
        # whole gate — compile smoke + corpus replay — every poll tick
        # forever; a rejected pack stays skipped until a NEW artifact
        # version appears
        self.rejected_versions: set = set()
        self._poster = poster or self._http_post
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _http_post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            "http://%s%s" % (self.serve_http, path),
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read() or b"{}")

    def newest_artifact(self) -> Optional[Path]:
        """Base path (no suffix) of the newest complete artifact pair."""
        if not self.artifact_dir.is_dir():
            return None
        cands = [p for p in self.artifact_dir.glob("*.json")
                 if p.with_suffix(".npz").is_file()]
        if not cands:
            return None
        return max(cands, key=lambda p: p.stat().st_mtime).with_suffix("")

    def check_once(self) -> bool:
        """Returns True if a swap was triggered."""
        art = self.newest_artifact()
        if art is None:
            return False
        try:
            version = json.loads(
                art.with_suffix(".json").read_text()).get("version")
        except (OSError, json.JSONDecodeError):
            self.errors += 1
            return False
        if version is None or version == self.current_version \
                or version in self.rejected_versions:
            return False
        try:
            out = self._poster("/configuration/ruleset", {"path": str(art)})
        except urllib.error.HTTPError as e:
            self.errors += 1
            if 400 <= e.code < 500 and e.code != 409:
                # DETERMINISTIC rejection (admission gate / unloadable
                # artifact): retrying every tick would re-run the whole
                # gate forever — remember the version until a new
                # artifact lands.  Transient refusals must stay
                # retryable: 409 (no controller / conflict) and the
                # 422 whose body says another rollout is in progress.
                try:
                    reason = json.loads(e.read() or b"{}").get("reason")
                except Exception:
                    reason = None
                if reason != "rollout_in_progress":
                    self.rejected_versions.add(version)
            return False
        except Exception:
            self.errors += 1
            return False
        # staged responses carry the CANDIDATE version ("candidate"/
        # "staged"); force responses carry "ruleset".  Either way the
        # push landed — don't re-push the same artifact next tick.
        self.current_version = out.get("ruleset") \
            or out.get("candidate") or version
        self.swaps += 1
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ipt-ruleset-watch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:
                self.errors += 1

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


# --------------------------------------------------------------- CLI
# The standalone consolidator the postanalytics Deployment runs (the
# cron-sidecar process of the reference†: read the queue store, ship
# attacks to the collector, keep nothing on failure loss-y).

def consolidate_once(spool_dir: str | Path, url: Optional[str] = None,
                     keep: bool = True) -> int:
    """Claim the current attacks.jsonl (atomic rename), forward/fold it.

    Returns records processed.  On delivery failure the claimed file is
    left in place (`*.sending`) and retried next cycle — at-least-once,
    like the reference's export scripts.
    """
    spool = Path(spool_dir)
    out = spool / "consolidated"
    out.mkdir(exist_ok=True)
    n = 0
    # retry leftovers first, then claim the live spool files (one per
    # writer process, plus the legacy shared name)
    seq = 0
    for live in sorted(spool.glob("attacks*.jsonl")):
        claimed = spool / ("attacks.%d_%d.sending"
                           % (int(time.time() * 1e6), seq))
        seq += 1
        try:
            live.rename(claimed)
        except OSError:
            pass
    def _unlink_claimed(f: Path, keep_from: int, nread: int) -> None:
        """Unlink a processed .sending file WITHOUT dropping bytes a
        still-in-flight writer appended after our read (round-2 advisor:
        the claim-rename can land mid-append; the writer's completed
        tail would die with the unlink).  Only ONE burst can race — the
        writer re-opens by name each cycle and the name now points to a
        fresh live file — so: wait for the size to go stable (bounded),
        then requeue everything from ``keep_from`` (the last PARSED line
        boundary, so a record straddling the read boundary is requeued
        whole, torn prefix included — round-3 review) as a new .sending.
        """
        try:
            size = f.stat().st_size
            # wait for STABILITY (size stops changing), not equality
            # with nread — once a tail exists the size can never re-equal
            # nread, and an in-flight flush straddling the window would
            # still be torn; no tail costs zero sleeps
            for _ in range(5):
                if size == nread:
                    break
                time.sleep(0.01)
                prev, size = size, f.stat().st_size
                if size == prev:
                    break
            if size > nread:
                # bytes WERE appended after our read: requeue from the
                # line boundary so the straddled record survives whole.
                # (With no append, a torn final fragment is dropped as
                # before — requeueing it unconditionally would loop
                # forever on a fragment no writer will ever complete.)
                with f.open("rb") as fh:
                    fh.seek(keep_from)
                    tail = fh.read()
                if tail.strip():
                    requeued = spool / ("attacks.%d_tail.sending"
                                        % int(time.time() * 1e6))
                    requeued.write_bytes(tail)
            f.unlink()
        except OSError:
            pass  # transient; the whole file is retried next cycle

    for f in sorted(spool.glob("attacks.*.sending")):
        try:
            raw = f.read_bytes()
        except OSError:
            continue  # transient; retried next cycle
        # start of the trailing incomplete line (== len(raw) if none):
        # the requeue boundary for a record straddling this read
        boundary = len(raw) if raw.endswith(b"\n") else raw.rfind(b"\n") + 1
        text = raw.decode("utf-8", "replace")
        # salvage line-by-line: a torn line from a partial append must not
        # discard the batch's valid records (at-least-once contract)
        records = []
        for line in text.splitlines():
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                pass
        if not records:
            _unlink_claimed(f, boundary, len(raw))
            continue
        if url:
            try:
                req = urllib.request.Request(
                    url, data=json.dumps(records).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=10).read()
            except Exception:
                continue  # left as .sending → retried next cycle
        if keep:
            with (out / "attacks.jsonl").open("a") as fh:
                for r in records:
                    fh.write(json.dumps(r) + "\n")
        _unlink_claimed(f, boundary, len(raw))
        n += len(records)
    return n


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(prog="ingress_plus_tpu.post.export")
    ap.add_argument("--spool-dir", required=True)
    ap.add_argument("--url", default=None,
                    help="HTTP collector; default keeps a consolidated "
                         "jsonl under <spool>/consolidated/")
    ap.add_argument("--interval-s", type=float, default=5.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args(argv)
    while True:
        # with a collector the records live there; keeping a local copy
        # too would grow the pod's emptyDir without bound
        n = consolidate_once(args.spool_dir, url=args.url,
                             keep=not args.url)
        if n:
            print("consolidated %d attack records" % n, flush=True)
        if args.once:
            break
        time.sleep(args.interval_s)


if __name__ == "__main__":
    main()
