"""Node counters — the `/wallarm-status`† counters endpoint analog
(SURVEY.md §3.5): the JSON the reference's collectd sidecar scrapes and
forwards to the cloud.  Served by the serve loop at ``/wallarm-status``.
"""

from __future__ import annotations

import time
from typing import Dict

from ingress_plus_tpu.utils.trace import named_lock


def _bump(d: Dict, key, cap: int, overflow) -> None:
    """Increment ``d[key]`` under a fixed key budget (the overflow
    bucket counts toward it): new keys past the budget fold into
    ``overflow`` — a hostile tenant/class stream can therefore never
    grow the status JSON without limit.  Existing keys keep counting."""
    if key in d:
        d[key] += 1
    elif len(d) < cap - (0 if overflow in d else 1):
        d[key] = 1
    else:
        d[overflow] = d.get(overflow, 0) + 1


class NodeCounters:
    """Monotonic counters, thread-safe, cheap enough for the verdict path
    (single lock, integer adds).  Keyed dicts are cardinality-capped
    (``MAX_*_KEYS`` + an ``other``/-1 overflow bucket)."""

    MAX_CLASS_KEYS = 64        # attack classes are a small closed set
    # tenants are system-bounded at control/sync.py MAX_TENANTS (4096):
    # the budget must cover every legal tenant (+1 for the overflow
    # slot) or late-arriving tenants lose attribution permanently
    # (_bump never evicts); export_events keys the composite
    # "class:tenant" space, so it gets a multiple of that bound
    MAX_TENANT_KEYS = 4096 + 1
    MAX_EXPORT_KEYS = 4 * 4096

    def __init__(self):
        self._lock = named_lock("NodeCounters._lock")
        self.started = time.time()
        self.requests = 0
        self.attacks = 0
        self.blocked = 0
        self.monitored = 0         # attacks seen in monitoring mode
        self.fail_open = 0
        self.by_class: Dict[str, int] = {}
        self.by_tenant: Dict[int, int] = {}   # attacks per tenant
        #: admission-level abuse visibility (ISSUE 10): which tenants'
        #: verdicts came back shed/fail-open or degraded (tenant-guard
        #: quarantine, overload) — postanalytics' view of the serve
        #: plane's tenant-isolation decisions.  Same cardinality cap +
        #: -1 overflow bucket as by_tenant.
        self.shed_by_tenant: Dict[int, int] = {}
        self.degraded_by_tenant: Dict[int, int] = {}
        #: EXPORTED ATTACK RECORDS by class (unit: aggregated attacks,
        #: not requests — by_class above counts per-request verdicts).
        #: This is the only place brute/dirbust rate detections appear:
        #: they have no per-request verdict, so the serve-path record()
        #: never sees them.  Keyed "class" and "class:tenant".
        self.export_events: Dict[str, int] = {}

    def record(self, *, attack: bool, blocked: bool, fail_open: bool,
               classes, tenant: int, mode: int,
               degraded: bool = False) -> None:
        with self._lock:
            self.requests += 1
            if fail_open:
                self.fail_open += 1
                _bump(self.shed_by_tenant, tenant,
                      self.MAX_TENANT_KEYS, -1)
            if degraded and not fail_open:
                # fail-open already counted above; a degraded-but-served
                # verdict (prefilter-only rung) books here
                _bump(self.degraded_by_tenant, tenant,
                      self.MAX_TENANT_KEYS, -1)
            if attack:
                self.attacks += 1
                if blocked:
                    self.blocked += 1
                elif mode == 1:
                    self.monitored += 1
                for c in classes:
                    _bump(self.by_class, c, self.MAX_CLASS_KEYS, "other")
                _bump(self.by_tenant, tenant, self.MAX_TENANT_KEYS, -1)

    def record_export_events(self, records) -> None:
        """Fold exporter-delivered attack records (incl. brute/dirbust)
        into the per-application counters the reference's collectd
        scrape forwards."""
        with self._lock:
            for r in records:
                cls = r.get("class", "unclassified")
                _bump(self.export_events, cls,
                      self.MAX_EXPORT_KEYS, "other")
                key = "%s:%s" % (cls, r.get("tenant", 0))
                _bump(self.export_events, key,
                      self.MAX_EXPORT_KEYS, "other")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_s": round(time.time() - self.started, 1),
                "requests": self.requests,
                "attacks": self.attacks,
                "blocked": self.blocked,
                "monitored": self.monitored,
                "fail_open": self.fail_open,
                "by_class": dict(self.by_class),
                "by_tenant": {str(k): v for k, v in self.by_tenant.items()},
                "shed_by_tenant": {str(k): v for k, v
                                   in self.shed_by_tenant.items()},
                "degraded_by_tenant": {str(k): v for k, v
                                       in self.degraded_by_tenant.items()},
                "export_events": dict(self.export_events),
            }
