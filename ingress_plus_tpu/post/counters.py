"""Node counters — the `/wallarm-status`† counters endpoint analog
(SURVEY.md §3.5): the JSON the reference's collectd sidecar scrapes and
forwards to the cloud.  Served by the serve loop at ``/wallarm-status``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class NodeCounters:
    """Monotonic counters, thread-safe, cheap enough for the verdict path
    (single lock, integer adds)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests = 0
        self.attacks = 0
        self.blocked = 0
        self.monitored = 0         # attacks seen in monitoring mode
        self.fail_open = 0
        self.by_class: Dict[str, int] = {}
        self.by_tenant: Dict[int, int] = {}   # attacks per tenant
        #: EXPORTED ATTACK RECORDS by class (unit: aggregated attacks,
        #: not requests — by_class above counts per-request verdicts).
        #: This is the only place brute/dirbust rate detections appear:
        #: they have no per-request verdict, so the serve-path record()
        #: never sees them.  Keyed "class" and "class:tenant".
        self.export_events: Dict[str, int] = {}

    def record(self, *, attack: bool, blocked: bool, fail_open: bool,
               classes, tenant: int, mode: int) -> None:
        with self._lock:
            self.requests += 1
            if fail_open:
                self.fail_open += 1
            if attack:
                self.attacks += 1
                if blocked:
                    self.blocked += 1
                elif mode == 1:
                    self.monitored += 1
                for c in classes:
                    self.by_class[c] = self.by_class.get(c, 0) + 1
                self.by_tenant[tenant] = self.by_tenant.get(tenant, 0) + 1

    def record_export_events(self, records) -> None:
        """Fold exporter-delivered attack records (incl. brute/dirbust)
        into the per-application counters the reference's collectd
        scrape forwards."""
        with self._lock:
            for r in records:
                cls = r.get("class", "unclassified")
                self.export_events[cls] = self.export_events.get(cls, 0) + 1
                key = "%s:%s" % (cls, r.get("tenant", 0))
                self.export_events[key] = self.export_events.get(key, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_s": round(time.time() - self.started, 1),
                "requests": self.requests,
                "attacks": self.attacks,
                "blocked": self.blocked,
                "monitored": self.monitored,
                "fail_open": self.fail_open,
                "by_class": dict(self.by_class),
                "by_tenant": {str(k): v for k, v in self.by_tenant.items()},
                "export_events": dict(self.export_events),
            }
