"""Hit queue — the Tarantool postanalytics queue analog (SURVEY.md §3.4).

Contract carried over from the reference: writes happen asynchronously
after the verdict is already delivered, and the queue being full or the
consumer being dead NEVER blocks or fails a request — postanalytics is
strictly off-path.  Hence: bounded deque, drop-oldest under pressure,
a drop counter for observability, and O(1) lock-held sections only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field, asdict
from typing import Dict, List, Optional, Tuple


@dataclass
class Hit:
    """One detection event (the module→Tarantool serialized record analog).

    The reference ships the whole serialized request; we ship the verdict
    facts plus enough request identity to aggregate (uri, client, tenant)
    — raw bodies stay out of the queue by default (bounded memory)."""

    ts: float
    request_id: str
    tenant: int
    client: str            # client identity: X-Real-IP / X-Forwarded-For
    method: str
    uri: str
    classes: Tuple[str, ...]
    rule_ids: Tuple[int, ...]
    score: int
    blocked: bool
    attack: bool
    fail_open: bool = False
    mode: int = 2
    #: detection latency (µs) the verdict carried — lets the export /
    #: spool side correlate slow verdicts with the serve plane's
    #: /traces/request?id= spans by request_id (ISSUE 1 attribution)
    elapsed_us: int = 0
    #: matched points ({rule_id, var, value-snippet}) — the reference
    #: ships the serialized request and the cloud re-derives points; we
    #: ship the points themselves (bounded, raw bodies stay out)
    matches: Tuple[dict, ...] = ()

    def to_dict(self) -> dict:
        d = asdict(self)
        d["classes"] = list(self.classes)
        d["rule_ids"] = list(self.rule_ids)
        d["matches"] = list(d["matches"])  # keep asdict's deep copies
        return d


class HitQueue:
    """Bounded MPSC-ish queue: many serve-loop producers, one exporter
    consumer.  `put` never blocks; overflow drops the OLDEST record
    (freshest data wins, like a ring buffer) and counts the drop."""

    def __init__(self, maxlen: int = 65536):
        self._dq: deque[Hit] = deque()
        self.maxlen = maxlen
        self._lock = threading.Lock()
        self.dropped = 0
        self.total = 0

    def put(self, hit: Hit) -> None:
        with self._lock:
            self.total += 1
            if len(self._dq) >= self.maxlen:
                self._dq.popleft()
                self.dropped += 1
            self._dq.append(hit)

    def drain(self, max_items: Optional[int] = None) -> List[Hit]:
        """Remove and return up to max_items oldest hits (all by default)."""
        out: List[Hit] = []
        with self._lock:
            n = len(self._dq) if max_items is None else min(
                max_items, len(self._dq))
            for _ in range(n):
                out.append(self._dq.popleft())
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)
