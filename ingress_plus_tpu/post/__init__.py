"""Postanalytics subsystem — the reference's L5 layer (SURVEY.md §1, §3.4).

In the reference, the nginx wallarm module asynchronously serializes each
request's detection result to a Tarantool in-memory queue (iproto TCP);
cron-driven wruby scripts aggregate hits into attacks and POST them to the
Wallarm cloud, `brute-detect` scans request rates, collectd scrapes the
module's `/wallarm-status` counters, and `sync-node` pulls fresh rulesets
(proton.db) for hot-swap.  All of that is OFF the request hot path: the
queue being down never blocks traffic.

TPU-native equivalents here, same contracts:

    HitQueue        — bounded in-memory queue (Tarantool analog); lossy
                      under pressure (drop-oldest + counter), never blocks
    aggregate_*     — hits → attacks windowed aggregation (export-attacks†)
    NodeCounters    — /wallarm-status counters (collectd feed analog)
    BruteDetector   — request-rate detection (brute-detect† analog)
    Exporter        — periodic drain → spool/POST (cloud-export analog;
                      this build has zero egress, so the wire sink is a
                      jsonl spool + optional HTTP hook)
    RulesetWatcher  — sync-node† analog: watches for new compiled-ruleset
                      artifacts and triggers the serve loop's hot-swap
"""

from ingress_plus_tpu.post.queue import Hit, HitQueue
from ingress_plus_tpu.post.aggregate import Attack, aggregate_attacks
from ingress_plus_tpu.post.counters import NodeCounters
from ingress_plus_tpu.post.brute import BruteDetector
from ingress_plus_tpu.post.export import Exporter, RulesetWatcher
from ingress_plus_tpu.post.channel import PostChannel
from ingress_plus_tpu.post.topk import SpaceSaving

__all__ = [
    "Hit", "HitQueue", "Attack", "aggregate_attacks", "NodeCounters",
    "BruteDetector", "Exporter", "RulesetWatcher", "PostChannel",
    "SpaceSaving",
]
