"""Hits → attacks aggregation — the wruby `export-attacks`† analog
(SURVEY.md §2.3, §3.4).

The reference's cron scripts read raw hits from Tarantool and fold them
into "attacks": one logical attack = a stream of hits from the same
source against the same target with the same attack class, within a time
window.  The cloud receives attacks, not raw hits.  Same fold here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

from ingress_plus_tpu.post.queue import Hit


@dataclass
class Attack:
    tenant: int
    client: str
    attack_class: str
    first_ts: float
    last_ts: float
    count: int = 0
    blocked: int = 0
    max_score: int = 0
    # bounded samples so a flood can't balloon the export record
    sample_uris: List[str] = field(default_factory=list)
    sample_rule_ids: List[int] = field(default_factory=list)
    sample_request_ids: List[str] = field(default_factory=list)
    sample_points: List[dict] = field(default_factory=list)
    # companion set for sample_rule_ids dedup: O(1) membership on the
    # verdict-record path instead of list scans; the exported to_dict
    # stays the capped, insertion-ordered LIST above
    _rid_seen: set = field(default_factory=set, repr=False, compare=False)

    MAX_SAMPLES = 8

    def add(self, hit: Hit) -> None:
        self.count += 1
        self.blocked += int(hit.blocked)
        self.max_score = max(self.max_score, hit.score)
        self.first_ts = min(self.first_ts, hit.ts)
        self.last_ts = max(self.last_ts, hit.ts)
        if len(self.sample_uris) < self.MAX_SAMPLES:
            self.sample_uris.append(hit.uri[:256])
            self.sample_request_ids.append(hit.request_id)
        for r in hit.rule_ids:
            if len(self.sample_rule_ids) >= self.MAX_SAMPLES:
                break
            if r not in self._rid_seen:
                self._rid_seen.add(r)
                self.sample_rule_ids.append(r)
        for p in hit.matches:
            if len(self.sample_points) >= self.MAX_SAMPLES:
                break
            if p not in self.sample_points:   # distinct points only
                self.sample_points.append(p)

    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant, "client": self.client,
            "class": self.attack_class, "first_ts": self.first_ts,
            "last_ts": self.last_ts, "count": self.count,
            "blocked": self.blocked, "max_score": self.max_score,
            "sample_uris": self.sample_uris,
            "sample_rule_ids": self.sample_rule_ids,
            "sample_request_ids": self.sample_request_ids,
            "sample_points": self.sample_points,
        }


def aggregate_attacks(hits: Sequence[Hit],
                      gap_s: float = 60.0) -> List[Attack]:
    """Fold hits into attacks.

    Key = (tenant, client, attack_class); a hit more than ``gap_s`` after
    the key's last hit starts a NEW attack (session-window semantics —
    the same shape the reference's exporter uses so repeat offenders over
    hours show as separate attacks, not one eternal record).  Hits with
    no classes (fail-open flags, clean-but-logged) are skipped.
    """
    open_attacks: Dict[Tuple[int, str, str], Attack] = {}
    done: List[Attack] = []
    for hit in sorted(hits, key=lambda h: h.ts):
        if not hit.attack:
            continue
        for cls in hit.classes or ("unclassified",):
            key = (hit.tenant, hit.client, cls)
            cur = open_attacks.get(key)
            if cur is not None and hit.ts - cur.last_ts > gap_s:
                done.append(cur)
                cur = None
            if cur is None:
                cur = Attack(tenant=hit.tenant, client=hit.client,
                             attack_class=cls, first_ts=hit.ts,
                             last_ts=hit.ts)
                open_attacks[key] = cur
            cur.add(hit)
    done.extend(open_attacks.values())
    return done
