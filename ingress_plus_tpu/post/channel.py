"""PostChannel — the serve loop's write side of postanalytics.

The reference's wallarm module serializes each request's outcome to
Tarantool in the nginx log phase, AFTER the response is on the wire
(SURVEY.md §3.3 "log phase: async serialize ... off hot path").  The
serve loop calls ``record`` after the verdict future resolves and the
response frame is queued — an O(1) counter update + deque append; the
exporter thread does everything heavy later.
"""

from __future__ import annotations

import time
from typing import Optional

from ingress_plus_tpu.post.brute import BruteConfig, BruteDetector
from ingress_plus_tpu.post.counters import NodeCounters
from ingress_plus_tpu.post.export import Exporter
from ingress_plus_tpu.post.queue import Hit, HitQueue
from ingress_plus_tpu.post.topk import SpaceSaving
from ingress_plus_tpu.serve.normalize import Request

_CLIENT_HEADERS = ("x-real-ip", "x-forwarded-for", "x-client-ip")


def client_key(request: Request) -> str:
    """Client identity for aggregation: proxy-provided real IP when the
    nginx shim forwards it, else a stable per-connection fallback."""
    lowered = {k.lower(): v for k, v in request.headers.items()}
    for h in _CLIENT_HEADERS:
        v = lowered.get(h)
        if v:
            return v.split(",")[0].strip()[:64]
    return "-"


class PostChannel:
    def __init__(self, spool_dir: Optional[str] = None,
                 http_url: Optional[str] = None,
                 interval_s: float = 5.0,
                 queue_len: int = 65536,
                 brute: bool = True,
                 brute_config: Optional[BruteConfig] = None):
        self.queue = HitQueue(maxlen=queue_len)
        self.counters = NodeCounters()
        # top-K attacked paths / tenants (bounded space-saving sketch,
        # post/topk.py) — heavy-hitter visibility without a counter per
        # distinct URI (a scanner sweep has unbounded distinct paths)
        self.top_paths = SpaceSaving(capacity=32)
        self.top_tenants = SpaceSaving(capacity=32)
        # admission-level abuse (ISSUE 10): which tenants draw the
        # shed/degraded verdicts — the tenant-guard's quarantine and
        # fair-admission decisions, visible to postanalytics
        self.top_shed_tenants = SpaceSaving(capacity=32)
        self.exporter = Exporter(
            self.queue, spool_dir=spool_dir, http_url=http_url,
            interval_s=interval_s,
            brute=BruteDetector(brute_config) if brute else None,
            # exported events (incl. brute/dirbust) feed the
            # per-application counters the status plane serves
            on_export=self.counters.record_export_events)

    def record(self, request: Request, verdict) -> None:
        degraded = bool(getattr(verdict, "degraded", False))
        self.counters.record(
            attack=verdict.attack, blocked=verdict.blocked,
            fail_open=verdict.fail_open, classes=verdict.classes,
            tenant=request.tenant, mode=request.mode,
            degraded=degraded)
        if verdict.attack:
            self.top_paths.offer(request.uri.split("?", 1)[0][:128])
            self.top_tenants.offer(str(request.tenant))
        if verdict.fail_open or degraded:
            self.top_shed_tenants.offer(str(request.tenant))
        # every request is queued (brute-detect needs clean-request rates);
        # the aggregator ignores non-attacks for attack export
        self.queue.put(Hit(
            ts=time.time(), request_id=request.request_id,
            tenant=request.tenant, client=client_key(request),
            method=request.method, uri=request.uri[:512],
            classes=tuple(verdict.classes),
            rule_ids=tuple(verdict.rule_ids),
            score=verdict.score, blocked=verdict.blocked,
            attack=verdict.attack, fail_open=verdict.fail_open,
            mode=request.mode,
            # verdict is duck-typed (ws/stream paths and tests pass
            # lightweight stubs) — matches/elapsed are optional there
            elapsed_us=int(getattr(verdict, "elapsed_us", 0)),
            matches=tuple(getattr(verdict, "matches", ()))))

    def start(self) -> None:
        self.exporter.start()

    def close(self) -> None:
        self.exporter.close()

    def status(self) -> dict:
        d = self.counters.snapshot()
        d["queue"] = {"depth": len(self.queue), "dropped": self.queue.dropped,
                      "total": self.queue.total}
        d["export"] = {"attacks": self.exporter.exported_attacks,
                       "errors": self.exporter.export_errors,
                       "consecutive_failures":
                           self.exporter.consecutive_failures,
                       "backoff_s": round(self.exporter.backoff_s, 3),
                       "spool_dropped_files":
                           self.exporter.spool_dropped_files,
                       "spool_dropped_bytes":
                           self.exporter.spool_dropped_bytes}
        d["top_attacked"] = {
            "paths": self.top_paths.items(10),
            "tenants": self.top_tenants.items(10),
            # admission-level abuse (ISSUE 10): shed/degraded verdict
            # heavy hitters — the serve plane's tenant-isolation
            # decisions, aggregated under the same sketch bound
            "shed_tenants": self.top_shed_tenants.items(10),
            "note": "space-saving sketch: count may over-estimate by "
                    "up to max_error",
        }
        return d
