"""Opportunistic TPU bench capture (VERDICT r03 next-round item #1).

The axon remote-TPU tunnel on this rig hangs at backend init
unpredictably for minutes at a time (observed rounds 1-3; BENCH_r03
recorded all three probes timing out).  A single bench attempt at
driver-chosen time therefore keeps missing the chip.  This tool inverts
the strategy: probe cheaply in a loop, and the FIRST time the chip
answers, immediately run the full bench back-to-back and persist an
AUDITABLE artifact:

    reports/TPU_BENCH_<utc>Z_<head>.json   — bench JSON line + device
        inventory + ruleset fingerprint + pointers to the raw logs
    reports/TPU_BENCH_<utc>Z_<head>.stderr.txt — complete raw stderr of
        the bench run (timing method, per-impl numbers, buckets)

so a later tunnel outage (e.g. during the driver's end-of-round bench)
cannot erase the evidence.  Run under tmux:  python tools/tpu_hunt.py
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORTS = os.path.join(REPO, "reports")
PROBE_TIMEOUT_S = 120
SLEEP_BETWEEN_PROBES_S = 180
BENCH_TIMEOUT_S = 1800


def log(msg: str) -> None:
    print("[tpu_hunt %s] %s"
          % (datetime.datetime.utcnow().strftime("%H:%M:%S"), msg),
          flush=True)


def probe() -> dict | None:
    """jax.devices() in a throwaway subprocess under a hard timeout
    (memory: a hung init is unrecoverable in-process)."""
    code = (
        "import jax, json; d = jax.devices();"
        "print(json.dumps({'platform': d[0].platform,"
        " 'devices': [str(x) for x in d],"
        " 'device_kind': getattr(d[0], 'device_kind', '?')}))"
    )
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return None
    if p.returncode != 0:
        log("probe rc=%d: %s" % (p.returncode,
                                 (p.stderr or "").strip()[-200:]))
        return None
    try:
        info = json.loads(p.stdout.strip().splitlines()[-1])
    except Exception:
        return None
    return info if info.get("platform") not in (None, "cpu") else None


def ruleset_fingerprint() -> dict:
    out = subprocess.run(
        [sys.executable, "-c",
         "from ingress_plus_tpu.compiler.sigpack import load_bundled_rules;"
         "from ingress_plus_tpu.compiler.ruleset import compile_ruleset;"
         "import hashlib, json;"
         "cr = compile_ruleset(load_bundled_rules());"
         "ids = ','.join(str(i) for i in sorted(cr.rule_ids));"
         "print(json.dumps({'n_rules': int(cr.n_rules),"
         " 'n_factors': int(cr.tables.n_factors),"
         " 'n_words': int(cr.tables.n_words),"
         " 'rule_ids_sha256': hashlib.sha256(ids.encode()).hexdigest()}))"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:
        return {"error": (out.stderr or "")[-300:]}


def _safe_fingerprint() -> dict:
    try:
        return ruleset_fingerprint()
    except Exception as e:  # artifact must survive a fingerprint failure
        return {"error": repr(e)[:300]}


def run_bench(tag: str, extra_args: list[str], env_extra: dict,
              timeout_s: int = BENCH_TIMEOUT_S):
    env = dict(os.environ)
    env["BENCH_WATCHDOG_S"] = str(timeout_s - 120)
    env.update(env_extra)
    t0 = time.time()
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")]
                       + extra_args,
                       capture_output=True, text=True, timeout=timeout_s,
                       env=env, cwd=REPO)
    dt = time.time() - t0
    line = (p.stdout.strip().splitlines() or ["{}"])[-1]
    try:
        result = json.loads(line)
    except Exception:
        result = {"parse_error": line[:300]}
    log("%s bench rc=%d in %.0fs: %s" % (tag, p.returncode, dt, line[:200]))
    return result, p.stderr, dt, p.returncode


def main() -> None:
    os.makedirs(REPORTS, exist_ok=True)
    attempt = 0
    while True:
        attempt += 1
        info = probe()
        if info is None:
            log("probe %d: tunnel down/hung; sleeping %ds"
                % (attempt, SLEEP_BETWEEN_PROBES_S))
            time.sleep(SLEEP_BETWEEN_PROBES_S)
            continue
        log("probe %d: LIVE %s" % (attempt, info))
        try:
            head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                                  capture_output=True, text=True,
                                  cwd=REPO).stdout.strip()
        except Exception as e:
            head = "unknown"
            log("git head lookup failed: %r" % (e,))
        stamp = datetime.datetime.utcnow().strftime("%Y%m%dT%H%M%S")
        base = os.path.join(REPORTS, "TPU_BENCH_%sZ_%s" % (stamp, head))
        try:
            result, stderr, dt, rc = run_bench("tpu", [], {})
        except Exception as e:
            # a mid-bench tunnel outage (incl. subprocess timeout) must
            # not kill the hunt loop — that outage is WHY it exists
            log("bench attempt failed: %r; continuing hunt" % (e,))
            time.sleep(SLEEP_BETWEEN_PROBES_S)
            continue
        with open(base + ".stderr.txt", "w") as f:
            f.write(stderr)
        artifact = {
            "captured_utc": stamp + "Z",
            "git_head": head,
            "probe_device_inventory": info,
            "bench_wall_s": round(dt, 1),
            "bench_rc": rc,
            "result": result,
            "ruleset": _safe_fingerprint(),
            "raw_stderr_file": os.path.relpath(base + ".stderr.txt", REPO),
            "method": ("bench.py end-to-end: probe ladder -> compile "
                       "bundled ruleset -> K-diff-timed state-chained "
                       "detect over the 2048-req corpus per scan impl "
                       "(take/pair/pallas) -> latency legs; see raw "
                       "stderr for every intermediate number"),
        }
        with open(base + ".json", "w") as f:
            json.dump(artifact, f, indent=1)
        log("artifact written: %s" % base + ".json")
        if result.get("platform") not in (None, "cpu"):
            # any non-CPU platform IS the chip on this rig — the axon
            # PJRT plugin may report "axon" or "tpu" depending on
            # version; demanding the literal "tpu" would loop forever
            # re-benching a live chip
            log("%s-platform result captured; hunt complete"
                % result["platform"])
            return
        log("bench fell back to %s; continuing hunt"
            % result.get("platform"))
        time.sleep(SLEEP_BETWEEN_PROBES_S)


if __name__ == "__main__":
    main()
