"""Bench trajectory: render the r01→rNN ``req/s/chip`` curve from the
checked-in BENCH_r*.json artifacts, and gate on regression.

    python tools/bench_trend.py            # table + exit status
    python tools/bench_trend.py --json     # machine-readable

Exit status 1 when the LATEST snapshot regresses >10% against the
previous one (the benchtrend CI gate in tools/lint.py; it also warns —
without failing — when the latest trails the best-ever point, which is
expected while a perf direction is mid-flight).  With fewer than two
artifacts there is nothing to compare: the tool reports SKIP and exits
0, so a fresh clone (or a repo that hasn't run the bench yet) never
fails CI on a missing artifact.

Artifacts are either the driver-wrapped shape ``{n, cmd, rc, tail,
parsed}`` or a bare bench JSON line — both load.

Backend guard (ISSUE 13): when the latest artifact's ``platform``
differs from the previous one's (a CPU→TPU flip, or the reverse
fallback), the comparison is REFUSED — status SKIP with an explicit
warning — because req/s/chip across backends is not one trajectory.
The best-ever trail note likewise only compares same-backend points.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REGRESSION_GATE = 0.10   # >10% drop vs the previous snapshot fails


def load_artifacts(repo: str = REPO) -> list:
    """[(tag, value, platform, note)] sorted by round number."""
    out = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_(r\d+)\.json$", path)
        if not m:
            continue
        try:
            d = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            continue
        parsed = d.get("parsed", d) or {}
        value = parsed.get("value")
        if value is None:
            continue
        out.append({
            "tag": m.group(1),
            "value": float(value),
            "platform": parsed.get("platform", "?"),
            "error": parsed.get("error"),
            "path": os.path.basename(path),
        })
    out.sort(key=lambda a: int(a["tag"][1:]))
    return out


def trend(artifacts: list) -> dict:
    """The trajectory + the gate decision."""
    if len(artifacts) < 2:
        return {"status": "SKIP",
                "detail": "fewer than 2 BENCH artifacts — nothing to "
                          "compare (%d found)" % len(artifacts),
                "points": artifacts}
    latest, prev = artifacts[-1], artifacts[-2]
    # backend guard (ISSUE 13 satellite): req/s/chip measured on
    # different backends is not one trajectory — a CPU→TPU flip must
    # not read as a 10x "win", nor the reverse as a regression.  The
    # gate REFUSES the comparison; re-baseline on the new backend
    # (legacy artifacts with unknown platform "?" keep comparing).
    if (latest["platform"] != prev["platform"]
            and "?" not in (latest["platform"], prev["platform"])):
        return {
            "status": "SKIP",
            "latest": latest["tag"],
            "latest_value": latest["value"],
            "prev_value": prev["value"],
            "delta_vs_prev": None,
            "best": None,
            "warnings": [
                "backend changed %s (%s) -> %s (%s): req/s/chip is "
                "not comparable across backends — regression NOT "
                "gated; the next same-backend artifact re-baselines "
                "the trend" % (prev["platform"], prev["tag"],
                               latest["platform"], latest["tag"])],
            "detail": "backend changed %s -> %s — artifacts not "
                      "comparable, gate skipped"
                      % (prev["platform"], latest["platform"]),
            "points": artifacts,
        }
    # best-ever trail note: only same-backend points are a trajectory
    same_backend = [a for a in artifacts
                    if a["platform"] == latest["platform"]
                    or "?" in (a["platform"], latest["platform"])]
    best = max(same_backend, key=lambda a: a["value"])
    drop_vs_prev = 1.0 - latest["value"] / prev["value"] \
        if prev["value"] > 0 else 0.0
    regressed = drop_vs_prev > REGRESSION_GATE
    warnings = []
    if regressed and latest.get("error"):
        # the artifact itself records a degraded measurement host
        # (e.g. "tpu-unavailable: backend init hung"): the number is
        # honest but not comparable — WARN instead of failing CI on
        # infrastructure (the r03→r04 precedent: a host change, not a
        # code regression, would have hard-failed the gate)
        warnings.append(
            "%s dropped %.1f%% vs %s but carries a degraded-host tag "
            "(%s) — regression NOT gated; rerun on a healthy host for "
            "the comparable number"
            % (latest["tag"], drop_vs_prev * 100, prev["tag"],
               latest["error"][:80]))
        regressed = False
    elif regressed:
        warnings.append(
            "%s regressed %.1f%% vs %s (%.1f -> %.1f req/s/chip; "
            "gate: <=%.0f%%)"
            % (latest["tag"], drop_vs_prev * 100, prev["tag"],
               prev["value"], latest["value"], REGRESSION_GATE * 100))
    if best["tag"] != latest["tag"] and best["value"] > 0 \
            and latest["value"] < 0.9 * best["value"]:
        warnings.append(
            "note: %s trails the best-ever point %s by %.1f%% "
            "(not gated)"
            % (latest["tag"], best["tag"],
               (1.0 - latest["value"] / best["value"]) * 100))
    return {
        "status": "FAIL" if regressed else "OK",
        "latest": latest["tag"],
        "latest_value": latest["value"],
        "prev_value": prev["value"],
        "delta_vs_prev": round(latest["value"] / prev["value"], 3)
        if prev["value"] > 0 else None,
        "best": best["tag"],
        "warnings": warnings,
        "detail": warnings[0] if regressed else
        "%s: %.1f req/s/chip (%.2fx vs %s)"
        % (latest["tag"], latest["value"],
           latest["value"] / prev["value"] if prev["value"] > 0 else 0,
           prev["tag"]),
        "points": artifacts,
    }


def render(report: dict) -> str:
    lines = ["req/s/chip trajectory (checked-in BENCH artifacts):", ""]
    pts = report.get("points", [])
    peak = max((a["value"] for a in pts), default=1.0) or 1.0
    for a in pts:
        bar = "#" * max(1, int(a["value"] / peak * 40))
        note = " [%s]" % a["error"][:40] if a.get("error") else ""
        lines.append("  %-4s %9.1f  %-40s %s%s"
                     % (a["tag"], a["value"], bar, a["platform"], note))
    lines.append("")
    lines.append("%s: %s" % (report["status"], report["detail"]))
    for w in report.get("warnings", []):
        lines.append("WARNING: %s" % w)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/bench_trend.py")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--repo", default=REPO)
    args = ap.parse_args(argv)
    report = trend(load_artifacts(args.repo))
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 1 if report["status"] == "FAIL" else 0


if __name__ == "__main__":
    sys.exit(main())
