"""Closed-loop pack retuner (ISSUE 15, docs/RETUNE.md): profile in →
retuned pack out, with every regeneration zero-FN-pinned.

    # export a MeasuredProfile from a telemetry replay (or curl
    # /rules/stats?format=profile from a live node instead)
    python tools/retune.py --export-profile profile.json

    # retune: compile the profile-priced pack, run the truth gates
    # (measured inflation, golden replay, staged rollout), A/B it
    python tools/retune.py --profile profile.json --out retuned.sigpack \
        --report reports/RETUNE_RUN.json

    # no profile argument: build one from a bench-shaped telemetry
    # replay first (the bootstrap loop a fresh deployment runs)
    python tools/retune.py --out retuned.sigpack

The loop this closes (ROADMAP item 4): the serve plane measures
per-rule candidate rates / confirm cost / quick-reject coverage and the
scanned-byte distribution (models/rule_stats.py), the compiler prices
its approximate reduction against those measurements instead of the
static byte model (compiler/profile.py → compiler/reduce.py), and the
result re-enters serving only through the SAME staged-rollout admission
gates a hand-rolled pack faces (control/rollout.py: golden-corpus
replay + shadow diff).  Truth gates, in order:

  1. measured inflation  — candidate superset check on a corpus sample
                           (``measure_inflation``): lost_candidates MUST
                           be 0; the measured inflation is recorded and
                           compared LOUDLY against the configured budget
  2. golden replay       — retuned vs static verdicts over the golden
                           corpus + benign fixtures: zero new false
                           negatives, zero new benign blocks
  3. staged rollout      — the pack is admitted into a real Batcher via
                           RolloutController.admit and driven through
                           shadow → canary → LIVE while mixed traffic
                           flows (exactly-one-verdict preserved)
  4. A/B throughput      — retuned pack + cross-cycle verdict cache vs
                           the static pack over a production-shaped
                           corpus (mixed + flood repeats): the ≥1.2x
                           pipeline.detect target the ISSUE pins

Determinism contract: the same profile BYTES + the same rules compile
to the same pack fingerprint (tools/lint.py retunegate retrains twice
and asserts it); profile timing fields are measurements, so two
independently-collected profiles legitimately differ.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # script execution puts tools/ first
    sys.path.insert(0, str(REPO))

#: the A/B target the ISSUE pins for the retuned pack + verdict cache
AB_TARGET = 1.2


def _load_rules(rules_dir: Optional[str] = None):
    from ingress_plus_tpu.compiler.seclang import load_seclang_dir
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules

    return load_seclang_dir(rules_dir) if rules_dir else load_bundled_rules()


def _corpus(n: int, seed: int, attack_fraction: float = 0.3) -> List:
    from ingress_plus_tpu.utils.corpus import generate_corpus

    return [lr.request for lr in generate_corpus(
        n=n, attack_fraction=attack_fraction, seed=seed)]


def build_profile(rules=None, corpus_n: int = 256, seed: int = 42,
                  batch: int = 64):
    """Bootstrap a MeasuredProfile from a telemetry replay: run the
    bench-shaped corpus through a CPU pipeline on the static-priced
    pack and freeze its RuleStats.  A production node exports the same
    artifact from real traffic via /rules/stats?format=profile."""
    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.models.pipeline import DetectionPipeline

    if rules is None:
        rules = _load_rules()
    cr = compile_ruleset(rules)
    pipe = DetectionPipeline(cr, mode="detect")
    corpus = _corpus(corpus_n, seed)
    for i in range(0, len(corpus), batch):
        pipe.detect(corpus[i:i + batch])
    return MeasuredProfile.from_rule_stats(pipe.rule_stats)


def _replay_fns(static_pipe, retuned_pipe, requests) -> dict:
    """Golden-replay diff: verdicts of the retuned pack vs the static
    pack over ``requests`` (exact CPU confirm semantics on both sides —
    detect_cpu_only, so the diff is about the PACKS, not the device)."""
    vs = static_pipe.detect_cpu_only(requests)
    vr = retuned_pipe.detect_cpu_only(requests)
    new_fns, new_fn_ids, new_blocks = 0, [], 0
    for a, b in zip(vs, vr):
        if a.attack and not b.attack:
            new_fns += 1
            new_fn_ids.append(a.request_id)
        if b.blocked and not a.blocked:
            new_blocks += 1
    return {"requests": len(requests), "new_fns": new_fns,
            "new_fn_ids": new_fn_ids[:16], "new_blocks": new_blocks}


def _staged_rollout(static_cr, retuned_cr, timeout_s: float = 120.0) -> dict:
    """Drive the retuned pack through the REAL staged-rollout machinery
    (admission → shadow → canary → LIVE) on a live CPU batcher while
    mixed traffic flows — the ISSUE's requirement that every
    regeneration re-enters serving through the PR 5 safety net."""
    from ingress_plus_tpu.control.rollout import (
        LIVE,
        REJECTED,
        ROLLED_BACK,
        RolloutConfig,
        RolloutController,
        RolloutRejected,
    )
    from ingress_plus_tpu.utils.faults import _collect, _mk_batcher

    # production-shaped traffic: corpus requests carry realistic headers.
    # The bare faults fixtures have NO headers, so the CRS header-absence
    # rules (920280/920320) fire on the shadow lane's exact CPU replay
    # but not on the device path — a pre-existing fixture artifact that
    # would book every candidate (even a bit-identical one) as a
    # verdict_diff and roll it back.
    traffic = _corpus(96, 20260805, attack_fraction=0.25)

    b = _mk_batcher(cr=static_cr)
    ro = RolloutController(b, RolloutConfig(
        steps=(0.25, 1.0), step_min_requests=8, shadow_min_requests=4,
        shadow_sample=1.0, corpus_n=64, diff_min_compared=4))
    b.rollout = ro
    out: dict = {"admitted": False, "state": None, "violations": []}
    try:
        try:
            report = ro.admit(ruleset=retuned_cr)
        except RolloutRejected as e:
            out["state"] = REJECTED
            out["reject"] = e.report
            return out
        out["admitted"] = True
        out["replay"] = report.get("replay")
        deadline = time.monotonic() + timeout_s
        wave = 0
        while ro.state not in (LIVE, ROLLED_BACK, REJECTED) \
                and time.monotonic() < deadline:
            lo = (wave * 24) % len(traffic)
            futs = [b.submit(r) for r in traffic[lo:lo + 24]]
            _vs, viol = _collect(futs, timeout_s=30)
            out["violations"] += viol
            wave += 1
        out["state"] = ro.state
        out["rollback_reason"] = ro.rollback_reason
        out["serving"] = b.pipeline.ruleset.version
    finally:
        b.close()
    return out


def _ab_throughput(static_cr, retuned_cr, corpus_n: int = 512,
                   seed: int = 42, flood_dup: int = 4, iters: int = 3,
                   cache_entries: int = 65536) -> dict:
    """A/B the closed loop end to end on a production-shaped corpus
    (mixed traffic + the flood shape TENANTFAIR generates: the first
    n//flood_dup requests repeated flood_dup times, shuffled): static
    pack with the per-cycle memo only, vs retuned pack + cross-cycle
    verdict cache.  Best-of-``iters`` pipeline.detect wall time."""
    from ingress_plus_tpu.models.pipeline import DetectionPipeline

    mixed = _corpus(corpus_n, seed, attack_fraction=0.2)
    flood = mixed[:max(1, corpus_n // flood_dup)] * flood_dup
    random.Random(7).shuffle(flood)
    corpus = mixed + flood

    def _run(pipe) -> float:
        best = float("inf")
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            for i in range(0, len(corpus), 64):
                pipe.detect(corpus[i:i + 64])
            best = min(best, time.perf_counter() - t0)
        return best

    arms = {}
    for tag, cr, cache in (("static", static_cr, 0),
                           ("retuned+cache", retuned_cr, cache_entries)):
        pipe = DetectionPipeline(cr, mode="detect",
                                 confirm_cache_entries=cache)
        # warm every serve shape out of the measurement
        for i in range(0, len(corpus), 64):
            pipe.detect(corpus[i:i + 64])
        sec = _run(pipe)
        arms[tag] = {
            "seconds": round(sec, 4),
            "req_per_s": round(len(corpus) / sec, 1),
            "cache": (pipe.confirm_cache.snapshot()
                      if pipe.confirm_cache is not None else None),
        }
    speedup = (arms["static"]["seconds"]
               / arms["retuned+cache"]["seconds"])
    return {"requests": len(corpus), "flood_dup": flood_dup,
            "iters": iters, "arms": arms,
            "speedup": round(speedup, 3), "target": AB_TARGET,
            "meets_target": speedup >= AB_TARGET}


def retune(rules=None, profile=None, corpus_n: int = 256, seed: int = 42,
           staged: bool = True, ab: bool = True, ab_iters: int = 3,
           inflation_rows: int = 256) -> dict:
    """The closed loop as a library call (the CLI and the retunegate CI
    gate both drive this).  Returns the full report dict; ``ok`` is the
    conjunction of every hard gate that RAN (A/B is measurement, not a
    library-level gate — CI applies its own threshold)."""
    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.compiler.reduce import (
        ReductionConfig,
        measure_inflation,
    )
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import merge_rows, \
        rows_for_requests

    t0 = time.time()
    if rules is None:
        rules = _load_rules()
    if profile is None:
        profile = build_profile(rules, corpus_n=corpus_n, seed=seed)
    elif not isinstance(profile, MeasuredProfile):
        profile = MeasuredProfile.load(profile)

    static_cr = compile_ruleset(rules)
    cfg = ReductionConfig(profile=profile)
    retuned_cr = compile_ruleset(rules, reduction=cfg)
    exact_cr = compile_ruleset(rules, reduction=ReductionConfig.off())

    report: dict = {
        "profile": {"hash": profile.content_hash(),
                    "source": profile.source,
                    "requests": profile.requests,
                    "rules": len(profile.rules),
                    "byte_axis": len(profile.byte_freq) == 256},
        "static_fingerprint": static_cr.version,
        "retuned_fingerprint": retuned_cr.version,
        "reduction": retuned_cr.reduction,
    }

    # gate 1: measured inflation — superset soundness + budget honesty
    sample = _corpus(inflation_rows, seed + 1)
    rows = merge_rows(rows_for_requests(sample))[0]
    infl_static = measure_inflation(exact_cr.tables, static_cr.tables, rows)
    infl = measure_inflation(exact_cr.tables, retuned_cr.tables, rows)
    report["inflation"] = {"static": infl_static, "retuned": infl,
                           "budget": cfg.budget}
    lost_ok = infl["lost_candidates"] == 0
    if not lost_ok:
        print("RETUNE FAIL: reduced pack LOST %d candidates — unsound "
              "reduction, this is a compiler bug"
              % infl["lost_candidates"], file=sys.stderr)
    if infl["inflation"] > cfg.budget:
        print("RETUNE WARNING: measured inflation %.3f exceeds the "
              "configured budget %.2f (model underprices this corpus; "
              "static-model pack measures %.3f)"
              % (infl["inflation"], cfg.budget, infl_static["inflation"]),
              file=sys.stderr)

    # gate 2: golden replay — zero new FNs / new blocks vs the static pack
    replay_corpus = _corpus(192, 20260804, attack_fraction=0.5)
    sp = DetectionPipeline(static_cr, mode="detect")
    rp = DetectionPipeline(retuned_cr, mode="detect")
    replay = _replay_fns(sp, rp, replay_corpus)
    report["replay"] = replay
    replay_ok = replay["new_fns"] == 0 and replay["new_blocks"] == 0
    if not replay_ok:
        print("RETUNE FAIL: golden replay diverged: %d new FNs, %d new "
              "blocks" % (replay["new_fns"], replay["new_blocks"]),
              file=sys.stderr)

    # gate 3: staged rollout to LIVE through the PR 5 machinery
    rollout_ok = True
    if staged and lost_ok and replay_ok:
        ro = _staged_rollout(static_cr, retuned_cr)
        report["rollout"] = ro
        rollout_ok = (ro.get("state") == "live"
                      and not ro.get("violations"))
        if not rollout_ok:
            print("RETUNE FAIL: staged rollout ended %s (violations: %s)"
                  % (ro.get("state"), ro.get("violations")),
                  file=sys.stderr)

    # stage 4: A/B throughput (measurement; CI gates on its own floor)
    if ab:
        report["ab"] = _ab_throughput(static_cr, retuned_cr,
                                      seed=seed, iters=ab_iters)

    report["ok"] = bool(lost_ok and replay_ok and rollout_ok)
    report["seconds"] = round(time.time() - t0, 1)
    report["_retuned_cr"] = retuned_cr    # stripped before serialization
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/retune.py")
    ap.add_argument("--rules", default=None,
                    help="seclang rules dir (default: bundled CRS subset)")
    ap.add_argument("--profile", default=None,
                    help="MeasuredProfile json (default: build one from "
                         "a telemetry replay)")
    ap.add_argument("--export-profile", default=None, metavar="FILE",
                    help="only build + save a profile, then exit")
    ap.add_argument("--out", default=None,
                    help="write the retuned pack artifact here")
    ap.add_argument("--report", default=None,
                    help="write the full report json here")
    ap.add_argument("--corpus-n", type=int, default=256)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--no-staged", action="store_true",
                    help="skip the staged-rollout stage")
    ap.add_argument("--no-ab", action="store_true",
                    help="skip the A/B throughput stage")
    args = ap.parse_args(argv)

    rules = _load_rules(args.rules)
    if args.export_profile:
        prof = build_profile(rules, corpus_n=args.corpus_n, seed=args.seed)
        prof.save(args.export_profile)
        print("profile %s (%d rules, %d requests) -> %s"
              % (prof.content_hash(), len(prof.rules), prof.requests,
                 args.export_profile))
        return 0

    report = retune(rules=rules, profile=args.profile,
                    corpus_n=args.corpus_n, seed=args.seed,
                    staged=not args.no_staged, ab=not args.no_ab,
                    ab_iters=args.iters)
    retuned_cr = report.pop("_retuned_cr")
    if args.out and report["ok"]:
        retuned_cr.save(args.out)
        print("retuned pack %s -> %s"
              % (retuned_cr.version, args.out))
    elif args.out:
        print("gates failed — NOT writing %s" % args.out, file=sys.stderr)
    if args.report:
        Path(args.report).parent.mkdir(parents=True, exist_ok=True)
        Path(args.report).write_text(json.dumps(report, indent=2,
                                                sort_keys=True))
    print(json.dumps({k: v for k, v in report.items()
                      if k in ("ok", "static_fingerprint",
                               "retuned_fingerprint", "seconds")},
                     indent=2))
    if "ab" in report:
        print("A/B speedup: %.2fx (target %.1fx, %s)"
              % (report["ab"]["speedup"], AB_TARGET,
                 "MET" if report["ab"]["meets_target"] else "NOT MET"))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
