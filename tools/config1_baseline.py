"""Measure BASELINE config #1 — the CPU baseline the reference table
demands we measure ourselves ("wallarm-mode=monitoring, libdetection
SQLi only, wrk2 replay of 10k-request CRS test corpus"; the reference
publishes no numbers, BASELINE.json "published": {}).

Shape: monitoring mode (flag, never block), the full bundled pack with
the strict-grammar confirm (libdetection analog) in the loop, a
10k-request labeled corpus replayed by the C++ loadgen through the C++
sidecar into the serve loop — the wrk2-replay analog on the UDS plane.
CPU platform by construction: this IS the baseline the TPU path is
measured against.

Writes reports/CONFIG1_CPU_BASELINE.json.  Run:
    python tools/config1_baseline.py [--requests 10000]
"""

import argparse
import asyncio
import json
import os
import socket as socketmod
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ingress_plus_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=10_000)
    ap.add_argument("--connections", type=int, default=4)
    ap.add_argument("--inflight", type=int, default=8)
    args = ap.parse_args()

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop
    from ingress_plus_tpu.utils.export_corpus import export

    sidecar_dir = os.path.join(REPO, "native", "sidecar")
    subprocess.run(["make", "-s", "-C", sidecar_dir], check=True,
                   capture_output=True, timeout=300)

    t0 = time.time()
    cr = compile_ruleset(load_bundled_rules())
    print("ruleset: %d rules (%.1fs)" % (cr.n_rules, time.time() - t0),
          file=sys.stderr)
    pipeline = DetectionPipeline(cr, mode="monitoring")
    batcher = Batcher(pipeline)

    tmp = tempfile.mkdtemp(prefix="ipt_cfg1_")
    srv_sock = os.path.join(tmp, "srv.sock")
    side_sock = os.path.join(tmp, "side.sock")
    serve = ServeLoop(batcher, srv_sock)
    loop = asyncio.new_event_loop()

    def runner():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(serve.start())
        loop.run_forever()

    threading.Thread(target=runner, daemon=True).start()

    def wait_sock(path, timeout_s=60):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(path):
                try:
                    s = socketmod.socket(socketmod.AF_UNIX)
                    s.connect(path)
                    s.close()
                    return True
                except OSError:
                    pass
            time.sleep(0.05)
        return False

    assert wait_sock(srv_sock), "serve loop socket never appeared"
    sidecar = subprocess.Popen(
        [os.path.join(sidecar_dir, "sidecar"), "--listen", side_sock,
         "--upstream", srv_sock, "--deadline-ms", "30000"],
        stderr=subprocess.DEVNULL)
    try:
        assert wait_sock(side_sock), "sidecar socket never appeared"
        corpus_path = os.path.join(tmp, "c.bin")
        export(corpus_path, n=args.requests, seed=17, attack_fraction=0.2)
        from ingress_plus_tpu.utils.corpus import generate_corpus
        n_attacks = sum(1 for lr in generate_corpus(
            n=args.requests, attack_fraction=0.2, seed=17)
            if lr.is_attack)
        loadgen = os.path.join(sidecar_dir, "loadgen")
        # warmup compiles the serving shapes out of the measurement
        subprocess.run(
            [loadgen, "--socket", side_sock, "--corpus", corpus_path,
             "--connections", str(args.connections),
             "--inflight", str(args.inflight), "--requests", "512"],
            capture_output=True, timeout=600)
        # stage histograms describe ONLY the measured pass (warmup's
        # first-dispatch compiles would otherwise dominate p99)
        batcher.reset_latency_observations()
        out = subprocess.run(
            [loadgen, "--socket", side_sock, "--corpus", corpus_path,
             "--connections", str(args.connections),
             "--inflight", str(args.inflight),
             "--requests", str(args.requests)],
            capture_output=True, text=True, timeout=1800)
        if out.returncode != 0:
            print("loadgen rc=%d: %s" % (out.returncode,
                                         out.stderr[-400:]),
                  file=sys.stderr)
            return 1
        r = json.loads(out.stdout)
        # stage-level latency attribution (ISSUE 1): same scrape path as
        # bench.py's latency leg; missing histograms are a LOUD warning
        from bench import scrape_stage_breakdown
        try:
            stage_breakdown = scrape_stage_breakdown(serve)
        except Exception as e:
            stage_breakdown = None
            print("WARNING: stage_breakdown scrape raised: %r" % (e,),
                  file=sys.stderr)
        if not stage_breakdown:
            print("WARNING: no stage_breakdown — /metrics stage "
                  "histograms missing or malformed", file=sys.stderr)
        # detection-plane telemetry (ISSUE 3): same convention as
        # stage_breakdown — missing is a LOUD warning, never silent
        from ingress_plus_tpu.models.rule_stats import bench_block
        try:
            rule_stats = bench_block(pipeline)
        except Exception as e:
            rule_stats = None
            print("WARNING: rule_stats collection raised: %r" % (e,),
                  file=sys.stderr)
        if not rule_stats:
            print("WARNING: no rule_stats — per-family false-candidate "
                  "rate and padding-waste ratio unmeasured",
                  file=sys.stderr)
        result = {
            "config": ("BASELINE config #1: wallarm-mode=monitoring, "
                       "strict-grammar (libdetection analog) confirm in "
                       "the loop, loadgen replay of the labeled corpus "
                       "(wrk2-replay analog), CPU platform"),
            "requests": r["requests"],
            "corpus_attacks": n_attacks,
            "rps": r["rps"],
            "p50_us": r["p50_us"], "p90_us": r["p90_us"],
            "p99_us": r["p99_us"], "p999_us": r["p999_us"],
            "fail_open": r["fail_open"],
            "stage_breakdown": stage_breakdown,
            "rule_stats": rule_stats,
            "flagged": r["attacks"],
            "blocked": r["blocked"],
            "mode": "monitoring",
            "ruleset": {"rules": int(cr.n_rules),
                        "version": cr.version},
            "concurrency": {"connections": args.connections,
                            "inflight": args.inflight},
            "host": "1-vCPU dev rig (the TPU path's comparison anchor)",
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        path = os.path.join(REPO, "reports", "CONFIG1_CPU_BASELINE.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(result), file=sys.stderr)
        print("wrote %s" % path, file=sys.stderr)
        return 0
    finally:
        sidecar.terminate()
        loop.call_soon_threadsafe(loop.stop)


if __name__ == "__main__":
    sys.exit(main())
