"""Unified lint + static-analysis gate — the single CI entry point.

    python tools/lint.py           # run everything, report, exit status
    python tools/lint.py --ci      # same + write reports/RULECHECK.json

Four gates, one verdict:

  ruff       style/correctness lint per [tool.ruff] in pyproject.toml
             (zero-warning baseline: the selected rule set must be
             clean; new violations fail the gate)
  mypy       targeted type check of compiler/, analysis/, serve/ (+ the
             detection-telemetry modules) per [tool.mypy] in
             pyproject.toml
  rulecheck  the ruleset static analyzer (ingress_plus_tpu/analysis/,
             docs/ANALYSIS.md) over the bundled CRS tree: zero
             unsuppressed error-severity findings required
  concheck   the serve-plane CONCURRENCY static analyzer
             (docs/ANALYSIS.md "Concurrency analysis"): thread-boundary
             map, guarded-by inference + unguarded-mutation findings,
             lock-order cycles, thread-lifecycle lint — zero
             unsuppressed error-severity findings required
             (reports/CONCHECK.json)
  evasiongate the evasion-closure pair (docs/ANALYSIS.md "Evasion
             analysis"): evadecheck — the static analyzer deciding per
             rule whether detection is closed under the modeled evasion
             families — must have zero unsuppressed findings at warning
             or above (every accepted weakness carries a reason in
             analysis/evadecheck-baseline.json), AND the utils/evasion.py
             seeded mutation harness replaying the golden corpus through
             detect_cpu_only must retain >= 95% detection in EVERY
             mutation family (reports/EVASION.json)
  deadrules  the RUNTIME twin of rulecheck (docs/OBSERVABILITY.md,
             detection-plane telemetry): the bench corpus runs through
             a CPU pipeline and any runtime-dead rule (confirm regex
             the runtime cannot evaluate) not suppressed in
             rulecheck-baseline.json fails the gate
  faultmatrix the fail-safe serve plane (docs/ROBUSTNESS.md): a real
             CPU batcher runs under every deterministic FaultPlan
             scenario (dispatch_hang/raise, recompile_storm, swap_fail,
             export_5xx, slow_confirm, the rollout-phase faults
             shadow_diverge/lkg_corrupt/promote-boundary swap_fail,
             the lane/confirm-worker isolation scenarios, and the
             tenant-isolation floods tenant_flood /
             tenant_flood_during_canary) plus a synthetic overload
             burst; the invariant "every admitted request gets exactly
             one verdict, and no fault becomes an unhandled exception
             or a block" must hold, the breaker must trip and recover
  swapdrill  the guarded-rollout state machine (docs/ROBUSTNESS.md
             "Guarded rollout"): a known-good pack is driven through
             the full staged rollout to LIVE, a rulecheck-dirty pack
             (dead-regex fixture) to REJECTED with zero traffic
             impact, and a forced mid-canary failure auto-rolls back
             to the incumbent — exactly-one-verdict throughout
  modelgate  the learned scoring lane (docs/LEARNED_SCORING.md): a
             deterministic seeded retrain on the exported golden-corpus
             feature dataset must reproduce the artifact hash, replay
             with zero new false negatives vs the fixed CRS weights,
             and flag strictly fewer benign requests at the calibrated
             threshold (reports/MODELGATE.json)
  devicegate Pallas device-path parity (ISSUE 13, docs/SCAN_KERNEL.md
             "Device path"): every Pallas kernel runs in Mosaic
             INTERPRET mode — the same kernel program the TPU lowering
             compiles — over a seeded corpus of ragged batches and
             must produce match words BIT-IDENTICAL to the ops/scan.py
             XLA reference; divergence fails the build before any TPU
             time is spent (reports/DEVICEGATE.json)
  promlint   Prometheus exposition hygiene (analysis/promlint.py):
             /metrics scraped from an in-process server after real
             multi-tenant traffic — ipt_ prefix, _total on counters,
             HELP/TYPE pairs, bounded label cardinality (fails on the
             first unbounded per-rule/per-tenant series)
  retunegate profile-guided retuning loop (ISSUE 15, docs/RETUNE.md):
             a deterministic mini-retune on the bundled pack — profile
             built once from a bench-corpus telemetry replay, compiled
             twice (fingerprint must reproduce), zero lost candidates
             vs the exact compile, zero new false negatives on the
             golden replay, and the retuned pack's measured candidate
             load must not exceed the static pack's
             (reports/RETUNE.json)
  fleetgate  the fleet telemetry plane (ISSUE 18,
             docs/OBSERVABILITY.md "Fleet telemetry"): three
             in-process serve loops under replayed corpus traffic,
             one aggregator — counter conservation (fleet == Σ
             per-node == counted traffic, including with one node
             faulted stale mid-run via the scrape_5xx site),
             MeasuredProfile.merge content-hash reproducibility, and
             a promlint-clean aggregated /fleet/metrics exposition
             (reports/FLEETOBS.json)
  fleetdrill the fleet control plane (ISSUE 19, docs/SERVING.md
             "Fleet serving"): a 3-node in-process fleet behind the
             shared admission front — one node killed mid-wave with
             zero verdict loss, the good pack staged node-by-node to
             LIVE with the fleet LKG pointer written, the broken pack
             stopped at central admission, a mid-wave node death
             rolling the whole fleet back to LKG, and one forced
             retune-daemon cycle landing fleet-wide
             (reports/FLEETDRILL.json)
  benchtrend the checked-in BENCH_r*.json req/s/chip trajectory
             (tools/bench_trend.py): >10% regression vs the previous
             snapshot fails; SKIPPED with fewer than two artifacts

The container policy is "no new installs": when ruff or mypy are not
present, those gates report SKIPPED (recorded in the CI report so the
absence is auditable) instead of failing — rulecheck always runs, it
has no external dependency.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # script execution puts tools/ first
    sys.path.insert(0, str(REPO))
#: the mypy gate is TARGETED: the correctness-critical planes first;
#: widen as modules gain annotations (zero-warning baseline per scope).
#: ISSUE 11 widened models/ to the whole package (pipeline.py and every
#: tenant_guard caller) — ops/ stays out (device-kernel code).
MYPY_SCOPE = ["ingress_plus_tpu/compiler", "ingress_plus_tpu/analysis",
              "ingress_plus_tpu/serve",   # includes serve/lanes.py
              "ingress_plus_tpu/models",  # pipeline + tenant_guard callers
              "ingress_plus_tpu/post/topk.py",
              "ingress_plus_tpu/control/rollout.py",
              "ingress_plus_tpu/control/fleetobs.py",
              "ingress_plus_tpu/control/fleetctl.py",
              "ingress_plus_tpu/control/retuned.py",
              "ingress_plus_tpu/parallel/serve_mesh.py",
              "ingress_plus_tpu/learn",
              "ingress_plus_tpu/utils/promparse.py",
              "ingress_plus_tpu/utils/slo.py"]


def _tool_available(module: str, binary: str) -> bool:
    return importlib.util.find_spec(module) is not None or \
        shutil.which(binary) is not None


def _run_tool(module: str, binary: str, args: list) -> dict:
    """Run a lint tool as `python -m module` (preferred: pinned to this
    interpreter) or the bare binary; SKIPPED when neither exists."""
    if not _tool_available(module, binary):
        return {"status": "SKIPPED",
                "detail": "%s not installed in this environment "
                          "(no-install policy); gate not evaluated"
                          % binary}
    if importlib.util.find_spec(module) is not None:
        cmd = [sys.executable, "-m", module] + args
    else:
        cmd = [binary] + args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    out = (proc.stdout + proc.stderr).strip()
    return {"status": "OK" if proc.returncode == 0 else "FAIL",
            "exit_code": proc.returncode,
            "seconds": round(time.time() - t0, 2),
            "detail": out[-4000:]}


def run_ruff() -> dict:
    return _run_tool("ruff", "ruff", ["check", "ingress_plus_tpu",
                                      "tools", "tests"])


def run_mypy() -> dict:
    return _run_tool("mypy", "mypy", MYPY_SCOPE)


def run_rulecheck(write_report: bool) -> dict:
    from ingress_plus_tpu.analysis import run_rulecheck as rc
    t0 = time.time()
    report = rc()
    gating = report.gating("error")
    result = {
        "status": "OK" if not gating else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "counts": report.counts(),
        "suppressed": sum(report.counts(suppressed=True).values()),
        "detail": "; ".join("%s %s (rule %s)" % (f.severity, f.check,
                                                 f.rule_id or f.subject)
                            for f in gating) or
                  "%d findings, 0 unsuppressed errors"
                  % len(report.findings),
    }
    if write_report:
        out = REPO / "reports" / "RULECHECK.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(report.to_json())
        result["report"] = str(out.relative_to(REPO))
    return result


def run_concheck_gate(write_report: bool) -> dict:
    """Concurrency static analysis of the serve-plane sources (ISSUE
    11, docs/ANALYSIS.md "Concurrency analysis"): zero unsuppressed
    error-severity findings — unguarded cross-thread mutations,
    live-view escapes, lock-order cycles, lifecycle lint."""
    from ingress_plus_tpu.analysis.concheck import run_concheck as cc
    t0 = time.time()
    report = cc()
    gating = report.gating("error")
    meta = report.meta or {}
    result = {
        "status": "OK" if not gating else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "counts": report.counts(),
        "suppressed": sum(report.counts(suppressed=True).values()),
        "functions": meta.get("functions"),
        "thread_roots": len(meta.get("thread_roots", ())),
        "lock_order_edges": len(meta.get("lock_order_edges", ())),
        "detail": "; ".join("%s %s (%s)" % (f.severity, f.check,
                                            f.subject)
                            for f in gating) or
                  "%d findings, 0 unsuppressed errors over %d functions"
                  % (len(report.findings), meta.get("functions", 0)),
    }
    if write_report:
        out = REPO / "reports" / "CONCHECK.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(report.to_json())
        result["report"] = str(out.relative_to(REPO))
    return result


#: per-family retention floor for the mutation harness (ISSUE 17): a
#: rule-pack or normalizer change that lets any modeled evasion family
#: strip >5% of detected attacks fails CI before it ships
EVASION_RETENTION_FLOOR = 0.95


def run_evasiongate(write_report: bool) -> dict:
    """Evasion-closure gate (ISSUE 17, docs/ANALYSIS.md "Evasion
    analysis"): the static evadecheck findings gate at WARNING (every
    accepted weakness must carry a reasoned baseline entry), and the
    seeded mutation harness must hold the per-family retention floor
    on the bundled pack.  The harness escapes feed back into the
    static report as corroboration, so a real runtime escape both
    drops retention and escalates its static finding to error."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.analysis import run_evadecheck as ec
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.utils.evasion import mutation_harness

    pipe = DetectionPipeline(compile_ruleset(load_bundled_rules()),
                             mode="monitoring")
    harness = mutation_harness(pipe)
    escapes = [e for fam in harness["families"].values()
               for e in fam["escapes"]]
    report = ec(escapes=escapes)
    gating = report.gating("warning")

    weak = {fam: st["retention"]
            for fam, st in harness["families"].items()
            if st["retention"] < EVASION_RETENTION_FLOOR}
    problems = ["%s %s (rule %s)" % (f.severity, f.check,
                                     f.rule_id or f.subject)
                for f in gating]
    problems += ["family %s retention %.3f < %.2f"
                 % (fam, r, EVASION_RETENTION_FLOOR)
                 for fam, r in sorted(weak.items())]
    result = {
        "status": "OK" if not problems else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "counts": report.counts(),
        "suppressed": sum(report.counts(suppressed=True).values()),
        "corroborated": (report.meta or {}).get("corroborated", 0),
        "min_retention": harness["min_retention"],
        "retention_floor": EVASION_RETENTION_FLOOR,
        "detail": "; ".join(problems) or
                  "%d findings all baselined, min retention %.3f over "
                  "%d families (%d base-detected attacks)"
                  % (len(report.findings), harness["min_retention"],
                     len(harness["families"]),
                     harness["corpus"]["base_detected"]),
    }
    if write_report:
        out = REPO / "reports" / "EVASION.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        payload = {
            "static": json.loads(report.to_json()),
            "harness": harness,
            "retention_floor": EVASION_RETENTION_FLOOR,
        }
        out.write_text(json.dumps(payload, indent=2) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_dead_rules() -> dict:
    """Runtime dead-rule gate (ISSUE 3): compile the bundled pack,
    drive the bench corpus through a CPU pipeline, and fail on any
    runtime-dead or latent-dead rule (confirm regex the runtime cannot
    evaluate — the runtime twin of rulecheck's
    ``regex.confirm-unparsable``) that is not already suppressed in the
    CRS tree's rulecheck-baseline.json.  This is the dynamic
    counterpart of the rulecheck gate: a rule the static audit missed
    still fails CI the moment real traffic candidates it."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.analysis import BUNDLED_RULES
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.utils.corpus import generate_corpus

    cr = compile_ruleset(load_bundled_rules())
    pipe = DetectionPipeline(cr, mode="monitoring")
    reqs = [lr.request for lr in
            generate_corpus(n=256, attack_fraction=0.2, seed=42)]
    for i in range(0, len(reqs), 64):
        pipe.detect(reqs[i:i + 64])
    health = pipe.rule_stats.health()

    suppressed = set()
    baseline = BUNDLED_RULES / "rulecheck-baseline.json"
    if baseline.exists():
        spec = json.loads(baseline.read_text())
        for e in spec.get("suppressions", []):
            if e.get("check") in ("regex.confirm-unparsable",
                                  "runtime.dead-rule"):
                suppressed.add(e.get("rule_id"))
    dead = [d for d in health["runtime_dead"] + health["latent_dead"]
            if d["rule_id"] not in suppressed]
    return {
        "status": "FAIL" if dead else "OK",
        "seconds": round(time.time() - t0, 2),
        "requests": health["requests"],
        "detail": "; ".join(
            "rule %d dead at runtime (%s)" % (d["rule_id"], d["reason"])
            for d in dead) or
            "0 unsuppressed runtime-dead rules over %d corpus requests"
            % health["requests"],
    }


def run_faultmatrix(write_report: bool) -> dict:
    """Fail-safe serve-plane gate (docs/ROBUSTNESS.md): every fault
    scenario + the overload burst against a real CPU batcher; any
    invariant violation fails CI.

    Runs with InstrumentedLock debugging ON (docs/ANALYSIS.md
    "Concurrency analysis"): every batcher the 15 scenarios build gets
    order-asserting locks, so the fault matrix doubles as a race/
    deadlock stress harness — any lock-pair observed in both orders
    fails the gate."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.utils.faults import run_fault_matrix
    from ingress_plus_tpu.utils.trace import (
        debug_locks_enabled,
        enable_debug_locks,
        lock_registry,
    )

    lock_registry.reset()
    was_on = debug_locks_enabled()
    enable_debug_locks(True)
    try:
        report = run_fault_matrix()
    finally:
        enable_debug_locks(was_on)
    locks = lock_registry.snapshot()
    report["lock_order"] = locks
    lock_violations = locks["violation_count"]
    failed = {name: r["violations"]
              for name, r in report["scenarios"].items() if not r["ok"]}
    if lock_violations:
        failed["lock_order"] = ["%s <-> %s" % tuple(v["pair"])
                                for v in locks["violations"]]
    result = {
        "status": ("OK" if report["passed"] and not lock_violations
                   else "FAIL"),
        "seconds": round(time.time() - t0, 2),
        "scenarios": {name: r["ok"]
                      for name, r in report["scenarios"].items()},
        "lock_acquisitions": locks["acquisitions"],
        "lock_order_violations": lock_violations,
        "detail": "; ".join("%s: %s" % (n, "; ".join(v))
                            for n, v in failed.items()) or
                  "%d scenarios, invariant held under every fault; "
                  "%d instrumented lock acquisitions, 0 order "
                  "violations"
                  % (len(report["scenarios"]), locks["acquisitions"]),
    }
    if write_report:
        out = REPO / "reports" / "FAULTMATRIX.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2, default=str) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_swapdrill(write_report: bool) -> dict:
    """Guarded-rollout gate (ISSUE 5): the rollout state machine proven
    on a real CPU batcher — good pack to LIVE, dirty pack REJECTED with
    zero traffic impact, forced mid-canary failure ROLLED_BACK — with
    the exactly-one-verdict invariant held throughout."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.control.rollout import run_swap_drill

    report = run_swap_drill()
    failed = {name: r["violations"]
              for name, r in report["drills"].items()
              if "ok" in r and not r["ok"]}
    result = {
        "status": "OK" if report["passed"] else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "drills": {name: r["ok"] for name, r in report["drills"].items()
                   if "ok" in r},
        "detail": "; ".join("%s: %s" % (n, "; ".join(v))
                            for n, v in failed.items()) or
                  "good pack LIVE, dirty pack REJECTED, mid-canary "
                  "fault ROLLED_BACK — one verdict per request held",
    }
    if write_report:
        out = REPO / "reports" / "SWAPDRILL.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2, default=str) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_modelgate(write_report: bool) -> dict:
    """Learned-scorer gate (ISSUE 8, docs/LEARNED_SCORING.md): a
    deterministic seeded retrain on the exported golden-corpus feature
    dataset must (1) reproduce the artifact hash across two trains
    (determinism + hash stability), (2) replay with ZERO new false
    negatives vs the fixed CRS weights, and (3) flag strictly fewer
    benign requests at the calibrated threshold (the ModSec-Learn
    claim) — the comparison lands in reports/MODELGATE.json."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.learn.train import (
        compare_scorers, train_from_dataset)
    from ingress_plus_tpu.utils.export_corpus import build_feature_dataset

    ds = build_feature_dataset(n=1024, seed=20260729)
    head_a = train_from_dataset(ds)
    head_b = train_from_dataset(ds)
    violations = []
    if head_a.fingerprint() != head_b.fingerprint():
        violations.append(
            "retrain not deterministic: %s != %s"
            % (head_a.fingerprint(), head_b.fingerprint()))
    cmp = compare_scorers(ds, head_a)
    if cmp["new_fn_vs_fixed"] != 0:
        violations.append("learned head lost %d attack(s) the fixed "
                          "weights caught" % cmp["new_fn_vs_fixed"])
    if cmp["learned"]["fn"] > cmp["fixed"]["fn"]:
        violations.append("learned fn %d > fixed fn %d"
                          % (cmp["learned"]["fn"], cmp["fixed"]["fn"]))
    if cmp["fixed"]["fp"] == 0:
        violations.append(
            "fixed weights produced 0 benign flags on this corpus — "
            "the FP-reduction claim is unmeasurable (corpus drifted?)")
    elif cmp["learned"]["fp"] >= cmp["fixed"]["fp"]:
        violations.append("learned fp %d not strictly below fixed fp %d"
                          % (cmp["learned"]["fp"], cmp["fixed"]["fp"]))
    report = {
        "passed": not violations,
        "violations": violations,
        "dataset": {"fingerprint": ds.fingerprint(), "rows": ds.n,
                    "attacks": int(ds.y.sum()),
                    "ruleset": ds.meta.get("ruleset")},
        "artifact": {"version": head_a.version,
                     "threshold": round(float(head_a.threshold), 6),
                     "retrain_stable":
                         head_a.fingerprint() == head_b.fingerprint()},
        "comparison": cmp,
    }
    result = {
        "status": "OK" if report["passed"] else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "detail": "; ".join(violations) or
                  "retrain stable (%s); fixed fp=%d -> learned fp=%d at "
                  "zero new FNs over %d rows"
                  % (head_a.version, cmp["fixed"]["fp"],
                     cmp["learned"]["fp"], ds.n),
    }
    if write_report:
        out = REPO / "reports" / "MODELGATE.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2, default=str) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


#: seeded SecLang fixture for the devicegate (compact on purpose: the
#: gate's job is KERNEL parity, not CRS coverage — the bundled-pack
#: geometry case below covers the multi-tile padding paths)
_DEVICEGATE_RULES = """
SecRule ARGS "@rx (?i)union\\s+select" "id:1,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS "@rx (?i)<script[^>]*>" "id:2,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS "@rx /etc/(?:passwd|shadow)" "id:3,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell load_file(" "id:4,phase:2,block,severity:ERROR,tag:'attack-sqli'"
SecRule ARGS "@rx (?:;|\\|)\\s*(?:cat|ls|id)\\b" "id:5,phase:2,block,severity:ERROR,tag:'attack-rce'"
"""


def _devicegate_batches(n_batches: int = 3, n_rows: int = 13):
    """Deterministic ragged batches: random printable rows with planted
    payloads at varying offsets, empty rows, and odd lengths."""
    import numpy as np

    from ingress_plus_tpu.ops.scan import pad_rows

    attacks = [b"1 union  select password from users",
               b"<script>alert(1)</script>", b"../../etc/passwd",
               b"; cat /etc/hosts", b"sleep(5) or benchmark(9,1)"]
    batches = []
    for seed in range(n_batches):
        rng = np.random.default_rng(seed)
        rows = []
        for i in range(n_rows):
            body = bytes(rng.integers(
                32, 127, size=int(rng.integers(0, 300))))
            if i % 3 == 0 and body:
                a = attacks[(seed + i) % len(attacks)]
                pos = int(rng.integers(0, max(1, len(body) - len(a))))
                body = body[:pos] + a + body[pos + len(a):]
            rows.append(body)
        tokens, lengths = pad_rows(rows, round_to=64)
        batches.append((seed, tokens, lengths))
    return batches


def run_devicegate(write_report: bool) -> dict:
    """Pallas device-path parity gate (ISSUE 13): interpret-mode
    kernels — the code path the JAX_PLATFORMS!=cpu lowering compiles —
    vs the ops/scan.py XLA reference, bit-identical match words over
    seeded ragged batches, on both the compact fixture pack and the
    bundled pack's real multi-tile geometry.  Writes
    reports/DEVICEGATE.json; any divergence fails the build."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    import numpy as np

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.ops.pallas_scan import (
        PallasByteScanner,
        PallasPairScanner,
        PallasScanner,
    )
    from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes

    tables = ScanTables.from_bitap(
        compile_ruleset(parse_seclang(_DEVICEGATE_RULES)).tables)
    kernels = {
        "pallas": PallasScanner(tables, TB=8, CL=64),
        "pallas2": PallasPairScanner(tables, TB=8, CL=16, MR=8),
        "pallas3": PallasByteScanner(tables, TB=8, CL=16, MR=8),
    }
    cases = []
    for seed, tokens, lengths in _devicegate_batches():
        want_m, want_s = scan_bytes(tables, tokens, lengths)
        want_m = np.asarray(want_m)
        for name, sc in kernels.items():
            got_m, got_s = sc(tokens, lengths, interpret=True)
            case = {
                "pack": "fixture", "kernel": name, "seed": seed,
                "B": int(tokens.shape[0]), "L": int(tokens.shape[1]),
                "match_equal": bool(
                    np.array_equal(np.asarray(got_m), want_m)),
            }
            if name == "pallas":
                # the byte kernel preserves the full scan_bytes state
                # contract; the pair kernels' dead-padding state is a
                # documented difference (only match is consumed)
                case["state_equal"] = bool(np.array_equal(
                    np.asarray(got_s), np.asarray(want_s)))
            cases.append(case)
    # bundled-pack geometry: the real serving width (multi-tile Wp,
    # K1p padding) through the raw-byte kernel — the shapes a first
    # TPU run would compile
    cr = compile_ruleset(load_bundled_rules())
    bt = ScanTables.from_bitap(cr.tables)
    rng = np.random.default_rng(7)
    toks = rng.integers(32, 127, (8, 128)).astype(np.uint8)
    atk = b"1' union select password from users -- "
    toks[0, :len(atk)] = np.frombuffer(atk, np.uint8)
    lens = np.asarray([128, 37, 0, 128, 5, 64, 127, 128], np.int32)
    want_m = np.asarray(scan_bytes(bt, toks, lens)[0])
    got_m, _ = PallasByteScanner(bt)(toks, lens, interpret=True)
    cases.append({
        "pack": "bundled (%d rules, %d words)" % (cr.n_rules,
                                                  bt.n_words),
        "kernel": "pallas3", "seed": 7, "B": 8, "L": 128,
        "match_equal": bool(np.array_equal(np.asarray(got_m), want_m)),
        "non_vacuous": bool(want_m[0].any()),
    })
    bad = [c for c in cases
           if not c["match_equal"] or c.get("state_equal") is False]
    report = {
        "passed": not bad,
        "cases": cases,
        "divergent": bad,
        "note": "interpret mode executes the same Mosaic kernel "
                "program the TPU lowering compiles — this gate is the "
                "CI-run exercise of the JAX_PLATFORMS!=cpu code path",
    }
    result = {
        "status": "OK" if not bad else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "cases": len(cases),
        "detail": "; ".join(
            "%s/%s seed %s DIVERGED" % (c["pack"], c["kernel"],
                                        c["seed"]) for c in bad) or
            "%d interpret-vs-reference cases bit-identical (incl. "
            "bundled-pack geometry)" % len(cases),
    }
    if write_report:
        out = REPO / "reports" / "DEVICEGATE.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_promlint() -> dict:
    """Prometheus exposition hygiene gate (ISSUE 12 satellite,
    analysis/promlint.py): scrape /metrics from an IN-PROCESS serve
    loop after real multi-tenant traffic — naming (ipt_ prefix, _total
    on counters), HELP/TYPE pairs, bounded label cardinality
    (bounded_counter_series respected), histogram shape.  Fails on the
    first unbounded per-rule or per-tenant series that slips into the
    text exposition."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.analysis.promlint import check_exposition
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop
    from ingress_plus_tpu.utils.corpus import generate_corpus

    cr = compile_ruleset(load_bundled_rules())
    pipe = DetectionPipeline(cr, mode="monitoring")
    batcher = Batcher(pipe, max_batch=32)
    try:
        # multi-tenant traffic so the per-tenant/per-family folds are
        # EXERCISED, not vacuously bounded: 48 distinct tenants is past
        # the 30-series budget, so the "other" fold must engage
        reqs = [lr.request for lr in
                generate_corpus(n=96, attack_fraction=0.3, seed=7)]
        for i, r in enumerate(reqs):
            r.tenant = i % 48
        futs = [batcher.submit(r) for r in reqs]
        for f in futs:
            f.result(timeout=120)
        serve = ServeLoop(batcher, socket_path="/tmp/ipt-promlint.sock")
        text = serve._metrics_text()
    finally:
        batcher.close()
    findings = check_exposition(text)
    return {
        "status": "FAIL" if findings else "OK",
        "seconds": round(time.time() - t0, 2),
        "series_lines": sum(1 for ln in text.splitlines()
                            if ln and not ln.startswith("#")),
        "detail": "; ".join(findings[:20]) or
        "exposition clean: %d series lines, every TYPE has HELP, all "
        "label sets bounded"
        % sum(1 for ln in text.splitlines()
              if ln and not ln.startswith("#")),
    }


def run_fleetgate(write_report: bool) -> dict:
    """Fleet telemetry gate (ISSUE 18, control/fleetobs.py): three
    IN-PROCESS serve loops, replayed corpus traffic, one aggregator.
    Asserts the fleet plane's three contracts: (1) counter
    conservation — the aggregated ipt_requests_total equals the sum of
    per-node counters equals the independently counted traffic, and
    keeps holding over the reachable subset when a node is faulted
    stale mid-run (scrape_5xx site); (2) merge determinism —
    MeasuredProfile.merge over the scraped per-node profiles
    reproduces the same content hash twice, argument order shuffled;
    (3) the aggregated /fleet/metrics exposition passes promlint in
    fleet mode.  Writes reports/FLEETOBS.json."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.analysis.promlint import check_exposition
    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.control.fleetobs import (
        FleetObserver,
        serve_loop_transport,
    )
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop
    from ingress_plus_tpu.utils import faults
    from ingress_plus_tpu.utils.corpus import generate_corpus

    n_nodes = 3
    checks: dict = {}
    failures: list = []
    cr = compile_ruleset(load_bundled_rules())
    batchers = [Batcher(DetectionPipeline(cr, mode="monitoring"),
                        max_batch=16) for _ in range(n_nodes)]
    saved_plan = faults.active()
    try:
        serves = [ServeLoop(b, socket_path="/tmp/ipt-fleetgate-%d.sock"
                            % i) for i, b in enumerate(batchers)]
        obs = FleetObserver()
        for i, s in enumerate(serves):
            obs.add_node("n%d" % i, transport=serve_loop_transport(s))

        def wave(seed: int, per_node: int = 32) -> int:
            futs = []
            for i, b in enumerate(batchers):
                reqs = [lr.request for lr in generate_corpus(
                    n=per_node, attack_fraction=0.25,
                    seed=seed * 10 + i)]
                for j, r in enumerate(reqs):
                    r.tenant = j % 8
                futs += [b.submit(r) for r in reqs]
            for f in futs:
                f.result(timeout=120)
            return len(futs)

        # leg 1: full fleet conservation
        sent = wave(1)
        obs.scrape()
        counters, per_node = obs.counters_snapshot()
        fleet_req = counters.get("ipt_requests_total")
        node_sum = sum(per_node.get("ipt_requests_total", {}).values())
        checks["conservation_full"] = {
            "submitted": sent, "fleet": fleet_req, "node_sum": node_sum,
            "ok": fleet_req == node_sum == float(sent)}
        if not checks["conservation_full"]["ok"]:
            failures.append("conservation (full fleet): fleet=%s "
                            "node_sum=%s submitted=%d"
                            % (fleet_req, node_sum, sent))

        # leg 2: aggregated exposition is promlint-clean (fleet mode)
        findings = check_exposition(obs.fleet_metrics(), fleet=True)
        checks["promlint_fleet"] = {"findings": findings[:10],
                                    "ok": not findings}
        if findings:
            failures.append("aggregate exposition: %s"
                            % "; ".join(findings[:5]))

        # leg 3: merge determinism (same inputs, shuffled order,
        # twice -> same canonical bytes, same content hash)
        profs = [n.profile for n in obs.nodes if n.profile is not None]
        h1 = MeasuredProfile.merge(profs).content_hash()
        h2 = MeasuredProfile.merge(list(reversed(profs))).content_hash()
        checks["merge_determinism"] = {
            "hash_1": h1, "hash_2": h2,
            "profiles": len(profs), "ok": h1 == h2 and len(profs) == 3}
        if not checks["merge_determinism"]["ok"]:
            failures.append("profile merge not deterministic: %s vs %s"
                            % (h1, h2))

        # leg 4: one node faulted stale mid-run — conservation must
        # hold over the reachable subset, stale node out of rollups
        faults.install(faults.FaultPlan.from_spec("scrape_5xx:times=1"))
        sent += wave(2)
        health = obs.scrape()
        counters, per_node = obs.counters_snapshot()
        reach = {k: v for k, v in
                 per_node.get("ipt_requests_total", {}).items()}
        checks["conservation_faulted"] = {
            "nodes_up": health["nodes_up"],
            "nodes_stale": health["nodes_stale"],
            "fleet": counters.get("ipt_requests_total"),
            "reachable_sum": sum(reach.values()),
            "stale_excluded": "n0" not in reach,
            "ok": (health["nodes_up"] == n_nodes - 1
                   and health["nodes_stale"] == 1
                   and "n0" not in reach
                   and counters.get("ipt_requests_total")
                   == sum(reach.values()))}
        if not checks["conservation_faulted"]["ok"]:
            failures.append("conservation (faulted): %r"
                            % checks["conservation_faulted"])

        # leg 5: recovery — plan exhausted, full fleet again
        faults.clear()
        health = obs.scrape()
        counters, _pn = obs.counters_snapshot()
        checks["recovery"] = {
            "nodes_up": health["nodes_up"],
            "fleet": counters.get("ipt_requests_total"),
            "ok": (health["nodes_up"] == n_nodes
                   and counters.get("ipt_requests_total")
                   == float(sent))}
        if not checks["recovery"]["ok"]:
            failures.append("recovery: %r" % checks["recovery"])
    finally:
        faults.install(saved_plan)
        for b in batchers:
            b.close()

    report = {"nodes": n_nodes, "checks": checks,
              "skew_findings": health.get("skew_findings", []),
              "passed": not failures}
    result = {
        "status": "FAIL" if failures else "OK",
        "seconds": round(time.time() - t0, 2),
        "detail": "; ".join(failures[:5]) or
        "conservation holds (full + 1-node-stale + recovery), merge "
        "hash %s reproduced, aggregate exposition clean"
        % checks["merge_determinism"]["hash_1"],
    }
    if write_report:
        out = REPO / "reports" / "FLEETOBS.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_retunegate(write_report: bool) -> dict:
    """Profile-guided retuning gate (ISSUE 15, docs/RETUNE.md): a
    deterministic mini-retune on the bundled pack.  The profile is
    built ONCE from a bench-corpus telemetry replay (profile TIMINGS
    are measurements and legitimately differ between replays — the
    determinism contract is same profile BYTES → same pack), then the
    compiler runs twice from those bytes and must (1) reproduce the
    pack fingerprint, (2) lose ZERO candidates vs the exact compile,
    (3) replay the golden corpus with ZERO new false negatives vs the
    static-model pack, and (4) not exceed the static pack's measured
    candidate load (the deterministic throughput proxy — fewer
    candidates IS the mechanism of the confirm-stage win)."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    sys.path.insert(0, str(REPO / "tools"))
    import retune as rt

    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.compiler.reduce import (
        ReductionConfig,
        measure_inflation,
    )
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import merge_rows, \
        rows_for_requests

    rules = rt._load_rules()
    prof = rt.build_profile(rules, corpus_n=192, seed=42)
    prof_bytes = prof.to_json()

    cr_a = compile_ruleset(rules, reduction=ReductionConfig(profile=prof))
    cr_b = compile_ruleset(rules, reduction=ReductionConfig(
        profile=MeasuredProfile.from_json(prof_bytes)))
    static_cr = compile_ruleset(rules)
    exact_cr = compile_ruleset(rules, reduction=ReductionConfig.off())

    rows = merge_rows(rows_for_requests(rt._corpus(192, 43)))[0]
    infl_static = measure_inflation(exact_cr.tables, static_cr.tables,
                                    rows)
    infl = measure_inflation(exact_cr.tables, cr_a.tables, rows)

    replay = rt._replay_fns(DetectionPipeline(static_cr, mode="detect"),
                            DetectionPipeline(cr_a, mode="detect"),
                            rt._corpus(192, 20260804,
                                       attack_fraction=0.5))

    checks = {
        "fingerprint_stable": cr_a.version == cr_b.version,
        "zero_lost_candidates": infl["lost_candidates"] == 0,
        "zero_new_fns": replay["new_fns"] == 0,
        "candidate_load_not_worse":
            infl["candidates_reduced"]
            <= infl_static["candidates_reduced"],
    }
    report = {
        "profile_hash": prof.content_hash(),
        "profile_rules": len(prof.rules),
        "static_fingerprint": static_cr.version,
        "retuned_fingerprint": cr_a.version,
        "retrain_fingerprint": cr_b.version,
        "inflation": {"static": infl_static, "retuned": infl},
        "replay": replay,
        "reduction": cr_a.reduction,
        "checks": checks,
        "passed": all(checks.values()),
    }
    failed = [k for k, ok in checks.items() if not ok]
    result = {
        "status": "OK" if report["passed"] else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "detail": ("; ".join(failed) if failed else
                   "profile %s -> pack %s reproducible, lost=0, "
                   "new_fns=0, candidates %d <= static %d"
                   % (report["profile_hash"], cr_a.version,
                      infl["candidates_reduced"],
                      infl_static["candidates_reduced"])),
    }
    if write_report:
        out = REPO / "reports" / "RETUNE.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2, default=str) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def run_benchtrend() -> dict:
    """Bench trajectory gate (ISSUE 12 satellite, tools/bench_trend.py):
    the latest checked-in BENCH_r*.json must not regress >10% vs the
    previous snapshot.  SKIPPED cleanly when fewer than two artifacts
    exist (a fresh tree has nothing to compare)."""
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "bench_trend.py"),
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    try:
        report = json.loads(proc.stdout)
    except json.JSONDecodeError:
        return {"status": "FAIL", "seconds": round(time.time() - t0, 2),
                "detail": "bench_trend emitted no JSON (rc=%d): %s"
                          % (proc.returncode,
                             (proc.stderr or proc.stdout)[-300:])}
    status = report.get("status", "FAIL")
    return {
        "status": {"OK": "OK", "SKIP": "SKIPPED"}.get(status, "FAIL"),
        "seconds": round(time.time() - t0, 2),
        "latest": report.get("latest"),
        "latest_value": report.get("latest_value"),
        "delta_vs_prev": report.get("delta_vs_prev"),
        "detail": report.get("detail", ""),
    }


def run_fleetdrill(write_report: bool) -> dict:
    """Fleet control-plane gate (ISSUE 19, control/fleetctl.py): the
    whole fleet choreography proven in one process — a 3-node front
    wave with one node killed mid-send (zero verdict loss), the good
    candidate promoted node by node to LIVE with the fleet LKG written,
    the broken pack stopped at central admission, a mid-wave node
    failure rolling the WHOLE fleet back to LKG, and one forced
    retune-daemon cycle end to end (profile → four gates →
    fleet-staged rollout).  Writes reports/FLEETDRILL.json."""
    t0 = time.time()
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.control.fleetctl import run_fleet_drill

    report = run_fleet_drill()
    failed = {name: leg for name, leg in report["legs"].items()
              if not leg["ok"]}
    result = {
        "status": "OK" if report["passed"] else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "legs": {name: leg["ok"] for name, leg in report["legs"].items()},
        "detail": "; ".join("%s: %s" % (n, leg.get("violations")
                                        or leg.get("reason")
                                        or leg.get("result"))
                            for n, leg in failed.items()) or
                  "front kill zero-loss, fleet LIVE + LKG, bad pack "
                  "stopped, mid-wave death rolled the fleet back, "
                  "daemon cycle to LIVE",
    }
    if write_report:
        out = REPO / "reports" / "FLEETDRILL.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2, default=str) + "\n")
        result["report"] = str(out.relative_to(REPO))
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/lint.py")
    ap.add_argument("--ci", action="store_true",
                    help="CI mode: also write reports/RULECHECK.json")
    ap.add_argument("--only",
                    choices=["ruff", "mypy", "rulecheck", "concheck",
                             "evasiongate", "deadrules", "faultmatrix",
                             "swapdrill", "modelgate", "devicegate",
                             "promlint", "benchtrend", "retunegate",
                             "fleetgate", "fleetdrill"],
                    default=None)
    args = ap.parse_args(argv)

    gates = {}
    if args.only in (None, "ruff"):
        gates["ruff"] = run_ruff()
    if args.only in (None, "mypy"):
        gates["mypy"] = run_mypy()
    if args.only in (None, "rulecheck"):
        gates["rulecheck"] = run_rulecheck(write_report=args.ci)
    if args.only in (None, "concheck"):
        gates["concheck"] = run_concheck_gate(write_report=args.ci)
    if args.only in (None, "evasiongate"):
        gates["evasiongate"] = run_evasiongate(write_report=args.ci)
    if args.only in (None, "deadrules"):
        gates["deadrules"] = run_dead_rules()
    if args.only in (None, "faultmatrix"):
        gates["faultmatrix"] = run_faultmatrix(write_report=args.ci)
    if args.only in (None, "swapdrill"):
        gates["swapdrill"] = run_swapdrill(write_report=args.ci)
    if args.only in (None, "modelgate"):
        gates["modelgate"] = run_modelgate(write_report=args.ci)
    if args.only in (None, "devicegate"):
        gates["devicegate"] = run_devicegate(write_report=args.ci)
    if args.only in (None, "promlint"):
        gates["promlint"] = run_promlint()
    if args.only in (None, "retunegate"):
        gates["retunegate"] = run_retunegate(write_report=args.ci)
    if args.only in (None, "fleetgate"):
        gates["fleetgate"] = run_fleetgate(write_report=args.ci)
    if args.only in (None, "fleetdrill"):
        gates["fleetdrill"] = run_fleetdrill(write_report=args.ci)
    if args.only in (None, "benchtrend"):
        gates["benchtrend"] = run_benchtrend()

    failed = False
    for name, r in gates.items():
        print("%-10s %-8s %s" % (name, r["status"],
                                 r.get("detail", "").splitlines()[0]
                                 if r.get("detail") else ""))
        if r["status"] == "FAIL":
            failed = True
            detail = r.get("detail", "")
            if detail:
                print("  " + "\n  ".join(detail.splitlines()[:40]))
    if args.ci:
        summary = REPO / "reports" / "LINT.json"
        summary.parent.mkdir(parents=True, exist_ok=True)
        # persist without per-run wall-clock noise: the checked-in
        # summary should only diff when a gate's outcome changes
        stable = {name: {k: v for k, v in r.items() if k != "seconds"}
                  for name, r in gates.items()}
        summary.write_text(json.dumps(stable, indent=2) + "\n")
        print("gate summary -> %s" % summary.relative_to(REPO))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
