"""Unified lint + static-analysis gate — the single CI entry point.

    python tools/lint.py           # run everything, report, exit status
    python tools/lint.py --ci      # same + write reports/RULECHECK.json

Three gates, one verdict:

  ruff       style/correctness lint per [tool.ruff] in pyproject.toml
             (zero-warning baseline: the selected rule set must be
             clean; new violations fail the gate)
  mypy       targeted type check of compiler/, analysis/, serve/ per
             [tool.mypy] in pyproject.toml
  rulecheck  the ruleset static analyzer (ingress_plus_tpu/analysis/,
             docs/ANALYSIS.md) over the bundled CRS tree: zero
             unsuppressed error-severity findings required

The container policy is "no new installs": when ruff or mypy are not
present, those gates report SKIPPED (recorded in the CI report so the
absence is auditable) instead of failing — rulecheck always runs, it
has no external dependency.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # script execution puts tools/ first
    sys.path.insert(0, str(REPO))
#: the mypy gate is TARGETED: the correctness-critical planes first;
#: widen as modules gain annotations (zero-warning baseline per scope)
MYPY_SCOPE = ["ingress_plus_tpu/compiler", "ingress_plus_tpu/analysis",
              "ingress_plus_tpu/serve"]


def _tool_available(module: str, binary: str) -> bool:
    return importlib.util.find_spec(module) is not None or \
        shutil.which(binary) is not None


def _run_tool(module: str, binary: str, args: list) -> dict:
    """Run a lint tool as `python -m module` (preferred: pinned to this
    interpreter) or the bare binary; SKIPPED when neither exists."""
    if not _tool_available(module, binary):
        return {"status": "SKIPPED",
                "detail": "%s not installed in this environment "
                          "(no-install policy); gate not evaluated"
                          % binary}
    if importlib.util.find_spec(module) is not None:
        cmd = [sys.executable, "-m", module] + args
    else:
        cmd = [binary] + args
    t0 = time.time()
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    out = (proc.stdout + proc.stderr).strip()
    return {"status": "OK" if proc.returncode == 0 else "FAIL",
            "exit_code": proc.returncode,
            "seconds": round(time.time() - t0, 2),
            "detail": out[-4000:]}


def run_ruff() -> dict:
    return _run_tool("ruff", "ruff", ["check", "ingress_plus_tpu",
                                      "tools", "tests"])


def run_mypy() -> dict:
    return _run_tool("mypy", "mypy", MYPY_SCOPE)


def run_rulecheck(write_report: bool) -> dict:
    from ingress_plus_tpu.analysis import run_rulecheck as rc
    t0 = time.time()
    report = rc()
    gating = report.gating("error")
    result = {
        "status": "OK" if not gating else "FAIL",
        "seconds": round(time.time() - t0, 2),
        "counts": report.counts(),
        "suppressed": sum(report.counts(suppressed=True).values()),
        "detail": "; ".join("%s %s (rule %s)" % (f.severity, f.check,
                                                 f.rule_id or f.subject)
                            for f in gating) or
                  "%d findings, 0 unsuppressed errors"
                  % len(report.findings),
    }
    if write_report:
        out = REPO / "reports" / "RULECHECK.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(report.to_json())
        result["report"] = str(out.relative_to(REPO))
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/lint.py")
    ap.add_argument("--ci", action="store_true",
                    help="CI mode: also write reports/RULECHECK.json")
    ap.add_argument("--only", choices=["ruff", "mypy", "rulecheck"],
                    default=None)
    args = ap.parse_args(argv)

    gates = {}
    if args.only in (None, "ruff"):
        gates["ruff"] = run_ruff()
    if args.only in (None, "mypy"):
        gates["mypy"] = run_mypy()
    if args.only in (None, "rulecheck"):
        gates["rulecheck"] = run_rulecheck(write_report=args.ci)

    failed = False
    for name, r in gates.items():
        print("%-10s %-8s %s" % (name, r["status"],
                                 r.get("detail", "").splitlines()[0]
                                 if r.get("detail") else ""))
        if r["status"] == "FAIL":
            failed = True
            detail = r.get("detail", "")
            if detail:
                print("  " + "\n  ".join(detail.splitlines()[:40]))
    if args.ci:
        summary = REPO / "reports" / "LINT.json"
        summary.parent.mkdir(parents=True, exist_ok=True)
        # persist without per-run wall-clock noise: the checked-in
        # summary should only diff when a gate's outcome changes
        stable = {name: {k: v for k, v in r.items() if k != "seconds"}
                  for name, r in gates.items()}
        summary.write_text(json.dumps(stable, indent=2) + "\n")
        print("gate summary -> %s" % summary.relative_to(REPO))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
