/*
 * ngx_http_detect_tpu_module — nginx-side shim for the TPU detection path.
 *
 * The native boundary of SURVEY.md §2.2: the reference integrates its WAF
 * as a closed-source nginx module (ngx_http_wallarm_module†) hooked into
 * the rewrite/access phases; this module is the open equivalent for the
 * TPU backend, implementing exactly the directives the template renderer
 * (ingress_plus_tpu/control/template.py) emits for
 * `detection-backend: tpu` locations:
 *
 *     detect_tpu on;
 *     detect_tpu_socket /run/ipt/detect.sock;
 *     detect_tpu_mode block | monitoring | off;
 *     detect_tpu_timeout_ms 30;
 *     detect_tpu_fail_open on;
 *     detect_tpu_tenant 7;
 *     detect_tpu_block_page /blocked.html;
 *     detect_tpu_parse_response on;        (body-filter phase, below)
 *     detect_tpu_parse_websocket on;
 *     detect_tpu_parser_disable xml;
 *     detect_tpu_metrics 127.0.0.1:9901;   (server scope)
 *
 * Request flow (nginx worker threads must never block on a verdict):
 *
 *   ACCESS phase, entry 1:  create ctx, start the client-body read
 *                           (ngx_http_read_client_request_body with a
 *                           continuation — the mirror-module pattern);
 *                           return NGX_DONE.
 *   body continuation:      re-enter the phase walk.
 *   ACCESS phase, entry 2:  capture method/uri/headers/body into the ctx
 *                           ON THE EVENT THREAD (the pool thread never
 *                           touches ngx_http_request_t), post the
 *                           blocking DetectClient round-trip
 *                           (detect_client.hpp) onto the "detect_tpu"
 *                           ngx_thread_pool; return NGX_AGAIN.
 *   task completion event:  (event-loop thread) mark the ctx done —
 *                           the ONLY completion signal the handler
 *                           reads — and re-enter the phase walk.
 *   ACCESS phase, entry 3:  apply the verdict: 403/block-page when
 *                           blocked in block mode; otherwise pass, with
 *                           an `X-Detect-TPU: fail-open` response header
 *                           when the verdict was a fail-open (the
 *                           load-bearing fallback contract, SURVEY.md §5
 *                           — enforced here AND in the sidecar).
 *
 * BUILD: requires the nginx source tree (not present in this dev image —
 * tests cover DetectClient itself via shim_selftest):
 *
 *     ./configure --add-module=/path/to/native/shim \
 *                 --with-threads --with-compat
 *
 * and an nginx.conf `thread_pool detect_tpu threads=32;` block.  The
 * `config` file next to this source declares the module to nginx's build
 * system; C++ linkage for detect_client is isolated behind
 * detect_tpu_roundtrip() (shim_bridge.cc).
 */

#include <ngx_config.h>
#include <ngx_core.h>
#include <ngx_http.h>

#include <unistd.h>   /* getpid() — ws stream ids (compat headers don't
                       * model the ngx_pid process global) */

/* implemented in shim_bridge.cc (C++, wraps ipt::DetectClient; one
 * thread-local client per pool thread, keyed on socket+timeout) */
extern ngx_int_t detect_tpu_roundtrip(
    const char *socket_path, double timeout_ms, uint64_t req_id,
    uint32_t tenant, uint8_t mode, const char *method, size_t method_len,
    const char *uri, size_t uri_len, const char *headers, size_t headers_len,
    const char *body, size_t body_len,
    /* out */ uint8_t *flags, uint32_t *score);

/* response-side twin (shim_bridge.cc): ships a PTPI response-scan frame,
 * waits for the leak verdict */
extern ngx_int_t detect_tpu_response_roundtrip(
    const char *socket_path, double timeout_ms, uint64_t req_id,
    uint32_t tenant, uint8_t mode, uint16_t status,
    const char *headers, size_t headers_len,
    const char *body, size_t body_len,
    /* out */ uint8_t *flags, uint32_t *score);

/* WebSocket capture twin (shim_bridge.cc): ships raw upgraded-connection
 * bytes under a persistent stream id; the returned flags are the
 * stream's STICKY verdict (once any message scanned as an attack, every
 * later call reports it), so the enforcement point closes the tunnel as
 * soon as a block flag comes back.  detect_tpu_parse_websocket gates it.
 *
 * Where it hooks: upgraded connections bypass nginx's HTTP filter
 * chain entirely (the proxy module tunnels at the event layer after the
 * 101), so capture CANNOT ride this module's access/body-filter phases —
 * the reference's module wraps the upgraded connection's read/write
 * handlers inside its closed-source core†.  Our equivalent enforcement
 * points are (a) the upgrade relay calling this bridge per tunnel read
 * (ngx_http_upstream's upgraded r/w handlers wrapped the same way — a
 * deeper nginx patch than the vendored API-subset headers model here),
 * and (b) sidecar-level capture for deployments where the sidecar IS the
 * relay.  The wire protocol, serve-side RFC 6455 parse/scan, sticky
 * verdicts and teardown are complete and e2e-tested through (b)
 * (tests/test_sidecar.py, tests/test_shim.py ws cases). */
extern ngx_int_t detect_tpu_ws_roundtrip(
    const char *socket_path, double timeout_ms, uint64_t req_id,
    uint64_t stream_id, uint32_t tenant, uint8_t mode,
    int server_to_client, int end,
    const char *data, size_t data_len,
    /* out */ uint8_t *flags, uint32_t *score);

/* response bodies beyond this are scanned in their first megabyte only
 * (the serve loop's oversized reroute guards the request side; response
 * leak patterns — error pages, stack traces — sit at the front) */
#define DETECT_TPU_RESP_CAP  (1024 * 1024)

#define DETECT_TPU_FLAG_ATTACK    0x01
#define DETECT_TPU_FLAG_BLOCKED   0x02
#define DETECT_TPU_FLAG_FAIL_OPEN 0x04

#include "detect_tpu_conf.h"   /* ngx_http_detect_tpu_loc_conf_t — shared
                                * with the phase-machine harness */

/* response-scan task context: lives in r->pool; the request is pinned
 * (r->main->count++) until the completion event finalizes it, so the
 * pooled buffers outlive the pool thread's read */
typedef struct {
    ngx_http_request_t  *request;
    ngx_str_t            headers_blob;   /* response headers */
    ngx_str_t            body;           /* captured (capped) body */
    ngx_str_t            socket_path;
    double               timeout_ms;
    uint32_t             tenant;
    uint8_t              mode;
    uint16_t             status;
    uint8_t              flags;
    uint32_t             score;
} ngx_http_detect_tpu_resp_ctx_t;

typedef struct {
    ngx_http_request_t  *request;
    /* captured on the event thread before the task is posted; the pool
     * thread reads ONLY this struct, never the ngx_http_request_t */
    ngx_str_t            method;
    ngx_str_t            uri;
    ngx_str_t            headers_blob;
    ngx_str_t            body;
    ngx_str_t            socket_path;
    double               timeout_ms;
    uint32_t             tenant;
    uint8_t              mode;
    /* result (written by the pool thread, read by the handler strictly
     * after the completion event — the pool queue is the barrier) */
    uint8_t              flags;
    uint32_t             score;
    /* state machine, event-loop thread only */
    unsigned             body_ready:1;
    unsigned             task_posted:1;
    unsigned             done_ev:1;
    /* response capture (body-filter phase, detect_tpu_parse_response) */
    u_char              *resp_buf;
    size_t               resp_len;
    size_t               resp_cap;       /* grown geometrically to the
                                          * 1MB ceiling — a flat 1MB per
                                          * response would pin ~1GB at
                                          * 1k concurrent responses */
    unsigned             resp_scanned:1;
} ngx_http_detect_tpu_ctx_t;

static ngx_int_t ngx_http_detect_tpu_handler(ngx_http_request_t *r);
static void ngx_http_detect_tpu_body_done(ngx_http_request_t *r);
static void ngx_http_detect_tpu_thread_func(void *data, ngx_log_t *log);
static void ngx_http_detect_tpu_thread_done(ngx_event_t *ev);
static void *ngx_http_detect_tpu_create_loc_conf(ngx_conf_t *cf);
static char *ngx_http_detect_tpu_merge_loc_conf(ngx_conf_t *cf, void *parent,
                                                void *child);
static ngx_int_t ngx_http_detect_tpu_init(ngx_conf_t *cf);

static ngx_conf_enum_t ngx_http_detect_tpu_modes[] = {
    { ngx_string("off"), 0 },
    { ngx_string("monitoring"), 1 },
    /* wire value 3; strength sits BETWEEN monitoring and block (the
     * serve pipeline's MODE_STRENGTH lookup) — blocks only greylisted
     * sources (frame greylist bit / server-side ACL greylist) */
    { ngx_string("safe_blocking"), 3 },
    { ngx_string("block"), 2 },
    { ngx_null_string, 0 }
};

static ngx_command_t ngx_http_detect_tpu_commands[] = {

    { ngx_string("detect_tpu"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_FLAG,
      ngx_conf_set_flag_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, enabled),
      NULL },

    { ngx_string("detect_tpu_socket"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_str_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, socket_path),
      NULL },

    { ngx_string("detect_tpu_mode"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_enum_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, mode),
      &ngx_http_detect_tpu_modes },

    { ngx_string("detect_tpu_timeout_ms"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_num_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, timeout_ms),
      NULL },

    { ngx_string("detect_tpu_fail_open"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_FLAG,
      ngx_conf_set_flag_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, fail_open),
      NULL },

    { ngx_string("detect_tpu_tenant"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_num_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, tenant),
      NULL },

    { ngx_string("detect_tpu_acl"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_str_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, acl),
      NULL },

    { ngx_string("detect_tpu_block_page"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_str_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, block_page),
      NULL },

    { ngx_string("detect_tpu_parse_response"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_FLAG,
      ngx_conf_set_flag_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, parse_response),
      NULL },

    { ngx_string("detect_tpu_parse_websocket"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_FLAG,
      ngx_conf_set_flag_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, parse_websocket),
      NULL },

    { ngx_string("detect_tpu_parser_disable"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_HTTP_LOC_CONF|NGX_CONF_1MORE,
      ngx_conf_set_str_array_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, parser_disable),
      NULL },

    { ngx_string("detect_tpu_metrics"),
      NGX_HTTP_MAIN_CONF|NGX_HTTP_SRV_CONF|NGX_CONF_TAKE1,
      ngx_conf_set_str_slot,
      NGX_HTTP_LOC_CONF_OFFSET,
      offsetof(ngx_http_detect_tpu_loc_conf_t, metrics_addr),
      NULL },

      ngx_null_command
};

static ngx_http_module_t ngx_http_detect_tpu_module_ctx = {
    NULL,                                  /* preconfiguration  */
    ngx_http_detect_tpu_init,              /* postconfiguration */
    NULL, NULL,                            /* main conf         */
    NULL, NULL,                            /* srv conf          */
    ngx_http_detect_tpu_create_loc_conf,   /* create loc conf   */
    ngx_http_detect_tpu_merge_loc_conf     /* merge loc conf    */
};

ngx_module_t ngx_http_detect_tpu_module = {
    NGX_MODULE_V1,
    &ngx_http_detect_tpu_module_ctx,
    ngx_http_detect_tpu_commands,
    NGX_HTTP_MODULE,
    NULL, NULL, NULL, NULL, NULL, NULL, NULL,
    NGX_MODULE_V1_PADDING
};

/* the trusted client-ip header the serve-side ACL engine consumes
 * (models/acl.py CLIENT_IP_HEADER): the shim OWNS this name — any
 * inbound copy is dropped (it would be attacker-controlled) and the
 * connection's source address is appended in its place */
#define DETECT_TPU_CLIENT_IP_HDR      "x-detect-tpu-client-ip"
#define DETECT_TPU_CLIENT_IP_HDR_LEN  (sizeof(DETECT_TPU_CLIENT_IP_HDR) - 1)

static ngx_int_t
ngx_http_detect_tpu_hdr_is_client_ip(ngx_table_elt_t *h)
{
    return h->key.len == DETECT_TPU_CLIENT_IP_HDR_LEN
           && ngx_strncasecmp(h->key.data,
                              (u_char *) DETECT_TPU_CLIENT_IP_HDR,
                              DETECT_TPU_CLIENT_IP_HDR_LEN) == 0;
}

/* join a header list as "k: v\x1f k: v" — the wire blob the serve
 * loop's normalizer splits back into per-header match units (used for
 * headers_in on the request path, headers_out on the response path).
 * ``client_ip`` non-NULL (request path): strip any inbound
 * DETECT_TPU_CLIENT_IP_HDR and append the trusted connection address
 * under that name. */
static ngx_int_t
ngx_http_detect_tpu_headers_blob(ngx_http_request_t *r, ngx_list_t *list,
                                 ngx_str_t *client_ip, ngx_str_t *out)
{
    size_t            len = 0;
    ngx_uint_t        i;
    ngx_list_part_t  *part;
    ngx_table_elt_t  *h;
    u_char           *p;

    for (part = &list->part; part; part = part->next) {
        h = part->elts;
        for (i = 0; i < part->nelts; i++) {
            if (client_ip != NULL
                && ngx_http_detect_tpu_hdr_is_client_ip(&h[i])) {
                continue;   /* forged/forwarded copy: never shipped */
            }
            len += h[i].key.len + 2 + h[i].value.len + 1;
        }
    }
    if (client_ip != NULL && client_ip->len) {
        len += DETECT_TPU_CLIENT_IP_HDR_LEN + 2 + client_ip->len + 1;
    }
    if (len == 0) {
        ngx_str_null(out);
        return NGX_OK;
    }
    p = ngx_pnalloc(r->pool, len);
    if (p == NULL) {
        return NGX_ERROR;
    }
    out->data = p;
    for (part = &list->part; part; part = part->next) {
        h = part->elts;
        for (i = 0; i < part->nelts; i++) {
            if (client_ip != NULL
                && ngx_http_detect_tpu_hdr_is_client_ip(&h[i])) {
                continue;
            }
            p = ngx_cpymem(p, h[i].key.data, h[i].key.len);
            *p++ = ':'; *p++ = ' ';
            p = ngx_cpymem(p, h[i].value.data, h[i].value.len);
            *p++ = 0x1f;
        }
    }
    if (client_ip != NULL && client_ip->len) {
        p = ngx_cpymem(p, DETECT_TPU_CLIENT_IP_HDR,
                       DETECT_TPU_CLIENT_IP_HDR_LEN);
        *p++ = ':'; *p++ = ' ';
        p = ngx_cpymem(p, client_ip->data, client_ip->len);
        *p++ = 0x1f;
    }
    out->len = p - out->data - 1;   /* drop the trailing separator */
    return NGX_OK;
}

/* detect_tpu_parser_disable values → request-frame mode-byte flag bits
 * (protocol.py PARSER_OFF_BITS).  The disables ride the TRUSTED config
 * plane inside the mode byte — never a request header, which a client
 * could forge to switch the serve loop's unpack stage off. */
static uint8_t
ngx_http_detect_tpu_parser_bits(ngx_array_t *parser_disable)
{
    static const struct { const char *name; size_t len; uint8_t bit; }
    map[] = {
        { "gzip",   4, 0x08 },
        { "base64", 6, 0x10 },
        { "json",   4, 0x20 },
        { "xml",    3, 0x40 },
    };
    uint8_t     bits = 0;
    ngx_uint_t  i, j;
    ngx_str_t  *v;

    if (parser_disable == NULL) {
        return 0;
    }
    v = parser_disable->elts;
    for (i = 0; i < parser_disable->nelts; i++) {
        for (j = 0; j < sizeof(map) / sizeof(map[0]); j++) {
            if (v[i].len == map[j].len
                && ngx_strncasecmp(v[i].data, (u_char *) map[j].name,
                                   map[j].len) == 0)
            {
                bits |= map[j].bit;
            }
        }
    }
    return bits;
}

/* flatten the read body chain (memory and file buffers both) into one
 * contiguous capture for the wire frame */
static ngx_int_t
ngx_http_detect_tpu_capture_body(ngx_http_request_t *r, ngx_str_t *out)
{
    size_t        len = 0, size;
    ssize_t       n;
    u_char       *p;
    ngx_buf_t    *b;
    ngx_chain_t  *cl;

    ngx_str_null(out);
    if (r->request_body == NULL || r->request_body->bufs == NULL) {
        return NGX_OK;
    }
    for (cl = r->request_body->bufs; cl; cl = cl->next) {
        b = cl->buf;
        len += b->in_file ? (size_t) (b->file_last - b->file_pos)
                          : (size_t) (b->last - b->pos);
    }
    if (len == 0) {
        return NGX_OK;
    }
    p = ngx_pnalloc(r->pool, len);
    if (p == NULL) {
        return NGX_ERROR;
    }
    out->data = p;
    out->len = len;
    for (cl = r->request_body->bufs; cl; cl = cl->next) {
        b = cl->buf;
        if (b->in_file) {
            size = (size_t) (b->file_last - b->file_pos);
            n = ngx_read_file(b->file, p, size, b->file_pos);
            if (n != (ssize_t) size) {
                return NGX_ERROR;
            }
            p += size;
        } else {
            p = ngx_cpymem(p, b->pos, b->last - b->pos);
        }
    }
    return NGX_OK;
}

/* client-body-read continuation: just re-enter the phase walk (the
 * mirror-module pattern); the handler's second entry does the capture */
static void
ngx_http_detect_tpu_body_done(ngx_http_request_t *r)
{
    ngx_http_detect_tpu_ctx_t *ctx;

    ctx = ngx_http_get_module_ctx(r, ngx_http_detect_tpu_module);
    ctx->body_ready = 1;
    r->preserve_body = 1;
    r->write_event_handler = ngx_http_core_run_phases;
    ngx_http_core_run_phases(r);
}

static void
ngx_http_detect_tpu_thread_func(void *data, ngx_log_t *log)
{
    ngx_http_detect_tpu_ctx_t *ctx = data;

    (void) log;
    /* blocking round-trip on the pool thread; reads only the ctx */
    if (detect_tpu_roundtrip((const char *) ctx->socket_path.data,
                             ctx->timeout_ms,
                             (uint64_t) (uintptr_t) ctx->request,
                             ctx->tenant, ctx->mode,
                             (const char *) ctx->method.data,
                             ctx->method.len,
                             (const char *) ctx->uri.data, ctx->uri.len,
                             (const char *) ctx->headers_blob.data,
                             ctx->headers_blob.len,
                             (const char *) ctx->body.data, ctx->body.len,
                             &ctx->flags, &ctx->score) != NGX_OK)
    {
        ctx->flags = DETECT_TPU_FLAG_FAIL_OPEN;
        ctx->score = 0;
    }
}

static void
ngx_http_detect_tpu_thread_done(ngx_event_t *ev)
{
    ngx_http_detect_tpu_ctx_t *ctx = ev->data;
    ngx_http_request_t        *r = ctx->request;

    r->main->blocked--;
    r->aio = 0;
    ctx->done_ev = 1;    /* the sole completion signal; set on the event
                          * loop so the handler can never observe a
                          * half-done state from the pool thread */
    r->write_event_handler = ngx_http_core_run_phases;
    ngx_http_core_run_phases(r);
}

static ngx_int_t
ngx_http_detect_tpu_add_fail_open_header(ngx_http_request_t *r)
{
    ngx_table_elt_t *h;

    h = ngx_list_push(&r->headers_out.headers);
    if (h == NULL) {
        return NGX_ERROR;
    }
    h->hash = 1;
    ngx_str_set(&h->key, "X-Detect-TPU");
    ngx_str_set(&h->value, "fail-open");
    return NGX_OK;
}

static ngx_int_t
ngx_http_detect_tpu_handler(ngx_http_request_t *r)
{
    ngx_http_detect_tpu_loc_conf_t  *conf;
    ngx_http_detect_tpu_ctx_t       *ctx;
    ngx_thread_task_t               *task;
    ngx_thread_pool_t               *tp;
    ngx_int_t                        rc;
    ngx_str_t                        pool_name = ngx_string("detect_tpu");

    conf = ngx_http_get_module_loc_conf(r, ngx_http_detect_tpu_module);
    if (!conf->enabled || conf->mode == 0) {
        return NGX_DECLINED;
    }

    ctx = ngx_http_get_module_ctx(r, ngx_http_detect_tpu_module);

    if (ctx == NULL) {
        /* entry 1: start the body read, suspend the phase walk */
        ctx = ngx_pcalloc(r->pool, sizeof(ngx_http_detect_tpu_ctx_t));
        if (ctx == NULL) {
            return conf->fail_open ? NGX_DECLINED : NGX_ERROR;
        }
        ctx->request = r;
        ngx_http_set_ctx(r, ctx, ngx_http_detect_tpu_module);
        rc = ngx_http_read_client_request_body(
            r, ngx_http_detect_tpu_body_done);
        if (rc >= NGX_HTTP_SPECIAL_RESPONSE) {
            return rc;
        }
        /* ngx_http_read_client_request_body() did r->main->count++; balance
         * it immediately (the mirror-module pattern) so the request is
         * freed and keepalive connections recycle once the normal content
         * path finalizes.  NGX_DONE alone would pin one refcount per
         * request forever. */
        ngx_http_finalize_request(r, NGX_DONE);
        return NGX_DONE;
    }

    if (!ctx->task_posted) {
        if (!ctx->body_ready) {
            return NGX_AGAIN;   /* body still streaming in */
        }
        /* entry 2: capture everything on the event thread, post task */
        tp = ngx_thread_pool_get((ngx_cycle_t *) ngx_cycle, &pool_name);
        if (tp == NULL) {
            /* no `thread_pool detect_tpu` block configured:
             * fail open rather than block traffic */
            return conf->fail_open ? NGX_DECLINED
                                   : NGX_HTTP_SERVICE_UNAVAILABLE;
        }
        if (ngx_http_detect_tpu_headers_blob(r, &r->headers_in.headers,
                                             &r->connection->addr_text,
                                             &ctx->headers_blob) != NGX_OK
            || ngx_http_detect_tpu_capture_body(r, &ctx->body) != NGX_OK)
        {
            return conf->fail_open ? NGX_DECLINED : NGX_ERROR;
        }
        ctx->method = r->method_name;
        ctx->uri = r->unparsed_uri;
        ctx->socket_path = conf->socket_path;
        ctx->timeout_ms = (double) conf->timeout_ms;
        ctx->tenant = (uint32_t) conf->tenant;
        ctx->mode = (uint8_t) conf->mode
                    | ngx_http_detect_tpu_parser_bits(conf->parser_disable);

        task = ngx_thread_task_alloc(r->pool, 0);
        if (task == NULL) {
            return conf->fail_open ? NGX_DECLINED : NGX_ERROR;
        }
        task->ctx = ctx;
        task->handler = ngx_http_detect_tpu_thread_func;
        task->event.handler = ngx_http_detect_tpu_thread_done;
        task->event.data = ctx;
        if (ngx_thread_task_post(tp, task) != NGX_OK) {
            return conf->fail_open ? NGX_DECLINED : NGX_ERROR;
        }
        ctx->task_posted = 1;
        r->main->blocked++;
        r->aio = 1;
        return NGX_AGAIN;
    }

    if (!ctx->done_ev) {
        return NGX_AGAIN;       /* verdict still in flight */
    }

    /* entry 3: verdict available — apply it (event-loop thread only) */
    /* modes 2 (block) and 3 (safe_blocking) both enforce; the serve
     * pipeline already restricted safe_blocking blocks to greylisted
     * sources, so the shim only honors the verdict bit */
    if ((ctx->flags & DETECT_TPU_FLAG_BLOCKED) && conf->mode >= 2) {
        if (conf->block_page.len) {
            /* the read-body refcount was balanced at entry 1, so the
             * redirect target's normal content path owns the remaining
             * count — no extra finalize here */
            (void) ngx_http_internal_redirect(r, &conf->block_page, NULL);
            return NGX_DONE;
        }
        return NGX_HTTP_FORBIDDEN;
    }
    if (ctx->flags & DETECT_TPU_FLAG_FAIL_OPEN) {
        /* the dominant failure path (sidecar down / deadline miss) arrives
         * here as a synthesized pass+FAIL_OPEN verdict; an operator who
         * configured fail-closed must NOT get unscanned traffic forwarded */
        if (!conf->fail_open) {
            return NGX_HTTP_SERVICE_UNAVAILABLE;
        }
        (void) ngx_http_detect_tpu_add_fail_open_header(r);
    }
    return NGX_DECLINED;        /* pass (clean, monitoring, or fail-open) */
}

static void *
ngx_http_detect_tpu_create_loc_conf(ngx_conf_t *cf)
{
    ngx_http_detect_tpu_loc_conf_t *conf;

    conf = ngx_pcalloc(cf->pool, sizeof(ngx_http_detect_tpu_loc_conf_t));
    if (conf == NULL) {
        return NULL;
    }
    conf->enabled = NGX_CONF_UNSET;
    conf->mode = NGX_CONF_UNSET_UINT;
    conf->timeout_ms = NGX_CONF_UNSET_UINT;
    conf->fail_open = NGX_CONF_UNSET;
    conf->tenant = NGX_CONF_UNSET_UINT;
    conf->parse_response = NGX_CONF_UNSET;
    conf->parse_websocket = NGX_CONF_UNSET;
    conf->parser_disable = NGX_CONF_UNSET_PTR;
    return conf;
}

static char *
ngx_http_detect_tpu_merge_loc_conf(ngx_conf_t *cf, void *parent, void *child)
{
    ngx_http_detect_tpu_loc_conf_t *prev = parent;
    ngx_http_detect_tpu_loc_conf_t *conf = child;

    (void) cf;   /* signature-mandated, unused here */

    ngx_conf_merge_value(conf->enabled, prev->enabled, 0);
    ngx_conf_merge_str_value(conf->socket_path, prev->socket_path,
                             "/run/ipt/detect.sock");
    ngx_conf_merge_uint_value(conf->mode, prev->mode, 1);
    ngx_conf_merge_uint_value(conf->timeout_ms, prev->timeout_ms, 30);
    ngx_conf_merge_value(conf->fail_open, prev->fail_open, 1);
    ngx_conf_merge_uint_value(conf->tenant, prev->tenant, 0);
    ngx_conf_merge_str_value(conf->acl, prev->acl, "");
    ngx_conf_merge_str_value(conf->block_page, prev->block_page, "");
    ngx_conf_merge_value(conf->parse_response, prev->parse_response, 0);
    ngx_conf_merge_value(conf->parse_websocket, prev->parse_websocket, 0);
    ngx_conf_merge_ptr_value(conf->parser_disable, prev->parser_disable,
                             NULL);
    ngx_conf_merge_str_value(conf->metrics_addr, prev->metrics_addr,
                             "127.0.0.1:9901");
    return NGX_CONF_OK;
}

/* ------------------------------------------------------------------ *
 * Response-side analysis (detect_tpu_parse_response): a body filter
 * captures the upstream response (bounded at DETECT_TPU_RESP_CAP) while
 * forwarding every buffer UNCHANGED — client latency never waits on the
 * scan.  At last_buf the capture is shipped to the serve loop as a PTPI
 * frame on a pool thread; the verdict is advisory (the serve loop
 * records leak hits in postanalytics — response bytes already sent
 * can't be retracted, matching the reference's parse_response
 * semantics†).  The request is pinned (count++) until the verdict event
 * so the pooled capture outlives the pool thread.
 * ------------------------------------------------------------------ */

static ngx_http_output_body_filter_pt ngx_http_detect_tpu_next_body_filter;

/* nginx keeps Content-Type / Content-Length OUT of the headers_out list
 * (dedicated fields, rendered by the header filter), but they're the
 * most commonly matched response headers (CRS 95x gating chains) — the
 * blob shipped for scanning must include them (round-3 review). */
static ngx_int_t
ngx_http_detect_tpu_resp_headers_blob(ngx_http_request_t *r, ngx_str_t *out)
{
    u_char     buf[64];
    u_char    *p, *q;
    size_t     extra = 0, cl_len = 0;
    ngx_str_t  list_blob;

    if (ngx_http_detect_tpu_headers_blob(r, &r->headers_out.headers,
                                         NULL, &list_blob) != NGX_OK)
    {
        return NGX_ERROR;
    }
    if (r->headers_out.content_type.len) {
        extra += sizeof("Content-Type: ") - 1
                 + r->headers_out.content_type.len + 1;
    }
    if (r->headers_out.content_length_n >= 0) {
        q = ngx_snprintf(buf, sizeof(buf), "%O",
                         r->headers_out.content_length_n);
        cl_len = (size_t) (q - buf);
        extra += sizeof("Content-Length: ") - 1 + cl_len + 1;
    }
    if (extra == 0) {
        *out = list_blob;
        return NGX_OK;
    }
    p = ngx_pnalloc(r->pool, list_blob.len + 1 + extra);
    if (p == NULL) {
        return NGX_ERROR;
    }
    out->data = p;
    if (r->headers_out.content_type.len) {
        p = ngx_cpymem(p, "Content-Type: ", sizeof("Content-Type: ") - 1);
        p = ngx_cpymem(p, r->headers_out.content_type.data,
                       r->headers_out.content_type.len);
        *p++ = 0x1f;
    }
    if (r->headers_out.content_length_n >= 0) {
        p = ngx_cpymem(p, "Content-Length: ",
                       sizeof("Content-Length: ") - 1);
        p = ngx_cpymem(p, buf, cl_len);
        *p++ = 0x1f;
    }
    if (list_blob.len) {
        p = ngx_cpymem(p, list_blob.data, list_blob.len);
    } else {
        p--;    /* drop the trailing separator */
    }
    out->len = (size_t) (p - out->data);
    return NGX_OK;
}

static void
ngx_http_detect_tpu_resp_thread_func(void *data, ngx_log_t *log)
{
    ngx_http_detect_tpu_resp_ctx_t *c = data;

    (void) log;
    if (detect_tpu_response_roundtrip(
            (const char *) c->socket_path.data, c->timeout_ms,
            (uint64_t) (uintptr_t) c->request, c->tenant, c->mode,
            c->status,
            (const char *) c->headers_blob.data, c->headers_blob.len,
            (const char *) c->body.data, c->body.len,
            &c->flags, &c->score) != NGX_OK)
    {
        c->flags = DETECT_TPU_FLAG_FAIL_OPEN;
        c->score = 0;
    }
}

static void
ngx_http_detect_tpu_resp_thread_done(ngx_event_t *ev)
{
    ngx_http_detect_tpu_resp_ctx_t *c = ev->data;

    /* release the pin taken at post time; verdict is advisory */
    ngx_http_finalize_request(c->request->main, NGX_DONE);
}

static ngx_int_t
ngx_http_detect_tpu_body_filter(ngx_http_request_t *r, ngx_chain_t *in)
{
    ngx_http_detect_tpu_loc_conf_t  *conf;
    ngx_http_detect_tpu_ctx_t       *ctx;
    ngx_http_detect_tpu_resp_ctx_t  *rc;
    ngx_thread_task_t               *task;
    ngx_thread_pool_t               *tp;
    ngx_chain_t                     *cl;
    ngx_buf_t                       *b;
    size_t                           n, room;
    ngx_uint_t                       last = 0;
    ngx_str_t                        pool_name = ngx_string("detect_tpu");

    conf = ngx_http_get_module_loc_conf(r, ngx_http_detect_tpu_module);
    if (r != r->main || !conf->enabled || !conf->parse_response
        || conf->mode == 0)
    {
        return ngx_http_detect_tpu_next_body_filter(r, in);
    }

    ctx = ngx_http_get_module_ctx(r, ngx_http_detect_tpu_module);
    if (ctx == NULL) {
        ctx = ngx_pcalloc(r->pool, sizeof(ngx_http_detect_tpu_ctx_t));
        if (ctx == NULL) {
            return ngx_http_detect_tpu_next_body_filter(r, in);
        }
        ctx->request = r;
        ngx_http_set_ctx(r, ctx, ngx_http_detect_tpu_module);
    }

    if (!ctx->resp_scanned) {
        for (cl = in; cl; cl = cl->next) {
            b = cl->buf;
            if (!b->in_file && b->last > b->pos) {
                /* bounded capture; file buffers (sendfile of static
                 * assets) are skipped — leak rules target dynamically
                 * generated error output, which is in-memory */
                n = (size_t) (b->last - b->pos);
                if (ctx->resp_len + n > ctx->resp_cap
                    && ctx->resp_cap < DETECT_TPU_RESP_CAP)
                {
                    /* grow geometrically toward the cap; size the first
                     * allocation from Content-Length when declared */
                    size_t  want = ctx->resp_len + n;
                    size_t  cap = ctx->resp_cap ? ctx->resp_cap * 2
                                                : (size_t) 16384;
                    if (ctx->resp_cap == 0
                        && r->headers_out.content_length_n > 0)
                    {
                        cap = (size_t) r->headers_out.content_length_n;
                    }
                    while (cap < want && cap < DETECT_TPU_RESP_CAP) {
                        cap *= 2;
                    }
                    if (cap > DETECT_TPU_RESP_CAP) {
                        cap = DETECT_TPU_RESP_CAP;
                    }
                    {
                        u_char *nb = ngx_pnalloc(r->pool, cap);
                        if (nb == NULL) {
                            ctx->resp_scanned = 1;   /* fail open, stop */
                            break;
                        }
                        if (ctx->resp_len) {
                            ngx_memcpy(nb, ctx->resp_buf, ctx->resp_len);
                        }
                        ctx->resp_buf = nb;
                        ctx->resp_cap = cap;
                    }
                }
                room = ctx->resp_cap - ctx->resp_len;
                if (n > room) {
                    n = room;
                }
                if (n) {
                    ngx_memcpy(ctx->resp_buf + ctx->resp_len, b->pos, n);
                    ctx->resp_len += n;
                }
            }
            if (b->last_buf) {
                last = 1;
            }
        }

        if (last) {
            ctx->resp_scanned = 1;
            tp = ngx_thread_pool_get((ngx_cycle_t *) ngx_cycle, &pool_name);
            /* post even with an empty capture: RESPONSE_STATUS /
             * RESPONSE_HEADERS rules (5xx leak, header fingerprints)
             * must fire for body-less and sendfile-only responses too
             * (round-3 review) */
            if (tp != NULL) {
                task = ngx_thread_task_alloc(
                    r->pool, sizeof(ngx_http_detect_tpu_resp_ctx_t));
                if (task != NULL) {
                    rc = task->ctx;
                    rc->request = r;
                    rc->socket_path = conf->socket_path;
                    rc->timeout_ms = (double) conf->timeout_ms;
                    rc->tenant = (uint32_t) conf->tenant;
                    rc->mode = (uint8_t) conf->mode
                        | ngx_http_detect_tpu_parser_bits(
                              conf->parser_disable);
                    rc->status = (uint16_t) r->headers_out.status;
                    rc->body.data = ctx->resp_buf;
                    rc->body.len = ctx->resp_len;
                    if (ngx_http_detect_tpu_resp_headers_blob(
                            r, &rc->headers_blob) == NGX_OK) {
                        task->handler = ngx_http_detect_tpu_resp_thread_func;
                        task->event.handler =
                            ngx_http_detect_tpu_resp_thread_done;
                        task->event.data = rc;
                        if (ngx_thread_task_post(tp, task) == NGX_OK) {
                            r->main->count++;   /* pinned until done ev */
                        }
                    }
                }
            }
        }
    }

    return ngx_http_detect_tpu_next_body_filter(r, in);
}

/* === WebSocket upgrade capture (detect_tpu_parse_websocket) ==========
 *
 * Upgraded connections bypass the HTTP filter chain entirely (after the
 * 101, ngx_http_upstream tunnels at the event layer), so capture rides
 * an explicit relay wrap instead of a phase handler: whatever relays
 * tunnel bytes calls ws_begin once after the 101, ws_data per read
 * (either direction), ws_end at teardown.  In a full nginx build the
 * call sites are the upgraded-connection read handlers
 * (ngx_http_upstream_process_upgraded — the same place the reference's
 * closed-source module wraps†, SURVEY.md §2.2 wallarm-parse-websocket
 * row); the test double's harness drives the identical entry points.
 *
 * The round-trip here is BLOCKING on the caller's thread.  Unlike the
 * access phase there is no thread-pool offload: relay reads are
 * per-message small, the serve loop is host-local UDS, and the deadline
 * (conf->timeout_ms) bounds the stall with fail-open semantics — the
 * same trade the reference makes for upgraded traffic.  Verdicts are
 * STICKY serve-side: once any message in the stream scanned as an
 * attack, every later call reports it, so enforcement (closing the
 * tunnel) catches attacks that spanned message boundaries too. */

static uint64_t  ngx_http_detect_tpu_ws_counter;

static ngx_int_t
ngx_http_detect_tpu_is_ws_upgrade(ngx_http_request_t *r)
{
    ngx_list_part_t  *part = &r->headers_in.headers.part;
    ngx_table_elt_t  *h = part->elts;
    ngx_uint_t        i;

    for (i = 0; /* void */; i++) {
        if (i >= part->nelts) {
            if (part->next == NULL) {
                break;
            }
            part = part->next;
            h = part->elts;
            i = 0;
        }
        if (h[i].key.len == 7
            && ngx_strncasecmp(h[i].key.data, (u_char *) "upgrade", 7) == 0
            && ngx_strcasestrn(h[i].value.data, "websocket", 9 - 1) != NULL)
        {
            return 1;
        }
    }
    return 0;
}

ngx_http_detect_tpu_ws_ctx_t *
ngx_http_detect_tpu_ws_begin(ngx_http_request_t *r)
{
    ngx_http_detect_tpu_loc_conf_t  *conf;
    ngx_http_detect_tpu_ws_ctx_t    *ws;

    conf = ngx_http_get_module_loc_conf(r, ngx_http_detect_tpu_module);
    if (!conf->enabled || !conf->parse_websocket || conf->mode == 0
        || conf->socket_path.len == 0)
    {
        return NULL;
    }
    if (!ngx_http_detect_tpu_is_ws_upgrade(r)) {
        return NULL;
    }
    ws = ngx_pcalloc(r->pool, sizeof(ngx_http_detect_tpu_ws_ctx_t));
    if (ws == NULL) {
        return NULL;
    }
    /* unique per worker process + per connection lifetime: the serve
     * side keys sticky stream state on this id */
    /* getpid() rather than ngx_pid: the vendored API-subset headers
     * (nginx_compat) don't model the process globals, and the value only
     * needs worker uniqueness */
    ws->stream_id = ((uint64_t) getpid() << 32)
        | (uint32_t) ++ngx_http_detect_tpu_ws_counter;
    ws->socket_path = conf->socket_path;
    ws->timeout_ms = (double) conf->timeout_ms;
    ws->tenant = (uint32_t) conf->tenant;
    /* parser-off bits ride the mode byte exactly like the access-phase
     * and response call sites — omitting them here silently re-enabled
     * disabled unpackers for ws traffic (review finding), where an
     * unpacker FP doesn't just flag, it closes the live tunnel */
    ws->mode = (uint8_t) conf->mode
        | ngx_http_detect_tpu_parser_bits(conf->parser_disable);
    ws->fail_open = conf->fail_open ? 1 : 0;
    return ws;
}

ngx_int_t
ngx_http_detect_tpu_ws_data(ngx_http_detect_tpu_ws_ctx_t *ws,
    ngx_uint_t server_to_client, u_char *data, size_t len)
{
    uint8_t   flags = 0;
    uint32_t  score = 0;

    if (ws == NULL) {
        return NGX_OK;          /* capture off: relay proceeds */
    }
    if (ws->blocked) {
        return NGX_ABORT;       /* sticky: tunnel must stay closed */
    }
    if (ws->ended || len == 0) {
        return NGX_OK;
    }
    (void) detect_tpu_ws_roundtrip(
        (const char *) ws->socket_path.data, ws->timeout_ms,
        ws->stream_id, ws->stream_id, ws->tenant, ws->mode,
        server_to_client ? 1 : 0, /* end= */ 0,
        (const char *) data, len, &flags, &score);
    if (flags & DETECT_TPU_FLAG_BLOCKED) {
        ws->blocked = 1;
        return NGX_ABORT;
    }
    if ((flags & DETECT_TPU_FLAG_FAIL_OPEN) && !ws->fail_open) {
        /* operator chose fail-closed: a dead serve loop closes the
         * tunnel rather than relaying unscanned bytes */
        ws->blocked = 1;
        return NGX_ABORT;
    }
    return NGX_OK;
}

void
ngx_http_detect_tpu_ws_end(ngx_http_detect_tpu_ws_ctx_t *ws)
{
    uint8_t   flags = 0;
    uint32_t  score = 0;

    if (ws == NULL || ws->ended) {
        return;
    }
    ws->ended = 1;
    /* frees the serve-side sticky stream state; verdict is irrelevant */
    (void) detect_tpu_ws_roundtrip(
        (const char *) ws->socket_path.data, ws->timeout_ms,
        ws->stream_id, ws->stream_id, ws->tenant, ws->mode,
        0, /* end= */ 1, "", 0, &flags, &score);
}

static ngx_int_t
ngx_http_detect_tpu_init(ngx_conf_t *cf)
{
    ngx_http_handler_pt        *h;
    ngx_http_core_main_conf_t  *cmcf;

    cmcf = ngx_http_conf_get_module_main_conf(cf, ngx_http_core_module);
    h = ngx_array_push(&cmcf->phases[NGX_HTTP_ACCESS_PHASE].handlers);
    if (h == NULL) {
        return NGX_ERROR;
    }
    *h = ngx_http_detect_tpu_handler;

    /* response-side body filter (runs for every request; cheap early-out
     * unless detect_tpu_parse_response is on for the location) */
    ngx_http_detect_tpu_next_body_filter = ngx_http_top_body_filter;
    ngx_http_top_body_filter = ngx_http_detect_tpu_body_filter;
    return NGX_OK;
}
