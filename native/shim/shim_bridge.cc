// C linkage bridge between the nginx module (C) and ipt::DetectClient
// (C++).  One thread-local client per ngx_thread_pool thread — threads in
// the "detect_tpu" pool each hold a persistent sidecar connection, so the
// per-request cost is one framed write + poll, no connect.

#include <cstdint>
#include <memory>
#include <string>

#include "detect_client.hpp"

namespace {

thread_local std::unique_ptr<ipt::DetectClient> g_client;
thread_local std::string g_client_path;
thread_local double g_client_timeout = 0;

ipt::DetectClient* ClientFor(const char* socket_path, double timeout_ms) {
  // keyed on (path, timeout): per-location detect_tpu_timeout_ms values
  // must not inherit whichever deadline this thread saw first
  if (!g_client || g_client_path != socket_path ||
      g_client_timeout != timeout_ms) {
    g_client_path = socket_path;
    g_client_timeout = timeout_ms;
    g_client = std::make_unique<ipt::DetectClient>(g_client_path, timeout_ms);
  }
  return g_client.get();
}

}  // namespace

// Response-side scan for the module's body-filter phase
// (detect_tpu_parse_response on): ships the buffered upstream response,
// returns verdict flags.  Fail-open like the request path.
extern "C" int detect_tpu_response_roundtrip(
    const char* socket_path, double timeout_ms, uint64_t req_id,
    uint32_t tenant, uint8_t mode, uint16_t status,
    const char* headers, size_t headers_len,
    const char* body, size_t body_len,
    uint8_t* flags, uint32_t* score) {
  try {
    ipt::DetectClient* client = ClientFor(socket_path, timeout_ms);
    ipt::ResponseScan rs;
    rs.req_id = req_id;
    rs.tenant = tenant;
    rs.mode = mode;
    rs.status = status;
    rs.headers_blob.assign(headers ? headers : "", headers_len);
    rs.body.assign(body ? body : "", body_len);
    ipt::Response r = client->DetectResponse(rs);
    *flags = r.flags;
    *score = r.score;
    return 0;  /* NGX_OK */
  } catch (...) {
    *flags = 4;  /* fail_open */
    *score = 0;
    return 0;
  }
}

// WebSocket capture for upgraded connections (detect_tpu_parse_websocket
// on): ships raw tunnel bytes (either direction), returns the stream's
// sticky verdict flags — the caller closes the tunnel on a block flag.
// `end` non-zero frees the serve-side stream state (connection closed).
extern "C" int detect_tpu_ws_roundtrip(
    const char* socket_path, double timeout_ms, uint64_t req_id,
    uint64_t stream_id, uint32_t tenant, uint8_t mode,
    int server_to_client, int end,
    const char* data, size_t data_len,
    uint8_t* flags, uint32_t* score) {
  try {
    ipt::DetectClient* client = ClientFor(socket_path, timeout_ms);
    std::string bytes(data ? data : "", data_len);
    ipt::Response r = client->DetectWsBytes(
        req_id, stream_id, bytes, tenant, mode, server_to_client != 0,
        end != 0);
    *flags = r.flags;
    *score = r.score;
    return 0;  /* NGX_OK */
  } catch (...) {
    *flags = 4;  /* fail_open */
    *score = 0;
    return 0;
  }
}

extern "C" int detect_tpu_roundtrip(
    const char* socket_path, double timeout_ms, uint64_t req_id,
    uint32_t tenant, uint8_t mode, const char* method, size_t method_len,
    const char* uri, size_t uri_len, const char* headers, size_t headers_len,
    const char* body, size_t body_len,
    uint8_t* flags, uint32_t* score) {
  try {
    ipt::DetectClient* client = ClientFor(socket_path, timeout_ms);
    ipt::Request req;
    req.req_id = req_id;
    req.tenant = tenant;
    req.mode = mode;
    req.method.assign(method ? method : "", method_len);
    req.uri.assign(uri ? uri : "", uri_len);
    req.headers_blob.assign(headers ? headers : "", headers_len);
    req.body.assign(body ? body : "", body_len);
    ipt::Response r = client->Detect(req);
    *flags = r.flags;
    *score = r.score;
    return 0;  /* NGX_OK */
  } catch (...) {
    *flags = 4;  /* fail_open */
    *score = 0;
    return 0;    /* fail open is a successful outcome, not an error */
  }
}
