/*
 * shim_harness — drives ngx_http_detect_tpu_module.c's access-phase
 * state machine end to end against a REAL serve loop over UDS
 * (VERDICT r03 item #5).
 *
 * Each scenario runs the full entry-1 (body read kickoff) →
 * continuation → entry-2 (capture + thread-pool post) → completion
 * event → entry-3 (verdict application) walk, through the production
 * shim_bridge/DetectClient wire path, and asserts the final status,
 * response headers, the internal-redirect target, and — after every
 * scenario — the request refcount invariants (count back to 1,
 * blocked==0, aio==0) that leak keepalive connections when wrong.
 *
 * Usage: shim_harness <serve-socket-path>
 * Output: one "ok <name>" / "FAIL <name>: ..." line per scenario;
 * exit 0 iff all pass.  tests/test_shim.py builds and runs it.
 */

#include <ngx_config.h>
#include <ngx_core.h>
#include <ngx_http.h>

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "detect_tpu_conf.h"
#include "ngx_test_double.h"

typedef ngx_http_detect_tpu_loc_conf_t td_loc_conf_t;

static int g_failures;

#define CHECK(name, cond, fmt, ...)                                        \
    do {                                                                   \
        if (cond) {                                                        \
            printf("ok %s\n", name);                                       \
        } else {                                                           \
            printf("FAIL %s: " fmt "\n", name, __VA_ARGS__);               \
            g_failures++;                                                  \
        }                                                                  \
    } while (0)

/* run one request to completion: start the phase walk, then drain
 * events until the request resolves (or times out) */
static int
run_request(td_request_t *td, int timeout_ms)
{
    int waited = 0;

    ngx_http_core_run_phases(&td->r);
    while (!td->done && waited < timeout_ms) {
        if (!td_run_one_event(50)) {
            waited += 50;
        }
    }
    return td->done;
}

static int
refcounts_ok(td_request_t *td)
{
    return td->r.count == 1 && td->r.blocked == 0 && td->r.aio == 0;
}

int
main(int argc, char **argv)
{
    td_setup_result_t  setup;
    td_loc_conf_t     *conf;
    td_request_t       td;
    ngx_pool_t        *rp;

    if (argc < 2) {
        fprintf(stderr, "usage: shim_harness <serve-socket>\n");
        return 2;
    }
    if (td_setup(&setup) != 0) {
        fprintf(stderr, "setup failed\n");
        return 2;
    }
    conf = setup.loc_conf;
    conf->enabled = 1;
    conf->socket_path.data = (u_char *) argv[1];
    conf->socket_path.len = strlen(argv[1]);
    conf->timeout_ms = 10000;
    conf->mode = 2;
    conf->fail_open = 1;
    td_configure_thread_pool("detect_tpu");

    /* 1. benign pass: full 3-entry walk, DECLINED at the end */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/products?page=2", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("benign_pass", td.done && td.final_status == 200,
          "done=%d status=%d rc=%d", td.done, td.final_status, td.last_rc);
    CHECK("benign_pass_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);

    /* 2. attack in block mode: 403 */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET",
                    "/q?a=1'+union+select+password+from+users--",
                    "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("attack_block_403", td.done && td.final_status == 403,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("attack_block_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);

    /* 3. attack with a block page: internal redirect, not bare 403 */
    ngx_str_set(&conf->block_page, "/blocked.html");
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "POST", "/c", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    td.body = "comment=<script>alert(document.cookie)</script>";
    td.body_len = strlen(td.body);
    td_add_header_in(&td, "Content-Type",
                     "application/x-www-form-urlencoded");
    td_add_header_in(&td, "Content-Length", "47");
    run_request(&td, 15000);
    CHECK("attack_block_page",
          td.done && td.final_status == 302
          && strcmp(td.redirect, "/blocked.html") == 0,
          "done=%d status=%d redirect=%s", td.done, td.final_status,
          td.redirect);
    CHECK("attack_block_page_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);
    conf->block_page.len = 0;
    conf->block_page.data = NULL;

    /* 4. monitoring mode: attack detected but forwarded */
    conf->mode = 1;
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET",
                    "/q?a=1'+union+select+password+from+users--",
                    "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("monitoring_forwards", td.done && td.final_status == 200,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("monitoring_forwards_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);
    conf->mode = 2;

    /* 5. fail-open: serve loop unreachable → pass + marker header */
    {
        ngx_str_t saved = conf->socket_path;
        ngx_str_set(&conf->socket_path, "/nonexistent/ipt.sock");
        rp = td_pool_create();
        td_request_init(&td, rp, conf, "GET", "/x", "192.0.2.10");
        td_add_header_in(&td, "Host", "shop.example.com");
        run_request(&td, 15000);
        CHECK("fail_open_pass",
              td.done && td.final_status == 200
              && td_find_header_out(&td, "X-Detect-TPU", "fail-open"),
              "done=%d status=%d hdr=%d", td.done, td.final_status,
              td_find_header_out(&td, "X-Detect-TPU", "fail-open"));
        CHECK("fail_open_refcount", refcounts_ok(&td),
              "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked,
              td.r.aio);
        td_pool_destroy(rp);

        /* 6. fail-closed: same outage, operator chose fail_open off */
        conf->fail_open = 0;
        rp = td_pool_create();
        td_request_init(&td, rp, conf, "GET", "/x", "192.0.2.10");
        td_add_header_in(&td, "Host", "shop.example.com");
        run_request(&td, 15000);
        CHECK("fail_closed_503", td.done && td.final_status == 503,
              "done=%d status=%d", td.done, td.final_status);
        CHECK("fail_closed_503_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);
        conf->fail_open = 1;
        conf->socket_path = saved;
    }

    /* 7. no thread_pool block configured: fail-open DECLINED at entry 2 */
    td_configure_thread_pool(NULL);
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/x", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    run_request(&td, 15000);
    CHECK("no_thread_pool_fail_open", td.done && td.final_status == 200,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("no_thread_pool_fail_open_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);
    td_configure_thread_pool("detect_tpu");

    /* 8. safe_blocking (mode 3) + greylisted source: the serve-side ACL
     * greylists 203.0.113.0/24; the module ships the connection address
     * and must enforce the returned BLOCKED verdict under mode 3 */
    conf->mode = 3;
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET",
                    "/q?a=1'+union+select+password+from+users--",
                    "203.0.113.9");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("safe_blocking_greylisted_403",
          td.done && td.final_status == 403,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("safe_blocking_greylisted_403_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);

    /* 9. safe_blocking, NON-greylisted source: monitored, forwarded */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET",
                    "/q?a=1'+union+select+password+from+users--",
                    "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("safe_blocking_neutral_forwards",
          td.done && td.final_status == 200,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("safe_blocking_neutral_forwards_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);
    conf->mode = 2;

    /* 10. client-ip spoof: the forged trusted header names a DENYLISTED
     * ip; the module must strip it and ship the (neutral) connection
     * address instead → request passes */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/benign", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    td_add_header_in(&td, "X-Detect-TPU-Client-IP", "10.66.66.66");
    run_request(&td, 15000);
    CHECK("client_ip_spoof_stripped", td.done && td.final_status == 200,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("client_ip_spoof_stripped_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);

    /* 11. denied source address: serve ACL denies 10.66.66.0/24; with
     * the REAL connection address in that range the verdict blocks */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/benign", "10.66.66.66");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "User-Agent", "Mozilla/5.0 (X11; Linux) Chrome");
    run_request(&td, 15000);
    CHECK("acl_denied_source_403", td.done && td.final_status == 403,
          "done=%d status=%d", td.done, td.final_status);
    CHECK("acl_denied_refcount", refcounts_ok(&td),
          "count=%d blocked=%d aio=%d", td.r.count, td.r.blocked, td.r.aio);
    td_pool_destroy(rp);

    /* 12. WebSocket upgrade capture (VERDICT r04 item #5): the module's
     * relay-wrap entry points — ws_begin after the 101, ws_data per
     * tunnel read, ws_end at teardown — against the REAL serve loop's
     * RFC 6455 parser and sticky stream verdicts */
    conf->parse_websocket = 1;
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/chat", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    td_add_header_in(&td, "Connection", "Upgrade");
    td_add_header_in(&td, "Upgrade", "websocket");
    td_add_header_in(&td, "Sec-WebSocket-Key", "dGhlIHNhbXBsZSBub25jZQ==");
    run_request(&td, 15000);
    CHECK("ws_upgrade_request_passes", td.done && td.final_status == 200,
          "done=%d status=%d", td.done, td.final_status);
    {
        ngx_http_detect_tpu_ws_ctx_t  *ws, *ws_off;
        u_char                         frame[256];
        size_t                         flen;
        ngx_int_t                      rc1, rc2, rc3, rc4;

        /* minimal RFC 6455 masked client frame builder */
        const u_char mask[4] = {0x21, 0x43, 0x65, 0x07};
#define WS_FRAME(payload, fin, cont)                                       \
        do {                                                               \
            size_t plen = strlen(payload);                                 \
            size_t k;                                                      \
            frame[0] = (u_char) ((fin ? 0x80 : 0x00) | (cont ? 0x0 : 0x1));\
            frame[1] = (u_char) (0x80 | plen);                             \
            memcpy(frame + 2, mask, 4);                                    \
            for (k = 0; k < plen; k++) {                                   \
                frame[6 + k] = (u_char) (payload[k] ^ mask[k & 3]);        \
            }                                                              \
            flen = 6 + plen;                                               \
        } while (0)

        ws = ngx_http_detect_tpu_ws_begin(&td.r);
        CHECK("ws_begin_on_upgrade", ws != NULL, "ws=%p", (void *) ws);

        if (ws != NULL) {
            WS_FRAME("hello there", 1, 0);
            rc1 = ngx_http_detect_tpu_ws_data(ws, 0, frame, flen);
            CHECK("ws_benign_frame_passes", rc1 == NGX_OK && !ws->blocked,
                  "rc=%d blocked=%d", (int) rc1, (int) ws->blocked);

            /* attack split across two capture reads: serve-side parser
             * carries frame + scan state between calls */
            WS_FRAME("1 union ", 0, 0);
            rc2 = ngx_http_detect_tpu_ws_data(ws, 0, frame, flen);
            WS_FRAME("select password", 1, 1);
            rc3 = ngx_http_detect_tpu_ws_data(ws, 0, frame, flen);
            CHECK("ws_attack_aborts_tunnel",
                  rc2 == NGX_OK && rc3 == NGX_ABORT && ws->blocked,
                  "rc2=%d rc3=%d blocked=%d", (int) rc2, (int) rc3,
                  (int) ws->blocked);

            /* sticky: the relay must stay closed without a round-trip */
            WS_FRAME("benign chatter", 1, 0);
            rc4 = ngx_http_detect_tpu_ws_data(ws, 0, frame, flen);
            CHECK("ws_sticky_verdict", rc4 == NGX_ABORT,
                  "rc=%d", (int) rc4);
            ngx_http_detect_tpu_ws_end(ws);
            CHECK("ws_end_marks_ended", ws->ended, "ended=%d",
                  (int) ws->ended);
        }

        /* server→client capture on a fresh stream (unmasked frame:
         * server frames carry no mask bit).  s2c bytes scan the
         * RESPONSE streams serve-side (leak families), so the payload
         * trips the harness pack's RESPONSE_BODY passwd-leak rule: the
         * NGX_ABORT proves the direction flag reached the serve loop
         * and the bytes were scanned as a response — an OK-or-ABORT
         * check was vacuous (review finding: ws_data has no third
         * return value) */
        ws = ngx_http_detect_tpu_ws_begin(&td.r);
        if (ws != NULL) {
            /* payload is 25 bytes -> length byte 0x19, frame 27 bytes */
            const char  *leak = "\x81\x19" "root:x:0:0:root:/bin/bash";
            rc1 = ngx_http_detect_tpu_ws_data(ws, 1, (u_char *) leak, 27);
            CHECK("ws_s2c_frame_scanned",
                  rc1 == NGX_ABORT && ws->blocked,
                  "rc=%d blocked=%d", (int) rc1, (int) ws->blocked);
            ngx_http_detect_tpu_ws_end(ws);
        }

        /* gating: directive off → no capture ctx */
        conf->parse_websocket = 0;
        ws_off = ngx_http_detect_tpu_ws_begin(&td.r);
        CHECK("ws_begin_gated_by_directive", ws_off == NULL, "ws=%p",
              (void *) ws_off);
        conf->parse_websocket = 1;
#undef WS_FRAME
    }
    td_pool_destroy(rp);

    /* 13. non-upgrade request never gets a ws ctx */
    rp = td_pool_create();
    td_request_init(&td, rp, conf, "GET", "/plain", "192.0.2.10");
    td_add_header_in(&td, "Host", "shop.example.com");
    run_request(&td, 15000);
    {
        ngx_http_detect_tpu_ws_ctx_t *ws =
            ngx_http_detect_tpu_ws_begin(&td.r);
        CHECK("ws_begin_requires_upgrade_header", ws == NULL, "ws=%p",
              (void *) ws);
    }
    td_pool_destroy(rp);
    conf->parse_websocket = 0;

    td_pool_destroy(setup.pool);
    printf("%s\n", g_failures ? "HARNESS-FAIL" : "HARNESS-OK");
    return g_failures ? 1 : 0;
}
