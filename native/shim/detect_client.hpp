// Synchronous detection client — the nginx-shim side of the UDS boundary
// (SURVEY.md §3.3 TPU variant: nginx ⇄ shim ⇄ sidecar ⇄ serve loop).
//
// This is the blocking core the nginx module (ngx_http_detect_tpu_module.c)
// runs on an ngx_thread_pool task, and what anything else that wants a
// verdict (tests, CLI tools, other data planes) links directly.  One
// instance per thread; it owns one connection to the sidecar (or a serve
// loop directly) and reconnects lazily.
//
// The fail-open contract lives HERE as well as in the sidecar: any error
// or deadline miss returns a pass+fail_open verdict — the caller never
// blocks traffic on WAF trouble (`wallarm-fallback`† behavior).

#pragma once

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <string>

#include "../sidecar/protocol.hpp"

namespace ipt {

class DetectClient {
 public:
  explicit DetectClient(std::string socket_path, double deadline_ms = 50.0)
      : path_(std::move(socket_path)), deadline_ms_(deadline_ms) {}

  ~DetectClient() { Close(); }

  DetectClient(const DetectClient&) = delete;
  DetectClient& operator=(const DetectClient&) = delete;

  // Blocking: ship the request, wait for its verdict until the deadline.
  // Never throws; never blocks past deadline_ms; fail-open on any trouble.
  Response Detect(const Request& req) {
    Response fail;
    fail.req_id = req.req_id;
    fail.flags = kFailOpen;
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    if (fd_ < 0 && !Connect()) return fail;
    std::string frame = EncodeRequest(req);
    if (!SendAll(frame.data(), frame.size(), deadline)) {
      Close();
      return fail;
    }
    return WaitVerdict(req.req_id, deadline, fail);
  }

  // Response-side analysis (wallarm_parse_response analog): ship an
  // upstream response for leak scanning, wait for the verdict.  Same
  // fail-open discipline as Detect.
  Response DetectResponse(const ResponseScan& resp) {
    Response fail;
    fail.req_id = resp.req_id;
    fail.flags = kFailOpen;
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    if (fd_ < 0 && !Connect()) return fail;
    std::string frame = EncodeResponseScan(resp);
    if (!SendAll(frame.data(), frame.size(), deadline)) {
      Close();
      return fail;
    }
    return WaitVerdict(resp.req_id, deadline, fail);
  }

  // Streaming-body variant: open with Detect-style request (mode must
  // include kModeStream), then feed chunks, then FinishStream for the
  // verdict.  Mirrors the wallarm module's incremental body parse†.
  bool BeginStream(const Request& req) {
    if (fd_ < 0 && !Connect()) return false;
    Request r = req;
    r.mode |= kModeStream;
    std::string frame = EncodeRequest(r);
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    if (!SendAll(frame.data(), frame.size(), deadline)) {
      Close();
      return false;
    }
    return true;
  }

  bool SendChunk(uint64_t req_id, const std::string& data,
                 bool last = false) {
    if (fd_ < 0) return false;
    std::string frame = EncodeChunk(req_id, data, last);
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    if (!SendAll(frame.data(), frame.size(), deadline)) {
      Close();
      return false;
    }
    return true;
  }

  Response FinishStream(uint64_t req_id) {
    Response fail;
    fail.req_id = req_id;
    fail.flags = kFailOpen;
    if (fd_ < 0) return fail;
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    return WaitVerdict(req_id, deadline, fail);
  }

  // WebSocket capture (wallarm_parse_websocket analog): ship raw
  // upgraded-connection bytes (either direction, any chunking) under a
  // persistent stream id; each call returns this frame's verdict — the
  // stream's sticky attack state, so the caller can kill the tunnel as
  // soon as any message scanned as an attack.  Pass `end=true` when the
  // connection closes so the serve side frees its parser state.  Same
  // fail-open discipline as Detect.
  Response DetectWsBytes(uint64_t req_id, uint64_t stream_id,
                         const std::string& data, uint32_t tenant = 0,
                         uint8_t mode = 2, bool server_to_client = false,
                         bool end = false) {
    Response fail;
    fail.req_id = req_id;
    fail.flags = kFailOpen;
    uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
    if (fd_ < 0 && !Connect()) return fail;
    uint8_t flags = (server_to_client ? kWsDirS2C : 0) | (end ? kWsEnd : 0);
    std::string frame = EncodeWs(req_id, stream_id, data, tenant, mode,
                                 flags);
    if (!SendAll(frame.data(), frame.size(), deadline)) {
      Close();
      return fail;
    }
    return WaitVerdict(req_id, deadline, fail);
  }

  bool connected() const { return fd_ >= 0; }

 private:
  static uint64_t NowNs() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
  }

  bool Connect() {
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return false;
    // nonblocking BEFORE connect: a wedged sidecar with a full accept
    // backlog must produce fail-open at the deadline, not a pinned
    // pool thread (connect on a blocking socket ignores the deadline)
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path_.c_str(), sizeof(addr.sun_path) - 1);
    if (connect(fd, (sockaddr*)&addr, sizeof addr) != 0) {
      if (errno != EINPROGRESS && errno != EAGAIN) {
        close(fd);
        return false;
      }
      uint64_t deadline = NowNs() + uint64_t(deadline_ms_ * 1e6);
      pollfd p{fd, POLLOUT, 0};
      uint64_t now = NowNs();
      int rc = now < deadline
          ? poll(&p, 1, int((deadline - now) / 1000000ull) + 1) : 0;
      int err = 0;
      socklen_t len = sizeof err;
      if (rc <= 0 || !(p.revents & POLLOUT) ||
          getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 ||
          err != 0) {
        close(fd);
        return false;
      }
    }
    fd_ = fd;
    reader_ = FrameReader();
    return true;
  }

  void Close() {
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }

  bool SendAll(const char* data, size_t n, uint64_t deadline) {
    size_t off = 0;
    while (off < n) {
      ssize_t w = send(fd_, data + off, n - off, MSG_NOSIGNAL);
      if (w > 0) {
        off += size_t(w);
        continue;
      }
      if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (!PollFor(POLLOUT, deadline)) return false;
        continue;
      }
      return false;
    }
    return true;
  }

  bool PollFor(short events, uint64_t deadline) {
    uint64_t now = NowNs();
    if (now >= deadline) return false;
    pollfd p{fd_, events, 0};
    int rc = poll(&p, 1, int((deadline - now) / 1000000ull) + 1);
    return rc > 0 && (p.revents & events);
  }

  // Reads frames until req_id's verdict or the deadline.  Verdicts for
  // OTHER ids (a previous call that timed out and was answered late) are
  // discarded — each client instance is single-stream by contract.
  Response WaitVerdict(uint64_t req_id, uint64_t deadline,
                       const Response& fail) {
    while (true) {
      Response got;
      bool have = false;
      try {
        // drain already-buffered frames first
        reader_.Feed(nullptr, 0, [&](const uint8_t* p, size_t len) {
          Response r = DecodeResponse(p, len);
          if (r.req_id == req_id) {
            got = r;
            have = true;
          }
        });
      } catch (const std::exception&) {
        Close();
        return fail;
      }
      if (have) return got;
      if (!PollFor(POLLIN, deadline)) return fail;  // deadline → fail-open
      uint8_t buf[1 << 16];
      ssize_t n = recv(fd_, buf, sizeof buf, 0);
      if (n <= 0) {
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) continue;
        Close();
        return fail;
      }
      try {
        reader_.Feed(buf, size_t(n), [&](const uint8_t* p, size_t len) {
          Response r = DecodeResponse(p, len);
          if (r.req_id == req_id) {
            got = r;
            have = true;
          }
        });
      } catch (const std::exception&) {
        Close();
        return fail;
      }
      if (have) return got;
    }
  }

  std::string path_;
  double deadline_ms_;
  int fd_ = -1;
  FrameReader reader_;
};

}  // namespace ipt
