/* nginx_compat: compile-check declarations — see README.md. */
#ifndef _NGX_CORE_H_INCLUDED_
#define _NGX_CORE_H_INCLUDED_

#include <ngx_config.h>

/* ---------------------------------------------------------- strings */

typedef struct {
    size_t  len;
    u_char *data;
} ngx_str_t;

#define ngx_string(str)  { sizeof(str) - 1, (u_char *) str }
#define ngx_null_string  { 0, NULL }
#define ngx_str_set(str, text) \
    (str)->len = sizeof(text) - 1; (str)->data = (u_char *) text
#define ngx_str_null(str)  (str)->len = 0; (str)->data = NULL

ngx_int_t ngx_strncasecmp(u_char *s1, u_char *s2, size_t n);
u_char *ngx_strcasestrn(u_char *s1, char *s2, size_t n);
u_char *ngx_snprintf(u_char *buf, size_t max, const char *fmt, ...);

/* ---------------------------------------------------- pools + memory */

typedef struct ngx_pool_s  ngx_pool_t;
typedef struct ngx_log_s   ngx_log_t;

void *ngx_pcalloc(ngx_pool_t *pool, size_t size);
void *ngx_pnalloc(ngx_pool_t *pool, size_t size);

/* ------------------------------------------------- array, list, hash */

typedef struct {
    void       *elts;
    ngx_uint_t  nelts;
    size_t      size;
    ngx_uint_t  nalloc;
    ngx_pool_t *pool;
} ngx_array_t;

void *ngx_array_push(ngx_array_t *a);

typedef struct ngx_list_part_s  ngx_list_part_t;

struct ngx_list_part_s {
    void            *elts;
    ngx_uint_t       nelts;
    ngx_list_part_t *next;
};

typedef struct {
    ngx_list_part_t *last;
    ngx_list_part_t  part;
    size_t           size;
    ngx_uint_t       nalloc;
    ngx_pool_t      *pool;
} ngx_list_t;

void *ngx_list_push(ngx_list_t *list);

typedef struct ngx_table_elt_s  ngx_table_elt_t;

struct ngx_table_elt_s {
    ngx_uint_t       hash;
    ngx_str_t        key;
    ngx_str_t        value;
    u_char          *lowcase_key;
    ngx_table_elt_t *next;
};

/* -------------------------------------------------------- buf, chain */

typedef struct ngx_file_s  ngx_file_t;

typedef struct ngx_buf_s  ngx_buf_t;

struct ngx_buf_s {
    u_char     *pos;
    u_char     *last;
    off_t       file_pos;
    off_t       file_last;
    u_char     *start;
    u_char     *end;
    void       *tag;
    ngx_file_t *file;
    ngx_buf_t  *shadow;
    unsigned    temporary:1;
    unsigned    memory:1;
    unsigned    mmap:1;
    unsigned    recycled:1;
    unsigned    in_file:1;
    unsigned    flush:1;
    unsigned    sync:1;
    unsigned    last_buf:1;
    unsigned    last_in_chain:1;
    unsigned    last_shadow:1;
    unsigned    temp_file:1;
};

typedef struct ngx_chain_s  ngx_chain_t;

struct ngx_chain_s {
    ngx_buf_t   *buf;
    ngx_chain_t *next;
};

ssize_t ngx_read_file(ngx_file_t *file, u_char *buf, size_t size,
                      off_t offset);

/* ------------------------------------------------------------ events */

typedef struct ngx_event_s  ngx_event_t;

struct ngx_event_s {
    void  *data;
    void (*handler)(ngx_event_t *ev);
    unsigned  active:1;
    unsigned  ready:1;
};

/* ------------------------------------------------------------- cycle */

typedef struct ngx_cycle_s  ngx_cycle_t;

struct ngx_cycle_s {
    void      ****conf_ctx;
    ngx_pool_t   *pool;
    ngx_log_t    *log;
};

extern volatile ngx_cycle_t *ngx_cycle;

/* ----------------------------------------------------- configuration */

#define NGX_CONF_OK     NULL
#define NGX_CONF_ERROR  ((char *) -1)

#define NGX_CONF_UNSET       ((ngx_flag_t) -1)
#define NGX_CONF_UNSET_UINT  ((ngx_uint_t) -1)
#define NGX_CONF_UNSET_PTR   ((void *) -1)
#define NGX_CONF_UNSET_SIZE  ((size_t) -1)

#define NGX_CONF_NOARGS  0x00000001
#define NGX_CONF_TAKE1   0x00000002
#define NGX_CONF_TAKE2   0x00000004
#define NGX_CONF_1MORE   0x00000800
#define NGX_CONF_FLAG    0x00000200

typedef struct ngx_conf_s     ngx_conf_t;
typedef struct ngx_command_s  ngx_command_t;

struct ngx_conf_s {
    char        *name;
    ngx_array_t *args;
    ngx_cycle_t *cycle;
    ngx_pool_t  *pool;
    ngx_log_t   *log;
    void        *ctx;
};

struct ngx_command_s {
    ngx_str_t   name;
    ngx_uint_t  type;
    char     *(*set)(ngx_conf_t *cf, ngx_command_t *cmd, void *conf);
    ngx_uint_t  conf;
    ngx_uint_t  offset;
    void       *post;
};

#define ngx_null_command  { ngx_null_string, 0, NULL, 0, 0, NULL }

typedef struct {
    ngx_str_t   name;
    ngx_uint_t  value;
} ngx_conf_enum_t;

char *ngx_conf_set_flag_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf);
char *ngx_conf_set_str_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf);
char *ngx_conf_set_str_array_slot(ngx_conf_t *cf, ngx_command_t *cmd,
                                  void *conf);
char *ngx_conf_set_num_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf);
char *ngx_conf_set_enum_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf);

#define ngx_conf_merge_value(conf, prev, default_)                          \
    if (conf == NGX_CONF_UNSET) {                                           \
        conf = (prev == NGX_CONF_UNSET) ? default_ : prev;                  \
    }

#define ngx_conf_merge_uint_value(conf, prev, default_)                     \
    if (conf == NGX_CONF_UNSET_UINT) {                                      \
        conf = (prev == NGX_CONF_UNSET_UINT) ? default_ : prev;             \
    }

#define ngx_conf_merge_ptr_value(conf, prev, default_)                      \
    if (conf == NGX_CONF_UNSET_PTR) {                                       \
        conf = (prev == NGX_CONF_UNSET_PTR) ? default_ : prev;              \
    }

#define ngx_conf_merge_str_value(conf, prev, default_)                      \
    if (conf.data == NULL) {                                                \
        if (prev.data) {                                                    \
            conf.len = prev.len;                                            \
            conf.data = prev.data;                                          \
        } else {                                                            \
            conf.len = sizeof(default_) - 1;                                \
            conf.data = (u_char *) default_;                                \
        }                                                                   \
    }

/* ------------------------------------------------------------ module */

#define NGX_MODULE_UNSET_INDEX  ((ngx_uint_t) -1)

#define NGX_MODULE_V1                                                       \
    NGX_MODULE_UNSET_INDEX, NGX_MODULE_UNSET_INDEX,                         \
    NULL, 0, 0, 0, (const char *) "compat"

#define NGX_MODULE_V1_PADDING  0, 0, 0, 0, 0, 0, 0, 0

typedef struct ngx_module_s  ngx_module_t;

struct ngx_module_s {
    ngx_uint_t     ctx_index;
    ngx_uint_t     index;
    char          *name;
    ngx_uint_t     spare0;
    ngx_uint_t     spare1;
    ngx_uint_t     version;
    const char    *signature;

    void          *ctx;
    ngx_command_t *commands;
    ngx_uint_t     type;

    ngx_int_t    (*init_master)(ngx_log_t *log);
    ngx_int_t    (*init_module)(ngx_cycle_t *cycle);
    ngx_int_t    (*init_process)(ngx_cycle_t *cycle);
    ngx_int_t    (*init_thread)(ngx_cycle_t *cycle);
    void         (*exit_thread)(ngx_cycle_t *cycle);
    void         (*exit_process)(ngx_cycle_t *cycle);
    void         (*exit_master)(ngx_cycle_t *cycle);

    uintptr_t      spare_hook0;
    uintptr_t      spare_hook1;
    uintptr_t      spare_hook2;
    uintptr_t      spare_hook3;
    uintptr_t      spare_hook4;
    uintptr_t      spare_hook5;
    uintptr_t      spare_hook6;
    uintptr_t      spare_hook7;
};

/* ------------------------------------------------------- thread pool */

typedef struct ngx_thread_pool_s  ngx_thread_pool_t;
typedef struct ngx_thread_task_s  ngx_thread_task_t;

struct ngx_thread_task_s {
    ngx_thread_task_t *next;
    ngx_uint_t         id;
    void              *ctx;
    void             (*handler)(void *data, ngx_log_t *log);
    ngx_event_t        event;
};

ngx_thread_pool_t *ngx_thread_pool_add(ngx_conf_t *cf, ngx_str_t *name);
ngx_thread_pool_t *ngx_thread_pool_get(ngx_cycle_t *cycle, ngx_str_t *name);
ngx_thread_task_t *ngx_thread_task_alloc(ngx_pool_t *pool, size_t size);
ngx_int_t ngx_thread_task_post(ngx_thread_pool_t *tp, ngx_thread_task_t *task);

#endif /* _NGX_CORE_H_INCLUDED_ */
