/* nginx_compat: compile-check declarations — see README.md.  Mirrors the
 * public nginx API subset ngx_http_detect_tpu_module.c uses (nginx is
 * BSD-2-Clause; these are API declarations, not nginx source). */
#ifndef _NGX_CONFIG_H_INCLUDED_
#define _NGX_CONFIG_H_INCLUDED_

#include <stddef.h>
#include <stdint.h>
#include <string.h>
#include <sys/types.h>

typedef unsigned char u_char;

typedef intptr_t  ngx_int_t;
typedef uintptr_t ngx_uint_t;
typedef intptr_t  ngx_flag_t;
typedef ngx_uint_t ngx_msec_t;

#define NGX_OK        0
#define NGX_ERROR    -1
#define NGX_AGAIN    -2
#define NGX_BUSY     -3
#define NGX_DONE     -4
#define NGX_DECLINED -5
#define NGX_ABORT    -6

#define NGX_THREADS   1

#define ngx_memcpy(dst, src, n)  (void) memcpy(dst, src, n)
#define ngx_cpymem(dst, src, n)  (((u_char *) memcpy(dst, src, n)) + (n))

#endif /* _NGX_CONFIG_H_INCLUDED_ */
