/* nginx_compat: compile-check declarations — see README.md. */
#ifndef _NGX_HTTP_H_INCLUDED_
#define _NGX_HTTP_H_INCLUDED_

#include <ngx_config.h>
#include <ngx_core.h>

typedef struct ngx_http_request_s  ngx_http_request_t;

/* ------------------------------------------------------------ phases */

typedef enum {
    NGX_HTTP_POST_READ_PHASE = 0,
    NGX_HTTP_SERVER_REWRITE_PHASE,
    NGX_HTTP_FIND_CONFIG_PHASE,
    NGX_HTTP_REWRITE_PHASE,
    NGX_HTTP_POST_REWRITE_PHASE,
    NGX_HTTP_PREACCESS_PHASE,
    NGX_HTTP_ACCESS_PHASE,
    NGX_HTTP_POST_ACCESS_PHASE,
    NGX_HTTP_PRECONTENT_PHASE,
    NGX_HTTP_CONTENT_PHASE,
    NGX_HTTP_LOG_PHASE
} ngx_http_phases;

typedef ngx_int_t (*ngx_http_handler_pt)(ngx_http_request_t *r);

typedef struct {
    ngx_array_t  handlers;
} ngx_http_phase_t;

typedef struct {
    ngx_array_t       servers;
    ngx_http_phase_t  phases[NGX_HTTP_LOG_PHASE + 1];
} ngx_http_core_main_conf_t;

/* --------------------------------------------------- status + module */

#define NGX_HTTP_SPECIAL_RESPONSE       300
#define NGX_HTTP_FORBIDDEN              403
#define NGX_HTTP_INTERNAL_SERVER_ERROR  500
#define NGX_HTTP_SERVICE_UNAVAILABLE    503

#define NGX_HTTP_MODULE  0x50545448  /* "HTTP" */

#define NGX_HTTP_MAIN_CONF  0x02000000
#define NGX_HTTP_SRV_CONF   0x04000000
#define NGX_HTTP_LOC_CONF   0x08000000

#define NGX_HTTP_MAIN_CONF_OFFSET  offsetof(ngx_http_conf_ctx_t, main_conf)
#define NGX_HTTP_SRV_CONF_OFFSET   offsetof(ngx_http_conf_ctx_t, srv_conf)
#define NGX_HTTP_LOC_CONF_OFFSET   offsetof(ngx_http_conf_ctx_t, loc_conf)

typedef struct {
    void **main_conf;
    void **srv_conf;
    void **loc_conf;
} ngx_http_conf_ctx_t;

typedef struct {
    ngx_int_t (*preconfiguration)(ngx_conf_t *cf);
    ngx_int_t (*postconfiguration)(ngx_conf_t *cf);
    void     *(*create_main_conf)(ngx_conf_t *cf);
    char     *(*init_main_conf)(ngx_conf_t *cf, void *conf);
    void     *(*create_srv_conf)(ngx_conf_t *cf);
    char     *(*merge_srv_conf)(ngx_conf_t *cf, void *prev, void *conf);
    void     *(*create_loc_conf)(ngx_conf_t *cf);
    char     *(*merge_loc_conf)(ngx_conf_t *cf, void *prev, void *conf);
} ngx_http_module_t;

extern ngx_module_t ngx_http_core_module;

/* ----------------------------------------------------------- request */

typedef struct {
    ngx_list_t        headers;
    ngx_table_elt_t  *host;
    ngx_table_elt_t  *content_length;
    off_t             content_length_n;
} ngx_http_headers_in_t;

typedef struct {
    ngx_list_t        headers;
    ngx_uint_t        status;
    ngx_str_t         status_line;
    ngx_str_t         content_type;
    off_t             content_length_n;
} ngx_http_headers_out_t;

typedef struct {
    ngx_chain_t  *bufs;
    off_t         rest;
} ngx_http_request_body_t;

typedef void (*ngx_http_event_handler_pt)(ngx_http_request_t *r);
typedef void (*ngx_http_client_body_handler_pt)(ngx_http_request_t *r);

/* connection subset: only the member the module reads (the textual
 * source address, nginx fills it at accept time) */
typedef struct {
    ngx_str_t                   addr_text;
} ngx_connection_t;

struct ngx_http_request_s {
    void                      **ctx;
    void                      **main_conf;
    void                      **srv_conf;
    void                      **loc_conf;

    ngx_connection_t           *connection;
    ngx_pool_t                 *pool;
    ngx_http_request_t         *main;
    ngx_http_request_t         *parent;

    ngx_http_headers_in_t       headers_in;
    ngx_http_headers_out_t      headers_out;
    ngx_http_request_body_t    *request_body;

    ngx_str_t                   method_name;
    ngx_str_t                   uri;
    ngx_str_t                   unparsed_uri;
    ngx_str_t                   args;

    ngx_http_event_handler_pt   read_event_handler;
    ngx_http_event_handler_pt   write_event_handler;

    unsigned                    count:16;
    unsigned                    blocked:8;
    unsigned                    aio:1;
    unsigned                    preserve_body:1;
};

/* ------------------------------------------------------------ macros */

#define ngx_http_get_module_ctx(r, module)  (r)->ctx[module.ctx_index]
#define ngx_http_set_ctx(r, c, module)      (r)->ctx[module.ctx_index] = c

#define ngx_http_get_module_main_conf(r, module)                            \
    (r)->main_conf[module.ctx_index]
#define ngx_http_get_module_loc_conf(r, module)                             \
    (r)->loc_conf[module.ctx_index]

#define ngx_http_conf_get_module_main_conf(cf, module)                      \
    ((ngx_http_conf_ctx_t *) cf->ctx)->main_conf[module.ctx_index]

/* --------------------------------------------------------- functions */

ngx_int_t ngx_http_read_client_request_body(
    ngx_http_request_t *r, ngx_http_client_body_handler_pt post_handler);
void ngx_http_finalize_request(ngx_http_request_t *r, ngx_int_t rc);
ngx_int_t ngx_http_internal_redirect(ngx_http_request_t *r, ngx_str_t *uri,
                                     ngx_str_t *args);
void ngx_http_core_run_phases(ngx_http_request_t *r);

/* ------------------------------------------------------------ filters */

typedef ngx_int_t (*ngx_http_output_header_filter_pt)(ngx_http_request_t *r);
typedef ngx_int_t (*ngx_http_output_body_filter_pt)(ngx_http_request_t *r,
                                                    ngx_chain_t *chain);

extern ngx_http_output_header_filter_pt  ngx_http_top_header_filter;
extern ngx_http_output_body_filter_pt    ngx_http_top_body_filter;

#endif /* _NGX_HTTP_H_INCLUDED_ */
