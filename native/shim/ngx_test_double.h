/* ngx_test_double — driver-facing API of the runtime nginx double.
 * See ngx_test_double.c; used by shim_harness.c. */
#ifndef NGX_TEST_DOUBLE_H
#define NGX_TEST_DOUBLE_H

#include <ngx_config.h>
#include <ngx_core.h>
#include <ngx_http.h>

/* the module under test */
extern ngx_module_t ngx_http_detect_tpu_module;

typedef struct {
    ngx_pool_t *pool;
    void       *loc_conf;   /* ngx_http_detect_tpu_loc_conf_t, defaults
                             * merged; the driver overrides fields */
} td_setup_result_t;

typedef struct {
    ngx_http_request_t r;        /* what the module sees (embedded) */
    ngx_connection_t   conn;
    void              *ctxs[1];
    void              *loc_confs[1];

    /* driver-preset body (memory buf) */
    const char        *body;
    size_t             body_len;
    ngx_buf_t          body_buf;
    ngx_chain_t        body_chain;
    ngx_http_request_body_t request_body;
    ngx_http_client_body_handler_pt body_post_handler;
    ngx_event_t        body_ready_ev;

    /* outcome */
    int  done;
    int  final_status;     /* 200 pass, 403, 503, 302=block-page redirect */
    int  last_rc;
    char redirect[256];
} td_request_t;

ngx_pool_t *td_pool_create(void);
void td_pool_destroy(ngx_pool_t *pool);
ngx_int_t td_array_init(ngx_array_t *a, ngx_pool_t *pool, ngx_uint_t n,
                        size_t size);
ngx_int_t td_list_init(ngx_list_t *l, ngx_pool_t *pool, ngx_uint_t n,
                       size_t size);

void td_post_event(ngx_event_t *ev);
int  td_run_one_event(int timeout_ms);
void td_configure_thread_pool(const char *name);   /* NULL = none */

int td_setup(td_setup_result_t *out);
int td_request_init(td_request_t *td, ngx_pool_t *pool, void *loc_conf,
                    const char *method, const char *uri,
                    const char *addr_text);
int td_add_header_in(td_request_t *td, const char *key, const char *value);
int td_find_header_out(td_request_t *td, const char *key, const char *value);
td_request_t *td_from_request(ngx_http_request_t *r);

#endif /* NGX_TEST_DOUBLE_H */
