/*
 * ngx_test_double — a RUNTIME implementation of the nginx_compat API
 * subset, so ngx_http_detect_tpu_module.c's phase state machine can
 * EXECUTE in CI (VERDICT r03 item #5: 881 LoC of re-entry/refcount/
 * verdict logic had only ever been compile-checked).
 *
 * Faithful to the semantics the module depends on:
 *   - pools: malloc arena, freed wholesale at destroy;
 *   - event loop: a FIFO the driver drains single-threaded — thread-pool
 *     completions enqueue here exactly like nginx's notify event, so the
 *     handler can never observe a half-done ctx from the pool thread;
 *   - thread pool: one real pthread running task->handler, then posting
 *     task->event (mutex-protected handoff);
 *   - ngx_http_read_client_request_body: takes the body preset by the
 *     driver, r->main->count++ (the refcount the module must balance),
 *     defers the continuation through the event queue (async path);
 *   - ngx_http_core_run_phases: the access-phase walk with nginx's rc
 *     contract (DECLINED → next phase/200, AGAIN/DONE → suspend,
 *     status → finalize with it);
 *   - ngx_http_finalize_request: refcount bookkeeping the driver asserts.
 *
 * The roundtrip itself is the REAL shim_bridge.cc → DetectClient → UDS →
 * Python serve loop: these scenarios execute the same wire path
 * production does, not a stubbed verdict.
 */

#define _POSIX_C_SOURCE 200809L

#include <ngx_config.h>
#include <ngx_core.h>
#include <ngx_http.h>

#include <pthread.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <strings.h>
#include <time.h>

#include "ngx_test_double.h"

/* ------------------------------------------------------------- pools */

typedef struct td_block_s {
    struct td_block_s *next;
} td_block_t;

struct ngx_pool_s {
    td_block_t *blocks;
};

ngx_pool_t *
td_pool_create(void)
{
    ngx_pool_t *p = calloc(1, sizeof(ngx_pool_t));
    return p;
}

void
td_pool_destroy(ngx_pool_t *pool)
{
    td_block_t *b, *next;

    if (pool == NULL) {
        return;
    }
    for (b = pool->blocks; b; b = next) {
        next = b->next;
        free(b);
    }
    free(pool);
}

void *
ngx_pnalloc(ngx_pool_t *pool, size_t size)
{
    td_block_t *b = malloc(sizeof(td_block_t) + size);

    if (b == NULL) {
        return NULL;
    }
    b->next = pool->blocks;
    pool->blocks = b;
    return (void *) (b + 1);
}

void *
ngx_pcalloc(ngx_pool_t *pool, size_t size)
{
    void *p = ngx_pnalloc(pool, size);

    if (p != NULL) {
        memset(p, 0, size);
    }
    return p;
}

/* ------------------------------------------------------------ strings */

ngx_int_t
ngx_strncasecmp(u_char *s1, u_char *s2, size_t n)
{
    return (ngx_int_t) strncasecmp((const char *) s1, (const char *) s2, n);
}

u_char *
ngx_strcasestrn(u_char *s1, char *s2, size_t n)
{
    /* nginx contract: s2 has n+1 significant chars; s1 NUL-terminated */
    size_t  len = strlen((const char *) s1);
    size_t  i;

    for (i = 0; i + n + 1 <= len; i++) {
        if (strncasecmp((const char *) s1 + i, s2, n + 1) == 0) {
            return s1 + i;
        }
    }
    return NULL;
}

u_char *
ngx_snprintf(u_char *buf, size_t max, const char *fmt, ...)
{
    /* the module uses only "%O" (off_t) — translate to %lld */
    va_list ap;
    int     n;
    char    tmp[64];

    va_start(ap, fmt);
    if (strcmp(fmt, "%O") == 0) {
        long long v = (long long) va_arg(ap, off_t);
        n = snprintf(tmp, sizeof(tmp), "%lld", v);
    } else {
        n = vsnprintf(tmp, sizeof(tmp), fmt, ap);
    }
    va_end(ap);
    if (n < 0) {
        n = 0;
    }
    if ((size_t) n > max) {
        n = (int) max;
    }
    memcpy(buf, tmp, (size_t) n);
    return buf + n;
}

/* -------------------------------------------------------- array, list */

ngx_int_t
td_array_init(ngx_array_t *a, ngx_pool_t *pool, ngx_uint_t n, size_t size)
{
    a->elts = ngx_pnalloc(pool, n * size);
    if (a->elts == NULL) {
        return NGX_ERROR;
    }
    a->nelts = 0;
    a->size = size;
    a->nalloc = n;
    a->pool = pool;
    return NGX_OK;
}

void *
ngx_array_push(ngx_array_t *a)
{
    if (a->nelts == a->nalloc) {
        void *n = ngx_pnalloc(a->pool, 2 * a->size * a->nalloc);
        if (n == NULL) {
            return NULL;
        }
        memcpy(n, a->elts, a->size * a->nelts);
        a->elts = n;
        a->nalloc *= 2;
    }
    return (u_char *) a->elts + a->size * a->nelts++;
}

ngx_int_t
td_list_init(ngx_list_t *l, ngx_pool_t *pool, ngx_uint_t n, size_t size)
{
    l->part.elts = ngx_pnalloc(pool, n * size);
    if (l->part.elts == NULL) {
        return NGX_ERROR;
    }
    l->part.nelts = 0;
    l->part.next = NULL;
    l->last = &l->part;
    l->size = size;
    l->nalloc = n;
    l->pool = pool;
    return NGX_OK;
}

void *
ngx_list_push(ngx_list_t *l)
{
    ngx_list_part_t *last = l->last;

    if (last->nelts == l->nalloc) {
        last = ngx_pcalloc(l->pool, sizeof(ngx_list_part_t));
        if (last == NULL) {
            return NULL;
        }
        last->elts = ngx_pnalloc(l->pool, l->nalloc * l->size);
        if (last->elts == NULL) {
            return NULL;
        }
        l->last->next = last;
        l->last = last;
    }
    return (u_char *) last->elts + l->size * last->nelts++;
}

ssize_t
ngx_read_file(ngx_file_t *file, u_char *buf, size_t size, off_t offset)
{
    (void) file; (void) buf; (void) size; (void) offset;
    return -1;   /* the double presents bodies as memory buffers only */
}

/* -------------------------------------------------- conf slot setters
 * (referenced by the module's command table; the driver fills conf
 * structs directly, so these can never be reached at runtime) */

char *ngx_conf_set_flag_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf)
{ (void) cf; (void) cmd; (void) conf; return NGX_CONF_ERROR; }
char *ngx_conf_set_str_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf)
{ (void) cf; (void) cmd; (void) conf; return NGX_CONF_ERROR; }
char *ngx_conf_set_str_array_slot(ngx_conf_t *cf, ngx_command_t *cmd,
                                  void *conf)
{ (void) cf; (void) cmd; (void) conf; return NGX_CONF_ERROR; }
char *ngx_conf_set_num_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf)
{ (void) cf; (void) cmd; (void) conf; return NGX_CONF_ERROR; }
char *ngx_conf_set_enum_slot(ngx_conf_t *cf, ngx_command_t *cmd, void *conf)
{ (void) cf; (void) cmd; (void) conf; return NGX_CONF_ERROR; }

/* ---------------------------------------------------------- event loop */

#define TD_MAX_EVENTS 256

static struct {
    ngx_event_t    *q[TD_MAX_EVENTS];
    int             head, tail;
    pthread_mutex_t mu;
    pthread_cond_t  cv;
} td_events = { {0}, 0, 0, PTHREAD_MUTEX_INITIALIZER,
                PTHREAD_COND_INITIALIZER };

void
td_post_event(ngx_event_t *ev)
{
    pthread_mutex_lock(&td_events.mu);
    td_events.q[td_events.tail % TD_MAX_EVENTS] = ev;
    td_events.tail++;
    pthread_cond_signal(&td_events.cv);
    pthread_mutex_unlock(&td_events.mu);
}

/* drain one event, waiting up to ms; 1 = ran one, 0 = timed out */
int
td_run_one_event(int timeout_ms)
{
    ngx_event_t     *ev = NULL;
    struct timespec  ts;

    clock_gettime(CLOCK_REALTIME, &ts);
    ts.tv_sec += timeout_ms / 1000;
    ts.tv_nsec += (long) (timeout_ms % 1000) * 1000000L;
    if (ts.tv_nsec >= 1000000000L) {
        ts.tv_sec++;
        ts.tv_nsec -= 1000000000L;
    }
    pthread_mutex_lock(&td_events.mu);
    while (td_events.head == td_events.tail) {
        if (pthread_cond_timedwait(&td_events.cv, &td_events.mu, &ts) != 0) {
            pthread_mutex_unlock(&td_events.mu);
            return 0;
        }
    }
    ev = td_events.q[td_events.head % TD_MAX_EVENTS];
    td_events.head++;
    pthread_mutex_unlock(&td_events.mu);
    ev->handler(ev);
    return 1;
}

/* --------------------------------------------------------- thread pool */

struct ngx_thread_pool_s {
    int dummy;
};

static ngx_thread_pool_t td_pool_obj;
static ngx_str_t         td_pool_name_configured;
volatile ngx_cycle_t    *ngx_cycle;
static ngx_cycle_t       td_cycle;

typedef struct {
    ngx_thread_task_t *task;
} td_thread_arg_t;

static void *
td_thread_main(void *arg)
{
    td_thread_arg_t   *a = arg;
    ngx_thread_task_t *task = a->task;

    free(a);
    task->handler(task->ctx, NULL);
    td_post_event(&task->event);   /* the notify-event handoff */
    return NULL;
}

ngx_thread_pool_t *
ngx_thread_pool_get(ngx_cycle_t *cycle, ngx_str_t *name)
{
    (void) cycle;
    if (td_pool_name_configured.len == 0
        || name->len != td_pool_name_configured.len
        || memcmp(name->data, td_pool_name_configured.data, name->len) != 0)
    {
        return NULL;   /* scenario: no thread_pool block configured */
    }
    return &td_pool_obj;
}

void
td_configure_thread_pool(const char *name)
{
    if (name == NULL) {
        td_pool_name_configured.len = 0;
        td_pool_name_configured.data = NULL;
        return;
    }
    td_pool_name_configured.len = strlen(name);
    td_pool_name_configured.data = (u_char *) name;
}

ngx_thread_task_t *
ngx_thread_task_alloc(ngx_pool_t *pool, size_t size)
{
    ngx_thread_task_t *task;

    task = ngx_pcalloc(pool, sizeof(ngx_thread_task_t) + size);
    if (task == NULL) {
        return NULL;
    }
    if (size) {
        task->ctx = task + 1;
    }
    return task;
}

ngx_int_t
ngx_thread_task_post(ngx_thread_pool_t *tp, ngx_thread_task_t *task)
{
    pthread_t        th;
    td_thread_arg_t *a;

    (void) tp;
    a = malloc(sizeof(*a));
    if (a == NULL) {
        return NGX_ERROR;
    }
    a->task = task;
    if (pthread_create(&th, NULL, td_thread_main, a) != 0) {
        free(a);
        return NGX_ERROR;
    }
    pthread_detach(th);
    return NGX_OK;
}

/* --------------------------------------------- request state + phases */

/* per-request driver state, reachable from the ngx_http_request_t the
 * module sees (container pattern: the request is embedded) */

td_request_t *
td_from_request(ngx_http_request_t *r)
{
    return (td_request_t *) ((char *) r - offsetof(td_request_t, r));
}

void
ngx_http_finalize_request(ngx_http_request_t *r, ngx_int_t rc)
{
    td_request_t *td = td_from_request(r->main);

    if (rc == NGX_DONE) {
        td->r.count--;
        return;
    }
    if (rc >= NGX_HTTP_SPECIAL_RESPONSE) {
        td->final_status = (int) rc;
        td->done = 1;
        return;
    }
    td->done = 1;
}

ngx_int_t
ngx_http_internal_redirect(ngx_http_request_t *r, ngx_str_t *uri,
                           ngx_str_t *args)
{
    td_request_t *td = td_from_request(r->main);

    (void) args;
    snprintf(td->redirect, sizeof(td->redirect), "%.*s",
             (int) uri->len, (const char *) uri->data);
    td->final_status = 302;   /* marker: internal redirect taken */
    td->done = 1;
    return NGX_OK;
}

/* continuation posted by the body-read double */
static void
td_body_ready_event(ngx_event_t *ev)
{
    td_request_t *td = ev->data;

    td->body_post_handler(&td->r);
}

ngx_int_t
ngx_http_read_client_request_body(ngx_http_request_t *r,
                                  ngx_http_client_body_handler_pt handler)
{
    td_request_t *td = td_from_request(r);

    r->main->count++;           /* what the module must balance */
    td->body_post_handler = handler;

    if (td->body_len) {
        td->body_buf.pos = (u_char *) td->body;
        td->body_buf.last = (u_char *) td->body + td->body_len;
        td->body_buf.memory = 1;
        td->body_chain.buf = &td->body_buf;
        td->body_chain.next = NULL;
        td->request_body.bufs = &td->body_chain;
    } else {
        td->request_body.bufs = NULL;
    }
    r->request_body = &td->request_body;

    /* async path: the continuation fires from the event loop, like a
     * client still streaming the body in */
    td->body_ready_ev.data = td;
    td->body_ready_ev.handler = td_body_ready_event;
    td_post_event(&td->body_ready_ev);
    return NGX_AGAIN;
}

/* the access-phase walk (subset: the handlers registered at init) */

static ngx_http_core_main_conf_t *td_cmcf;

void
ngx_http_core_run_phases(ngx_http_request_t *r)
{
    td_request_t       *td = td_from_request(r);
    ngx_http_handler_pt *h;
    ngx_uint_t           i;
    ngx_int_t            rc;

    if (td->done) {
        return;
    }
    h = td_cmcf->phases[NGX_HTTP_ACCESS_PHASE].handlers.elts;
    for (i = 0; i < td_cmcf->phases[NGX_HTTP_ACCESS_PHASE].handlers.nelts;
         i++)
    {
        rc = h[i](r);
        td->last_rc = (int) rc;
        if (rc == NGX_DECLINED) {
            continue;            /* next handler / next phase */
        }
        if (rc == NGX_AGAIN || rc == NGX_DONE) {
            return;              /* suspended: wait for an event */
        }
        ngx_http_finalize_request(r, rc);
        return;
    }
    /* all access handlers declined: request proceeds (content phase) */
    td->final_status = 200;
    td->done = 1;
}

/* -------------------------------------------------------- module setup */

static ngx_module_t *td_modules[2];

ngx_module_t ngx_http_core_module;   /* index only */

int
td_setup(td_setup_result_t *out)
{
    ngx_http_module_t *mctx;
    ngx_conf_t         cf;
    ngx_http_conf_ctx_t conf_ctx;
    static void       *main_confs[2];
    static ngx_http_core_main_conf_t cmcf_storage;

    ngx_http_detect_tpu_module.ctx_index = 0;
    ngx_http_core_module.ctx_index = 1;
    td_modules[0] = &ngx_http_detect_tpu_module;
    td_modules[1] = &ngx_http_core_module;

    out->pool = td_pool_create();
    if (out->pool == NULL) {
        return -1;
    }
    td_cmcf = &cmcf_storage;
    if (td_array_init(&td_cmcf->phases[NGX_HTTP_ACCESS_PHASE].handlers,
                      out->pool, 4, sizeof(ngx_http_handler_pt)) != NGX_OK) {
        return -1;
    }
    main_confs[1] = td_cmcf;
    conf_ctx.main_conf = main_confs;
    conf_ctx.srv_conf = NULL;
    conf_ctx.loc_conf = NULL;

    memset(&cf, 0, sizeof(cf));
    cf.pool = out->pool;
    cf.ctx = &conf_ctx;

    ngx_cycle = &td_cycle;

    mctx = ngx_http_detect_tpu_module.ctx;
    out->loc_conf = mctx->create_loc_conf(&cf);
    if (out->loc_conf == NULL) {
        return -1;
    }
    /* merge against an empty parent applies the documented defaults */
    {
        void *parent = mctx->create_loc_conf(&cf);
        if (parent == NULL
            || mctx->merge_loc_conf(&cf, parent, out->loc_conf)
               != NGX_CONF_OK) {
            return -1;
        }
    }
    if (mctx->postconfiguration(&cf) != NGX_OK) {
        return -1;
    }
    return 0;
}

int
td_request_init(td_request_t *td, ngx_pool_t *pool, void *loc_conf,
                const char *method, const char *uri,
                const char *addr_text)
{
    memset(td, 0, sizeof(*td));
    td->r.pool = pool;
    td->r.main = &td->r;
    td->r.count = 1;
    td->ctxs[0] = NULL;
    td->loc_confs[0] = loc_conf;
    td->r.ctx = td->ctxs;
    td->r.loc_conf = td->loc_confs;
    td->r.method_name.data = (u_char *) method;
    td->r.method_name.len = strlen(method);
    td->r.unparsed_uri.data = (u_char *) uri;
    td->r.unparsed_uri.len = strlen(uri);
    td->conn.addr_text.data = (u_char *) addr_text;
    td->conn.addr_text.len = strlen(addr_text);
    td->r.connection = &td->conn;
    td->r.headers_out.content_length_n = -1;
    if (td_list_init(&td->r.headers_in.headers, pool, 8,
                     sizeof(ngx_table_elt_t)) != NGX_OK
        || td_list_init(&td->r.headers_out.headers, pool, 8,
                        sizeof(ngx_table_elt_t)) != NGX_OK) {
        return -1;
    }
    return 0;
}

int
td_add_header_in(td_request_t *td, const char *key, const char *value)
{
    ngx_table_elt_t *h = ngx_list_push(&td->r.headers_in.headers);

    if (h == NULL) {
        return -1;
    }
    h->hash = 1;
    h->key.data = (u_char *) key;
    h->key.len = strlen(key);
    h->value.data = (u_char *) value;
    h->value.len = strlen(value);
    return 0;
}

int
td_find_header_out(td_request_t *td, const char *key, const char *value)
{
    ngx_list_part_t *part;
    ngx_table_elt_t *h;
    ngx_uint_t       i;

    for (part = &td->r.headers_out.headers.part; part; part = part->next) {
        h = part->elts;
        for (i = 0; i < part->nelts; i++) {
            if (h[i].key.len == strlen(key)
                && strncasecmp((const char *) h[i].key.data, key,
                               h[i].key.len) == 0
                && h[i].value.len == strlen(value)
                && memcmp(h[i].value.data, value, h[i].value.len) == 0) {
                return 1;
            }
        }
    }
    return 0;
}

/* ----------------------------------------------------- filter chain */

static ngx_int_t
td_terminal_body_filter(ngx_http_request_t *r, ngx_chain_t *in)
{
    (void) r; (void) in;
    return NGX_OK;
}

ngx_http_output_header_filter_pt ngx_http_top_header_filter;
ngx_http_output_body_filter_pt   ngx_http_top_body_filter =
    td_terminal_body_filter;
