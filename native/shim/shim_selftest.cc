// Self-test driver for DetectClient — exercises the blocking shim path a
// real nginx worker thread would run: plain verdicts, a streamed body,
// and the fail-open deadline against a dead socket.  Prints one JSON line
// per scenario; tests/test_shim.py asserts on them.

#include <stdio.h>

#include <string>

#include "detect_client.hpp"

static void print_verdict(const char* name, const ipt::Response& r) {
  printf("{\"case\": \"%s\", \"attack\": %s, \"blocked\": %s, "
         "\"fail_open\": %s, \"n_rules\": %zu}\n",
         name, r.attack() ? "true" : "false", r.blocked() ? "true" : "false",
         r.fail_open() ? "true" : "false", r.rule_ids.size());
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: shim_selftest <socket> [dead_socket]\n");
    return 2;
  }
  // generous deadline: the CI box is 1 vCPU and first-touch XLA compiles
  // of a cold shape can take tens of seconds under full-suite load — a
  // tight deadline here tests the scheduler, not the shim
  ipt::DetectClient client(argv[1], /*deadline_ms=*/60000);

  ipt::Request attack;
  attack.req_id = 1;
  attack.uri = "/?q=1%20union%20select%20x";
  attack.headers_blob = "Host: t";
  print_verdict("attack", client.Detect(attack));

  ipt::Request benign;
  benign.req_id = 2;
  benign.uri = "/index.html?page=3";
  benign.headers_blob = "Host: t";
  print_verdict("benign", client.Detect(benign));

  // streamed body: attack split across chunk boundaries
  ipt::Request stream;
  stream.req_id = 3;
  stream.uri = "/upload";
  stream.headers_blob = "Host: t";
  stream.body = "x=1 uni";
  if (client.BeginStream(stream) && client.SendChunk(3, "on sel") &&
      client.SendChunk(3, "ect password from users", /*last=*/true)) {
    print_verdict("stream", client.FinishStream(3));
  } else {
    printf("{\"case\": \"stream\", \"error\": true}\n");
  }

  // websocket capture: masked client frame carrying an attack, split
  // across two capture calls (the serve-side parser carries state), then
  // a benign frame that must report the sticky verdict, then the end
  {
    // warmup: compile the ws/stream-scan shapes on a throwaway stream so
    // the asserted cases below measure behavior, not first-compile time
    client.DetectWsBytes(100, 899, std::string("\x81\x02ok", 4));
    client.DetectWsBytes(101, 899, "", 0, 2, false, /*end=*/true);
    // minimal RFC 6455 client frame: FIN|text, masked, payload<126
    auto ws_frame = [](const std::string& payload, bool fin, bool cont) {
      std::string f;
      f.push_back(char((fin ? 0x80 : 0x00) | (cont ? 0x0 : 0x1)));
      f.push_back(char(0x80 | payload.size()));
      const char mask[4] = {0x21, 0x43, 0x65, 0x07};
      f.append(mask, 4);
      for (size_t i = 0; i < payload.size(); ++i)
        f.push_back(char(payload[i] ^ mask[i & 3]));
      return f;
    };
    ipt::Response r1 = client.DetectWsBytes(
        5, 900, ws_frame("1 union ", false, false));
    ipt::Response r2 = client.DetectWsBytes(
        6, 900, ws_frame("select 2", true, true));
    print_verdict("ws_attack", r2);
    ipt::Response r3 = client.DetectWsBytes(
        7, 900, ws_frame("benign chatter", true, false));
    print_verdict("ws_sticky", r3);
    client.DetectWsBytes(8, 900, "", 0, 2, false, /*end=*/true);
    (void)r1;
  }

  if (argc > 2) {
    ipt::DetectClient dead(argv[2], /*deadline_ms=*/100);
    ipt::Request r;
    r.req_id = 4;
    r.uri = "/?q=<script>";
    print_verdict("dead_socket", dead.Detect(r));
  }
  return 0;
}
