/* detect_tpu_conf.h — the module's location-conf layout, shared with the
 * phase-machine harness (shim_harness.c) so the two can never drift: a
 * field reorder that would silently corrupt a hand-mirrored copy is a
 * compile-visible change here. */
#ifndef DETECT_TPU_CONF_H
#define DETECT_TPU_CONF_H

#include <ngx_config.h>
#include <ngx_core.h>

typedef struct {
    ngx_flag_t   enabled;          /* detect_tpu              */
    ngx_str_t    socket_path;      /* detect_tpu_socket       */
    ngx_uint_t   mode;             /* 0 off 1 monitoring 2 block
                                    * 3 safe_blocking (wire values;
                                    * strength order lives serve-side) */
    ngx_uint_t   timeout_ms;       /* detect_tpu_timeout_ms   */
    ngx_flag_t   fail_open;        /* detect_tpu_fail_open    */
    ngx_uint_t   tenant;           /* detect_tpu_tenant       */
    ngx_str_t    acl;              /* detect_tpu_acl: informational at
                                    * the data plane — enforcement runs
                                    * serve-side via the tenant→acl
                                    * binding the sync loop pushes;
                                    * declared so rendered configs parse */
    ngx_str_t    block_page;       /* detect_tpu_block_page   */
    /* response/websocket scanning + parser toggles are captured from the
     * rendered config for parity with the reference's wallarm_* set; the
     * response side hooks a body filter in a later phase of the build */
    ngx_flag_t   parse_response;   /* detect_tpu_parse_response  */
    ngx_flag_t   parse_websocket;  /* detect_tpu_parse_websocket */
    ngx_array_t *parser_disable;   /* detect_tpu_parser_disable  */
    ngx_str_t    metrics_addr;     /* detect_tpu_metrics: the serve loop's
                                    * HTTP config/metrics plane (rendered
                                    * at server scope by the template) */
} ngx_http_detect_tpu_loc_conf_t;

/* Per-upgraded-connection WebSocket capture state (the module's
 * upgrade-relay wrap — see the "WebSocket upgrade capture" section of
 * ngx_http_detect_tpu_module.c).  Shared with the harness so the test
 * double can drive the tunnel-byte path the way a relay would. */
typedef struct {
    uint64_t     stream_id;        /* serve-side stream key            */
    ngx_str_t    socket_path;
    double       timeout_ms;
    uint32_t     tenant;
    uint8_t      mode;
    unsigned     fail_open:1;      /* conf->fail_open at begin time    */
    unsigned     blocked:1;        /* sticky: relay must close tunnel  */
    unsigned     ended:1;          /* end frame sent; no more capture  */
} ngx_http_detect_tpu_ws_ctx_t;

#ifdef __cplusplus
extern "C" {
#endif

struct ngx_http_request_s;
ngx_http_detect_tpu_ws_ctx_t *ngx_http_detect_tpu_ws_begin(
    struct ngx_http_request_s *r);
ngx_int_t ngx_http_detect_tpu_ws_data(ngx_http_detect_tpu_ws_ctx_t *ws,
    ngx_uint_t server_to_client, u_char *data, size_t len);
void ngx_http_detect_tpu_ws_end(ngx_http_detect_tpu_ws_ctx_t *ws);

#ifdef __cplusplus
}
#endif

#endif /* DETECT_TPU_CONF_H */
