// Strict-syntax SQLi / XSS detectors — C++ twin of
// ingress_plus_tpu/models/libdetect.py (the libdetection analog;
// SURVEY.md §2.2: "TPU tokenizer/lexer kernel or C++ confirm stage in
// sidecar").  Built as libiptdetect.so with a C ABI; the Python module
// dispatches here via ctypes when the library is present, and the
// differential test (tests/test_native_confirm.py) pins this
// implementation byte-for-byte to the Python reference.
//
// The grammar notes live in the Python file; this file mirrors its
// observable behavior exactly — including the tokenizer's alternation
// order (comment before '-'/'/' operators, hex before num), doubled-quote
// string continuation, unterminated strings, and the unknown-byte skip.

#include <cctype>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

constexpr size_t kMaxLen = 4096;
constexpr size_t kMaxTokens = 512;

// ------------------------------------------------------------------ SQLi

const std::unordered_set<std::string>& SqlKeywords() {
  static const auto* kw = new std::unordered_set<std::string>{
      "select", "union", "insert", "update", "delete", "drop", "create",
      "alter", "truncate", "replace", "merge", "exec", "execute", "declare",
      "from", "where", "having", "group", "order", "limit", "offset", "into",
      "values", "table", "database", "and", "or", "not", "like", "between",
      "in", "is", "null", "case", "when", "then", "else", "end", "cast",
      "convert", "waitfor", "delay",
  };
  return *kw;
}

const std::unordered_set<std::string>& SqlFunctions() {
  static const auto* fn = new std::unordered_set<std::string>{
      "sleep", "benchmark", "pg_sleep", "load_file", "version", "user",
      "current_user", "session_user", "system_user", "database", "schema",
      "concat", "group_concat", "char", "chr", "ascii", "substring",
      "substr", "mid", "hex", "unhex", "extractvalue", "updatexml",
      "xp_cmdshell", "randomblob", "sqlite_version", "utl_inaddr",
      "dbms_pipe",
  };
  return *fn;
}

enum class Kind : uint8_t {
  kComment, kStr, kHex, kNum, kWord, kFn, kOp,
  kKwUnion, kKwSelect, kKwFrom, kKwOr, kKwAnd, kKwOther,
};

struct Token {
  Kind kind;
  std::string text;  // lowercased for words/ops where compared
  std::string kw;    // keyword name when kind is kKw*
};

inline bool IsSpace(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}
inline bool IsDigit(uint8_t c) { return c >= '0' && c <= '9'; }
inline bool IsAlpha(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z');
}
inline bool IsHexDigit(uint8_t c) {
  return IsDigit(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F');
}
inline bool IsWordStart(uint8_t c) { return IsAlpha(c) || c == '_'; }
inline bool IsWordCont(uint8_t c) {
  return IsAlpha(c) || IsDigit(c) || c == '_' || c == '$';
}

inline std::string Lower(const uint8_t* p, size_t n) {
  std::string s(reinterpret_cast<const char*>(p), n);
  for (char& c : s)
    if (c >= 'A' && c <= 'Z') c += 32;
  return s;
}

// Quoted literal starting at data[i] (q = ' " or `).  Mirrors the Python
// pattern '(?:[^'\\]|\\.|'')*'? — doubled-quote continuation for '/",
// backslash escapes (none for `), unterminated allowed, and a lone
// trailing backslash is left unconsumed.
size_t LexString(const uint8_t* data, size_t n, size_t i) {
  uint8_t q = data[i];
  bool escapes = (q != '`');
  size_t j = i + 1;
  while (j < n) {
    uint8_t c = data[j];
    if (escapes && c == '\\') {
      if (j + 1 < n) { j += 2; continue; }
      break;  // trailing backslash: regex leaves it for the next token
    }
    if (c == q) {
      if (escapes && j + 1 < n && data[j + 1] == q) { j += 2; continue; }
      return j + 1;  // closed
    }
    ++j;
  }
  return j;  // unterminated
}

std::vector<Token> TokenizeSql(const uint8_t* data, size_t n) {
  std::vector<Token> toks;
  size_t i = 0;
  while (i < n && toks.size() < kMaxTokens) {
    uint8_t c = data[i];
    if (IsSpace(c)) { ++i; continue; }
    // comments (before the '-' '/' '#' operators, like the regex order)
    if (c == '-' && i + 1 < n && data[i + 1] == '-') {
      size_t j = i + 2;
      while (j < n && data[j] != '\n') ++j;
      toks.push_back({Kind::kComment, "--", ""});
      i = j;
      continue;
    }
    if (c == '#') {
      size_t j = i + 1;
      while (j < n && data[j] != '\n') ++j;
      toks.push_back({Kind::kComment, "#", ""});
      i = j;
      continue;
    }
    if (c == '/' && i + 1 < n && data[i + 1] == '*') {
      size_t j = i + 2;
      while (j + 1 < n && !(data[j] == '*' && data[j + 1] == '/')) ++j;
      i = (j + 1 < n) ? j + 2 : n;  // closed or runs to end
      toks.push_back({Kind::kComment, "/*", ""});
      continue;
    }
    if (c == '\'' || c == '"' || c == '`') {
      i = LexString(data, n, i);
      toks.push_back({Kind::kStr, "", ""});
      continue;
    }
    if (c == '0' && i + 1 < n && (data[i + 1] == 'x' || data[i + 1] == 'X')) {
      // the Python pattern is 0x only (lowercase x), hex digits required
      if (data[i + 1] == 'x' && i + 2 < n && IsHexDigit(data[i + 2])) {
        size_t j = i + 2;
        while (j < n && IsHexDigit(data[j])) ++j;
        toks.push_back({Kind::kHex, "", ""});
        i = j;
        continue;
      }
    }
    if (IsDigit(c)) {
      size_t j = i + 1;
      while (j < n && IsDigit(data[j])) ++j;
      if (j + 1 < n && data[j] == '.' && IsDigit(data[j + 1])) {
        ++j;
        while (j < n && IsDigit(data[j])) ++j;
      }
      toks.push_back({Kind::kNum, "", ""});
      i = j;
      continue;
    }
    if (IsWordStart(c)) {
      size_t j = i + 1;
      while (j < n && IsWordCont(data[j])) ++j;
      std::string w = Lower(data + i, j - i);
      const auto& kws = SqlKeywords();
      if (kws.count(w)) {
        Kind k = Kind::kKwOther;
        if (w == "union") k = Kind::kKwUnion;
        else if (w == "select") k = Kind::kKwSelect;
        else if (w == "from") k = Kind::kKwFrom;
        else if (w == "or") k = Kind::kKwOr;
        else if (w == "and") k = Kind::kKwAnd;
        toks.push_back({k, w, w});
      } else if (SqlFunctions().count(w)) {
        toks.push_back({Kind::kFn, w, ""});
      } else {
        toks.push_back({Kind::kWord, w, ""});
      }
      i = j;
      continue;
    }
    // operators, multi-char first (same order as the Python alternation)
    static const char* kOps2[] = {"||", "&&", "<=", ">=", "<>", "!=", "@@"};
    bool matched = false;
    for (const char* op : kOps2) {
      if (i + 1 < n && c == uint8_t(op[0]) && data[i + 1] == uint8_t(op[1])) {
        toks.push_back({Kind::kOp, op, ""});
        i += 2;
        matched = true;
        break;
      }
    }
    if (matched) continue;
    if (std::strchr("=<>+-*/%(),;@!~^&|", c)) {
      toks.push_back({Kind::kOp, std::string(1, char(c)), ""});
      ++i;
      continue;
    }
    ++i;  // unknown byte: skip (strict grammar tolerates noise gaps)
  }
  return toks;
}

inline bool IsValue(const Token& t) {
  return t.kind == Kind::kStr || t.kind == Kind::kNum ||
         t.kind == Kind::kHex || t.kind == Kind::kWord ||
         t.kind == Kind::kFn;
}

inline bool IsLiteral(const Token& t) {
  return t.kind == Kind::kStr || t.kind == Kind::kNum || t.kind == Kind::kHex;
}

inline bool IsKw(const Token& t) {
  return t.kind >= Kind::kKwUnion && t.kind <= Kind::kKwOther;
}

inline bool IsCmpText(const std::string& s) {
  return s == "=" || s == "<" || s == ">" || s == "<=" || s == ">=" ||
         s == "<>" || s == "!=" || s == "like";
}

// True iff toks[lo, hi) contains no `run` consecutive bare words — the
// strictness test separating SQL select-lists from English prose (mirrors
// models/libdetect.py _no_word_run; round-4 fix: co-occurrence matching
// made the strict confirm fire on ordinary sentences).
inline bool NoWordRun(const std::vector<Token>& toks, size_t lo, size_t hi,
                      int run = 3) {
  int streak = 0;
  for (size_t i = lo; i < hi && i < toks.size(); ++i) {
    streak = (toks[i].kind == Kind::kWord) ? streak + 1 : 0;
    if (streak >= run) return false;
  }
  return true;
}

bool SqliTokenPatterns(const std::vector<Token>& toks) {
  // UNION [ALL|DISTINCT] SELECT — structurally adjacent, not mere
  // co-occurrence.  Comments and an opening paren between the keywords
  // are the canonical obfuscations (`union/**/select`, `union(select`)
  // and stay adjacent; arbitrary prose words do not.
  for (size_t i = 0; i < toks.size(); ++i) {
    if (toks[i].kind != Kind::kKwUnion) continue;
    size_t j = i + 1;
    bool saw_modifier = false;
    while (j < toks.size()) {
      const Token& tj = toks[j];
      if (tj.kind == Kind::kComment ||
          (tj.kind == Kind::kOp && tj.text == "(")) {
        ++j;
        continue;
      }
      if (!saw_modifier && tj.kind == Kind::kWord &&
          (tj.text == "all" || tj.text == "distinct")) {
        saw_modifier = true;
        ++j;
        continue;
      }
      break;
    }
    if (j < toks.size() && toks[j].kind == Kind::kKwSelect) return true;
  }
  // SELECT <list> FROM <ref> — SQL-shaped list/ref (no prose word runs
  // within the clause or the 3 tokens after FROM), bounded gap
  for (size_t i = 0; i < toks.size(); ++i) {
    if (toks[i].kind != Kind::kKwSelect) continue;
    size_t hi = std::min(i + 33, toks.size());
    for (size_t j = i + 1; j < hi; ++j) {
      if (toks[j].kind == Kind::kKwFrom) {
        if (NoWordRun(toks, i + 1, std::min(j + 4, toks.size()))) return true;
        break;
      }
    }
  }
  // stacked query: ';' followed by a statement keyword within 3 tokens
  static const std::unordered_set<std::string> kStmt{
      "select", "insert", "update", "delete", "drop", "create",
      "alter", "exec", "execute", "declare", "truncate"};
  for (size_t i = 0; i < toks.size(); ++i) {
    if (toks[i].kind == Kind::kOp && toks[i].text == ";") {
      for (size_t j = i + 1; j < toks.size() && j <= i + 3; ++j)
        if (IsKw(toks[j]) && kStmt.count(toks[j].kw)) return true;
    }
  }
  // boolean glue + comparison: (OR|AND) value cmp value; or bare truthy
  for (size_t i = 0; i < toks.size(); ++i) {
    if (toks[i].kind != Kind::kKwOr && toks[i].kind != Kind::kKwAnd)
      continue;
    size_t rest = toks.size() - (i + 1);
    if (rest < 2) continue;  // python guard: i + 3 <= len(tokens), so a
                             // bare "AND word" at end-of-input is no hit
    // comparison shape over the first 3 NON-comment tokens: inline
    // comments are token separators (OR/**/1/**/=/**/1 ≡ OR 1=1); the
    // truncation test below still reads positions with comments intact
    size_t v[3];
    int nv = 0;
    for (size_t j = i + 1; j < toks.size() && nv < 3; ++j) {
      if (toks[j].kind == Kind::kComment) continue;
      v[nv++] = j;
    }
    if (nv == 3 && IsValue(toks[v[0]]) && IsCmpText(toks[v[1]].text) &&
        IsValue(toks[v[2]]))
      return true;
    // bare truthy value then TRUNCATION: a line comment anywhere, or
    // an inline comment that ENDS the input.  A mid-expression /**/ is
    // not truncation — benign globstar queries ("src/**/lib or
    // docs/**/api") tokenize as value+comment there (review finding).
    if (IsValue(toks[i + 1]) && toks[i + 2].kind == Kind::kComment &&
        (rest == 2 || toks[i + 2].text != "/*"))
      return true;
  }
  // time/exfil function call: fn '('
  for (size_t i = 0; i + 1 < toks.size(); ++i)
    if (toks[i].kind == Kind::kFn && toks[i + 1].text == "(") return true;
  // leading tautology: literal cmp literal (bare words excluded)
  if (toks.size() >= 3 && IsLiteral(toks[0]) &&
      (toks[1].text == "=" || toks[1].text == "<>" || toks[1].text == "!=") &&
      IsLiteral(toks[2]))
    return true;
  return false;
}

bool DetectSqli(const uint8_t* data, size_t n) {
  if (n > kMaxLen) n = kMaxLen;
  if (n == 0) return false;
  for (int pfx = 0; pfx < 3; ++pfx) {
    uint8_t quote = pfx == 1 ? '\'' : '"';
    std::vector<uint8_t> buf;
    const uint8_t* p = data;
    size_t pn = n;
    if (pfx > 0) {
      if (std::memchr(data, quote, n) == nullptr) {
        // python: payload = data when the quote char is absent — the bare
        // pass already covered it
      } else {
        buf.reserve(n + 1);
        buf.push_back(quote);
        buf.insert(buf.end(), data, data + n);
        p = buf.data();
        pn = buf.size();
      }
    }
    std::vector<Token> toks = TokenizeSql(p, pn);
    if (toks.empty()) continue;
    // comment truncation straight after a quote-break: '--, '#, '/* —
    // like the Python, checked on quote passes even when the prefix was
    // not prepended (a string of another quote type still satisfies it)
    if (pfx > 0 && toks.size() >= 2 &&
        toks.front().kind == Kind::kStr && toks.back().kind == Kind::kComment)
      return true;
    if (SqliTokenPatterns(toks)) return true;
  }
  return false;
}

// ------------------------------------------------------------------- XSS

const std::unordered_set<std::string>& ActiveTags() {
  static const auto* tags = new std::unordered_set<std::string>{
      "script", "iframe", "embed", "object", "applet", "svg", "math",
      "base", "meta", "form", "video", "audio", "img", "input",
      "body", "style", "link", "marquee", "details", "template",
  };
  return *tags;
}

inline bool IsWordByte(uint8_t c) {
  return IsAlpha(c) || IsDigit(c) || c == '_';
}

bool XssActiveTag(const std::string& low) {
  for (size_t i = 0; i < low.size(); ++i) {
    if (low[i] != '<') continue;
    size_t j = i + 1;
    while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
    if (j < low.size() && low[j] == '/') {
      ++j;
      while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
    }
    if (j >= low.size() || !IsAlpha(uint8_t(low[j]))) continue;
    size_t k = j + 1;
    while (k < low.size() &&
           (IsAlpha(uint8_t(low[k])) || IsDigit(uint8_t(low[k])) ||
            low[k] == '-'))
      ++k;
    if (ActiveTags().count(low.substr(j, k - j))) return true;
  }
  return false;
}

// \bon[a-zA-Z]{3,30}\s*=\s*["'`]?[^\s"'`>]
bool XssEventAttr(const std::string& low) {
  for (size_t i = 0; i + 1 < low.size(); ++i) {
    if (low[i] != 'o' || low[i + 1] != 'n') continue;
    if (i > 0 && IsWordByte(uint8_t(low[i - 1]))) continue;  // \b
    size_t j = i + 2, letters = 0;
    while (j < low.size() && IsAlpha(uint8_t(low[j]))) { ++j; ++letters; }
    if (letters < 3 || letters > 30) continue;
    while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
    if (j >= low.size() || low[j] != '=') continue;
    ++j;
    while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
    if (j < low.size() &&
        (low[j] == '"' || low[j] == '\'' || low[j] == '`'))
      ++j;
    if (j >= low.size()) continue;
    uint8_t c = uint8_t(low[j]);
    if (!IsSpace(c) && c != '"' && c != '\'' && c != '`' && c != '>')
      return true;
  }
  return false;
}

bool XssJsUri(const std::string& low) {
  for (const char* kw : {"javascript", "vbscript"}) {
    size_t at = 0;
    size_t kn = std::strlen(kw);
    while ((at = low.find(kw, at)) != std::string::npos) {
      size_t j = at + kn;
      while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
      if (j < low.size() && low[j] == ':') return true;
      ++at;
    }
  }
  return false;
}

// data\s*:[^,]{0,60};\s*base64 — note [^,] also matches ';', so (with
// backtracking) ANY ';' within the first 61 non-comma chars can be the
// literal one; try each.
bool XssDataUri(const std::string& low) {
  size_t at = 0;
  while ((at = low.find("data", at)) != std::string::npos) {
    size_t j = at + 4;
    while (j < low.size() && IsSpace(uint8_t(low[j]))) ++j;
    if (j < low.size() && low[j] == ':') {
      ++j;
      for (size_t scanned = 0; j < low.size() && scanned <= 60;
           ++j, ++scanned) {
        if (low[j] == ',') break;
        if (low[j] == ';') {
          size_t k = j + 1;
          while (k < low.size() && IsSpace(uint8_t(low[k]))) ++k;
          if (low.compare(k, 6, "base64") == 0) return true;
        }
      }
    }
    ++at;
  }
  return false;
}

bool DetectXss(const uint8_t* data, size_t n) {
  if (n > kMaxLen) n = kMaxLen;
  if (n == 0) return false;
  std::string low = Lower(data, n);
  if (XssActiveTag(low)) return true;
  if (XssEventAttr(low)) return true;
  if (XssJsUri(low)) return true;
  if (XssDataUri(low)) return true;
  if (low.find("&#") != std::string::npos &&
      low.find("script") != std::string::npos)
    return true;
  return false;
}

}  // namespace

extern "C" {

int ipt_detect_sqli(const uint8_t* data, size_t len) {
  return DetectSqli(data, len) ? 1 : 0;
}

int ipt_detect_xss(const uint8_t* data, size_t len) {
  return DetectXss(data, len) ? 1 : 0;
}

}  // extern "C"
