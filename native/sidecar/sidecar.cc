// ingress_plus_tpu native sidecar — the nginx-side native boundary of the
// TPU detection path (SURVEY.md §3.3 TPU variant; §2.2 "C++ shim module or
// location-level routing to sidecar").
//
// Role: many downstream connections (nginx shim workers / loadgen) fan in
// over a unix socket; the sidecar muxes their request/chunk frames onto ONE
// upstream connection to the Python serve loop (whose Batcher forms device
// batches), fans verdicts back, and — critically — OWNS the fail-open SLO:
//
//   * per-request deadline (default 50ms): expired requests get a
//     synthesized pass+fail_open verdict; a late upstream verdict is
//     dropped and counted.  Traffic is never blocked on the WAF being slow
//     (the reference's `wallarm-fallback` contract, SURVEY.md §5).
//   * upstream down / reconnecting: requests fail open immediately; the
//     sidecar reconnects with backoff (TPU-restart story: buffer nothing,
//     fail open until the serve loop is back).
//   * upstream backpressure: if the upstream outbuf exceeds its cap the
//     sidecar sheds load by failing new requests open (overload).
//
// Single-threaded epoll event loop — the nginx-worker concurrency model the
// reference's data plane uses; run N processes for N cores.
//
// Counters are served as one-shot JSON on --status-port (the
// `/wallarm-status` analog scraped by collectd in the reference).

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <netinet/in.h>
#include <time.h>
#include <unistd.h>

#include <memory>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "protocol.hpp"

namespace {

uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

struct Options {
  std::string listen_path;
  std::string upstream_path;
  double deadline_ms = 50.0;
  int status_port = 0;
  size_t max_upstream_buf = 4u << 20;   // shed load past this backlog
  size_t max_down_buf = 8u << 20;       // slow downstream reader → close
  int reconnect_ms = 100;
};

struct Counters {
  uint64_t requests_in = 0;
  uint64_t chunks_in = 0;
  uint64_t forwarded = 0;
  uint64_t responses = 0;
  uint64_t fail_open_deadline = 0;
  uint64_t fail_open_upstream = 0;
  uint64_t fail_open_overload = 0;
  uint64_t late_responses = 0;
  uint64_t down_conns_total = 0;
  uint64_t down_conns_active = 0;
  uint64_t bad_frames = 0;
  uint64_t upstream_reconnects = 0;
};

// The downstream direction carries TWO frame types (requests + body
// chunks); min payload lengths are enforced by the framing layer.
inline ipt::MultiFrameReader MakeDownReader() {
  return ipt::MultiFrameReader({
      {ipt::kReqMagic, 0, ipt::kMinRequestPayload},
      {ipt::kChunkMagic, 1, ipt::kMinChunkPayload},
  });
}

struct DownConn {
  int fd = -1;
  uint64_t id = 0;  // monotonic; pending entries reference conns by id so a
                    // reused fd can never receive another conn's verdict
  ipt::MultiFrameReader reader = MakeDownReader();
  std::string outbuf;
  size_t out_off = 0;
  bool want_out = false;
  // orig req_ids of this conn's open body streams, so a dying conn (or an
  // expired stream) can be aborted upstream — otherwise the serve loop's
  // per-connection StreamState leaks on the long-lived mux connection
  // until its per-conn cap trips and streaming fails open permanently
  std::unordered_set<uint64_t> open_streams;
};

struct Pending {
  uint64_t conn_id = 0;
  uint64_t orig_id = 0;    // downstream's req_id, restored on the way back
  uint64_t deadline_ns = 0;
};

class Sidecar {
 public:
  explicit Sidecar(const Options& opt) : opt_(opt) {}

  int Run() {
    ep_ = epoll_create1(0);
    if (ep_ < 0) { perror("epoll_create1"); return 4; }
    if (!OpenListener()) return 3;
    if (opt_.status_port && !OpenStatusListener()) return 3;
    ConnectUpstream();  // failure tolerated: requests fail open meanwhile

    epoll_event events[128];
    while (true) {
      int timeout = NextTimeoutMs();
      int nev = epoll_wait(ep_, events, 128, timeout);
      if (nev < 0) {
        if (errno == EINTR) continue;
        perror("epoll_wait");
        return 4;
      }
      for (int i = 0; i < nev; ++i) Dispatch(events[i]);
      uint64_t now = NowNs();
      ExpireDeadlines(now);
      ExpireStatusConns(now);
      if (up_fd_ < 0 && now >= up_retry_at_ns_) ConnectUpstream();
      else if (up_connecting_ && now >= up_connect_deadline_ns_)
        DropUpstream();  // connect() never completed
      FlushUpstream();
      // (no per-conn flush sweep: every downstream write path flushes
      // inline, and partial writes arm EPOLLOUT which re-enters FlushDown)
      CloseDoomed();
    }
  }

 private:
  // ---------------------------------------------------------- setup

  static void SetNonblock(int fd) { fcntl(fd, F_SETFL, O_NONBLOCK); }

  bool OpenListener() {
    listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, opt_.listen_path.c_str(),
            sizeof(addr.sun_path) - 1);
    unlink(opt_.listen_path.c_str());
    if (bind(listen_fd_, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("bind(listen)");
      return false;
    }
    if (listen(listen_fd_, 512) != 0) { perror("listen"); return false; }
    SetNonblock(listen_fd_);
    Register(listen_fd_, EPOLLIN, kTagListener, 0);
    return true;
  }

  bool OpenStatusListener() {
    status_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(status_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(uint16_t(opt_.status_port));
    if (bind(status_fd_, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("bind(status)");
      return false;
    }
    if (listen(status_fd_, 16) != 0) { perror("listen(status)"); return false; }
    SetNonblock(status_fd_);
    Register(status_fd_, EPOLLIN, kTagStatus, 0);
    return true;
  }

  bool UpReady() const { return up_fd_ >= 0 && !up_connecting_; }

  void ConnectUpstream() {
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    SetNonblock(fd);  // BEFORE connect: a blocking connect (full listen
                      // backlog on a wedged serve loop) would freeze the
                      // event loop and turn fail-open into a hang
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, opt_.upstream_path.c_str(),
            sizeof(addr.sun_path) - 1);
    int rc = connect(fd, (sockaddr*)&addr, sizeof addr);
    if (rc != 0 && errno != EINPROGRESS && errno != EAGAIN) {
      close(fd);
      up_retry_at_ns_ = NowNs() + uint64_t(opt_.reconnect_ms) * 1000000ull;
      return;
    }
    up_fd_ = fd;
    up_connecting_ = (rc != 0);
    up_connect_deadline_ns_ = NowNs() + 1000000000ull;  // 1s to complete
    up_reader_ = ipt::FrameReader();
    up_outbuf_.clear();
    up_out_off_ = 0;
    up_want_out_ = false;
    Register(fd, up_connecting_ ? (EPOLLIN | EPOLLOUT) : EPOLLIN,
             kTagUpstream, 0);
    if (!up_connecting_) ++counters_.upstream_reconnects;
  }

  void DropUpstream() {
    if (up_fd_ >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, up_fd_, nullptr);
      close(up_fd_);
      up_fd_ = -1;
    }
    up_connecting_ = false;
    up_outbuf_.clear();
    up_out_off_ = 0;
    // everything in flight on that connection is gone — fail it all open
    for (auto& [up_id, p] : pending_) {
      ++counters_.fail_open_upstream;
      SendFailOpen(p);
    }
    pending_.clear();
    streams_.clear();
    for (auto& [id, c] : conns_) c->open_streams.clear();
    up_retry_at_ns_ = NowNs() + uint64_t(opt_.reconnect_ms) * 1000000ull;
  }

  // ---------------------------------------------------------- epoll plumbing

  // epoll_data.u64 layout: high 3 bits = tag, low 61 bits = payload.
  // Downstream conns (tag 0) carry their 64-bit monotonic conn id (fits:
  // 2^61 conns is unreachable), NOT the fd — a stale queued event for a
  // closed fd that was reused within the same epoll_wait batch must not
  // resolve to the new connection.
  static constexpr int kTagShift = 61;
  static constexpr uint64_t kPayloadMask = (1ull << kTagShift) - 1;
  static constexpr uint32_t kTagListener = 1;
  static constexpr uint32_t kTagUpstream = 2;
  static constexpr uint32_t kTagStatus = 3;
  static constexpr uint32_t kTagStatusConn = 4;

  void Register(int fd, uint32_t ev_mask, uint32_t tag, uint64_t payload) {
    epoll_event ev{};
    ev.events = ev_mask;
    ev.data.u64 = (uint64_t(tag) << kTagShift) | (payload & kPayloadMask);
    epoll_ctl(ep_, EPOLL_CTL_ADD, fd, &ev);
  }

  void Modify(int fd, uint32_t ev_mask, uint32_t tag, uint64_t payload) {
    epoll_event ev{};
    ev.events = ev_mask;
    ev.data.u64 = (uint64_t(tag) << kTagShift) | (payload & kPayloadMask);
    epoll_ctl(ep_, EPOLL_CTL_MOD, fd, &ev);
  }

  void Dispatch(const epoll_event& ev) {
    uint32_t tag = uint32_t(ev.data.u64 >> kTagShift);
    uint64_t payload = ev.data.u64 & kPayloadMask;
    switch (tag) {
      case kTagListener: AcceptDown(); break;
      case kTagUpstream: HandleUpstream(ev.events); break;
      case kTagStatus: AcceptStatus(); break;
      case kTagStatusConn: HandleStatusConn(int(payload)); break;
      default: HandleDown(payload, ev.events); break;  // tag 0: conn id
    }
  }

  int NextTimeoutMs() {
    uint64_t now = NowNs();
    uint64_t next = UINT64_MAX;
    while (!deadlines_.empty()) {
      auto [dl, up_id] = deadlines_.top();
      auto it = pending_.find(up_id);
      if (it == pending_.end() || it->second.deadline_ns != dl) {
        deadlines_.pop();  // stale (answered, or deadline refreshed)
        continue;
      }
      next = dl;
      break;
    }
    if (up_fd_ < 0 && up_retry_at_ns_ < next) next = up_retry_at_ns_;
    if (next == UINT64_MAX) return 1000;
    if (next <= now) return 0;
    uint64_t ms = (next - now) / 1000000ull;
    return int(ms > 1000 ? 1000 : ms) + 1;
  }

  // ---------------------------------------------------------- downstream

  void AcceptDown() {
    while (true) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      SetNonblock(fd);
      // a doomed conn's entry may still occupy this (reused) fd key until
      // the end-of-iteration CloseDoomed sweep — clear it now
      auto stale = conns_.find(fd);
      if (stale != conns_.end()) conns_.erase(stale);
      auto c = std::make_unique<DownConn>();
      c->fd = fd;
      c->id = ++next_conn_id_;
      Register(fd, EPOLLIN, 0, c->id);
      ++counters_.down_conns_total;
      ++counters_.down_conns_active;
      conns_by_id_[c->id] = c.get();
      conns_.emplace(fd, std::move(c));
    }
  }

  void HandleDown(uint64_t conn_id, uint32_t events) {
    auto it = conns_by_id_.find(conn_id);
    if (it == conns_by_id_.end() || it->second->fd < 0) return;
    DownConn* c = it->second;
    if (events & (EPOLLHUP | EPOLLERR)) { Doom(c); return; }
    if (events & EPOLLIN) {
      uint8_t buf[1 << 16];
      ssize_t n;
      while ((n = read(c->fd, buf, sizeof buf)) > 0) {
        try {
          c->reader.Feed(buf, size_t(n),
                         [&](int kind, const uint8_t* p, size_t len) {
            if (kind == 0) OnRequest(c, p, len);
            else OnChunk(c, p, len);
          });
        } catch (const std::exception&) {
          ++counters_.bad_frames;
          Doom(c);
          return;
        }
      }
      if (n == 0) { Doom(c); return; }
      if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) { Doom(c); return; }
    }
    FlushDown(c);
  }

  void OnRequest(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.requests_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    uint8_t mode = payload[12];  // after req_id u64 + tenant u32
    if (!UpReady()) {
      ++counters_.fail_open_upstream;
      SendFailOpenTo(c, orig_id);
      return;
    }
    if (up_outbuf_.size() - up_out_off_ > opt_.max_upstream_buf) {
      ++counters_.fail_open_overload;
      SendFailOpenTo(c, orig_id);
      return;
    }
    uint64_t up_id = ++next_up_id_;
    uint64_t dl = NowNs() + uint64_t(opt_.deadline_ms * 1e6);
    pending_[up_id] = Pending{c->id, orig_id, dl};
    deadlines_.emplace(dl, up_id);
    if (mode & ipt::kModeStream) {
      streams_[StreamKey(c->id, orig_id)] = up_id;
      c->open_streams.insert(orig_id);
    }
    AppendUpstream(ipt::kReqMagic, payload, len, up_id);
  }

  void OnChunk(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.chunks_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    auto it = streams_.find(StreamKey(c->id, orig_id));
    if (it == streams_.end()) return;  // stream already failed open/expired
    uint64_t up_id = it->second;
    bool last = payload[8] & ipt::kChunkLast;
    if (up_outbuf_.size() - up_out_off_ > opt_.max_upstream_buf) {
      // applies to last chunks too — the shed path's synthetic abort is
      // 17 bytes where the real chunk could be megabytes
      // backlog cap applies to chunk flow too: a single fast uploader
      // against a stalled upstream must not grow the buffer unboundedly.
      // Shed the whole stream: fail it open now, abort it upstream.
      streams_.erase(it);
      c->open_streams.erase(orig_id);
      pending_.erase(up_id);
      ++counters_.fail_open_overload;
      SendFailOpenTo(c, orig_id);
      AbortStreamUpstream(up_id);
      return;
    }
    if (last) {
      streams_.erase(it);
      c->open_streams.erase(orig_id);
    }
    auto p = pending_.find(up_id);
    if (p != pending_.end()) {
      // a stream is alive while chunks flow: refresh its deadline so a
      // long upload isn't failed open mid-body (the SLO covers verdict
      // latency after body end, matching the reference's incremental parse)
      p->second.deadline_ns = NowNs() + uint64_t(opt_.deadline_ms * 1e6);
      deadlines_.emplace(p->second.deadline_ns, up_id);
    }
    AppendUpstream(ipt::kChunkMagic, payload, len, up_id);
  }

  // Synthesize an empty last-chunk so the serve loop finalizes and frees
  // the stream's state (its verdict, if any, is dropped as late).
  void AbortStreamUpstream(uint64_t up_id) {
    if (!UpReady()) return;
    std::string payload;
    ipt::detail::put<uint64_t>(&payload, up_id);
    payload.push_back(char(ipt::kChunkLast));
    AppendUpstream(ipt::kChunkMagic,
                   reinterpret_cast<const uint8_t*>(payload.data()),
                   payload.size(), up_id);
  }

  void FlushDown(DownConn* c) {
    if (c->fd < 0) return;
    while (c->out_off < c->outbuf.size()) {
      ssize_t n = write(c->fd, c->outbuf.data() + c->out_off,
                        c->outbuf.size() - c->out_off);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        Doom(c);
        return;
      }
      c->out_off += size_t(n);
    }
    if (c->out_off == c->outbuf.size()) {
      c->outbuf.clear();
      c->out_off = 0;
    } else if (c->outbuf.size() - c->out_off > opt_.max_down_buf) {
      Doom(c);  // reader stopped draining verdicts
      return;
    }
    bool want = !c->outbuf.empty();
    if (want != c->want_out) {
      c->want_out = want;
      Modify(c->fd, want ? (EPOLLIN | EPOLLOUT) : EPOLLIN, 0, c->id);
    }
  }

  void Doom(DownConn* c) {
    if (c->fd >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, c->fd, nullptr);
      close(c->fd);
      doomed_.push_back(c->fd);
      c->fd = -1;
      --counters_.down_conns_active;
      conns_by_id_.erase(c->id);
      // abort any body streams the conn left open, freeing the serve
      // loop's per-stream state (verdicts for them will drop as late)
      for (uint64_t orig_id : c->open_streams) {
        auto it = streams_.find(StreamKey(c->id, orig_id));
        if (it == streams_.end()) continue;
        AbortStreamUpstream(it->second);
        streams_.erase(it);
      }
      c->open_streams.clear();
    }
  }

  void CloseDoomed() {
    for (int fd : doomed_) {
      auto it = conns_.find(fd);
      // fd<0 check: a new conn may have reused the fd key this iteration
      if (it != conns_.end() && it->second->fd < 0) conns_.erase(it);
    }
    // pending entries for closed conns stay until answer/deadline; the
    // response path drops verdicts whose conn id no longer resolves
    doomed_.clear();
  }

  // ---------------------------------------------------------- upstream

  static uint64_t StreamKey(uint64_t conn_id, uint64_t orig_id) {
    // conn ids are small monotonic; mix so (conn, req) collisions need
    // matching low bits on both — fine for a lookup key (not security)
    return conn_id * 0x9e3779b97f4a7c15ull ^ orig_id;
  }

  void AppendUpstream(const char magic[4], const uint8_t* payload, size_t len,
                      uint64_t up_id) {
    up_outbuf_.append(magic, 4);
    ipt::detail::put<uint32_t>(&up_outbuf_, uint32_t(len));
    size_t at = up_outbuf_.size();
    up_outbuf_.append(reinterpret_cast<const char*>(payload), len);
    std::memcpy(&up_outbuf_[at], &up_id, 8);  // re-id for global uniqueness
    ++counters_.forwarded;
  }

  void FlushUpstream() {
    if (up_fd_ < 0) return;
    while (up_out_off_ < up_outbuf_.size()) {
      ssize_t n = write(up_fd_, up_outbuf_.data() + up_out_off_,
                        up_outbuf_.size() - up_out_off_);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        DropUpstream();
        return;
      }
      up_out_off_ += size_t(n);
    }
    if (up_out_off_ == up_outbuf_.size()) {
      up_outbuf_.clear();
      up_out_off_ = 0;
    }
    bool want = !up_outbuf_.empty();
    if (want != up_want_out_) {
      up_want_out_ = want;
      Modify(up_fd_, want ? (EPOLLIN | EPOLLOUT) : EPOLLIN, kTagUpstream, 0);
    }
  }

  void HandleUpstream(uint32_t events) {
    if (up_connecting_) {
      if (events & (EPOLLHUP | EPOLLERR)) { DropUpstream(); return; }
      if (events & EPOLLOUT) {  // nonblocking connect completed — how?
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(up_fd_, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) { DropUpstream(); return; }
        up_connecting_ = false;
        up_want_out_ = false;
        Modify(up_fd_, EPOLLIN, kTagUpstream, 0);
        ++counters_.upstream_reconnects;
      }
      return;
    }
    if (events & (EPOLLHUP | EPOLLERR)) { DropUpstream(); return; }
    if (events & EPOLLIN) {
      uint8_t buf[1 << 16];
      ssize_t n;
      while (up_fd_ >= 0 && (n = read(up_fd_, buf, sizeof buf)) > 0) {
        try {
          up_reader_.Feed(buf, size_t(n), [&](const uint8_t* p, size_t len) {
            OnVerdict(p, len);
          });
        } catch (const std::exception& e) {
          fprintf(stderr, "upstream protocol error: %s\n", e.what());
          DropUpstream();
          return;
        }
      }
      if (up_fd_ >= 0 && n == 0) { DropUpstream(); return; }
      if (up_fd_ >= 0 && n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        DropUpstream();  // hard error (e.g. ECONNRESET without EPOLLERR):
        return;          // leaving the fd registered would busy-loop
      }
    }
    FlushUpstream();
  }

  void OnVerdict(const uint8_t* payload, size_t len) {
    uint64_t up_id = ipt::detail::get<uint64_t>(payload);
    auto it = pending_.find(up_id);
    if (it == pending_.end()) {
      ++counters_.late_responses;  // answered after deadline fail-open
      return;
    }
    Pending p = it->second;
    pending_.erase(it);
    ++counters_.responses;
    auto cit = conns_by_id_.find(p.conn_id);
    if (cit == conns_by_id_.end() || cit->second->fd < 0) return;  // gone
    DownConn* c = cit->second;
    // restore the downstream req_id in place, reuse the rest verbatim
    std::string frame;
    frame.reserve(8 + len);
    frame.append(ipt::kRespMagic, 4);
    ipt::detail::put<uint32_t>(&frame, uint32_t(len));
    size_t at = frame.size();
    frame.append(reinterpret_cast<const char*>(payload), len);
    std::memcpy(&frame[at], &p.orig_id, 8);
    c->outbuf += frame;
    FlushDown(c);
  }

  // ---------------------------------------------------------- fail-open

  void SendFailOpen(const Pending& p) {
    auto cit = conns_by_id_.find(p.conn_id);
    if (cit == conns_by_id_.end() || cit->second->fd < 0) return;
    SendFailOpenTo(cit->second, p.orig_id);
  }

  void SendFailOpenTo(DownConn* c, uint64_t orig_id) {
    ipt::Response r;
    r.req_id = orig_id;
    r.flags = ipt::kFailOpen;  // pass + flag, never block on WAF trouble
    c->outbuf += ipt::EncodeResponse(r);
    FlushDown(c);
  }

  void ExpireDeadlines(uint64_t now) {
    while (!deadlines_.empty()) {
      auto [dl, up_id] = deadlines_.top();
      if (dl > now) break;
      deadlines_.pop();
      auto it = pending_.find(up_id);
      if (it == pending_.end() || it->second.deadline_ns != dl) continue;
      Pending p = it->second;
      pending_.erase(it);
      auto sit = streams_.find(StreamKey(p.conn_id, p.orig_id));
      if (sit != streams_.end()) {  // stream stalled mid-body: abort it
        AbortStreamUpstream(sit->second);
        streams_.erase(sit);
        auto cit = conns_by_id_.find(p.conn_id);
        if (cit != conns_by_id_.end())
          cit->second->open_streams.erase(p.orig_id);
      }
      ++counters_.fail_open_deadline;
      SendFailOpen(p);
    }
  }

  // ---------------------------------------------------------- status

  void AcceptStatus() {
    while (true) {
      int fd = accept(status_fd_, nullptr, nullptr);
      if (fd < 0) return;
      if (status_conns_.size() >= 32) { close(fd); continue; }  // bounded
      SetNonblock(fd);
      // answer after the client's (tiny) request arrives: writing before
      // reading risks an RST discarding the response on close
      Register(fd, EPOLLIN, kTagStatusConn, uint64_t(fd));
      status_conns_[fd] = NowNs() + 5000000000ull;  // idle cutoff: 5s
    }
  }

  void CloseStatusConn(int fd) {
    epoll_ctl(ep_, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    status_conns_.erase(fd);
  }

  void ExpireStatusConns(uint64_t now) {
    for (auto it = status_conns_.begin(); it != status_conns_.end();) {
      int fd = it->first;
      uint64_t dl = it->second;
      ++it;  // CloseStatusConn erases; advance first
      if (now >= dl) CloseStatusConn(fd);
    }
  }

  void HandleStatusConn(int fd) {
    if (!status_conns_.count(fd)) return;  // stale event after close
    uint8_t drain[4096];
    ssize_t n = read(fd, drain, sizeof drain);
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    char body[1024];
    int blen = snprintf(
        body, sizeof body,
        "{\"requests_in\": %llu, \"chunks_in\": %llu, "
        "\"forwarded\": %llu, \"responses\": %llu, "
        "\"fail_open_deadline\": %llu, \"fail_open_upstream\": %llu, "
        "\"fail_open_overload\": %llu, \"late_responses\": %llu, "
        "\"down_conns_total\": %llu, \"down_conns_active\": %llu, "
        "\"bad_frames\": %llu, \"upstream_reconnects\": %llu, "
        "\"upstream_connected\": %s, \"pending\": %zu}\n",
        (unsigned long long)counters_.requests_in,
        (unsigned long long)counters_.chunks_in,
        (unsigned long long)counters_.forwarded,
        (unsigned long long)counters_.responses,
        (unsigned long long)counters_.fail_open_deadline,
        (unsigned long long)counters_.fail_open_upstream,
        (unsigned long long)counters_.fail_open_overload,
        (unsigned long long)counters_.late_responses,
        (unsigned long long)counters_.down_conns_total,
        (unsigned long long)counters_.down_conns_active,
        (unsigned long long)counters_.bad_frames,
        (unsigned long long)counters_.upstream_reconnects,
        up_fd_ >= 0 ? "true" : "false", pending_.size());
    char resp[1400];
    int rlen = snprintf(resp, sizeof resp,
                        "HTTP/1.0 200 OK\r\n"
                        "Content-Type: application/json\r\n"
                        "Content-Length: %d\r\n\r\n%s",
                        blen, body);
    // one-shot local scrape: a single write covers it (fits the sndbuf)
    ssize_t w = write(fd, resp, size_t(rlen));
    (void)w;
    CloseStatusConn(fd);
  }

  Options opt_;
  Counters counters_;
  int ep_ = -1;
  int listen_fd_ = -1;
  int status_fd_ = -1;

  // conns_ (fd-keyed) owns; conns_by_id_ routes both epoll events and
  // verdicts by the monotonic conn id, so neither a reused fd nor a stale
  // queued epoll event can ever reach the wrong connection
  std::unordered_map<int, std::unique_ptr<DownConn>> conns_;
  std::unordered_map<uint64_t, DownConn*> conns_by_id_;
  std::vector<int> doomed_;
  uint64_t next_conn_id_ = 0;
  std::unordered_map<int, uint64_t> status_conns_;  // fd → idle deadline

  int up_fd_ = -1;
  bool up_connecting_ = false;
  uint64_t up_connect_deadline_ns_ = 0;
  ipt::FrameReader up_reader_;
  std::string up_outbuf_;
  size_t up_out_off_ = 0;
  bool up_want_out_ = false;
  uint64_t up_retry_at_ns_ = 0;

  uint64_t next_up_id_ = 0;
  std::unordered_map<uint64_t, Pending> pending_;
  std::unordered_map<uint64_t, uint64_t> streams_;  // (conn,orig) → up_id
  // min-heap of (deadline, up_id); stale entries dropped lazily
  using DlEntry = std::pair<uint64_t, uint64_t>;
  std::priority_queue<DlEntry, std::vector<DlEntry>, std::greater<DlEntry>>
      deadlines_;
};

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "missing value for %s\n", a.c_str());
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--listen") opt.listen_path = next();
    else if (a == "--upstream") opt.upstream_path = next();
    else if (a == "--deadline-ms") opt.deadline_ms = atof(next());
    else if (a == "--status-port") opt.status_port = atoi(next());
    else if (a == "--max-upstream-buf") opt.max_upstream_buf = size_t(atol(next()));
    else if (a == "--max-down-buf") opt.max_down_buf = size_t(atol(next()));
    else if (a == "--reconnect-ms") opt.reconnect_ms = atoi(next());
    else { fprintf(stderr, "unknown arg %s\n", a.c_str()); return 2; }
  }
  if (opt.listen_path.empty() || opt.upstream_path.empty()) {
    fprintf(stderr,
            "usage: sidecar --listen <uds> --upstream <uds> "
            "[--deadline-ms N] [--status-port P] [--max-upstream-buf B] "
            "[--max-down-buf B] [--reconnect-ms N]\n");
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  return Sidecar(opt).Run();
}
