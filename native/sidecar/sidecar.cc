// ingress_plus_tpu native sidecar — the nginx-side native boundary of the
// TPU detection path (SURVEY.md §3.3 TPU variant; §2.2 "C++ shim module or
// location-level routing to sidecar").
//
// Role: many downstream connections (nginx shim workers / loadgen) fan in
// over a unix socket; the sidecar balances their request/chunk frames
// across one or more upstream serve loops (one per chip), fans verdicts
// back, and — critically — OWNS the fail-open SLO:
//
//   * per-request deadline (default 50ms): expired requests get a
//     synthesized pass+fail_open verdict; a late upstream verdict is
//     dropped and counted.  Traffic is never blocked on the WAF being slow
//     (the reference's `wallarm-fallback` contract, SURVEY.md §5).
//   * upstream down / reconnecting: that upstream's in-flight requests
//     fail open and it is taken out of rotation while the sidecar
//     reconnects with backoff (TPU-restart story: buffer nothing, fail
//     open until a serve loop is back).
//   * upstream backpressure: if an upstream's outbuf exceeds its cap the
//     request is routed elsewhere or shed fail-open (overload).
//
// Balancing (the reference's balancer.lua analog at the native boundary —
// round_robin/ewma/chash strategies, SURVEY.md §2.3), selected with
// --balance:
//   rr    — rotate over ready upstreams (default)
//   ewma  — lowest latency EWMA scaled by in-flight (peak-EWMA style)
//   chash — consistent hash on the tenant id (keeps a tenant's rule
//           masks/XLA shapes hot on one chip), 64 vnodes per upstream
// Body streams are always sticky to the upstream that saw the first frame
// (the sticky-session analog: carried NFA state lives there).
//
// Single-threaded epoll event loop — the nginx-worker concurrency model the
// reference's data plane uses; run N processes for N cores.
//
// Counters are served as one-shot HTTP/1.0 JSON on --status-port (the
// `/wallarm-status` analog scraped by collectd in the reference).

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <netinet/in.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <memory>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "protocol.hpp"

namespace {

uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

enum class Balance { kRoundRobin, kEwma, kChash };

struct Options {
  std::string listen_path;
  std::vector<std::string> upstream_paths;
  Balance balance = Balance::kRoundRobin;
  double deadline_ms = 50.0;
  int status_port = 0;
  size_t max_upstream_buf = 4u << 20;   // per-upstream backlog cap
  size_t max_down_buf = 8u << 20;       // slow downstream reader → close
  int reconnect_ms = 100;
};

struct Counters {
  uint64_t requests_in = 0;
  uint64_t chunks_in = 0;
  uint64_t ws_frames_in = 0;
  uint64_t forwarded = 0;
  uint64_t responses = 0;
  uint64_t fail_open_deadline = 0;
  uint64_t fail_open_upstream = 0;
  uint64_t fail_open_overload = 0;
  uint64_t late_responses = 0;
  uint64_t down_conns_total = 0;
  uint64_t down_conns_active = 0;
  uint64_t bad_frames = 0;
  uint64_t upstream_reconnects = 0;
};

// The downstream direction carries TWO frame types (requests + body
// chunks); min payload lengths are enforced by the framing layer.
inline ipt::MultiFrameReader MakeDownReader() {
  return ipt::MultiFrameReader({
      {ipt::kReqMagic, 0, ipt::kMinRequestPayload},
      {ipt::kChunkMagic, 1, ipt::kMinChunkPayload},
      {ipt::kRespScanMagic, 2, ipt::kMinRespScanPayload},
      {ipt::kWsMagic, 3, ipt::kMinWsPayload},
  });
}

struct DownConn {
  int fd = -1;
  uint64_t id = 0;  // monotonic; all routing references conns by id so a
                    // reused fd / stale epoll event can never cross wires
  ipt::MultiFrameReader reader = MakeDownReader();
  std::string outbuf;
  size_t out_off = 0;
  bool want_out = false;
  // orig req_ids of this conn's open body streams, so a dying conn (or an
  // expired stream) can be aborted upstream — otherwise the serve loop's
  // per-connection StreamState leaks on the long-lived mux connection
  // until its per-conn cap trips and streaming fails open permanently
  std::unordered_set<uint64_t> open_streams;
  // orig stream ids of this conn's open WebSocket captures (same leak
  // argument: the serve side holds parser + sticky-verdict state per
  // upgraded connection until an end frame arrives)
  std::unordered_set<uint64_t> open_ws;
};

struct Upstream {
  std::string path;
  int fd = -1;
  bool connecting = false;
  uint64_t connect_deadline_ns = 0;
  uint64_t retry_at_ns = 0;
  ipt::FrameReader reader;
  std::string outbuf;
  size_t out_off = 0;
  bool want_out = false;
  double ewma_ms = 1.0;   // optimistic prior so fresh upstreams get traffic
  uint64_t inflight = 0;
  uint64_t forwarded = 0;

  bool Ready() const { return fd >= 0 && !connecting; }
  size_t Backlog() const { return outbuf.size() - out_off; }
};

struct Pending {
  uint64_t conn_id = 0;
  uint64_t orig_id = 0;    // downstream's req_id, restored on the way back
  uint64_t deadline_ns = 0;
  uint64_t sent_ns = 0;
  int up_idx = 0;
};

class Sidecar {
 public:
  explicit Sidecar(const Options& opt) : opt_(opt) {
    for (const std::string& p : opt_.upstream_paths) {
      ups_.emplace_back();
      ups_.back().path = p;
    }
    // consistent-hash ring: 64 vnodes per upstream (FNV-mixed)
    for (size_t u = 0; u < ups_.size(); ++u)
      for (uint64_t v = 0; v < 64; ++v) {
        uint64_t h = 1469598103934665603ull;
        for (char c : ups_[u].path) h = (h ^ uint8_t(c)) * 1099511628211ull;
        h = (h ^ v) * 1099511628211ull;
        ring_[h] = int(u);
      }
  }

  int Run() {
    ep_ = epoll_create1(0);
    if (ep_ < 0) { perror("epoll_create1"); return 4; }
    if (!OpenListener()) return 3;
    if (opt_.status_port && !OpenStatusListener()) return 3;
    for (size_t u = 0; u < ups_.size(); ++u) ConnectUpstream(int(u));

    epoll_event events[128];
    while (true) {
      int timeout = NextTimeoutMs();
      int nev = epoll_wait(ep_, events, 128, timeout);
      if (nev < 0) {
        if (errno == EINTR) continue;
        perror("epoll_wait");
        return 4;
      }
      for (int i = 0; i < nev; ++i) Dispatch(events[i]);
      uint64_t now = NowNs();
      ExpireDeadlines(now);
      ExpireStatusConns(now);
      for (size_t u = 0; u < ups_.size(); ++u) {
        Upstream& up = ups_[u];
        if (up.fd < 0 && now >= up.retry_at_ns) ConnectUpstream(int(u));
        else if (up.connecting && now >= up.connect_deadline_ns)
          DropUpstream(int(u));  // connect() never completed
        FlushUpstream(int(u));
      }
      // (no per-conn flush sweep: every downstream write path flushes
      // inline, and partial writes arm EPOLLOUT which re-enters FlushDown)
      CloseDoomed();
    }
  }

 private:
  // ---------------------------------------------------------- setup

  static void SetNonblock(int fd) { fcntl(fd, F_SETFL, O_NONBLOCK); }

  bool OpenListener() {
    listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, opt_.listen_path.c_str(),
            sizeof(addr.sun_path) - 1);
    unlink(opt_.listen_path.c_str());
    if (bind(listen_fd_, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("bind(listen)");
      return false;
    }
    if (listen(listen_fd_, 512) != 0) { perror("listen"); return false; }
    SetNonblock(listen_fd_);
    Register(listen_fd_, EPOLLIN, kTagListener, 0);
    return true;
  }

  bool OpenStatusListener() {
    status_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(status_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(uint16_t(opt_.status_port));
    if (bind(status_fd_, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("bind(status)");
      return false;
    }
    if (listen(status_fd_, 16) != 0) { perror("listen(status)"); return false; }
    SetNonblock(status_fd_);
    Register(status_fd_, EPOLLIN, kTagStatus, 0);
    return true;
  }

  void ConnectUpstream(int u) {
    Upstream& up = ups_[size_t(u)];
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    SetNonblock(fd);  // BEFORE connect: a blocking connect (full listen
                      // backlog on a wedged serve loop) would freeze the
                      // event loop and turn fail-open into a hang
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, up.path.c_str(), sizeof(addr.sun_path) - 1);
    int rc = connect(fd, (sockaddr*)&addr, sizeof addr);
    if (rc != 0 && errno != EINPROGRESS && errno != EAGAIN) {
      close(fd);
      up.retry_at_ns = NowNs() + uint64_t(opt_.reconnect_ms) * 1000000ull;
      return;
    }
    up.fd = fd;
    up.connecting = (rc != 0);
    up.connect_deadline_ns = NowNs() + 1000000000ull;  // 1s to complete
    up.reader = ipt::FrameReader();
    up.outbuf.clear();
    up.out_off = 0;
    up.want_out = false;
    Register(fd, up.connecting ? (EPOLLIN | EPOLLOUT) : EPOLLIN,
             kTagUpstream, uint64_t(u));
    if (!up.connecting) ++counters_.upstream_reconnects;
  }

  void DropUpstream(int u) {
    Upstream& up = ups_[size_t(u)];
    if (up.fd >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, up.fd, nullptr);
      close(up.fd);
      up.fd = -1;
    }
    up.connecting = false;
    up.outbuf.clear();
    up.out_off = 0;
    up.inflight = 0;
    // everything in flight on that connection is gone — fail it all open;
    // other upstreams' requests are untouched
    for (auto it = pending_.begin(); it != pending_.end();) {
      if (it->second.up_idx != u) { ++it; continue; }
      Pending p = it->second;
      it = pending_.erase(it);
      streams_.erase(StreamKey(p.conn_id, p.orig_id));
      auto cit = conns_by_id_.find(p.conn_id);
      if (cit != conns_by_id_.end())
        cit->second->open_streams.erase(p.orig_id);
      ++counters_.fail_open_upstream;
      SendFailOpen(p);
    }
    up.retry_at_ns = NowNs() + uint64_t(opt_.reconnect_ms) * 1000000ull;
  }

  // ---------------------------------------------------------- balancing

  bool AnyReady() const {
    for (const Upstream& up : ups_)
      if (up.Ready()) return true;
    return false;
  }

  // -1 = nothing usable (all down or over backlog cap) → caller fails open
  int PickUpstream(uint32_t tenant) {
    auto usable = [&](int u) {
      const Upstream& up = ups_[size_t(u)];
      return up.Ready() && up.Backlog() <= opt_.max_upstream_buf;
    };
    int n = int(ups_.size());
    switch (opt_.balance) {
      case Balance::kRoundRobin: {
        for (int step = 0; step < n; ++step) {
          int u = int((rr_next_ + uint64_t(step)) % uint64_t(n));
          if (usable(u)) {
            rr_next_ = uint64_t(u) + 1;
            return u;
          }
        }
        return -1;
      }
      case Balance::kEwma: {
        // peak-EWMA: score = latency estimate × (1 + inflight) — the same
        // load-shading the reference's ewma.lua applies
        int best = -1;
        double best_score = 0;
        for (int u = 0; u < n; ++u) {
          if (!usable(u)) continue;
          const Upstream& up = ups_[size_t(u)];
          double score = up.ewma_ms * double(1 + up.inflight);
          if (best < 0 || score < best_score) {
            best = u;
            best_score = score;
          }
        }
        return best;
      }
      case Balance::kChash: {
        uint64_t h = 1469598103934665603ull;
        for (int b = 0; b < 4; ++b)
          h = (h ^ ((tenant >> (8 * b)) & 0xff)) * 1099511628211ull;
        auto it = ring_.lower_bound(h);
        // walk the ring until a usable upstream (consistent failover)
        for (size_t step = 0; step < ring_.size(); ++step, ++it) {
          if (it == ring_.end()) it = ring_.begin();
          if (usable(it->second)) return it->second;
        }
        return -1;
      }
    }
    return -1;
  }

  // ---------------------------------------------------------- epoll plumbing

  // epoll_data.u64 layout: high 3 bits = tag, low 61 bits = payload.
  // Downstream conns (tag 0) carry their 64-bit monotonic conn id (fits:
  // 2^61 conns is unreachable), NOT the fd — a stale queued event for a
  // closed fd that was reused within the same epoll_wait batch must not
  // resolve to the new connection.
  static constexpr int kTagShift = 61;
  static constexpr uint64_t kPayloadMask = (1ull << kTagShift) - 1;
  static constexpr uint32_t kTagListener = 1;
  static constexpr uint32_t kTagUpstream = 2;
  static constexpr uint32_t kTagStatus = 3;
  static constexpr uint32_t kTagStatusConn = 4;

  void Register(int fd, uint32_t ev_mask, uint32_t tag, uint64_t payload) {
    epoll_event ev{};
    ev.events = ev_mask;
    ev.data.u64 = (uint64_t(tag) << kTagShift) | (payload & kPayloadMask);
    epoll_ctl(ep_, EPOLL_CTL_ADD, fd, &ev);
  }

  void Modify(int fd, uint32_t ev_mask, uint32_t tag, uint64_t payload) {
    epoll_event ev{};
    ev.events = ev_mask;
    ev.data.u64 = (uint64_t(tag) << kTagShift) | (payload & kPayloadMask);
    epoll_ctl(ep_, EPOLL_CTL_MOD, fd, &ev);
  }

  void Dispatch(const epoll_event& ev) {
    uint32_t tag = uint32_t(ev.data.u64 >> kTagShift);
    uint64_t payload = ev.data.u64 & kPayloadMask;
    switch (tag) {
      case kTagListener: AcceptDown(); break;
      case kTagUpstream: HandleUpstream(int(payload), ev.events); break;
      case kTagStatus: AcceptStatus(); break;
      case kTagStatusConn: HandleStatusConn(int(payload)); break;
      default: HandleDown(payload, ev.events); break;  // tag 0: conn id
    }
  }

  int NextTimeoutMs() {
    uint64_t now = NowNs();
    uint64_t next = UINT64_MAX;
    while (!deadlines_.empty()) {
      auto [dl, up_id] = deadlines_.top();
      auto it = pending_.find(up_id);
      if (it == pending_.end() || it->second.deadline_ns != dl) {
        deadlines_.pop();  // stale (answered, or deadline refreshed)
        continue;
      }
      next = dl;
      break;
    }
    for (const Upstream& up : ups_) {
      if (up.fd < 0 && up.retry_at_ns < next) next = up.retry_at_ns;
      if (up.connecting && up.connect_deadline_ns < next)
        next = up.connect_deadline_ns;
    }
    if (next == UINT64_MAX) return 1000;
    if (next <= now) return 0;
    uint64_t ms = (next - now) / 1000000ull;
    return int(ms > 1000 ? 1000 : ms) + 1;
  }

  // ---------------------------------------------------------- downstream

  void AcceptDown() {
    while (true) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      SetNonblock(fd);
      // a doomed conn's entry may still occupy this (reused) fd key until
      // the end-of-iteration CloseDoomed sweep — clear it now
      auto stale = conns_.find(fd);
      if (stale != conns_.end()) conns_.erase(stale);
      auto c = std::make_unique<DownConn>();
      c->fd = fd;
      c->id = ++next_conn_id_;
      Register(fd, EPOLLIN, 0, c->id);
      ++counters_.down_conns_total;
      ++counters_.down_conns_active;
      conns_by_id_[c->id] = c.get();
      conns_.emplace(fd, std::move(c));
    }
  }

  void HandleDown(uint64_t conn_id, uint32_t events) {
    auto it = conns_by_id_.find(conn_id);
    if (it == conns_by_id_.end() || it->second->fd < 0) return;
    DownConn* c = it->second;
    if (events & (EPOLLHUP | EPOLLERR)) { Doom(c); return; }
    if (events & EPOLLIN) {
      uint8_t buf[1 << 16];
      ssize_t n;
      while ((n = read(c->fd, buf, sizeof buf)) > 0) {
        try {
          c->reader.Feed(buf, size_t(n),
                         [&](int kind, const uint8_t* p, size_t len) {
            if (kind == 0) OnRequest(c, p, len);
            else if (kind == 2) OnRespScan(c, p, len);
            else if (kind == 3) OnWsFrame(c, p, len);
            else OnChunk(c, p, len);
          });
        } catch (const std::exception&) {
          ++counters_.bad_frames;
          Doom(c);
          return;
        }
      }
      if (n == 0) { Doom(c); return; }
      if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) { Doom(c); return; }
    }
    FlushDown(c);
  }

  void OnRequest(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.requests_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    uint32_t tenant = ipt::detail::get<uint32_t>(payload + 8);
    uint8_t mode = payload[12];  // after req_id u64 + tenant u32
    int u = PickUpstream(tenant);
    if (u < 0) {
      if (AnyReady()) ++counters_.fail_open_overload;
      else ++counters_.fail_open_upstream;
      SendFailOpenTo(c, orig_id);
      return;
    }
    uint64_t now = NowNs();
    uint64_t up_id = ++next_up_id_;
    uint64_t dl = now + uint64_t(opt_.deadline_ms * 1e6);
    pending_[up_id] = Pending{c->id, orig_id, dl, now, u};
    deadlines_.emplace(dl, up_id);
    if (mode & ipt::kModeStream) {
      streams_[StreamKey(c->id, orig_id)] = up_id;
      c->open_streams.insert(orig_id);
    }
    AppendUpstream(u, ipt::kReqMagic, payload, len, up_id);
  }

  // Response-scan frames route exactly like requests (req_id-rewritten,
  // balanced, deadline-tracked; the verdict rides a normal RTPI frame
  // back) — minus stream bookkeeping, which rscan doesn't use.
  void OnRespScan(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.requests_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    uint32_t tenant = ipt::detail::get<uint32_t>(payload + 8);
    int u = PickUpstream(tenant);
    if (u < 0) {
      if (AnyReady()) ++counters_.fail_open_overload;
      else ++counters_.fail_open_upstream;
      SendFailOpenTo(c, orig_id);
      return;
    }
    uint64_t now = NowNs();
    uint64_t up_id = ++next_up_id_;
    uint64_t dl = now + uint64_t(opt_.deadline_ms * 1e6);
    pending_[up_id] = Pending{c->id, orig_id, dl, now, u};
    deadlines_.emplace(dl, up_id);
    AppendUpstream(u, ipt::kRespScanMagic, payload, len, up_id);
  }

  // WebSocket capture frames: routed like requests (pending entry per
  // frame → one RTPI verdict each), but STICKY to one upstream per
  // upgraded connection — the serve loop's RFC 6455 parser and sticky
  // verdict live there, so a frame on another upstream would desync the
  // byte stream.  The stream id is rewritten (like req_id) so captures
  // from different downstream conns can't collide on the shared mux.
  void OnWsFrame(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.ws_frames_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    uint64_t orig_stream = ipt::detail::get<uint64_t>(payload + 8);
    uint32_t tenant = ipt::detail::get<uint32_t>(payload + 16);
    uint8_t flags = payload[21];
    uint64_t key = StreamKey(c->id, orig_stream);
    auto it = ws_streams_.find(key);
    int u;
    uint64_t up_stream;
    if (it == ws_streams_.end()) {
      u = PickUpstream(tenant);
      if (u < 0) {
        if (AnyReady()) ++counters_.fail_open_overload;
        else ++counters_.fail_open_upstream;
        SendFailOpenTo(c, orig_id);
        return;
      }
      up_stream = ++next_up_id_;
      ws_streams_[key] = WsBinding{u, up_stream};
      c->open_ws.insert(orig_stream);
    } else {
      u = it->second.up_idx;
      up_stream = it->second.up_stream_id;
      if (!ups_[size_t(u)].Ready()) {
        // the bound upstream died: its parser state died with it, so
        // later bytes can't be scanned coherently — fail the stream
        // open and drop the binding (a re-established upstream would
        // see a mid-stream byte sequence it can't parse)
        ws_streams_.erase(it);
        c->open_ws.erase(orig_stream);
        ++counters_.fail_open_upstream;
        SendFailOpenTo(c, orig_id);
        return;
      }
    }
    if (ups_[size_t(u)].Backlog() > opt_.max_upstream_buf) {
      // backlog shed (same cap as body chunks): end the capture — a
      // gap in the byte stream would poison the serve-side parser
      // anyway, so tell it to free state and fail this frame open
      ws_streams_.erase(key);
      c->open_ws.erase(orig_stream);
      ++counters_.fail_open_overload;
      SendFailOpenTo(c, orig_id);
      EndWsUpstream(u, up_stream);
      return;
    }
    uint64_t now = NowNs();
    uint64_t up_id = ++next_up_id_;
    uint64_t dl = now + uint64_t(opt_.deadline_ms * 1e6);
    pending_[up_id] = Pending{c->id, orig_id, dl, now, u};
    deadlines_.emplace(dl, up_id);
    AppendUpstream(u, ipt::kWsMagic, payload, len, up_id, &up_stream);
    if (flags & ipt::kWsEnd) {
      ws_streams_.erase(key);
      c->open_ws.erase(orig_stream);
    }
  }

  // Synthesize an end frame so the serve loop frees the upgraded
  // connection's parser/verdict state.  The serve loop answers EVERY
  // WTPI frame, so the synthetic one gets a real pending entry under
  // conn id 0 (no downstream conn ever has id 0): OnVerdict consumes it
  // symmetrically (inflight/ewma) and finds no conn to deliver to —
  // without the entry its guaranteed reply would count late_responses
  // on every disconnect with open captures (round-3 review finding).
  void EndWsUpstream(int u, uint64_t up_stream) {
    if (!ups_[size_t(u)].Ready()) return;
    std::string payload(22, '\0');
    payload[20] = 2;  // mode: any non-zero; state-free end either way
    payload[21] = char(ipt::kWsEnd);
    uint64_t now = NowNs();
    uint64_t up_id = ++next_up_id_;
    uint64_t dl = now + uint64_t(opt_.deadline_ms * 1e6);
    pending_[up_id] = Pending{/*conn_id=*/0, /*orig_id=*/0, dl, now, u};
    deadlines_.emplace(dl, up_id);
    AppendUpstream(u, ipt::kWsMagic,
                   reinterpret_cast<const uint8_t*>(payload.data()),
                   payload.size(), up_id, &up_stream);
  }

  void OnChunk(DownConn* c, const uint8_t* payload, size_t len) {
    ++counters_.chunks_in;
    uint64_t orig_id = ipt::detail::get<uint64_t>(payload);
    auto it = streams_.find(StreamKey(c->id, orig_id));
    if (it == streams_.end()) return;  // stream already failed open/expired
    uint64_t up_id = it->second;
    auto p = pending_.find(up_id);
    if (p == pending_.end()) {  // should not happen; be safe
      streams_.erase(it);
      c->open_streams.erase(orig_id);
      return;
    }
    int u = p->second.up_idx;  // streams are sticky to their upstream
    bool last = payload[8] & ipt::kChunkLast;
    if (ups_[size_t(u)].Backlog() > opt_.max_upstream_buf) {
      // backlog cap applies to chunk flow too (last chunks included: the
      // shed path's synthetic abort is 17 bytes where the real chunk
      // could be megabytes) — a single fast uploader against a stalled
      // upstream must not grow the buffer unboundedly
      streams_.erase(it);
      c->open_streams.erase(orig_id);
      pending_.erase(up_id);
      ++counters_.fail_open_overload;
      SendFailOpenTo(c, orig_id);
      AbortStreamUpstream(u, up_id);
      return;
    }
    if (last) {
      streams_.erase(it);
      c->open_streams.erase(orig_id);
    }
    // a stream is alive while chunks flow: refresh its deadline so a
    // long upload isn't failed open mid-body (the SLO covers verdict
    // latency after body end, matching the reference's incremental parse)
    p->second.deadline_ns = NowNs() + uint64_t(opt_.deadline_ms * 1e6);
    deadlines_.emplace(p->second.deadline_ns, up_id);
    AppendUpstream(u, ipt::kChunkMagic, payload, len, up_id);
  }

  // Synthesize an empty last-chunk so the serve loop finalizes and frees
  // the stream's state (its verdict, if any, is dropped as late).
  void AbortStreamUpstream(int u, uint64_t up_id) {
    if (!ups_[size_t(u)].Ready()) return;
    std::string payload;
    ipt::detail::put<uint64_t>(&payload, up_id);
    payload.push_back(char(ipt::kChunkLast));
    AppendUpstream(u, ipt::kChunkMagic,
                   reinterpret_cast<const uint8_t*>(payload.data()),
                   payload.size(), up_id);
  }

  void FlushDown(DownConn* c) {
    if (c->fd < 0) return;
    while (c->out_off < c->outbuf.size()) {
      ssize_t n = write(c->fd, c->outbuf.data() + c->out_off,
                        c->outbuf.size() - c->out_off);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        Doom(c);
        return;
      }
      c->out_off += size_t(n);
    }
    if (c->out_off == c->outbuf.size()) {
      c->outbuf.clear();
      c->out_off = 0;
    } else if (c->outbuf.size() - c->out_off > opt_.max_down_buf) {
      Doom(c);  // reader stopped draining verdicts
      return;
    }
    bool want = !c->outbuf.empty();
    if (want != c->want_out) {
      c->want_out = want;
      Modify(c->fd, want ? (EPOLLIN | EPOLLOUT) : EPOLLIN, 0, c->id);
    }
  }

  void Doom(DownConn* c) {
    if (c->fd >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, c->fd, nullptr);
      close(c->fd);
      doomed_.push_back(c->fd);
      c->fd = -1;
      --counters_.down_conns_active;
      conns_by_id_.erase(c->id);
      // abort any body streams the conn left open, freeing the serve
      // loop's per-stream state (verdicts for them will drop as late)
      for (uint64_t orig_id : c->open_streams) {
        auto it = streams_.find(StreamKey(c->id, orig_id));
        if (it == streams_.end()) continue;
        auto p = pending_.find(it->second);
        if (p != pending_.end())
          AbortStreamUpstream(p->second.up_idx, it->second);
        streams_.erase(it);
      }
      c->open_streams.clear();
      // same for ws captures: tell the serve loop to free parser state
      for (uint64_t orig_stream : c->open_ws) {
        auto it = ws_streams_.find(StreamKey(c->id, orig_stream));
        if (it == ws_streams_.end()) continue;
        EndWsUpstream(it->second.up_idx, it->second.up_stream_id);
        ws_streams_.erase(it);
      }
      c->open_ws.clear();
    }
  }

  void CloseDoomed() {
    for (int fd : doomed_) {
      auto it = conns_.find(fd);
      // fd<0 check: a new conn may have reused the fd key this iteration
      if (it != conns_.end() && it->second->fd < 0) conns_.erase(it);
    }
    // pending entries for closed conns stay until answer/deadline; the
    // response path drops verdicts whose conn id no longer resolves
    doomed_.clear();
  }

  // ---------------------------------------------------------- upstream

  static uint64_t StreamKey(uint64_t conn_id, uint64_t orig_id) {
    // conn ids are small monotonic; mix so (conn, req) collisions need
    // matching low bits on both — fine for a lookup key (not security)
    return conn_id * 0x9e3779b97f4a7c15ull ^ orig_id;
  }

  void AppendUpstream(int u, const char magic[4], const uint8_t* payload,
                      size_t len, uint64_t up_id,
                      const uint64_t* ws_stream = nullptr) {
    Upstream& up = ups_[size_t(u)];
    up.outbuf.append(magic, 4);
    ipt::detail::put<uint32_t>(&up.outbuf, uint32_t(len));
    size_t at = up.outbuf.size();
    up.outbuf.append(reinterpret_cast<const char*>(payload), len);
    std::memcpy(&up.outbuf[at], &up_id, 8);  // re-id for global uniqueness
    if (ws_stream != nullptr)                // ws frames re-id the stream too
      std::memcpy(&up.outbuf[at + 8], ws_stream, 8);
    if (std::memcmp(magic, ipt::kChunkMagic, 4) != 0) {
      // requests, response-scans and ws frames (including the
      // synthesized ws end frame — it has a pending entry consumed by
      // OnVerdict/ExpireDeadlines like any other) count toward
      // balancing state; chunks belong to an already-counted stream
      ++up.inflight;
      ++up.forwarded;
    }
    ++counters_.forwarded;
  }

  void FlushUpstream(int u) {
    Upstream& up = ups_[size_t(u)];
    if (up.fd < 0 || up.connecting) return;
    while (up.out_off < up.outbuf.size()) {
      ssize_t n = write(up.fd, up.outbuf.data() + up.out_off,
                        up.outbuf.size() - up.out_off);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        DropUpstream(u);
        return;
      }
      up.out_off += size_t(n);
    }
    if (up.out_off == up.outbuf.size()) {
      up.outbuf.clear();
      up.out_off = 0;
    }
    bool want = !up.outbuf.empty();
    if (want != up.want_out) {
      up.want_out = want;
      Modify(up.fd, want ? (EPOLLIN | EPOLLOUT) : EPOLLIN, kTagUpstream,
             uint64_t(u));
    }
  }

  void HandleUpstream(int u, uint32_t events) {
    Upstream& up = ups_[size_t(u)];
    if (up.connecting) {
      if (events & (EPOLLHUP | EPOLLERR)) { DropUpstream(u); return; }
      if (events & EPOLLOUT) {  // nonblocking connect completed — how?
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(up.fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) { DropUpstream(u); return; }
        up.connecting = false;
        up.want_out = false;
        Modify(up.fd, EPOLLIN, kTagUpstream, uint64_t(u));
        ++counters_.upstream_reconnects;
      }
      return;
    }
    if (events & (EPOLLHUP | EPOLLERR)) { DropUpstream(u); return; }
    if (events & EPOLLIN) {
      uint8_t buf[1 << 16];
      ssize_t n = -1;   /* read only when fd >= 0; guards below re-check */
      while (up.fd >= 0 && (n = read(up.fd, buf, sizeof buf)) > 0) {
        try {
          up.reader.Feed(buf, size_t(n), [&](const uint8_t* p, size_t len) {
            OnVerdict(u, p, len);
          });
        } catch (const std::exception& e) {
          fprintf(stderr, "upstream %s protocol error: %s\n",
                  up.path.c_str(), e.what());
          DropUpstream(u);
          return;
        }
      }
      if (up.fd >= 0 && n == 0) { DropUpstream(u); return; }
      if (up.fd >= 0 && n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        DropUpstream(u);  // hard error (e.g. ECONNRESET without EPOLLERR):
        return;           // leaving the fd registered would busy-loop
      }
    }
    FlushUpstream(u);
  }

  void OnVerdict(int u, const uint8_t* payload, size_t len) {
    uint64_t up_id = ipt::detail::get<uint64_t>(payload);
    auto it = pending_.find(up_id);
    Upstream& up = ups_[size_t(u)];
    if (it == pending_.end()) {
      // answered after deadline fail-open — ExpireDeadlines already
      // decremented inflight for it; decrementing again here would hide
      // a slow upstream's load from the ewma policy
      ++counters_.late_responses;
      return;
    }
    if (up.inflight > 0) --up.inflight;
    Pending p = it->second;
    pending_.erase(it);
    ++counters_.responses;
    // EWMA latency update (α = 0.1) feeds the ewma balancing policy
    double ms = double(NowNs() - p.sent_ns) / 1e6;
    up.ewma_ms += 0.1 * (ms - up.ewma_ms);
    auto cit = conns_by_id_.find(p.conn_id);
    if (cit == conns_by_id_.end() || cit->second->fd < 0) return;  // gone
    DownConn* c = cit->second;
    // restore the downstream req_id in place, reuse the rest verbatim
    std::string frame;
    frame.reserve(8 + len);
    frame.append(ipt::kRespMagic, 4);
    ipt::detail::put<uint32_t>(&frame, uint32_t(len));
    size_t at = frame.size();
    frame.append(reinterpret_cast<const char*>(payload), len);
    std::memcpy(&frame[at], &p.orig_id, 8);
    c->outbuf += frame;
    FlushDown(c);
  }

  // ---------------------------------------------------------- fail-open

  void SendFailOpen(const Pending& p) {
    auto cit = conns_by_id_.find(p.conn_id);
    if (cit == conns_by_id_.end() || cit->second->fd < 0) return;
    SendFailOpenTo(cit->second, p.orig_id);
  }

  void SendFailOpenTo(DownConn* c, uint64_t orig_id) {
    ipt::Response r;
    r.req_id = orig_id;
    r.flags = ipt::kFailOpen;  // pass + flag, never block on WAF trouble
    c->outbuf += ipt::EncodeResponse(r);
    FlushDown(c);
  }

  void ExpireDeadlines(uint64_t now) {
    while (!deadlines_.empty()) {
      auto [dl, up_id] = deadlines_.top();
      if (dl > now) break;
      deadlines_.pop();
      auto it = pending_.find(up_id);
      if (it == pending_.end() || it->second.deadline_ns != dl) continue;
      Pending p = it->second;
      pending_.erase(it);
      Upstream& up = ups_[size_t(p.up_idx)];
      if (up.inflight > 0) --up.inflight;
      auto sit = streams_.find(StreamKey(p.conn_id, p.orig_id));
      if (sit != streams_.end()) {  // stream stalled mid-body: abort it
        AbortStreamUpstream(p.up_idx, sit->second);
        streams_.erase(sit);
        auto cit = conns_by_id_.find(p.conn_id);
        if (cit != conns_by_id_.end())
          cit->second->open_streams.erase(p.orig_id);
      }
      ++counters_.fail_open_deadline;
      SendFailOpen(p);
    }
  }

  // ---------------------------------------------------------- status

  void AcceptStatus() {
    while (true) {
      int fd = accept(status_fd_, nullptr, nullptr);
      if (fd < 0) return;
      if (status_conns_.size() >= 32) { close(fd); continue; }  // bounded
      SetNonblock(fd);
      // answer after the client's (tiny) request arrives: writing before
      // reading risks an RST discarding the response on close
      Register(fd, EPOLLIN, kTagStatusConn, uint64_t(fd));
      status_conns_[fd] = NowNs() + 5000000000ull;  // idle cutoff: 5s
    }
  }

  void CloseStatusConn(int fd) {
    epoll_ctl(ep_, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    status_conns_.erase(fd);
  }

  void ExpireStatusConns(uint64_t now) {
    for (auto it = status_conns_.begin(); it != status_conns_.end();) {
      int fd = it->first;
      uint64_t dl = it->second;
      ++it;  // CloseStatusConn erases; advance first
      if (now >= dl) CloseStatusConn(fd);
    }
  }

  void HandleStatusConn(int fd) {
    if (!status_conns_.count(fd)) return;  // stale event after close
    uint8_t drain[4096];
    ssize_t n = read(fd, drain, sizeof drain);
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    // std::string throughout: upstream count/paths are unbounded, so a
    // fixed stack buffer would truncate — or worse, a raw snprintf return
    // used as a write length would leak adjacent stack bytes
    auto item = [](const char* fmt, auto... args) {
      char b[512];
      int n = snprintf(b, sizeof b, fmt, args...);
      if (n < 0) n = 0;
      if (n >= int(sizeof b)) n = int(sizeof b) - 1;
      return std::string(b, size_t(n));
    };
    std::string ups_json = "[";
    for (size_t u = 0; u < ups_.size(); ++u)
      ups_json += item(
          "%s{\"path\": \"%s\", \"connected\": %s, \"ewma_ms\": %.3f, "
          "\"inflight\": %llu, \"forwarded\": %llu}",
          u ? ", " : "", ups_[u].path.c_str(),
          ups_[u].Ready() ? "true" : "false", ups_[u].ewma_ms,
          (unsigned long long)ups_[u].inflight,
          (unsigned long long)ups_[u].forwarded);
    ups_json += "]";
    std::string body = item(
        "{\"requests_in\": %llu, \"chunks_in\": %llu, "
        "\"ws_frames_in\": %llu, "
        "\"forwarded\": %llu, \"responses\": %llu, "
        "\"fail_open_deadline\": %llu, \"fail_open_upstream\": %llu, "
        "\"fail_open_overload\": %llu, \"late_responses\": %llu, "
        "\"down_conns_total\": %llu, \"down_conns_active\": %llu, "
        "\"bad_frames\": %llu, \"upstream_reconnects\": %llu, "
        "\"upstream_connected\": %s, \"pending\": %zu, ",
        (unsigned long long)counters_.requests_in,
        (unsigned long long)counters_.chunks_in,
        (unsigned long long)counters_.ws_frames_in,
        (unsigned long long)counters_.forwarded,
        (unsigned long long)counters_.responses,
        (unsigned long long)counters_.fail_open_deadline,
        (unsigned long long)counters_.fail_open_upstream,
        (unsigned long long)counters_.fail_open_overload,
        (unsigned long long)counters_.late_responses,
        (unsigned long long)counters_.down_conns_total,
        (unsigned long long)counters_.down_conns_active,
        (unsigned long long)counters_.bad_frames,
        (unsigned long long)counters_.upstream_reconnects,
        AnyReady() ? "true" : "false", pending_.size());
    body += "\"upstreams\": " + ups_json + "}\n";
    std::string resp =
        item("HTTP/1.0 200 OK\r\nContent-Type: application/json\r\n"
             "Content-Length: %zu\r\n\r\n", body.size()) + body;
    // one-shot local scrape: a single write covers it (fits the sndbuf)
    ssize_t w = write(fd, resp.data(), resp.size());
    (void)w;
    CloseStatusConn(fd);
  }

  Options opt_;
  Counters counters_;
  int ep_ = -1;
  int listen_fd_ = -1;
  int status_fd_ = -1;

  // conns_ (fd-keyed) owns; conns_by_id_ routes both epoll events and
  // verdicts by the monotonic conn id, so neither a reused fd nor a stale
  // queued epoll event can ever reach the wrong connection
  std::unordered_map<int, std::unique_ptr<DownConn>> conns_;
  std::unordered_map<uint64_t, DownConn*> conns_by_id_;
  std::vector<int> doomed_;
  uint64_t next_conn_id_ = 0;
  std::unordered_map<int, uint64_t> status_conns_;  // fd → idle deadline

  std::vector<Upstream> ups_;
  std::map<uint64_t, int> ring_;  // chash: vnode hash → upstream index
  uint64_t rr_next_ = 0;

  uint64_t next_up_id_ = 0;
  std::unordered_map<uint64_t, Pending> pending_;
  std::unordered_map<uint64_t, uint64_t> streams_;  // (conn,orig) → up_id
  // WebSocket capture bindings: (conn, orig stream) → sticky upstream +
  // globally-unique rewritten stream id (the serve loop keys parser and
  // sticky-verdict state by it, so every frame of one upgraded
  // connection MUST reach the same upstream under the same id)
  struct WsBinding { int up_idx; uint64_t up_stream_id; };
  std::unordered_map<uint64_t, WsBinding> ws_streams_;
  // min-heap of (deadline, up_id); stale entries dropped lazily
  using DlEntry = std::pair<uint64_t, uint64_t>;
  std::priority_queue<DlEntry, std::vector<DlEntry>, std::greater<DlEntry>>
      deadlines_;
};

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "missing value for %s\n", a.c_str());
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--listen") opt.listen_path = next();
    else if (a == "--upstream") {
      // comma-separated list of serve-loop sockets (one per chip)
      std::string v = next();
      size_t start = 0;
      while (start <= v.size()) {
        size_t comma = v.find(',', start);
        std::string p = v.substr(
            start, comma == std::string::npos ? std::string::npos
                                              : comma - start);
        if (!p.empty()) opt.upstream_paths.push_back(p);
        if (comma == std::string::npos) break;
        start = comma + 1;
      }
    }
    else if (a == "--balance") {
      std::string v = next();
      if (v == "rr") opt.balance = Balance::kRoundRobin;
      else if (v == "ewma") opt.balance = Balance::kEwma;
      else if (v == "chash") opt.balance = Balance::kChash;
      else { fprintf(stderr, "unknown balance policy %s\n", v.c_str()); return 2; }
    }
    else if (a == "--deadline-ms") opt.deadline_ms = atof(next());
    else if (a == "--status-port") opt.status_port = atoi(next());
    else if (a == "--max-upstream-buf") opt.max_upstream_buf = size_t(atol(next()));
    else if (a == "--max-down-buf") opt.max_down_buf = size_t(atol(next()));
    else if (a == "--reconnect-ms") opt.reconnect_ms = atoi(next());
    else { fprintf(stderr, "unknown arg %s\n", a.c_str()); return 2; }
  }
  if (opt.listen_path.empty() || opt.upstream_paths.empty()) {
    fprintf(stderr,
            "usage: sidecar --listen <uds> --upstream <uds>[,<uds>...] "
            "[--balance rr|ewma|chash] [--deadline-ms N] [--status-port P] "
            "[--max-upstream-buf B] [--max-down-buf B] [--reconnect-ms N]\n");
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  return Sidecar(opt).Run();
}
