// Wire protocol for the ingress_plus_tpu serve loop — C++ twin of
// ingress_plus_tpu/serve/protocol.py (byte-for-byte; see that file for the
// frame layouts and the reasons this is a fixed little-endian format
// rather than gRPC).
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace ipt {

constexpr uint32_t kMaxFrame = 8u << 20;
inline const char kReqMagic[4] = {'Q', 'T', 'P', 'I'};
inline const char kRespMagic[4] = {'R', 'T', 'P', 'I'};
inline const char kChunkMagic[4] = {'K', 'T', 'P', 'I'};
// Response-scan frame (upstream HTTP response → leak analysis; the
// wallarm_parse_response analog).  Verdict returns as a normal RTPI frame.
inline const char kRespScanMagic[4] = {'P', 'T', 'P', 'I'};
// WebSocket capture frame (raw upgraded-connection bytes, either
// direction; the wallarm_parse_websocket analog).  One RTPI verdict per
// frame; `stream` keys persistent parser/scan state on the serve side.
inline const char kWsMagic[4] = {'W', 'T', 'P', 'I'};

enum Flags : uint8_t {
  kAttack = 1,
  kBlocked = 2,
  kFailOpen = 4,
};

// Request-frame mode bit: body arrives as chunk frames (config #5).
constexpr uint8_t kModeStream = 0x80;

// Mode-byte bits 3-6: per-location parser disables (twin of protocol.py
// PARSER_OFF_BITS) — trusted config plane, never a client header.
constexpr uint8_t kParserOffGzip = 0x08;
constexpr uint8_t kParserOffBase64 = 0x10;
constexpr uint8_t kParserOffJson = 0x20;
constexpr uint8_t kParserOffXml = 0x40;
constexpr uint8_t kChunkLast = 1;

// WS-frame flag bits (twin of protocol.py WS_DIR_S2C / WS_END).
constexpr uint8_t kWsDirS2C = 1;  // bytes are server→client
constexpr uint8_t kWsEnd = 2;     // upgraded connection closed

struct Request {
  uint64_t req_id = 0;
  uint32_t tenant = 0;
  uint8_t mode = 2;  // 0 off, 1 monitoring, 2 block
  std::string method = "GET";
  std::string uri = "/";
  // headers are shipped pre-joined: "key: value\x1f key: value"
  std::string headers_blob;
  std::string body;
};

struct Response {
  uint64_t req_id = 0;
  uint8_t flags = 0;
  uint32_t score = 0;
  std::vector<uint8_t> class_ids;
  std::vector<uint64_t> rule_ids;

  bool attack() const { return flags & kAttack; }
  bool blocked() const { return flags & kBlocked; }
  bool fail_open() const { return flags & kFailOpen; }
};

namespace detail {
template <typename T>
inline void put(std::string* out, T v) {
  char buf[sizeof(T)];
  std::memcpy(buf, &v, sizeof(T));  // assumes little-endian host
  out->append(buf, sizeof(T));
}
template <typename T>
inline T get(const uint8_t* p) {
  T v;
  std::memcpy(&v, p, sizeof(T));
  return v;
}
}  // namespace detail

inline std::string EncodeRequest(const Request& r) {
  std::string payload;
  payload.reserve(22 + r.method.size() + r.uri.size() +
                  r.headers_blob.size() + r.body.size());
  detail::put<uint64_t>(&payload, r.req_id);
  detail::put<uint32_t>(&payload, r.tenant);
  payload.push_back(static_cast<char>(r.mode));
  payload.push_back(static_cast<char>(r.method.size()));
  detail::put<uint32_t>(&payload, static_cast<uint32_t>(r.uri.size()));
  detail::put<uint32_t>(&payload,
                        static_cast<uint32_t>(r.headers_blob.size()));
  detail::put<uint32_t>(&payload, static_cast<uint32_t>(r.body.size()));
  payload += r.method;
  payload += r.uri;
  payload += r.headers_blob;
  payload += r.body;

  std::string frame;
  frame.reserve(8 + payload.size());
  frame.append(kReqMagic, 4);
  detail::put<uint32_t>(&frame, static_cast<uint32_t>(payload.size()));
  frame += payload;
  return frame;
}

// Upstream HTTP response for leak scanning (twin of protocol.py
// encode_response_scan: req_id u64, tenant u32, mode u8, status u16,
// hdr_len u32, body_len u32, headers blob, body).
struct ResponseScan {
  uint64_t req_id = 0;
  uint32_t tenant = 0;
  uint8_t mode = 2;
  uint16_t status = 200;
  std::string headers_blob;  // "key: value\x1f key: value"
  std::string body;
};

inline std::string EncodeResponseScan(const ResponseScan& r) {
  std::string payload;
  payload.reserve(23 + r.headers_blob.size() + r.body.size());
  detail::put<uint64_t>(&payload, r.req_id);
  detail::put<uint32_t>(&payload, r.tenant);
  payload.push_back(static_cast<char>(r.mode));
  detail::put<uint16_t>(&payload, r.status);
  detail::put<uint32_t>(&payload,
                        static_cast<uint32_t>(r.headers_blob.size()));
  detail::put<uint32_t>(&payload, static_cast<uint32_t>(r.body.size()));
  payload += r.headers_blob;
  payload += r.body;

  std::string frame;
  frame.reserve(8 + payload.size());
  frame.append(kRespScanMagic, 4);
  detail::put<uint32_t>(&frame, static_cast<uint32_t>(payload.size()));
  frame += payload;
  return frame;
}

// Body chunk for a stream opened with kModeStream (twin of
// protocol.py encode_chunk: req_id u64, flags u8, data).
inline std::string EncodeChunk(uint64_t req_id, const std::string& data,
                               bool last) {
  std::string payload;
  payload.reserve(9 + data.size());
  detail::put<uint64_t>(&payload, req_id);
  payload.push_back(static_cast<char>(last ? kChunkLast : 0));
  payload += data;
  std::string frame;
  frame.reserve(8 + payload.size());
  frame.append(kChunkMagic, 4);
  detail::put<uint32_t>(&frame, static_cast<uint32_t>(payload.size()));
  frame += payload;
  return frame;
}

// WebSocket capture frame (twin of protocol.py encode_ws: req_id u64,
// stream u64, tenant u32, mode u8, flags u8, raw ws bytes).
inline std::string EncodeWs(uint64_t req_id, uint64_t stream_id,
                            const std::string& data, uint32_t tenant = 0,
                            uint8_t mode = 2, uint8_t flags = 0) {
  std::string payload;
  payload.reserve(22 + data.size());
  detail::put<uint64_t>(&payload, req_id);
  detail::put<uint64_t>(&payload, stream_id);
  detail::put<uint32_t>(&payload, tenant);
  payload.push_back(static_cast<char>(mode));
  payload.push_back(static_cast<char>(flags));
  payload += data;
  std::string frame;
  frame.reserve(8 + payload.size());
  frame.append(kWsMagic, 4);
  detail::put<uint32_t>(&frame, static_cast<uint32_t>(payload.size()));
  frame += payload;
  return frame;
}

// Verdict frame, server → client.  The sidecar also synthesizes these for
// fail-open verdicts (deadline exceeded / upstream down — SURVEY.md §5
// "fail-open contract is load-bearing").
inline std::string EncodeResponse(const Response& r) {
  // Wire format caps: u8 class count, u16 rule count.  Clamp (mirroring
  // protocol.py encode_response) so an oversized vector can never
  // truncate the counts and desynchronize the decoder's offsets.
  const size_t n_cls = std::min<size_t>(r.class_ids.size(), 255);
  const size_t n_rules = std::min<size_t>(r.rule_ids.size(), 65535);
  std::string payload;
  payload.reserve(16 + n_cls + 8 * n_rules);
  detail::put<uint64_t>(&payload, r.req_id);
  payload.push_back(static_cast<char>(r.flags));
  detail::put<uint32_t>(&payload, r.score);
  payload.push_back(static_cast<char>(n_cls));
  detail::put<uint16_t>(&payload, static_cast<uint16_t>(n_rules));
  for (size_t i = 0; i < n_cls; ++i)
    payload.push_back(static_cast<char>(r.class_ids[i]));
  for (size_t i = 0; i < n_rules; ++i)
    detail::put<uint64_t>(&payload, r.rule_ids[i]);
  std::string frame;
  frame.reserve(8 + payload.size());
  frame.append(kRespMagic, 4);
  detail::put<uint32_t>(&frame, static_cast<uint32_t>(payload.size()));
  frame += payload;
  return frame;
}

inline Response DecodeResponse(const uint8_t* p, size_t n) {
  if (n < 16) throw std::runtime_error("short response frame");
  Response r;
  r.req_id = detail::get<uint64_t>(p);
  r.flags = p[8];
  r.score = detail::get<uint32_t>(p + 9);
  uint8_t n_cls = p[13];
  uint16_t n_rules = detail::get<uint16_t>(p + 14);
  size_t off = 16;
  if (n < off + n_cls + 8ull * n_rules)
    throw std::runtime_error("truncated response frame");
  r.class_ids.assign(p + off, p + off + n_cls);
  off += n_cls;
  r.rule_ids.resize(n_rules);
  for (uint16_t i = 0; i < n_rules; ++i)
    r.rule_ids[i] = detail::get<uint64_t>(p + off + 8ull * i);
  return r;
}

// Fixed-header payload minimums, enforced at the framing layer so no
// consumer ever indexes a header field out of bounds.
constexpr size_t kMinRequestPayload = 26;   // _REQ_HEAD: Q I B B I I I
constexpr size_t kMinResponsePayload = 16;  // _RESP_HEAD + counts
constexpr size_t kMinChunkPayload = 9;      // _CHUNK_HEAD: Q B
constexpr size_t kMinRespScanPayload = 23;  // _RSCAN_HEAD: Q I B H I I
constexpr size_t kMinWsPayload = 22;        // _WS_HEAD: Q Q I B B

// Incremental splitter for a stream interleaving several frame kinds —
// C++ twin of protocol.py's MultiFrameReader (the framing loop exists
// once; per-direction readers are instantiations).
class MultiFrameReader {
 public:
  struct Kind {
    const char* magic;  // 4 bytes
    int kind;
    size_t min_payload;
  };

  explicit MultiFrameReader(std::vector<Kind> kinds)
      : kinds_(std::move(kinds)) {}

  // Appends data; invokes cb(kind, payload, len) per complete frame.
  // Throws on a protocol violation (unknown magic / bad length).
  template <typename Cb>
  void Feed(const uint8_t* data, size_t n, Cb cb) {
    if (n) buf_.insert(buf_.end(), data, data + n);
    size_t off = 0;
    while (buf_.size() - off >= 8) {
      const Kind* k = nullptr;
      for (const Kind& cand : kinds_)
        if (std::memcmp(buf_.data() + off, cand.magic, 4) == 0) {
          k = &cand;
          break;
        }
      if (!k) throw std::runtime_error("bad frame magic");
      uint32_t len = detail::get<uint32_t>(buf_.data() + off + 4);
      if (len > kMaxFrame) throw std::runtime_error("oversized frame");
      if (len < k->min_payload) throw std::runtime_error("short frame");
      if (buf_.size() - off < 8ull + len) break;
      cb(k->kind, buf_.data() + off + 8, size_t(len));
      off += 8ull + len;
    }
    buf_.erase(buf_.begin(), buf_.begin() + off);
  }

 private:
  std::vector<Kind> kinds_;
  std::vector<uint8_t> buf_;
};

// Single-kind reader for the response stream (loadgen / shim side).
class FrameReader {
 public:
  FrameReader()
      : inner_({{kRespMagic, 0, kMinResponsePayload}}) {}

  template <typename Cb>
  void Feed(const uint8_t* data, size_t n, Cb cb) {
    inner_.Feed(data, n,
                [&](int, const uint8_t* p, size_t len) { cb(p, len); });
  }

 private:
  MultiFrameReader inner_;
};

}  // namespace ipt
